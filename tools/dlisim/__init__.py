"""dlisim — trace-calibrated discrete-event cluster simulator.

Runs the REAL control plane — ``runtime/master.py``'s scheduler
(``_pick_node``/``_plan_disagg``), circuit breaker, retry/backoff
machinery, the group-commit ``Store``, the TSDB and the flight
recorder — against a fleet of *synthetic* workers on a
``utils/clock.VirtualClock``. Only the two worker RPC methods and the
scrape fan-out are replaced (``sim.SimMaster``); every scheduling
decision, journal event, metric and SQL row is produced by the same
code that runs in production.

What that buys (docs/simulator.md):

- **Scale**: 1000+ nodes and 100k+ requests exercise the scheduler's
  sampled pick path, breaker sweeps and journal volume in seconds of
  wall time — hours of cluster time on a laptop CPU.
- **Determinism**: one seed fixes the arrival trace, the jitter
  stream and the pick RNG; two runs produce byte-identical decision
  journals (the ``journal_hash`` in the report is the proof).
- **Calibration**: ``fit.py`` fits the synthetic workers' service
  model from the fleet's own telemetry (cost-ledger rows, bench
  JSONs, the ``request-submitted`` arrival trace) and
  ``calibrate.py`` replays a recorded real run, failing CI when
  sim-vs-real divergence exceeds the documented tolerance.

Entry points: ``python -m tools.dlisim`` (CLI),
``bench.py --scenario sim_scale|sim_calibrate`` (CI gates).
"""

from .fleet import NodeSpec, SimNode, SyntheticFleet, WorkerModel
from .fit import (DEFAULT_MODEL, arrival_trace_from_events,
                  fit_from_artifacts, fit_worker_model,
                  synthetic_arrivals)
from .sim import SimConfig, SimMaster, SimReport, run_sim
from .calibrate import DEFAULT_TOLERANCES, divergence_report

__all__ = [
    "NodeSpec", "SimNode", "SyntheticFleet", "WorkerModel",
    "DEFAULT_MODEL", "arrival_trace_from_events", "fit_from_artifacts",
    "fit_worker_model", "synthetic_arrivals",
    "SimConfig", "SimMaster", "SimReport", "run_sim",
    "DEFAULT_TOLERANCES", "divergence_report",
]
