"""Planner sweep: validate the auto-parallelism planner against the
simulator (``python -m tools.dlisim --planner-sweep``).

The sweep builds one heterogeneous synthetic fleet — a slow tail of
nodes whose per-token service time violates the ITL SLO — and measures
the ground truth the planner only *estimates*: for each candidate
prefill-quarantine size ``k`` (slowest ``k`` nodes flipped to the
strict prefill role, exactly what the rebalancer does when it steers
toward a planner target) it runs a full virtual-clock simulation and
reads the within-SLO goodput off the journal.

The planner then prices the same fleet from the same worker models the
simulator executes (decode rate = ``1000 / (decode_ms_per_token x
speed)``), and the sweep asserts its top choice lands within
``DLI_PLANNER_TOLERANCE`` of the sim-measured best. Everything is a
pure function of the seed — per-candidate journal hashes land in the
report so two runs can be diffed byte-for-byte.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List

from distributed_llm_inferencing_tpu.parallel import planner as _planner
from distributed_llm_inferencing_tpu.runtime.tsdb import slo_targets

from .fit import DEFAULT_MODEL
from .sim import SimConfig, run_sim


def _fleet_views(nodes: int, slow_nodes: int, slow_speed: float,
                 model=None) -> List[dict]:
    """Planner-side node views replaying the sim's fitted worker
    models: what the master's ``_planner_views`` would report after the
    TSDB has seen this fleet serve (rate = the worker model's actual
    decode step rate, latency = its e2e service time)."""
    model = model or DEFAULT_MODEL
    views = []
    for i in range(nodes):
        speed = slow_speed if i < slow_nodes else 1.0
        decode_ms = model.decode_ms_per_token * speed
        views.append({
            "id": i + 1,   # sim registration order: slow nodes first
            "name": f"sim-{i}",
            "devices": [{"kind": "sim-tpu", "memory_bytes": 16 << 30}],
            "decode_tok_s": 1000.0 / decode_ms,
            "latency_ms": model.overhead_ms * speed,
        })
    return views


def sweep(nodes: int = 120, slow_frac: float = 1.0 / 3.0,
          slow_speed: float = 24.0, requests: int = 3000,
          duration_s: float = 300.0, seed: int = 42,
          model_name: str = "tiny-llama") -> Dict[str, Any]:
    """Run the sweep; returns the report dict (``ok`` = planner's top
    choice within tolerance of the sim-measured best)."""
    slow_nodes = max(1, int(nodes * slow_frac))
    speeds = [slow_speed] * slow_nodes          # slowest first: a k-node
    # prefill pool == quarantining the k slowest (planner picks whole
    # slow classes, whose node ids are exactly this prefix)
    targets = slo_targets()

    # ---- planner side: price the fleet from the worker models --------
    views = _fleet_views(nodes, slow_nodes, slow_speed)
    classes = _planner.fit_node_classes(views)
    inputs = _planner.CostInputs(
        est_prompt_tokens=64, est_decode_tokens=16,
        prefill_ms_per_tok=DEFAULT_MODEL.prefill_ms_per_token,
        slo_itl_ms=targets["itl_p95_ms"])
    decision = _planner.search(model_name, classes, inputs, now=0.0)
    chosen = decision.get("chosen") or {}
    planner_k = len(chosen.get("prefill_nodes") or [])

    # ---- sim side: measure each candidate quarantine size ------------
    cand_ks = sorted({0, 1, slow_nodes // 2, slow_nodes, planner_k})
    candidates = []
    for k in cand_ks:
        rep = run_sim(SimConfig(
            nodes=nodes, requests=requests, duration_s=duration_s,
            arrival="uniform", seed=seed, speeds=speeds,
            prefill_nodes=k))
        candidates.append({
            "prefill_nodes": k,
            "goodput_req_per_s": rep.goodput_req_per_s or 0.0,
            "completed": rep.completed, "failed": rep.failed,
            "journal_hash": rep.journal_hash,
        })
    best = max(candidates, key=lambda c: c["goodput_req_per_s"])
    planner_row = next(c for c in candidates
                       if c["prefill_nodes"] == planner_k)
    tol = _planner.PLANNER_TOLERANCE
    ok = (planner_row["goodput_req_per_s"]
          >= (1.0 - tol) * best["goodput_req_per_s"])
    # strip the bulky partition-spec plan: the report compares scores,
    # the full decision record lives in the master's meta row / journal
    slim = {k2: v for k2, v in decision.items() if k2 != "chosen"}
    if chosen:
        slim["chosen"] = {k2: v for k2, v in chosen.items()
                          if k2 != "plan"}
    return {
        "scenario": "planner-sweep",
        "model": model_name,
        "nodes": nodes, "slow_nodes": slow_nodes,
        "slow_speed": slow_speed,
        "requests": requests, "duration_s": duration_s, "seed": seed,
        "slo": {"itl_p95_ms": targets["itl_p95_ms"],
                "ttft_ms": targets["ttft_ms"]},
        "planner": {"decision": slim, "prefill_nodes": planner_k,
                    "goodput_req_per_s":
                        planner_row["goodput_req_per_s"]},
        "candidates": candidates,
        "sim_best": best,
        "tolerance": tol,
        "ok": ok,
    }


def main(args) -> int:
    report = sweep(nodes=args.nodes, requests=args.requests,
                   duration_s=args.duration, seed=args.seed)
    line = json.dumps(report, sort_keys=True)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if not report["ok"]:
        import sys
        print(f"planner sweep FAILED: planner choice "
              f"k={report['planner']['prefill_nodes']} reached "
              f"{report['planner']['goodput_req_per_s']} req/s vs "
              f"sim best {report['sim_best']['goodput_req_per_s']} "
              f"(tolerance {report['tolerance']})", file=sys.stderr)
        return 1
    return 0
