"""The discrete-event engine: real control plane, virtual time.

:class:`SimMaster` subclasses the production ``Master`` and replaces
exactly three things — the two worker RPC methods and the concurrent
scrape fan-out — with deterministic, in-process equivalents backed by
the :class:`~tools.dlisim.fleet.SyntheticFleet`. Everything else (the
scheduler, breaker state machine, retry/backoff, the group-commit
``Store``, the TSDB, the flight recorder) is the shipped code.

:func:`run_sim` owns the virtual clock and the event loop. It drives
the master at function level, mirroring the real thread structure:

- an *arrival* calls ``api_submit`` (journals ``request-submitted``);
- a *dispatch* pass does what one ``_dispatch_loop`` wave does —
  ``claim_next_pending_many`` then per request ``_plan_disagg`` /
  ``_reserve_node_for`` / ``_note_dispatch`` — and hands the request
  to the synthetic node, scheduling its completion event;
- a *completion* runs the real terminal tails
  (``_complete_request`` / ``_fail_sub``) with the same
  in-flight/processing bookkeeping ``_execute_on_node`` keeps;
- *health* and *telemetry* events invoke the real sweeps on their
  configured cadence.

Determinism: the virtual clock only moves in the event loop; the
global ``random`` seed fixes backoff jitter; the master's private
pick RNG is fixed-seeded; scrapes run sequentially in node order; the
store is flushed at every decision point so group-commit visibility
never depends on the background flusher's real-time race. The
``journal_hash`` in the report digests every emitted event — two runs
with the same config and seed must produce the same hash.

Invariant checking rides the dispatch path (see
:class:`InvariantChecker`): violations are collected, never raised,
so a gate run reports all of them.
"""

from __future__ import annotations

import hashlib
import heapq
import json
import random
import time as _time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from distributed_llm_inferencing_tpu.runtime import master as master_mod
from distributed_llm_inferencing_tpu.runtime import tsdb as tsdb_mod
from distributed_llm_inferencing_tpu.utils import clock

from .fit import DEFAULT_MODEL, synthetic_arrivals
from .fleet import SyntheticFleet, WorkerModel


class _FakeResponse:
    """The minimal surface the master reads off a worker response."""

    def __init__(self, status: int = 200, body: Optional[dict] = None,
                 text: Optional[str] = None):
        self.status_code = status
        self._body = body
        self.text = (text if text is not None
                     else (json.dumps(body) if body is not None else ""))
        self.headers: Dict[str, str] = {}

    def json(self):
        if self._body is None:
            raise ValueError("no JSON body")
        return self._body

    def raise_for_status(self):
        if self.status_code >= 400:
            raise RuntimeError(f"sim worker HTTP {self.status_code}")


class SimMaster(master_mod.Master):
    """The production master with its worker I/O redirected at the
    synthetic fleet. Node rows address fleet members by port."""

    def __init__(self, fleet: SyntheticFleet, vclock, **kw):
        self._fleet = fleet
        self._vclock = vclock
        kw.setdefault("tsdb_snapshot_s", 0.0)   # multi-MB dumps off
        kw.setdefault("rebalance", False)
        super().__init__(":memory:", **kw)

    def _sim_node(self, node):
        sn = self._fleet.by_port.get(node["port"])
        if sn is None:
            raise ConnectionError(f"sim: unknown node {node.get('name')}")
        if sn.is_down(clock.now()):
            raise ConnectionError(f"sim: {sn.spec.name} unreachable")
        return sn

    def _worker_get(self, node, path, timeout, stream=False):
        sn = self._sim_node(node)
        now = clock.now()
        if path == "/health":
            return _FakeResponse(200, sn.health_body(now))
        if path == "/metrics":
            return _FakeResponse(200, text=sn.metrics_text(now))
        return _FakeResponse(404, {"status": "error",
                                   "message": f"sim: no GET {path}"})

    def _worker_post(self, node, path, body, timeout, stream=False):
        sn = self._sim_node(node)
        if path == "/cancel":
            # orphan cancels (terminal timeout / completed-elsewhere):
            # acknowledge; the synthetic generation holds no real slot
            return _FakeResponse(200, {"status": "success"})
        if path in ("/role", "/admin/role"):
            # the real master's _flip_role posts /role; accept the
            # legacy /admin/role spelling too
            sn.role = str((body or {}).get("role") or sn.role)
            return _FakeResponse(200, {"status": "success",
                                       "role": sn.role})
        return _FakeResponse(404, {"status": "error",
                                   "message": f"sim: no POST {path}"})

    def _scrape_workers(self, path: str, nodes=None):
        # sequential and in node order — the real thread-pool fan-out
        # would interleave _note_runtime updates nondeterministically
        if nodes is None:
            nodes = self.store.list_nodes(active_only=True)
        out = []
        for n in nodes:
            try:
                r = self._worker_get(n, path, 1.0)
                r.raise_for_status()
                out.append((n, r, None))
            except Exception as e:
                out.append((n, None, str(e)[:200]))
        return out

    def _purge_session(self, node):
        pass   # no real sockets to purge


@dataclass
class SimConfig:
    nodes: int = 100
    requests: int = 10_000
    duration_s: float = 600.0         # virtual seconds of arrivals
    arrival: str = "diurnal"          # uniform|diurnal|bursty|adversarial
    seed: int = 42
    slots_per_node: int = 8
    prefill_nodes: int = 0            # >0 enables the disagg planner path
    model: WorkerModel = field(default_factory=lambda: DEFAULT_MODEL)
    health_interval_s: float = 15.0
    telemetry_interval_s: float = 30.0
    dispatch_batch: Optional[int] = None
    sched_sample: Optional[int] = None
    disagg_min_prompt: Optional[int] = None
    #: fault injection: (node_index, down_from_s, down_until_s) —
    #: relative virtual time; the node refuses RPCs in the window and
    #: loses generations in flight across its opening edge
    fail_nodes: List[Tuple[int, float, float]] = field(default_factory=list)
    #: explicit arrival trace (fit.arrival_trace_from_events output);
    #: overrides (requests, duration_s, arrival)
    arrivals: Optional[List[dict]] = None
    #: how long past the last arrival to keep draining (virtual s)
    drain_s: float = 600.0
    #: overload front door (docs/robustness.md "Overload control"):
    #: slo_mix assigns SLO classes and tenants to arrivals
    #: deterministically (class i%3, tenant t{i%4}); the admit_*
    #: knobs forward to the Master constructor; overload drives
    #: _overload_sweep from the health cadence with the burn
    #: threshold pinned to 0 — a queue-only ladder, so the walk is a
    #: pure function of the virtual queue series (byte-deterministic)
    slo_mix: bool = False
    admit_rate: float = 0.0
    admit_burst: float = 0.0
    admit_max_pending: int = 0
    overload: bool = False
    overload_queue: float = 64.0
    overload_hold_s: float = 10.0
    #: per-node speed multipliers (>1 = slower), applied index-wise
    #: over the synthetic fleet — the heterogeneity input for the
    #: planner sweep (tools/dlisim/planner.py); shorter lists leave
    #: the tail at 1.0
    speeds: Optional[List[float]] = None
    #: >0: ONE claim wave per dispatch event, the next wave at
    #: +interval — pending accumulates between waves, which is what
    #: makes starvation_max_waves (claim waves a request sat pending)
    #: a meaningful anti-starvation measurement; 0 keeps the legacy
    #: drain-the-queue dispatch pass
    claim_interval_s: float = 0.0


@dataclass
class SimReport:
    config: dict
    requests: int = 0
    completed: int = 0
    failed: int = 0
    wall_s: float = 0.0
    sim_s: float = 0.0
    journal_hash: str = ""
    journal_counts: Dict[str, int] = field(default_factory=dict)
    metrics: Dict[str, float] = field(default_factory=dict)
    violations: List[dict] = field(default_factory=list)
    starved: int = 0
    pick_us_mean: float = 0.0
    pick_us_p95: float = 0.0
    ttft_ms_p50: Optional[float] = None
    ttft_ms_p95: Optional[float] = None
    goodput_req_per_s: Optional[float] = None
    queue_depth_mean: Optional[float] = None
    queue_depth_max: int = 0
    breaker: Dict[str, int] = field(default_factory=dict)
    # overload front door: honest refusals (429 + Retry-After) by
    # reason, class sheds, the highest rung the ladder reached, and
    # the anti-starvation measurement (max claim waves any admitted
    # request sat pending; bounded when admission bounds the queue)
    rejected: int = 0
    rejected_by_reason: Dict[str, int] = field(default_factory=dict)
    shed: Dict[str, int] = field(default_factory=dict)
    overload_level_max: int = 0
    claim_waves: int = 0
    #: claim waves run with the rung-4 gate closed (latency-only):
    #: waves non-latency work could not have been claimed in, so the
    #: anti-starvation bound adds them on top of the aging span
    waves_frozen: int = 0
    starvation_max_waves: int = 0

    def to_json(self) -> dict:
        return dict(self.__dict__)


class InvariantChecker:
    """Dispatch-time and end-state invariants, snapshot-consistent:
    every check compares against the node snapshot the scheduler
    itself used, so a mid-wave breaker transition (which the real
    dispatcher also cannot see) is not a false positive."""

    def __init__(self, master):
        self.m = master
        self.violations: List[dict] = []

    def _flag(self, kind: str, **ctx):
        self.violations.append({"kind": kind, "t": clock.now(), **ctx})

    def _by_id(self, nodes) -> dict:
        # keyed on the snapshot list's identity: the engine hands the
        # same cached list to every wave until a node row changes, so
        # this O(fleet) build runs per refresh, not per request
        if getattr(self, "_by_id_key", None) != id(nodes):
            self._by_id_key = id(nodes)
            self._by_id_map = {n["id"]: n for n in nodes}
        return self._by_id_map

    def _schedulable(self, n) -> bool:
        if n.get("draining"):
            return False
        bs = n.get("breaker_state") or "closed"
        if bs == "open":
            return False
        return not (bs == "half_open"
                    and self.m._inflight.get(n["id"], 0) > 0)

    def post_pick(self, req, node, nodes) -> None:
        snap = self._by_id(nodes).get(node["id"])
        if snap is None:
            self._flag("pick-outside-snapshot", request_id=req["id"],
                       node_id=node["id"])
            return
        bs = snap.get("breaker_state") or "closed"
        if bs == "open" or not snap.get("is_active"):
            self._flag("dispatch-to-open-breaker", request_id=req["id"],
                       node_id=node["id"], breaker_state=bs)
        if bs == "half_open":
            with self.m._inflight_lock:
                inflight = self.m._inflight.get(node["id"], 0)
            if inflight > 1:
                self._flag("half-open-multi-probe", request_id=req["id"],
                           node_id=node["id"], inflight=inflight)
        if snap.get("draining"):
            self._flag("dispatch-to-draining", request_id=req["id"],
                       node_id=node["id"])
        excluded = set(req.get("excluded_nodes") or [])
        if node["id"] in excluded:
            # the exclusion-fallback pick is legitimate only when no
            # non-excluded candidate was schedulable; the O(fleet)
            # re-check runs only on this rare path
            with self.m._inflight_lock:
                had_other = any(
                    n["id"] not in excluded and self._schedulable(n)
                    and n["id"] != node["id"] for n in nodes)
            if had_other:
                self._flag("exclusion-ignored", request_id=req["id"],
                           node_id=node["id"], excluded=sorted(excluded))
        if req["attempts"] >= master_mod.MAX_ATTEMPTS:
            self._flag("attempts-exceeded", request_id=req["id"],
                       attempts=req["attempts"])

    def end_state(self, store) -> None:
        for n in store.list_nodes():
            bs = n.get("breaker_state") or "closed"
            if bs == "open" and n.get("is_active"):
                self._flag("open-breaker-active", node_id=n["id"])
            if bs == "half_open" and not n.get("is_active"):
                self._flag("half-open-inactive", node_id=n["id"])
        counts = store.counts()
        for status in ("pending", "processing"):
            if counts.get(status, 0):
                self._flag("non-terminal-requests", status=status,
                           count=counts[status])


# event-kind ordinals: at one virtual instant, completions land before
# the dispatch pass (a freed slot is claimable by the same wave) and
# dispatch runs after arrivals
_K_RELEASE, _K_COMPLETE, _K_ARRIVE, _K_HEALTH, _K_TELEM, _K_DISPATCH = \
    range(6)


def run_sim(cfg: SimConfig) -> SimReport:
    """Run one simulation to completion and return its report."""
    vc = clock.VirtualClock()
    prev = clock.set_clock(vc)
    m = None
    try:
        random.seed(cfg.seed)
        fleet = SyntheticFleet.uniform(
            cfg.nodes, cfg.model, slots=cfg.slots_per_node,
            prefill_nodes=cfg.prefill_nodes)
        for i, sp in enumerate(cfg.speeds or []):
            if i < len(fleet.nodes):
                fleet.nodes[i].spec.speed = float(sp)
        base = vc.now()
        for idx, down_at, up_at in cfg.fail_nodes:
            fleet.nodes[idx % len(fleet)].fail_between(
                base + down_at, base + up_at)
        kw = {}
        if cfg.dispatch_batch is not None:
            kw["dispatch_batch"] = cfg.dispatch_batch
        if cfg.sched_sample is not None:
            kw["sched_sample"] = cfg.sched_sample
        if cfg.disagg_min_prompt is not None:
            kw["disagg_min_prompt"] = cfg.disagg_min_prompt
        if cfg.admit_rate:
            kw["admit_rate"] = cfg.admit_rate
            kw["admit_burst"] = cfg.admit_burst
        if cfg.admit_max_pending:
            kw["admit_max_pending"] = cfg.admit_max_pending
        if cfg.overload:
            # queue-only ladder (burn threshold 0): deterministic on
            # the virtual queue series; swept from the health cadence
            kw["overload_burn"] = 0.0
            kw["overload_queue"] = cfg.overload_queue
            kw["overload_hold_s"] = cfg.overload_hold_s
        m = SimMaster(fleet, vc, health_interval=cfg.health_interval_s,
                      **kw)
        # register the fleet: active rows with the health body as the
        # registration info (the pick path's _node_models source), and
        # the runtime view warmed exactly as a first health sweep would
        now = vc.now()
        for sn in fleet.nodes:
            body = sn.health_body(now)
            nid = m.store.add_node(sn.spec.name, "sim.invalid",
                                   sn.spec.port, is_active=True)
            m.store.update_node(nid, info=body, last_heartbeat=now)
            m._note_runtime(nid, body)

        digest = hashlib.sha256()
        jcounts: Dict[str, int] = {}
        orig_emit = m.events.emit

        def emit(etype, **kwargs):
            ev = orig_emit(etype, **kwargs)
            jcounts[etype] = jcounts.get(etype, 0) + 1
            digest.update(json.dumps(
                [round(clock.now(), 6), etype, kwargs],
                sort_keys=True, default=repr).encode())
            return ev

        m.events.emit = emit

        arrivals = cfg.arrivals
        if arrivals is None:
            arrivals = synthetic_arrivals(
                cfg.arrival, cfg.requests, cfg.duration_s, seed=cfg.seed)
        if cfg.slo_mix:
            classes = ("latency", "throughput", "batch")
            for i, a in enumerate(arrivals):
                a.setdefault("slo_class", classes[i % 3])
                a.setdefault("tenant", f"t{i % 4}")
        engine = _Engine(m, fleet, vc, InvariantChecker(m), cfg)
        wall0 = _time.perf_counter()
        engine.run(arrivals, base, cfg.drain_s)
        wall = _time.perf_counter() - wall0

        m.store.flush()
        engine.inv.end_state(m.store)
        counts = m.store.counts()
        snap = m.metrics.snapshot()
        c = snap["counters"]
        rep = SimReport(config={
            "nodes": cfg.nodes, "requests": len(arrivals),
            "arrival": cfg.arrival if cfg.arrivals is None else "trace",
            "seed": cfg.seed, "duration_s": cfg.duration_s,
            "prefill_nodes": cfg.prefill_nodes,
            "slots_per_node": cfg.slots_per_node,
            "model_source": dict(cfg.model.source),
            "fail_nodes": list(cfg.fail_nodes),
            "slo_mix": cfg.slo_mix, "admit_rate": cfg.admit_rate,
            "admit_max_pending": cfg.admit_max_pending,
            "overload": cfg.overload,
            "overload_queue": cfg.overload_queue,
            "claim_interval_s": cfg.claim_interval_s,
        })
        rep.requests = len(arrivals)
        rep.completed = counts.get("completed", 0)
        rep.failed = counts.get("failed", 0)
        rep.starved = counts.get("pending", 0) + counts.get("processing", 0)
        rep.wall_s = round(wall, 3)
        rep.sim_s = round(vc.now() - base, 3)
        rep.journal_hash = digest.hexdigest()
        rep.journal_counts = jcounts
        rep.violations = engine.inv.violations
        picks = sorted(engine.pick_times_us)
        if picks:
            rep.pick_us_mean = round(sum(picks) / len(picks), 2)
            rep.pick_us_p95 = round(picks[int(0.95 * (len(picks) - 1))], 2)
        ttfts = sorted(engine.ttfts_ms)
        if ttfts:
            rep.ttft_ms_p50 = round(ttfts[len(ttfts) // 2], 2)
            rep.ttft_ms_p95 = round(ttfts[int(0.95 * (len(ttfts) - 1))], 2)
        if engine.queue_samples:
            rep.queue_depth_mean = round(
                sum(engine.queue_samples) / len(engine.queue_samples), 2)
            rep.queue_depth_max = max(engine.queue_samples)
        if rep.sim_s > 0:
            rep.goodput_req_per_s = round(
                engine.within_slo / rep.sim_s, 3)
        rep.metrics = {k: v for k, v in sorted(c.items())
                       if k.startswith(("requests_", "scheduler_",
                                        "breaker_", "slo_", "admit_",
                                        "shed_"))}
        rep.rejected = engine.rejected
        rep.rejected_by_reason = dict(sorted(
            engine.rejected_by_reason.items()))
        rep.shed = {k[len("shed_"):]: int(v)
                    for k, v in sorted(c.items())
                    if k.startswith("shed_") and v}
        rep.overload_level_max = engine.overload_level_max
        rep.claim_waves = engine.claim_waves
        rep.waves_frozen = engine.waves_frozen
        rep.starvation_max_waves = engine.starvation_max_waves
        rep.breaker = {
            "opened": int(c.get("breaker_opened", 0)),
            "half_opened": int(c.get("breaker_half_opened", 0)),
            "closed": int(c.get("breaker_closed", 0)),
        }
        return rep
    finally:
        if m is not None:
            try:
                m.stop()
            except Exception:
                pass
        clock.set_clock(prev)


class _Engine:
    """The heapq event loop. One instance per run."""

    def __init__(self, m: SimMaster, fleet: SyntheticFleet, vc,
                 inv: InvariantChecker, cfg: SimConfig):
        self.m = m
        self.fleet = fleet
        self.vc = vc
        self.inv = inv
        self.heap: List[tuple] = []
        self._seq = 0
        self._dispatch_at: Optional[float] = None
        self.pick_times_us: List[float] = []
        self.ttfts_ms: List[float] = []
        self.queue_samples: List[int] = []
        self.within_slo = 0
        self._slo_targets = tsdb_mod.slo_targets()
        # overload front door (SimConfig doc): claim-wave accounting
        # for the anti-starvation bound + honest-refusal bookkeeping
        self._overload = cfg.overload
        self._claim_interval = cfg.claim_interval_s
        self.rejected = 0
        self.rejected_by_reason: Dict[str, int] = {}
        self.overload_level_max = 0
        self.claim_waves = 0
        self.waves_frozen = 0
        self.starvation_max_waves = 0
        self._submit_wave: Dict[int, int] = {}
        # active-node snapshot cache: the real dispatcher re-queries
        # per wave, but its rows only change when something writes the
        # nodes table — so the engine intercepts update_node and
        # re-queries per CHANGE instead of per wave (at 1000 nodes and
        # 100k requests the per-wave query alone would dominate wall
        # time without altering a single scheduling outcome)
        self._nodes_cache: Optional[list] = None
        orig_update = m.store.update_node

        def _update_node(node_id, **fields):
            self._nodes_cache = None
            return orig_update(node_id, **fields)

        m.store.update_node = _update_node

    def _active_nodes(self) -> list:
        if self._nodes_cache is None:
            self._nodes_cache = self.m.store.list_nodes(active_only=True)
        return self._nodes_cache

    def _push(self, t: float, kind: int, payload=None):
        self._seq += 1
        heapq.heappush(self.heap, (t, kind, self._seq, payload))

    def _sched_dispatch(self, t: float):
        if self._dispatch_at is None or t < self._dispatch_at:
            self._dispatch_at = t
            self._push(t, _K_DISPATCH)

    def run(self, arrivals: List[dict], base: float, drain_s: float):
        m, vc = self.m, self.vc
        last_at = 0.0
        for i, a in enumerate(arrivals):
            self._push(base + a["at"], _K_ARRIVE, (i, a))
            last_at = max(last_at, a["at"])
        end_guard = base + last_at + drain_s
        self._push(base + m.health_interval, _K_HEALTH)
        self._push(base + m.tsdb.step_s, _K_TELEM)
        while self.heap:
            t, kind, _, payload = heapq.heappop(self.heap)
            if t > end_guard:
                break
            if t > vc.now():
                vc.advance(t - vc.now())
            if kind == _K_ARRIVE:
                self._on_arrive(payload[0], payload[1])
            elif kind == _K_COMPLETE:
                self._on_complete(*payload)
            elif kind == _K_RELEASE:
                self._on_release(*payload)
            elif kind == _K_HEALTH:
                self._on_health()
                if self._work_remaining():
                    self._push(t + m.health_interval, _K_HEALTH)
            elif kind == _K_TELEM:
                m._telemetry_sweep()
                if self._work_remaining():
                    self._push(t + m.tsdb.step_s, _K_TELEM)
            elif kind == _K_DISPATCH:
                if self._dispatch_at is not None and t >= self._dispatch_at:
                    self._dispatch_at = None
                    self._dispatch_pass()

    def _work_remaining(self) -> bool:
        return any(k in (_K_ARRIVE, _K_COMPLETE, _K_DISPATCH)
                   for _, k, _, _ in self.heap) or bool(self._dispatch_at)

    # ---- event handlers ----------------------------------------------

    def _on_arrive(self, i: int, a: dict):
        prompt = f"req{i:06d}:" + "x" * max(0, a["prompt_chars"] - 10)
        body = {"model_name": a["model"], "prompt": prompt,
                "max_new_tokens": a["max_new_tokens"],
                "sampling": {"do_sample": False}}
        if a.get("slo_class"):
            body["slo_class"] = a["slo_class"]
        if a.get("tenant"):
            body["tenant"] = a["tenant"]
        resp = self.m.api_submit(body)
        if isinstance(resp, tuple):
            if resp[0] == 429:
                # an honest admission refusal is a legitimate outcome,
                # not a violation — UNLESS it forgot the Retry-After
                # contract (the client could never back off honestly)
                headers = resp[2] if len(resp) > 2 else {}
                if not (headers or {}).get("Retry-After"):
                    self.inv._flag("reject-without-retry-after",
                                   arrival=i, resp=repr(resp))
                self.rejected += 1
                reason = (resp[1] or {}).get("reason", "?")
                self.rejected_by_reason[reason] = \
                    self.rejected_by_reason.get(reason, 0) + 1
                return
            self.inv._flag("submit-rejected", arrival=i, resp=repr(resp))
            return
        if resp.get("status") != "success":
            self.inv._flag("submit-rejected", arrival=i, resp=repr(resp))
            return
        self._submit_wave[resp["request_id"]] = self.claim_waves
        # claim-interval mode paces the waves: an arrival must not pull
        # a wave forward (that would drain the queue per-arrival and no
        # backlog could ever form), it only ensures the NEXT wave is
        # scheduled. Legacy mode keeps the immediate dispatch.
        self._sched_dispatch(self.vc.now() + self._claim_interval)

    def _dispatch_pass(self):
        m = self.m
        m.store.flush()
        parked = False
        while True:
            mp = m._claim_max_priority()
            reqs = m.store.claim_next_pending_many(
                m.dispatch_batch, max_priority=mp)
            if not reqs:
                break
            # wave accounting: starvation_max_waves is the most claim
            # waves any admitted request sat pending before one took it
            # — the bound the aging claim order must keep
            self.claim_waves += 1
            if mp is not None:
                self.waves_frozen += 1
            for req in reqs:
                waited = self.claim_waves - self._submit_wave.pop(
                    req["id"], self.claim_waves)
                if waited > self.starvation_max_waves:
                    self.starvation_max_waves = waited
                parked |= self._dispatch_one(req, self._active_nodes())
            m.store.flush()
            if self._claim_interval > 0:
                break   # one wave per dispatch event (SimConfig doc)
        if self._claim_interval > 0 and \
                m.store.counts().get("pending", 0):
            self._sched_dispatch(self.vc.now() + self._claim_interval)
        if parked:
            # a park requeued with a future due time; failure paths
            # schedule their own follow-up, parks are detected here
            due = m.store.next_pending_due()
            if due is not None:
                self._sched_dispatch(max(due, self.vc.now()))

    def _dispatch_one(self, req, nodes) -> bool:
        """Dispatch one claimed request; True when the master parked it
        (nothing schedulable) and a future dispatch wave is needed."""
        m = self.m
        now = self.vc.now()
        plan = None
        cap = m._sched_sample
        if m._disagg and (not cap or len(nodes) <= cap):
            # the disagg planner's census scans the full snapshot per
            # request; above the sampling cap that scan is exactly the
            # O(fleet) cost the sampled pick exists to avoid, so
            # large-fleet sims take the plain path (equivalent to a
            # mixed fleet, where the planner bails on the empty
            # strict-prefill pool anyway)
            plan = m._plan_disagg(req, nodes)
        if plan is not None:
            self._dispatch_disagg(req, plan, nodes)
            return False
        t0 = _time.perf_counter()
        node = m._reserve_node_for(req, nodes=nodes)
        self.pick_times_us.append((_time.perf_counter() - t0) * 1e6)
        if node is None:
            return True   # the master parked or terminally failed it
        self.inv.post_pick(req, node, nodes)
        sn = self.fleet.by_port[node["port"]]
        if sn.is_down(now):
            # the dispatch RPC would fail at connect
            self._fail_dispatch(req, node, nodes)
            return False
        m._note_dispatch(req, node)
        m._processing[req["id"]] = node
        end, cost = sn.assign(now, len(req["prompt"] or ""),
                              req.get("max_new_tokens") or 16)
        self._push(end, _K_COMPLETE, (req, node, None, cost, now))
        return False

    def _dispatch_disagg(self, req, plan, nodes):
        m = self.m
        now = self.vc.now()
        pnode, dnode = plan
        self.inv.post_pick(req, pnode, nodes)
        self.inv.post_pick(req, dnode, nodes)
        psn = self.fleet.by_port[pnode["port"]]
        dsn = self.fleet.by_port[dnode["port"]]
        if psn.is_down(now) or dsn.is_down(now):
            # phase-1 failure degrades to plain dispatch in the real
            # flow; model the cheap equivalent — release both slots and
            # requeue through the failure tail
            with m._inflight_lock:
                for n in (pnode, dnode):
                    m._inflight[n["id"]] = max(
                        0, m._inflight.get(n["id"], 1) - 1)
            self._fail_dispatch(req, pnode if psn.is_down(now) else dnode,
                                None, release=False)
            return
        ptoks = self.fleet.model.tokens(len(req["prompt"] or ""))
        p_end, _ = psn.assign(now, len(req["prompt"] or ""), 1,
                              prefill_only=True)
        self._push(p_end, _K_RELEASE, (pnode, psn))
        m._note_dispatch(req, dnode)
        m._processing[req["id"]] = dnode
        end, cost = dsn.assign(p_end, len(req["prompt"] or ""),
                               req.get("max_new_tokens") or 16,
                               cached_tokens=ptoks)
        cost["queue_ms"] = round(cost["queue_ms"] + (p_end - now) * 1e3, 3)
        cost["kv_transfer_bytes"] = ptoks * 4096
        self._push(end, _K_COMPLETE, (req, dnode, None, cost, now))

    def _fail_dispatch(self, req, node, nodes, release=True):
        m = self.m
        err = ConnectionError(
            f"sim: connection to {node.get('name')} refused")
        m._fail_sub(req, node, err, nodes=nodes)
        if release:
            with m._inflight_lock:
                m._inflight[node["id"]] = max(
                    0, m._inflight.get(node["id"], 1) - 1)
        m.store.flush()
        due = m.store.next_pending_due()
        if due is not None:
            self._sched_dispatch(max(due, self.vc.now()))

    def _on_release(self, node_row, sn):
        sn.release(self.vc.now())
        with self.m._inflight_lock:
            self.m._inflight[node_row["id"]] = max(
                0, self.m._inflight.get(node_row["id"], 1) - 1)

    def _on_complete(self, req, node, _unused, cost, dispatched_at):
        m = self.m
        now = self.vc.now()
        sn = self.fleet.by_port[node["port"]]
        sn.release(now)
        with m._inflight_lock:
            m._inflight[node["id"]] = max(
                0, m._inflight.get(node["id"], 1) - 1)
        m._processing.pop(req["id"], None)
        if sn.went_down_during(dispatched_at, now):
            # the node died under the generation: the RPC the real
            # master had in flight dies with the socket
            m._fail_sub(req, node,
                        ConnectionError(f"sim: {sn.spec.name} died "
                                        "mid-generation"))
            m.store.flush()
            due = m.store.next_pending_due()
            if due is not None:
                self._sched_dispatch(max(due, now))
            return
        exec_s = (cost["prefill_ms"] + cost["decode_ms"]) / 1e3
        tokens = cost.get("decode_tokens") or 1
        ttft_ms = cost["queue_ms"] + cost["prefill_ms"]
        data = {
            "result": f"sim:{tokens}tok",
            "execution_time": round(exec_s, 6),
            "tokens_per_s": round(tokens / exec_s, 3) if exec_s else 0.0,
            "ttft_ms": round(ttft_ms, 3),
            "cost": cost,
        }
        m._complete_request(req, node, data)
        self.ttfts_ms.append(ttft_ms)
        if tsdb_mod.cost_within_slo(cost, self._slo_targets):
            self.within_slo += 1

    def _on_health(self):
        m = self.m
        m._health_sweep()
        # the health loop's queue-depth gauge rides the same cadence;
        # its samples double as the report's queue-depth series (the
        # calibration gate compares it against the real master's)
        m.store.flush()
        pending = m.store.counts().get("pending", 0)
        m.metrics.gauge("queue_pending", pending)
        self.queue_samples.append(pending)
        if self._overload:
            # the ladder walks on the health cadence (the real
            # _overload_loop is a thread; the sim drives the same
            # sweep at deterministic instants)
            m._overload_sweep()
            if m._overload_level > self.overload_level_max:
                self.overload_level_max = m._overload_level
            if pending:
                # a rung change can unfreeze claims (e.g. 4 -> 3
                # reopens non-latency work): make sure a wave runs
                self._sched_dispatch(self.vc.now())
