"""Sim-vs-real calibration: replay a recorded run, measure divergence.

The calibration gate (``bench.py --scenario sim_calibrate``, wired
into ``scripts/check.sh``) runs a small REAL cluster — master plus
in-process workers — records its arrival trace (the
``request-submitted`` journal rows) and its cost-ledger rows, fits a
:class:`~tools.dlisim.fleet.WorkerModel` from them, replays the exact
trace through :func:`~tools.dlisim.sim.run_sim`, and compares the
three headline signals:

- **goodput** (SLO-passing completions per second),
- **TTFT p50** (queue + prefill, the cost ledger's definition),
- **mean queue depth** (the ``queue_pending`` gauge's series).

Tolerances are deliberately generous (see ``DEFAULT_TOLERANCES`` and
docs/simulator.md "Calibration tolerance"): the gate exists to catch
*rot* — a scheduler change that halves real goodput while the sim
still predicts the old number, a service-model regression that makes
the sim useless for capacity questions — not to pretend a
discrete-event model reproduces a real machine to the percent. A
divergence report lands next to the bench artifact either way, so CI
keeps a history of how faithful the sim is.
"""

from __future__ import annotations

from typing import Dict, Optional

#: relative-error ceilings per metric; queue depth also passes within
#: ``queue_depth_abs`` requests absolute (both sides are near zero in
#: a healthy small run, where relative error is meaningless)
DEFAULT_TOLERANCES = {
    "goodput_req_per_s": 0.50,
    "ttft_ms_p50": 0.75,
    "queue_depth_mean": 1.00,
    "queue_depth_abs": 3.0,
}


def _rel_err(real: float, sim: float) -> Optional[float]:
    if real is None or sim is None:
        return None
    denom = max(abs(real), 1e-9)
    return abs(sim - real) / denom


def divergence_report(real: Dict[str, float], sim: Dict[str, float],
                      tolerances: Optional[Dict[str, float]] = None
                      ) -> dict:
    """Compare real-run metrics against the sim replay's.

    ``real`` and ``sim`` each carry ``goodput_req_per_s``,
    ``ttft_ms_p50`` and ``queue_depth_mean`` (None = unmeasured; an
    unmeasured metric is skipped, not failed — a smoke run too short
    to produce a queue-depth series must not fail the gate on it).
    Returns ``{"ok": bool, "metrics": {name: {...}}}``."""
    tol = dict(DEFAULT_TOLERANCES)
    tol.update(tolerances or {})
    out: Dict[str, dict] = {}
    ok = True
    for key in ("goodput_req_per_s", "ttft_ms_p50", "queue_depth_mean"):
        r, s = real.get(key), sim.get(key)
        entry = {"real": r, "sim": s, "tolerance": tol[key]}
        err = _rel_err(r, s)
        entry["rel_err"] = round(err, 3) if err is not None else None
        if err is None:
            entry["ok"] = None   # unmeasured on a side: skip
        else:
            within = err <= tol[key]
            if key == "queue_depth_mean" and not within:
                # near-empty queues: a 0.2-vs-0.8 depth is a 3x
                # relative error and an operationally identical run
                within = abs(s - r) <= tol["queue_depth_abs"]
            entry["ok"] = within
            ok = ok and within
        out[key] = entry
    return {"ok": ok, "metrics": out}
