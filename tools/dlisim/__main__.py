"""CLI: ``python -m tools.dlisim [options]`` — run one simulation and
print its JSON report (one line, bench-artifact style).

Examples::

    # 1000 nodes, 100k requests, diurnal arrivals
    python -m tools.dlisim --nodes 1000 --requests 100000

    # adversarial arrivals with three nodes failing mid-run
    python -m tools.dlisim --nodes 200 --requests 20000 \\
        --arrival adversarial --fail 0:100:200 --fail 1:100:300

    # replay a captured workload (debug bundle workload_capture.json
    # or /api/events?type=request-submitted output)
    python -m tools.dlisim --trace workload_capture.json
"""

from __future__ import annotations

import argparse
import json
import sys

from .fit import arrival_trace_from_events
from .sim import SimConfig, run_sim


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tools.dlisim",
        description="deterministic cluster simulator over the real "
                    "control plane (docs/simulator.md)")
    p.add_argument("--nodes", type=int, default=100)
    p.add_argument("--requests", type=int, default=10_000)
    p.add_argument("--duration", type=float, default=600.0,
                   help="virtual seconds of arrivals (default 600)")
    p.add_argument("--arrival", default="diurnal",
                   choices=["uniform", "diurnal", "bursty", "adversarial"])
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--slots", type=int, default=8,
                   help="batcher slots per synthetic node")
    p.add_argument("--prefill-nodes", type=int, default=0,
                   help="strict prefill-role pool size (enables the "
                        "disagg planner path)")
    p.add_argument("--fail", action="append", default=[],
                   metavar="IDX:FROM:UNTIL",
                   help="take node IDX down over [FROM, UNTIL) virtual "
                        "seconds; repeatable")
    p.add_argument("--trace", default=None,
                   help="JSON file of request-submitted journal rows "
                        "(or {'events': [...]}) to replay instead of "
                        "synthetic arrivals")
    p.add_argument("--overload", action="store_true",
                   help="overload front door: mixed SLO classes/"
                        "tenants on arrivals, bounded pending queue, "
                        "queue-only degradation ladder swept on the "
                        "health cadence (docs/robustness.md)")
    p.add_argument("--admit-max-pending", type=int, default=512,
                   help="pending-queue bound under --overload "
                        "(default 512)")
    p.add_argument("--overload-queue", type=float, default=64.0,
                   help="ladder queue threshold under --overload")
    p.add_argument("--claim-interval", type=float, default=1.0,
                   help="seconds between claim waves under --overload "
                        "(one wave per dispatch event)")
    p.add_argument("--planner-sweep", action="store_true",
                   help="planner validation sweep: simulate each "
                        "candidate prefill-quarantine size on a "
                        "heterogeneous fleet and assert the "
                        "auto-parallelism planner's top choice lands "
                        "within DLI_PLANNER_TOLERANCE of the "
                        "sim-measured best (docs/architecture.md)")
    p.add_argument("--out", default=None,
                   help="also write the report to this path")
    args = p.parse_args(argv)

    if args.planner_sweep:
        from .planner import main as planner_main
        return planner_main(args)

    fails = []
    for spec in args.fail:
        idx, t0, t1 = spec.split(":")
        fails.append((int(idx), float(t0), float(t1)))
    arrivals = None
    if args.trace:
        with open(args.trace) as f:
            raw = json.load(f)
        rows = raw.get("events", raw) if isinstance(raw, dict) else raw
        arrivals = arrival_trace_from_events(rows)
        if not arrivals:
            print(f"no request-submitted rows in {args.trace}",
                  file=sys.stderr)
            return 2
    kw = {}
    if args.overload:
        kw = dict(slo_mix=True, overload=True,
                  admit_max_pending=args.admit_max_pending,
                  overload_queue=args.overload_queue,
                  overload_hold_s=30.0,
                  claim_interval_s=args.claim_interval)
    cfg = SimConfig(nodes=args.nodes, requests=args.requests,
                    duration_s=args.duration, arrival=args.arrival,
                    seed=args.seed, slots_per_node=args.slots,
                    prefill_nodes=args.prefill_nodes,
                    fail_nodes=fails, arrivals=arrivals, **kw)
    report = run_sim(cfg).to_json()
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    if report["violations"] or report["starved"]:
        print(f"sim FAILED: {len(report['violations'])} invariant "
              f"violation(s), {report['starved']} starved request(s)",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
