"""Synthetic worker fleet: the data plane the simulator replaces.

A :class:`SimNode` stands in for one ``runtime/worker.py`` process. It
does no inference — it answers the master's ``/health`` and
``/metrics`` RPCs with the same body shapes a real worker advertises
(``_note_runtime``'s contract) and, when the simulator dispatches a
request to it, computes a *service time* from its fitted
:class:`WorkerModel` plus a deterministic slot-queueing discipline.

The queueing model mirrors the real batcher's admission shape at the
fidelity the control plane can observe: ``slots`` concurrent
sequences, FIFO admission into the earliest-free slot, queue time =
time spent waiting for a slot. Everything the master's queue-aware
scheduler reads (queue depth, free blocks, arena occupancy, role,
prefix advertisements) is synthesized here from that state.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class WorkerModel:
    """Fitted per-worker service-time model (see ``fit.py``).

    Times are the per-phase costs the cost ledger records for real
    requests, so a fitted model's replay produces cost rows directly
    comparable to the originals:

    - ``prefill_ms_per_token``: uncached prompt-token cost;
    - ``decode_ms_per_token``: per generated token cost;
    - ``overhead_ms``: fixed per-request overhead (RPC + admission);
    - ``chars_per_token``: the prompt-chars -> tokens estimate, kept
      identical to the master's ``_DISAGG_CHARS_PER_TOKEN`` so both
      sides of a disagg decision price the same token count.
    """

    prefill_ms_per_token: float = 0.35
    decode_ms_per_token: float = 18.0
    overhead_ms: float = 8.0
    chars_per_token: int = 4
    #: provenance: where each parameter came from ("prior",
    #: "cost-ledger", "bench:<file>") — carried into reports so a
    #: calibration failure names its inputs
    source: Dict[str, str] = field(default_factory=dict)

    def tokens(self, prompt_chars: int) -> int:
        return max(1, int(prompt_chars) // max(1, self.chars_per_token))

    def service(self, prompt_chars: int, max_new_tokens: int,
                cached_tokens: int = 0) -> Tuple[float, float, int]:
        """(prefill_ms, decode_ms, decode_tokens) for one request."""
        ptoks = self.tokens(prompt_chars)
        uncached = max(0, ptoks - int(cached_tokens))
        prefill_ms = self.overhead_ms + uncached * self.prefill_ms_per_token
        dtoks = max(1, int(max_new_tokens))
        decode_ms = dtoks * self.decode_ms_per_token
        return prefill_ms, decode_ms, dtoks


@dataclass
class NodeSpec:
    """Static shape of one synthetic node."""

    name: str
    port: int
    role: str = "mixed"            # mixed | prefill | decode
    slots: int = 8
    blocks_total: int = 256
    #: host-arena occupancy advertised on /health; non-None means the
    #: node can export KV (the master's _node_can_export gate)
    arena_occ: Optional[float] = 0.1
    #: speed multiplier (>1 = slower node); heterogeneous fleets
    speed: float = 1.0


class SimNode:
    """One synthetic worker: slot queue + health/metrics synthesis."""

    def __init__(self, spec: NodeSpec, model: WorkerModel,
                 models: Tuple[str, ...] = ("tiny-llama",)):
        self.spec = spec
        self.model = model
        self.models = models
        self.role = spec.role
        # earliest virtual time each batcher slot frees up
        self._slot_free: List[float] = [0.0] * max(1, spec.slots)
        self.inflight = 0
        self.completed = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        # fault injection: [down_from, down_until) virtual-time windows
        self.down_windows: List[Tuple[float, float]] = []
        self.draining = False

    # ---- fault injection ---------------------------------------------

    def fail_between(self, start: float, end: float) -> None:
        self.down_windows.append((float(start), float(end)))

    def is_down(self, now: float) -> bool:
        return any(s <= now < e for s, e in self.down_windows)

    def went_down_during(self, start: float, end: float) -> bool:
        """Did a fault window open inside [start, end)? (A generation
        in flight across the window's opening edge is lost.)"""
        return any(start < e and s < end for s, e in self.down_windows)

    # ---- service -----------------------------------------------------

    def assign(self, now: float, prompt_chars: int, max_new_tokens: int,
               cached_tokens: int = 0,
               prefill_only: bool = False) -> Tuple[float, dict]:
        """Admit one request at virtual time ``now``: occupy the
        earliest-free slot, return ``(finish_time, cost_record)``.

        The cost record is the same shape the real batcher's
        ``_cost_record`` persists (the keys the SLO evaluator and
        ``fit.py`` read), so simulated ledger rows round-trip through
        the exact fitting path real rows do."""
        prefill_ms, decode_ms, dtoks = self.model.service(
            prompt_chars, max_new_tokens, cached_tokens)
        if prefill_only:
            decode_ms, dtoks = 0.0, 0
        slot = min(range(len(self._slot_free)),
                   key=lambda i: self._slot_free[i])
        start = max(now, self._slot_free[slot])
        service_s = (prefill_ms + decode_ms) * self.spec.speed / 1e3
        end = start + service_s
        self._slot_free[slot] = end
        self.inflight += 1
        queue_ms = (start - now) * 1e3
        if cached_tokens > 0:
            self.prefix_hits += 1
        else:
            self.prefix_misses += 1
        ptoks = self.model.tokens(prompt_chars)
        cost = {
            "queue_ms": round(queue_ms, 3),
            "prefill_ms": round(prefill_ms * self.spec.speed, 3),
            "decode_ms": round(decode_ms * self.spec.speed, 3),
            "prefill_cached_tokens": int(cached_tokens),
            "prefill_uncached_tokens": max(0, ptoks - int(cached_tokens)),
            "decode_tokens": dtoks,
            "weight_passes": 1 + dtoks,
            "kv_blocks_peak": max(1, (ptoks + dtoks) // 8),
            "preemptions": 0,
        }
        if dtoks:
            # the real batcher's cost record carries the request's p95
            # inter-token gap (batcher.py _cost_record); sim decode is
            # a uniform token cadence, so p95 == the mean gap — without
            # this the SLO evaluator judges TTFT only and a slow node's
            # decode tail is invisible to the goodput accounting
            cost["itl_p95_ms"] = round(
                decode_ms * self.spec.speed / dtoks, 3)
        return end, cost

    def release(self, now: float) -> None:
        self.inflight = max(0, self.inflight - 1)
        self.completed += 1

    def queued(self, now: float) -> int:
        """Requests admitted but not yet holding a slot at ``now``."""
        return max(0, self.inflight - len(self._slot_free))

    def blocks_free(self, now: float) -> int:
        busy = sum(1 for t in self._slot_free if t > now)
        per_seq = max(1, self.spec.blocks_total // len(self._slot_free))
        return max(0, self.spec.blocks_total - busy * per_seq)

    # ---- what the master sees ----------------------------------------

    def health_body(self, now: float) -> dict:
        sched = {
            "queued": self.queued(now),
            "blocks_free": self.blocks_free(now),
            "pool": {"prefix_hits": self.prefix_hits,
                     "prefix_misses": self.prefix_misses},
        }
        if self.spec.arena_occ is not None:
            sched["kvtier"] = {"occupancy": self.spec.arena_occ}
        return {
            "status": "draining" if self.draining else "ok",
            "role": self.role,
            "draining": self.draining,
            "arena_occupancy": self.spec.arena_occ,
            "loaded_models": [
                {"name": m, "scheduler": dict(sched)} for m in self.models],
        }

    def metrics_text(self, now: float) -> str:
        """Minimal Prometheus exposition — just the series the master's
        telemetry sweep derives ratios from, plus a depth gauge."""
        return (
            f"dli_radix_prefix_hits_total {self.prefix_hits}\n"
            f"dli_radix_prefix_misses_total {self.prefix_misses}\n"
            f"dli_batcher_queue_depth {self.queued(now)}\n"
            f"dli_requests_completed_total {self.completed}\n")


class SyntheticFleet:
    """The full node set, addressable the way the master addresses
    real workers: by the (host, port) on the registered node row."""

    BASE_PORT = 20000

    def __init__(self, specs: List[NodeSpec], model: WorkerModel):
        self.model = model
        self.nodes: List[SimNode] = [SimNode(s, model) for s in specs]
        self.by_port: Dict[int, SimNode] = {
            n.spec.port: n for n in self.nodes}

    @classmethod
    def uniform(cls, n: int, model: WorkerModel, *, slots: int = 8,
                prefill_nodes: int = 0,
                arena_occ: Optional[float] = 0.1) -> "SyntheticFleet":
        """``n`` homogeneous nodes; the first ``prefill_nodes`` declare
        the strict prefill role (the pool ``_plan_disagg`` requires)."""
        specs = []
        for i in range(n):
            role = "prefill" if i < prefill_nodes else "mixed"
            specs.append(NodeSpec(name=f"sim{i:04d}",
                                  port=cls.BASE_PORT + i, role=role,
                                  slots=slots, arena_occ=arena_occ))
        return cls(specs, model)

    def __len__(self) -> int:
        return len(self.nodes)
