"""Fitting the synthetic fleet from the real fleet's telemetry.

Three input families, in decreasing order of fidelity (each documented
with its provenance in the fitted model's ``source`` map):

1. **Cost-ledger rows** — the per-request phase records the master
   persists on every completed request row (``runtime/batcher.py``
   ``_cost_record``). Per-token prefill/decode rates fall straight out
   as robust medians.
2. **Bench artifacts** — ``BENCH_*.json`` / ``MULTICHIP_*.json``
   emitted by ``bench.py``: decode tok/s and TTFT numbers.
3. **Priors** — CPU tiny-llama-scale defaults, used wherever no
   recorded telemetry covers a parameter.

The arrival side comes from the flight recorder: every ``api_submit``
journals a ``request-submitted`` event whose ``ts`` is the arrival
time and whose data carries the workload shape, so any journal read
(or debug bundle's ``workload_capture.json``) IS a replayable trace.
"""

from __future__ import annotations

import json
import math
import random
from typing import Dict, Iterable, List, Optional, Tuple

from .fleet import WorkerModel

#: CPU tiny-llama-scale priors; every fitted model starts here and
#: overrides per parameter as telemetry covers it
DEFAULT_MODEL = WorkerModel(source={"prefill_ms_per_token": "prior",
                                    "decode_ms_per_token": "prior",
                                    "overhead_ms": "prior"})


def _median(xs: List[float]) -> Optional[float]:
    if not xs:
        return None
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def fit_worker_model(cost_rows: Iterable[dict],
                     base: Optional[WorkerModel] = None) -> WorkerModel:
    """Fit per-token service rates from cost-ledger records.

    Accepts the ``cost`` dicts off completed request rows (JSON strings
    tolerated). Median, not mean: a single preempted or cold-compile
    outlier must not skew the whole fleet's service model."""
    base = base or DEFAULT_MODEL
    prefill_rates: List[float] = []
    decode_rates: List[float] = []
    overheads: List[float] = []
    n = 0
    for cost in cost_rows:
        if isinstance(cost, str):
            try:
                cost = json.loads(cost)
            except ValueError:
                continue
        if not isinstance(cost, dict):
            continue
        n += 1
        pf = cost.get("prefill_ms")
        unc = cost.get("prefill_uncached_tokens")
        if isinstance(pf, (int, float)) and isinstance(unc, int) and unc > 0:
            # the same mostly-uncached filter the master's prefill EWMA
            # applies: cache-hit prefills say nothing about compute cost
            cached = cost.get("prefill_cached_tokens") or 0
            if unc >= cached:
                prefill_rates.append(float(pf) / unc)
        dm = cost.get("decode_ms")
        dt = cost.get("decode_tokens")
        if isinstance(dm, (int, float)) and isinstance(dt, int) and dt > 1:
            # first-token cost rides prefill; per-token rate from the
            # remaining gap keeps the two phases separable
            decode_rates.append(float(dm) / dt)
        if isinstance(dm, (int, float)) and isinstance(dt, int) and dt == 1:
            overheads.append(float(dm))
    source = dict(base.source)
    pr = _median(prefill_rates)
    dr = _median(decode_rates)
    ov = _median(overheads)
    if pr is not None:
        source["prefill_ms_per_token"] = f"cost-ledger({len(prefill_rates)})"
    if dr is not None:
        source["decode_ms_per_token"] = f"cost-ledger({len(decode_rates)})"
    if ov is not None:
        source["overhead_ms"] = f"cost-ledger({len(overheads)})"
    return WorkerModel(
        prefill_ms_per_token=pr if pr is not None
        else base.prefill_ms_per_token,
        decode_ms_per_token=dr if dr is not None
        else base.decode_ms_per_token,
        overhead_ms=ov if ov is not None else base.overhead_ms,
        chars_per_token=base.chars_per_token,
        source=source)


def fit_from_artifacts(paths: Iterable[str],
                       base: Optional[WorkerModel] = None) -> WorkerModel:
    """Fold bench JSON artifacts (``BENCH_*.json``, ``MULTICHIP_*.json``,
    ``/tmp/dli_bench_*.json``) into the model: any ``tok_s`` /
    ``tokens_per_s`` number bounds the decode rate, any ``ttft_ms``
    the fixed overhead. Liberal by design — artifact schemas differ
    per scenario and a fitter that rejects unknown shapes would rot
    with every new bench."""
    base = base or DEFAULT_MODEL
    tok_s: List[float] = []
    ttft_ms: List[float] = []

    def walk(obj):
        if isinstance(obj, dict):
            for k, v in obj.items():
                lk = str(k).lower()
                if isinstance(v, (int, float)) and v > 0:
                    if "tok_s" in lk or "tokens_per_s" in lk:
                        tok_s.append(float(v))
                    elif "ttft" in lk and "ms" in lk:
                        ttft_ms.append(float(v))
                else:
                    walk(v)
        elif isinstance(obj, list):
            for v in obj:
                walk(v)

    used = []
    for p in paths:
        try:
            with open(p) as f:
                walk(json.load(f))
            used.append(p)
        except (OSError, ValueError):
            continue
    source = dict(base.source)
    dr = _median(tok_s)
    ov = _median(ttft_ms)
    decode = base.decode_ms_per_token
    overhead = base.overhead_ms
    if dr:
        decode = 1e3 / dr
        source["decode_ms_per_token"] = f"bench:{','.join(used)}"
    if ov:
        overhead = ov
        source["overhead_ms"] = f"bench:{','.join(used)}"
    return WorkerModel(prefill_ms_per_token=base.prefill_ms_per_token,
                       decode_ms_per_token=decode, overhead_ms=overhead,
                       chars_per_token=base.chars_per_token, source=source)


# ---- arrival traces --------------------------------------------------

def arrival_trace_from_events(rows: Iterable[dict]) -> List[dict]:
    """Journal rows (``type=request-submitted``, from
    ``Store.query_events`` or a debug bundle's ``workload_capture.json``)
    -> replayable arrival trace: relative arrival offset + workload
    shape per request, submission order preserved."""
    out: List[dict] = []
    t0: Optional[float] = None
    for r in rows:
        if r.get("type") not in (None, "request-submitted"):
            continue
        ts = r.get("ts")
        if ts is None:
            continue
        data = r.get("data") or {}
        if isinstance(data, str):
            try:
                data = json.loads(data)
            except ValueError:
                data = {}
        if t0 is None:
            t0 = float(ts)
        out.append({
            "at": float(ts) - t0,
            "model": data.get("model") or "tiny-llama",
            "prompt_chars": int(data.get("prompt_chars") or 16),
            "max_new_tokens": int(data.get("max_new_tokens")
                                  or data.get("max_length") or 16),
        })
    return out


def synthetic_arrivals(kind: str, n: int, duration_s: float,
                       seed: int = 0, model: str = "tiny-llama",
                       prompt_chars: Tuple[int, int] = (32, 512),
                       max_new: Tuple[int, ...] = (8, 16, 32, 64),
                       ) -> List[dict]:
    """Deterministic synthetic arrival trace of exactly ``n`` requests
    over ``duration_s`` virtual seconds.

    - ``uniform``: evenly spaced with jitter;
    - ``diurnal``: sinusoidal rate (one full day-shaped cycle over the
      window) — arrival times are the inverse-CDF of the rate curve,
      so the count is exact and the shape seed-independent;
    - ``bursty``: on/off square wave — 80% of traffic in 20% of time;
    - ``adversarial``: bursty arrivals plus heavy-tailed prompts,
      token-budget spikes and same-instant ties (the scheduler's
      worst-case inputs).
    """
    rng = random.Random(seed)
    times: List[float] = []
    if kind == "uniform":
        for i in range(n):
            times.append(duration_s * (i + rng.random()) / n)
    elif kind == "diurnal":
        # rate(t) = 1 + 0.8*sin(2*pi*t/T); CDF inverted on a grid
        grid = 2048
        cdf = [0.0]
        for g in range(grid):
            t = duration_s * (g + 0.5) / grid
            rate = 1.0 + 0.8 * math.sin(2 * math.pi * t / duration_s)
            cdf.append(cdf[-1] + rate)
        total = cdf[-1]
        gi = 0
        for i in range(n):
            target = total * (i + rng.random()) / n
            while gi < grid and cdf[gi + 1] < target:
                gi += 1
            # linear interp inside the grid cell
            lo, hi = cdf[gi], cdf[min(grid, gi + 1)]
            frac = 0.0 if hi <= lo else (target - lo) / (hi - lo)
            times.append(duration_s * (gi + frac) / grid)
    elif kind in ("bursty", "adversarial"):
        bursts = 8
        for i in range(n):
            b = rng.randrange(bursts)
            window = duration_s / bursts
            if rng.random() < 0.8:
                t = b * window + rng.random() * 0.2 * window
            else:
                t = b * window + rng.random() * window
            if kind == "adversarial" and rng.random() < 0.05:
                t = b * window   # exact tie: same-instant spike
            times.append(t)
        times.sort()
    else:
        raise ValueError(f"unknown arrival kind {kind!r}")
    out = []
    lo, hi = prompt_chars
    for t in sorted(times):
        if kind == "adversarial" and rng.random() < 0.03:
            pc = hi * 8   # heavy tail: pathological prompt
            mn = max_new[-1] * 4
        else:
            pc = rng.randint(lo, hi)
            mn = rng.choice(max_new)
        out.append({"at": round(t, 6), "model": model,
                    "prompt_chars": pc, "max_new_tokens": mn})
    return out
