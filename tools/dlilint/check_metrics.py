"""Metrics checker: every referenced ``dli_*`` name is registered.

The PR 5 rule: a scrape (or the TSDB catalog behind it) must never
confuse "no events yet" with "metric not exported", so counters the
dashboards/benches/docs key off are pre-registered at 0 near the owning
subsystem's init. This checker machine-checks both halves:

- ``metric-unregistered``   — a metric name referenced by the dashboard
  (``TS_METRICS`` + literal ``dli_*`` strings), the bench/TSDB smoke
  gates, or the docs, with no registration call in code.
- ``metric-counter-no-total`` — a counter referenced in exposition form
  without its ``_total`` suffix (the exposition always appends it, so
  the bare name can never exist on the wire).
- ``metric-not-preregistered`` — a counter or gauge the dashboard's
  ``TS_METRICS`` charts that is never pre-registered at 0
  (``inc(name, 0)`` / ``gauge(name, 0)``), so its series would not
  exist until the first event.

Registration sites are found by AST over the whole package:
``.inc(name, ...)`` / ``.gauge(name, ...)`` / ``.observe(name, ...)``
calls with a literal name, an f-string name (holes become wildcards), or
a loop variable over a literal tuple (the pre-registration idiom); plus
direct TSDB series records ``.record(node, name, ...)``.

Reference sites, per source:

- dashboard: entries of the ``TS_METRICS`` JS array (TSDB series names)
  and any ``dli_*`` string (exposition names);
- bench.py / telemetry_smoke.py: ``params={"metric": ...}`` values,
  ``delta("...")`` / ``q("...", ...)`` helper calls, ``.get("...")`` on
  counter/gauge snapshot dicts, and ``dli_*`` literals;
- docs/*.md: ``dli_*`` tokens, with ``{a,b,c}`` brace alternation
  expanded and ``<placeholder>``/``*`` treated as wildcards.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from .core import Ctx, SourceFile, Violation, const_num, const_str, \
    filter_suppressed, joined_str_pattern

RULES = ("metric-unregistered", "metric-counter-no-total",
         "metric-not-preregistered")

_NAME_SAN = re.compile(r"[^a-zA-Z0-9_:]")
_DLI_TOKEN = re.compile(r"dli_[a-zA-Z0-9_{},<>*]+")
_TS_METRICS_RE = re.compile(
    r"TS_METRICS\s*=\s*\[(.*?)\];", re.S)
_TS_ENTRY_RE = re.compile(r"\[\s*'([a-z0-9_]+)'")
# dict-snapshot receivers whose .get()/[] keys are metric names
_SNAPSHOT_RECEIVERS = {"mc", "wc", "counters", "gauges", "cm"}
_GATE_HELPERS = {"delta", "q"}


def _san(name: str) -> str:
    s = _NAME_SAN.sub("_", name)
    if not s or s[0].isdigit():
        s = "_" + s
    return s


class Registrations:
    """Metric names registered in code, exact + patterns."""

    def __init__(self):
        self.counters: Set[str] = set()
        self.gauges: Set[str] = set()
        self.hists: Dict[str, str] = {}     # base -> unit ("" = none)
        self.series: Set[str] = set()       # direct tsdb.record names
        self.counter_patterns: List[str] = []   # regex on base name
        self.gauge_patterns: List[str] = []
        self.hist_patterns: List[str] = []
        self.prereg_zero: Set[str] = set()  # inc(x, 0)/gauge(x, 0) bases
        self.prereg_patterns: List[str] = []

    # ---- queries ------------------------------------------------------

    def _match(self, base: str, exact: Set[str], patterns: List[str]) -> bool:
        if base in exact:
            return True
        return any(re.fullmatch(p, base) for p in patterns)

    def is_counter(self, base: str) -> bool:
        return self._match(base, self.counters, self.counter_patterns)

    def is_gauge(self, base: str) -> bool:
        return self._match(base, self.gauges, self.gauge_patterns)

    def is_hist(self, base: str) -> bool:
        return self._match(base, set(self.hists), self.hist_patterns)

    def preregistered(self, base: str) -> bool:
        return self._match(base, self.prereg_zero, self.prereg_patterns)

    def series_exists(self, name: str) -> bool:
        """A registry/series name: stripped counter base (rates), gauge
        base, a histogram base (bench gates read ``snapshot()``
        percentiles by the same name), or a direct TSDB record."""
        return (name in self.series or self.is_counter(name)
                or self.is_gauge(name) or self.is_hist(name))

    def exposition_exists(self, token: str) -> bool:
        """``token`` (wire form ``dli_...``, possibly with wildcards
        from docs placeholders) resolves against some registered
        family."""
        if not token.startswith("dli_"):
            return False
        body = token[4:]
        if "*" in body or "<" in body:
            rx = re.escape(body).replace(r"\*", "[A-Za-z0-9_]*")
            rx = re.sub(r"\\<[^>]*\\>", "[A-Za-z0-9_]+", rx)
            return self._exposition_rx(rx)
        # counter: dli_<base>_total
        if body.endswith("_total") and self.is_counter(body[:-6]):
            return True
        # gauge: dli_<base>
        if self.is_gauge(body):
            return True
        # histogram families: dli_<base>[_<unit>][_bucket|_sum|_count]
        for suffix in ("", "_bucket", "_sum", "_count"):
            if suffix and body.endswith(suffix):
                body2 = body[: -len(suffix)]
            elif suffix:
                continue
            else:
                body2 = body
            for base, unit in self.hists.items():
                if body2 == (f"{_san(base)}_{unit}" if unit else _san(base)):
                    return True
            if any(re.fullmatch(p + r"(_[a-z]+)?", body2)
                   for p in self.hist_patterns):
                return True
        return False

    def _exposition_rx(self, rx: str) -> bool:
        for base in self.counters:
            if re.fullmatch(rx, _san(base) + "_total"):
                return True
        for base in self.gauges:
            if re.fullmatch(rx, _san(base)):
                return True
        for base, unit in self.hists.items():
            family = f"{_san(base)}_{unit}" if unit else _san(base)
            for sfx in ("", "_bucket", "_sum", "_count"):
                if re.fullmatch(rx, family + sfx):
                    return True
        return False


def _loop_const_names(fn_node: ast.AST) -> Dict[str, List[str]]:
    """loop-var -> constants for the registration idioms
    ``for name in ("a", "b"):`` and
    ``for key, mname in (("k1", "m1"), ("k2", "m2")):`` anywhere under
    ``fn_node``."""
    out: Dict[str, List[str]] = {}
    for node in ast.walk(fn_node):
        if not (isinstance(node, ast.For)
                and isinstance(node.iter, (ast.Tuple, ast.List))):
            continue
        if isinstance(node.target, ast.Name):
            vals = [const_str(e) for e in node.iter.elts]
            if all(v is not None for v in vals):
                out.setdefault(node.target.id, []).extend(vals)
        elif isinstance(node.target, ast.Tuple) and all(
                isinstance(t, ast.Name) for t in node.target.elts):
            width = len(node.target.elts)
            rows = [e for e in node.iter.elts
                    if isinstance(e, (ast.Tuple, ast.List))
                    and len(e.elts) == width]
            if len(rows) == len(node.iter.elts):
                for i, t in enumerate(node.target.elts):
                    vals = [const_str(r.elts[i]) for r in rows]
                    if all(v is not None for v in vals):
                        out.setdefault(t.id, []).extend(vals)
    return out


def collect_registrations(files) -> Registrations:
    reg = Registrations()
    for sf in files:
        if sf.tree is None:
            continue
        loops = _loop_const_names(sf.tree)
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not isinstance(fn, ast.Attribute) or not node.args:
                continue
            meth = fn.attr
            if meth in ("inc", "gauge", "observe"):
                arg = node.args[0]
                names, pattern = [], None
                s = const_str(arg)
                if s is not None:
                    names = [s]
                elif isinstance(arg, ast.JoinedStr):
                    pattern = joined_str_pattern(arg)[0]
                elif isinstance(arg, ast.Name) and arg.id in loops:
                    names = loops[arg.id]
                else:
                    continue
                zero = (len(node.args) > 1
                        and const_num(node.args[1]) == 0.0)
                if meth == "inc":
                    reg.counters.update(names)
                    if pattern:
                        reg.counter_patterns.append(pattern)
                    # a bare inc() (no value) at init is not a
                    # pre-registration; inc(x, 0) is
                    if zero:
                        reg.prereg_zero.update(names)
                        if pattern:
                            reg.prereg_patterns.append(pattern)
                elif meth == "gauge":
                    reg.gauges.update(names)
                    if pattern:
                        reg.gauge_patterns.append(pattern)
                    if zero:
                        reg.prereg_zero.update(names)
                        if pattern:
                            reg.prereg_patterns.append(pattern)
                else:
                    unit = "seconds"
                    for kw in node.keywords:
                        if kw.arg == "unit":
                            unit = const_str(kw.value) or ""
                    for n in names:
                        reg.hists[n] = unit
                    if pattern:
                        reg.hist_patterns.append(pattern)
            elif meth == "record" and len(node.args) >= 3:
                s = const_str(node.args[1])
                if s is not None:
                    reg.series.add(s)
    return reg


# ---- reference extraction ---------------------------------------------

def dashboard_refs(sf: SourceFile) -> Tuple[List[Tuple[int, str]],
                                            List[Tuple[int, str]]]:
    """(series_refs, exposition_refs) as (line, name) pairs."""
    series, expo = [], []
    m = _TS_METRICS_RE.search(sf.text)
    if m:
        base_line = sf.text[: m.start()].count("\n") + 1
        for e in _TS_ENTRY_RE.finditer(m.group(1)):
            line = base_line + m.group(1)[: e.start()].count("\n")
            series.append((line, e.group(1)))
    for i, line_text in enumerate(sf.text.splitlines(), 1):
        for tok in re.finditer(r"\bdli_[a-z0-9_]+", line_text):
            expo.append((i, tok.group(0)))
    return series, expo


def gate_refs(sf: SourceFile) -> Tuple[List[Tuple[int, str]],
                                       List[Tuple[int, str]]]:
    """Metric names the bench/smoke gates key off."""
    series, expo = [], []
    if sf.tree is None:
        return series, expo
    for node in ast.walk(sf.tree):
        if isinstance(node, ast.Call):
            fn = node.func
            # counters.get("name") / mc.get("name", 0)
            if (isinstance(fn, ast.Attribute) and fn.attr == "get"
                    and isinstance(fn.value, ast.Name)
                    and fn.value.id in _SNAPSHOT_RECEIVERS and node.args):
                s = const_str(node.args[0])
                if s is not None:
                    series.append((node.lineno, s))
            # delta("name") / q("name", ...)
            elif (isinstance(fn, ast.Name) and fn.id in _GATE_HELPERS
                    and node.args):
                s = const_str(node.args[0])
                if s is not None:
                    series.append((node.lineno, s))
            # requests.get(..., params={"metric": "name"}) — a
            # /api/timeseries query; a bare {"metric": ...} dict
            # elsewhere is just someone's result schema
            for kw in node.keywords:
                if kw.arg == "params" and isinstance(kw.value, ast.Dict):
                    for k, v in zip(kw.value.keys, kw.value.values):
                        if k is not None and const_str(k) == "metric":
                            s = const_str(v)
                            if s is not None:
                                series.append((v.lineno, s))
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            if re.fullmatch(r"dli_[a-z0-9_]+", node.value):
                expo.append((node.lineno, node.value))
    return series, expo


def doc_refs(path: str) -> List[Tuple[int, str]]:
    out = []
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f, 1):
            for m in _DLI_TOKEN.finditer(line):
                # skip path components (~/.cache/dli_models) — a metric
                # reference is never preceded by / . or -
                if m.start() and line[m.start() - 1] in "/.-":
                    continue
                tok = m.group(0).rstrip("_")
                for expanded in _expand_braces(tok):
                    out.append((i, expanded))
    return out


def _expand_braces(tok: str) -> List[str]:
    """``dli_cost_{queue,prefill}_x`` -> both concrete names."""
    m = re.search(r"\{([^{}]*)\}", tok)
    if not m:
        return [tok]
    alts = m.group(1).split(",") or [""]
    out = []
    for a in alts:
        out.extend(_expand_braces(tok[: m.start()] + a + tok[m.end():]))
    return out


# ---- the check --------------------------------------------------------

def check(ctx: Ctx) -> List[Violation]:
    violations: List[Violation] = []
    files = {sf.rel: sf for sf in
             ctx.package_files + ctx.gate_files
             + ([ctx.dashboard_file] if ctx.dashboard_file else [])}
    reg = collect_registrations(ctx.package_files)

    ts_series_refs: List[Tuple[str, int, str]] = []
    if ctx.dashboard_file is not None:
        series, expo = dashboard_refs(ctx.dashboard_file)
        rel = ctx.dashboard_file.rel
        ts_series_refs += [(rel, ln, n) for ln, n in series]
        for ln, tok in expo:
            if not reg.exposition_exists(tok):
                violations.append(Violation(
                    "metric-unregistered", rel, ln,
                    f"dashboard references {tok}, never registered"))
    for sf in ctx.gate_files:
        series, expo = gate_refs(sf)
        for ln, name in series:
            if not reg.series_exists(name):
                violations.append(Violation(
                    "metric-unregistered", sf.rel, ln,
                    f"gate keys off series {name!r}, never registered "
                    f"(no inc/gauge/record site)"))
        for ln, tok in expo:
            if not reg.exposition_exists(tok):
                violations.append(Violation(
                    "metric-unregistered", sf.rel, ln,
                    f"gate references {tok}, never registered"))
    for path in ctx.doc_paths:
        rel = path[len(ctx.root) + 1:] if path.startswith(ctx.root) else path
        for ln, tok in doc_refs(path):
            if reg.exposition_exists(tok):
                continue
            body = tok[4:]
            if reg.is_counter(body):
                violations.append(Violation(
                    "metric-counter-no-total", rel, ln,
                    f"doc references counter {tok} without _total — the "
                    f"exposed name is {tok}_total"))
            else:
                violations.append(Violation(
                    "metric-unregistered", rel, ln,
                    f"doc references {tok}, never registered"))

    # TS_METRICS chart names: must exist as series AND (counters/gauges)
    # be pre-registered at 0
    for rel, ln, name in ts_series_refs:
        if not reg.series_exists(name):
            violations.append(Violation(
                "metric-unregistered", rel, ln,
                f"TS_METRICS charts series {name!r}, never registered"))
        elif name not in reg.series and not reg.preregistered(name):
            violations.append(Violation(
                "metric-not-preregistered", rel, ln,
                f"TS_METRICS charts {name!r} but no inc({name!r}, 0) / "
                f"gauge({name!r}, 0) pre-registration exists — the "
                f"series is invisible until the first event (PR 5 rule)"))

    return filter_suppressed(violations, files)
