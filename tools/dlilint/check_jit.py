"""JIT purity checker: no host work inside traced code.

Anything reachable from a ``jax.jit`` / ``pallas_call`` callable runs
at *trace* time — a ``time.time()`` there stamps the compile, not the
step; ``np.random`` silently freezes one draw into the compiled
program; logging and lock acquisition execute once per compile and then
never again, which is almost never what the author meant. And a
``jax.jit(...)`` *constructed* inside a loop or per-request path builds
a fresh cache entry per iteration — the classic recompile hazard
(BENCH_r05's inversion was one of these at heart: compiles landing
inside measured windows).

Rules:

- ``jit-impure``  — ``time.*``, ``np.random.*``, ``os.environ`` /
  ``os.getenv``, logging/``print``, or lock acquisition inside a
  function reachable from a ``jax.jit`` / ``pallas_call`` site.
- ``jit-in-loop`` — ``jax.jit(...)`` called lexically inside a
  ``for``/``while`` body (wrap once outside, or memoize in a program
  cache keyed by static shape).

Reachability is best-effort static analysis: from each jitted callable,
same-module calls resolve by name (module functions, nested defs,
``self.`` methods), and ``from X import f`` calls follow into package
modules, to a bounded depth. Dynamic dispatch it cannot see; the
checker is a tripwire, not a proof.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

from .core import Ctx, SourceFile, Violation, dotted_name, filter_suppressed

RULES = ("jit-impure", "jit-in-loop")

MAX_DEPTH = 3

_TIME_CALLS = {"time.time", "time.perf_counter", "time.monotonic",
               "time.sleep", "time.process_time", "time.thread_time"}
_LOG_METHODS = {"debug", "info", "warning", "error", "exception",
                "critical", "warn"}
_LOG_BASES = {"log", "logger", "logging"}


def _is_jit_call(node: ast.Call) -> bool:
    dn = dotted_name(node.func)
    return dn in ("jax.jit", "jit") or (dn or "").endswith(".jit")


def _is_pallas_call(node: ast.Call) -> bool:
    dn = dotted_name(node.func) or ""
    return dn == "pallas_call" or dn.endswith(".pallas_call")


class _Module:
    """Per-file symbol tables for call resolution."""

    def __init__(self, sf: SourceFile):
        self.sf = sf
        self.funcs: Dict[str, List[ast.AST]] = {}
        self.imports: Dict[str, Tuple[str, str]] = {}   # name -> (mod, orig)
        if sf.tree is None:
            return
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs.setdefault(node.name, []).append(node)
            elif isinstance(node, ast.ImportFrom) and node.module:
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        node.module, alias.name)


class _Impurity(ast.NodeVisitor):
    """Scan ONE function body (not nested defs) for host work, and
    collect outgoing calls for the reachability walk."""

    def __init__(self, root_fn: ast.AST):
        self.root = root_fn
        self.impure: List[Tuple[int, str]] = []
        self.calls: List[ast.Call] = []

    def run(self):
        for stmt in self.root.body:
            self.visit(stmt)
        return self

    def visit_FunctionDef(self, node):
        pass   # nested defs analyzed only if actually called

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self.generic_visit(node)   # inline lambdas trace with the body

    def visit_Call(self, node: ast.Call):
        dn = dotted_name(node.func)
        if dn in _TIME_CALLS:
            self.impure.append((node.lineno, f"{dn}() traces host time "
                                "into the compiled program"))
        elif dn in ("os.getenv",):
            self.impure.append((node.lineno,
                                "os.getenv freezes env state at trace time"))
        elif dn == "print":
            self.impure.append((node.lineno,
                                "print() runs once per compile, not per "
                                "step (use jax.debug.print)"))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr in _LOG_METHODS:
            base = dotted_name(node.func.value)
            if base and (base in _LOG_BASES or base.startswith("logging.")
                         or base.split(".")[-1] in _LOG_BASES):
                self.impure.append((node.lineno,
                                    f"logging call {base}.{node.func.attr} "
                                    "inside traced code"))
        elif isinstance(node.func, ast.Attribute) \
                and node.func.attr == "acquire":
            self.impure.append((node.lineno,
                                "lock acquisition inside traced code"))
        self.calls.append(node)
        self.generic_visit(node)

    def visit_Attribute(self, node: ast.Attribute):
        dn = dotted_name(node)
        if dn:
            if dn.startswith(("np.random.", "numpy.random.")):
                self.impure.append((node.lineno,
                                    f"{dn} draws host randomness at trace "
                                    "time (use jax.random)"))
                return   # don't re-report the inner np.random node
            if dn in ("os.environ",) or dn.startswith("os.environ."):
                self.impure.append((node.lineno,
                                    "os.environ read freezes env state at "
                                    "trace time"))
                return   # don't re-report os.environ inside the chain
        self.generic_visit(node)

    def visit_With(self, node: ast.With):
        for item in node.items:
            dn = dotted_name(item.context_expr) or ""
            if "lock" in dn.lower().rsplit(".", 1)[-1]:
                self.impure.append((item.context_expr.lineno,
                                    f"lock `{dn}` held around traced code"))
        self.generic_visit(node)


def _resolve_target(arg: ast.AST, mod: _Module) -> List[ast.AST]:
    """Function-def nodes a jit first-argument may denote."""
    if isinstance(arg, ast.Lambda):
        return [arg]
    if isinstance(arg, ast.Name):
        return list(mod.funcs.get(arg.id, ()))
    if isinstance(arg, ast.Attribute):
        return list(mod.funcs.get(arg.attr, ()))
    if isinstance(arg, ast.Call):
        # functools.partial(fn, ...) / shard_map(fn, ...): first arg
        if arg.args:
            return _resolve_target(arg.args[0], mod)
    return []


def _scan_fn(fn: ast.AST) -> _Impurity:
    if isinstance(fn, ast.Lambda):
        imp = _Impurity.__new__(_Impurity)
        imp.root, imp.impure, imp.calls = fn, [], []
        imp.visit(fn.body)
        return imp
    return _Impurity(fn).run()


def check(ctx: Ctx) -> List[Violation]:
    violations: List[Violation] = []
    files = {sf.rel: sf for sf in ctx.package_files}
    modules = {sf.rel: _Module(sf) for sf in ctx.package_files}
    # module path index for from-import resolution:
    #   distributed_llm_inferencing_tpu/ops/rope.py  ->  "....ops.rope"
    by_modname: Dict[str, _Module] = {}
    for rel, mod in modules.items():
        name = rel[:-3].replace(os.sep, ".")
        if name.endswith(".__init__"):
            name = name[: -len(".__init__")]
        by_modname[name] = mod

    def resolve_import(mod: _Module, called: str) -> List[Tuple[_Module, ast.AST]]:
        ent = mod.imports.get(called)
        if not ent:
            return []
        imod, orig = ent
        # relative imports were flattened by ast (module keeps dots off);
        # match by suffix against package module names
        for name, m2 in by_modname.items():
            if name == imod or name.endswith("." + imod):
                return [(m2, fn) for fn in m2.funcs.get(orig, ())]
        return []

    for sf in ctx.package_files:
        if sf.tree is None:
            continue
        mod = modules[sf.rel]

        # --- jit-in-loop: jax.jit(...) lexically under For/While ------
        loop_stack: List[ast.AST] = []

        def walk_loops(node):
            in_loop = bool(loop_stack)
            if isinstance(node, ast.Call) and _is_jit_call(node) and in_loop:
                violations.append(Violation(
                    "jit-in-loop", sf.rel, node.lineno,
                    "jax.jit(...) constructed inside a loop — every "
                    "iteration builds a fresh traced callable (memoize "
                    "it, or hoist outside)"))
            is_loop = isinstance(node, (ast.For, ast.While, ast.AsyncFor))
            if is_loop:
                loop_stack.append(node)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                    # a def inside a loop restarts the loop context
                    saved, loop_stack[:] = list(loop_stack), []
                    walk_loops(child)
                    loop_stack[:] = saved
                else:
                    walk_loops(child)
            if is_loop:
                loop_stack.pop()

        walk_loops(sf.tree)

        # --- jit-impure: reachability from jit/pallas roots ------------
        roots: List[Tuple[ast.AST, int]] = []
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call) \
                    and (_is_jit_call(node) or _is_pallas_call(node)) \
                    and node.args:
                roots.extend((fn, node.lineno)
                             for fn in _resolve_target(node.args[0], mod))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    dn = dotted_name(dec) or ""
                    if dn in ("jax.jit", "jit") or dn.endswith(".jit"):
                        roots.append((node, node.lineno))
                    elif isinstance(dec, ast.Call):
                        ddn = dotted_name(dec.func) or ""
                        if ddn.endswith("partial") and dec.args:
                            adn = dotted_name(dec.args[0]) or ""
                            if adn in ("jax.jit", "jit") \
                                    or adn.endswith(".jit"):
                                roots.append((node, node.lineno))

        seen: Set[int] = set()
        queue: List[Tuple[_Module, ast.AST, int, int]] = [
            (mod, fn, root_line, 0) for fn, root_line in roots]
        while queue:
            cmod, fn, root_line, depth = queue.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            imp = _scan_fn(fn)
            for line, why in imp.impure:
                violations.append(Violation(
                    "jit-impure", cmod.sf.rel, line,
                    f"{why} (reachable from the jit/pallas_call site at "
                    f"{sf.rel}:{root_line})"))
            if depth >= MAX_DEPTH:
                continue
            for call in imp.calls:
                targets: List[Tuple[_Module, ast.AST]] = []
                f = call.func
                if isinstance(f, ast.Name):
                    targets += [(cmod, t) for t in cmod.funcs.get(f.id, ())]
                    targets += resolve_import(cmod, f.id)
                elif isinstance(f, ast.Attribute) and \
                        isinstance(f.value, ast.Name) and f.value.id == "self":
                    targets += [(cmod, t)
                                for t in cmod.funcs.get(f.attr, ())]
                for tmod, t in targets:
                    queue.append((tmod, t, root_line, depth + 1))

    return filter_suppressed(violations, files)
