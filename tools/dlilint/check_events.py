"""Events checker: emit sites == ``runtime/events.py`` registry == docs.

The flight recorder (docs/observability.md "Flight recorder") is only
trustworthy if the declared event registry IS the set of events the
cluster can emit — an undeclared emit would throw at the decision site,
a declared-but-never-emitted type is dead documentation a postmortem
would wait for forever, and a stale docs table teaches operators event
semantics the code no longer has. Three-way parity, mirroring the knobs
checker:

- ``event-undeclared``   — an ``events.emit("<type>", ...)`` call whose
  literal type has no row in ``runtime.events.EVENT_TYPES``.
- ``event-unemitted``    — a declared type with no statically-visible
  emit site in the package or the gate scripts.
- ``event-undoc``        — a declared type with an empty ``doc`` (the
  registry's own import-time assertion catches this for the real
  module; the rule keeps synthetic/test registries honest too).
- ``event-table-stale``  — the generated appendix block in
  docs/observability.md does not match ``events.generated_block()``
  (regenerate with ``python -m tools.dlilint --write-event-table``).

Emit sites are found by AST: any call whose dotted callee ends in
``events.emit`` (the module helper ``events.emit(...)`` and the
master's ``self.events.emit(...)`` both match) with a constant first
argument. A dynamic first argument is invisible to this checker —
``EventJournal.emit`` raises on undeclared types at runtime, so the
dynamic case fails loudly in tests instead of silently here.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from .core import Ctx, SourceFile, Violation, const_str, dotted_name, \
    filter_suppressed

RULES = ("event-undeclared", "event-unemitted", "event-undoc",
         "event-table-stale")


def collect_emit_sites(files) -> List[Tuple[SourceFile, int, str]]:
    """(file, line, type-name) for every statically-visible
    ``events.emit("<literal>", ...)`` call in ``files``."""
    out = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            dn = dotted_name(node.func)
            if dn is None or not dn.endswith("events.emit"):
                continue
            name = const_str(node.args[0])
            if name is not None:
                out.append((sf, node.lineno, name))
    return out


def check(ctx: Ctx) -> List[Violation]:
    violations: List[Violation] = []
    files = {sf.rel: sf for sf in ctx.package_files + ctx.gate_files}
    registry = ctx.event_registry
    if registry is None:
        return []

    sites = collect_emit_sites(files.values())
    emitted = {}
    for sf, line, name in sites:
        emitted.setdefault(name, (sf.rel, line))
    # 1. every emit site declared
    for sf, line, name in sites:
        if name not in registry:
            violations.append(Violation(
                "event-undeclared", sf.rel, line,
                f"event type {name!r} emitted here but missing from "
                f"runtime/events.py EVENT_TYPES"))
    # 2. every declared type emitted somewhere
    ev_rel = ("distributed_llm_inferencing_tpu/runtime/events.py")
    for name in sorted(registry):
        if name not in emitted:
            violations.append(Violation(
                "event-unemitted", ev_rel, 1,
                f"declared event type {name!r} has no emit site — "
                "dead documentation a postmortem would wait for "
                "forever"))
        decl = registry[name]
        doc = getattr(decl, "doc", None)
        if doc is not None and not str(doc).strip():
            violations.append(Violation(
                "event-undoc", ev_rel, 1,
                f"declared event type {name!r} has an empty doc"))

    # 3. generated docs appendix freshness (real registry only — a
    # synthetic test registry can't match the module's rendering)
    if ctx.observability_md and ctx.events_mod is not None:
        real = getattr(ctx.events_mod, "registry", lambda: None)()
        if real is not None and set(registry) == set(real):
            with open(ctx.observability_md, encoding="utf-8") as f:
                text = f.read()
            block = _extract_block(text, ctx.events_mod.DOC_BEGIN,
                                   ctx.events_mod.DOC_END)
            want = ctx.events_mod.generated_block()
            if block is None:
                violations.append(Violation(
                    "event-table-stale", "docs/observability.md", 1,
                    "generated event table markers missing — run "
                    "python -m tools.dlilint --write-event-table"))
            elif block.strip() != want.strip():
                violations.append(Violation(
                    "event-table-stale", "docs/observability.md", 1,
                    "generated event table drifted from "
                    "runtime/events.py — run python -m tools.dlilint "
                    "--write-event-table"))

    return filter_suppressed(violations, files)


def _extract_block(text: str, begin: str, end: str) -> Optional[str]:
    i = text.find(begin)
    j = text.find(end)
    if i < 0 or j < 0:
        return None
    return text[i:j + len(end)]


def write_event_table(observability_md: str, events_mod) -> bool:
    """Rewrite (or append) the generated block in ``observability_md``.
    Returns True when the file changed."""
    with open(observability_md, encoding="utf-8") as f:
        text = f.read()
    want = events_mod.generated_block()
    cur = _extract_block(text, events_mod.DOC_BEGIN, events_mod.DOC_END)
    if cur is None:
        new = (text.rstrip("\n")
               + "\n\n### Appendix: declared event types\n\n" + want
               + "\n")
    elif cur == want:
        return False
    else:
        new = text.replace(cur, want)
    with open(observability_md, "w", encoding="utf-8") as f:
        f.write(new)
    return True
