"""CLI: ``python -m tools.dlilint [--only a,b] [--write-knob-table]``.

Prints every violation (``path:line: [rule] message``) plus a
per-checker count summary, and exits non-zero when anything fired —
the form scripts/check.sh consumes.
"""

from __future__ import annotations

import argparse
import sys

from . import CHECKERS, run_all
from .core import Ctx


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dlilint",
        description="Repo-native invariant checkers (docs/static_analysis.md)")
    ap.add_argument("--only", default="",
                    help="comma list of checkers to run "
                         f"({', '.join(CHECKERS)})")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--write-knob-table", action="store_true",
                    help="regenerate the docs/serving.md knob table from "
                         "utils/knobs.py, then check")
    ap.add_argument("--write-lifecycle-diagram", action="store_true",
                    help="regenerate the docs/robustness.md lifecycle "
                         "diagram from runtime/lifecycle.py, then check")
    ap.add_argument("--write-event-table", action="store_true",
                    help="regenerate the docs/observability.md event "
                         "table from runtime/events.py, then check")
    args = ap.parse_args(argv)

    ctx = Ctx.for_repo(args.root)
    if args.write_knob_table:
        from .check_knobs import write_knob_table
        if ctx.serving_md is None:
            print("dlilint: docs/serving.md not found", file=sys.stderr)
            return 2
        changed = write_knob_table(ctx.serving_md)
        print(f"knob table: {'rewritten' if changed else 'already current'}")
        ctx = Ctx.for_repo(args.root)   # re-read the docs we just wrote
    if args.write_lifecycle_diagram:
        from .check_lifecycle import write_lifecycle_diagram
        if ctx.robustness_md is None:
            print("dlilint: docs/robustness.md not found", file=sys.stderr)
            return 2
        changed = write_lifecycle_diagram(ctx.robustness_md,
                                          ctx.lifecycle_mod)
        print(f"lifecycle diagram: "
              f"{'rewritten' if changed else 'already current'}")
        ctx = Ctx.for_repo(args.root)
    if args.write_event_table:
        from .check_events import write_event_table
        if ctx.observability_md is None:
            print("dlilint: docs/observability.md not found",
                  file=sys.stderr)
            return 2
        changed = write_event_table(ctx.observability_md, ctx.events_mod)
        print(f"event table: "
              f"{'rewritten' if changed else 'already current'}")
        ctx = Ctx.for_repo(args.root)

    only = {s.strip() for s in args.only.split(",") if s.strip()} or None
    bad = sorted((only or set()) - set(CHECKERS))
    if bad:
        print(f"dlilint: unknown checker(s): {', '.join(bad)}",
              file=sys.stderr)
        return 2

    results = run_all(ctx, only=only)
    total = 0
    for name in CHECKERS:
        if name not in results:
            continue
        for v in sorted(results[name], key=lambda v: (v.path, v.line)):
            print(v)
        total += len(results[name])
    print("--")
    for name in CHECKERS:
        if name in results:
            print(f"dlilint {name}: {len(results[name])} violation(s)")
    print(f"dlilint total: {total} violation(s) "
          f"{'— FAIL' if total else '— clean'}")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
