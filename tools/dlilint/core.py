"""Shared infrastructure for the dlilint checkers.

Everything is plain ``ast`` + file IO — no third-party deps, no imports
of the runtime package except ``utils.knobs`` (a pure-data module). A
checker is a function ``check(ctx) -> list[Violation]`` over a
:class:`Ctx` describing which files play which role; tests build tiny
synthetic ``Ctx`` objects around seeded-violation fixtures, CI builds
the real one with :meth:`Ctx.for_repo`.

Suppression: append ``# dlilint: disable=<rule>[,<rule>...]`` to the
offending line (or the line directly above it), or put
``# dlilint: disable-file=<rule>`` on any line to waive a rule for the
whole file. Suppressions are for *reviewed* exceptions — the pragma is
greppable precisely so a reviewer can audit every waiver.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

_PRAGMA_RE = re.compile(r"#\s*dlilint:\s*disable=([a-z0-9_,\- ]+)")
_PRAGMA_FILE_RE = re.compile(r"#\s*dlilint:\s*disable-file=([a-z0-9_,\- ]+)")


@dataclass(frozen=True)
class Violation:
    rule: str
    path: str          # repo-relative
    line: int
    msg: str

    def __str__(self):
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}"


@dataclass
class SourceFile:
    """One parsed python file: AST + per-line pragma index."""

    path: str                       # absolute
    rel: str                        # repo-relative (for reports)
    text: str
    tree: Optional[ast.AST]
    parse_error: Optional[str] = None
    _line_pragmas: Dict[int, set] = field(default_factory=dict)
    _file_pragmas: set = field(default_factory=set)

    @classmethod
    def load(cls, path: str, root: str) -> "SourceFile":
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            text = f.read()
        tree, err = None, None
        try:
            tree = ast.parse(text, filename=path)
        except SyntaxError as e:
            err = str(e)
        sf = cls(path=path, rel=os.path.relpath(path, root), text=text,
                 tree=tree, parse_error=err)
        for i, line in enumerate(text.splitlines(), 1):
            m = _PRAGMA_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
                sf._line_pragmas[i] = rules
            m = _PRAGMA_FILE_RE.search(line)
            if m:
                sf._file_pragmas |= {r.strip()
                                     for r in m.group(1).split(",")
                                     if r.strip()}
        return sf

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_pragmas or "all" in self._file_pragmas:
            return True
        for ln in (line, line - 1):
            rules = self._line_pragmas.get(ln)
            if rules and (rule in rules or "all" in rules):
                return True
        return False

    # ---- AST conveniences ---------------------------------------------

    def module_constants(self) -> Dict[str, str]:
        """Module-level ``NAME = "string"`` assignments — used to
        resolve env-var names read through a constant."""
        out: Dict[str, str] = {}
        if self.tree is None:
            return out
        for node in self.tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                out[node.targets[0].id] = node.value.value
        return out


@dataclass
class Ctx:
    """What the checkers scan. Paths are absolute; ``root`` anchors the
    repo-relative names in reports."""

    root: str
    package_files: List[SourceFile] = field(default_factory=list)
    runtime_files: List[SourceFile] = field(default_factory=list)
    gate_files: List[SourceFile] = field(default_factory=list)
    test_files: List[SourceFile] = field(default_factory=list)
    dashboard_file: Optional[SourceFile] = None
    doc_paths: List[str] = field(default_factory=list)
    shell_paths: List[str] = field(default_factory=list)
    serving_md: Optional[str] = None
    robustness_md: Optional[str] = None
    knob_registry: Optional[dict] = None     # name -> Knob (or test dict)
    lifecycle_transitions: Optional[tuple] = None   # runtime/lifecycle.py
    lifecycle_mod: Optional[object] = None   # the module (diagram check)
    observability_md: Optional[str] = None
    event_registry: Optional[dict] = None    # name -> EventType (or test)
    events_mod: Optional[object] = None      # runtime/events.py (table)

    @classmethod
    def for_repo(cls, root: Optional[str] = None) -> "Ctx":
        root = os.path.abspath(root or repo_root())
        pkg = os.path.join(root, "distributed_llm_inferencing_tpu")
        package_files = [SourceFile.load(p, root)
                         for p in iter_py_files(pkg)]
        runtime_files = [sf for sf in package_files
                         if os.sep + "runtime" + os.sep in sf.path]
        gates = [os.path.join(root, "bench.py"),
                 os.path.join(root, "scripts", "telemetry_smoke.py")]
        gate_files = [SourceFile.load(p, root) for p in gates
                      if os.path.exists(p)]
        tests_dir = os.path.join(root, "tests")
        test_files = ([SourceFile.load(p, root)
                       for p in iter_py_files(tests_dir)]
                      if os.path.isdir(tests_dir) else [])
        dash = os.path.join(pkg, "runtime", "dashboard_html.py")
        dashboard = (SourceFile.load(dash, root)
                     if os.path.exists(dash) else None)
        docs_dir = os.path.join(root, "docs")
        doc_paths = sorted(
            os.path.join(docs_dir, f) for f in os.listdir(docs_dir)
            if f.endswith(".md")) if os.path.isdir(docs_dir) else []
        serving = os.path.join(docs_dir, "serving.md")
        scripts_dir = os.path.join(root, "scripts")
        shell_paths = sorted(
            os.path.join(scripts_dir, f) for f in os.listdir(scripts_dir)
            if f.endswith(".sh")) if os.path.isdir(scripts_dir) else []
        from distributed_llm_inferencing_tpu.utils import knobs
        lifecycle = load_lifecycle(root)
        events = load_events(root)
        robustness = os.path.join(docs_dir, "robustness.md")
        observability = os.path.join(docs_dir, "observability.md")
        return cls(root=root, package_files=package_files,
                   runtime_files=runtime_files, gate_files=gate_files,
                   test_files=test_files,
                   dashboard_file=dashboard, doc_paths=doc_paths,
                   shell_paths=shell_paths,
                   serving_md=serving if os.path.exists(serving) else None,
                   robustness_md=(robustness if os.path.exists(robustness)
                                  else None),
                   observability_md=(observability
                                     if os.path.exists(observability)
                                     else None),
                   knob_registry=knobs.registry(),
                   lifecycle_transitions=lifecycle.TRANSITIONS,
                   lifecycle_mod=lifecycle,
                   event_registry=events.registry(),
                   events_mod=events)


def repo_root() -> str:
    """tools/dlilint/core.py -> two dirs up."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def load_lifecycle(root: str):
    """Import runtime/lifecycle.py by FILE PATH — the declared state
    machine is pure data, but ``runtime/__init__`` imports the engine
    (and with it jax); loading by path keeps ``python -m tools.dlilint``
    a sub-second stdlib-only gate."""
    import importlib.util
    path = os.path.join(root, "distributed_llm_inferencing_tpu",
                        "runtime", "lifecycle.py")
    spec = importlib.util.spec_from_file_location("_dli_lifecycle", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_events(root: str):
    """Import runtime/events.py by FILE PATH, same discipline as
    :func:`load_lifecycle`: the declared event registry is data + string
    rendering (its journal half leans only on ``utils.locks``), and
    loading by path keeps the checker gate off ``runtime/__init__``'s
    import graph."""
    import importlib.util
    path = os.path.join(root, "distributed_llm_inferencing_tpu",
                        "runtime", "events.py")
    spec = importlib.util.spec_from_file_location("_dli_events", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def iter_py_files(*dirs: str) -> List[str]:
    out = []
    for d in dirs:
        for base, subdirs, files in os.walk(d):
            subdirs[:] = [s for s in subdirs if s != "__pycache__"]
            out.extend(os.path.join(base, f) for f in files
                       if f.endswith(".py"))
    return sorted(out)


# ---- small AST helpers shared by checkers -----------------------------

def dotted_name(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def const_num(node: ast.AST) -> Optional[float]:
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return float(node.value)
    return None


def joined_str_pattern(node: ast.JoinedStr) -> Tuple[str, str]:
    """(regex, prefix) for an f-string metric name: constant parts kept
    verbatim, formatted holes become ``[A-Za-z0-9_.:-]+``."""
    rx, prefix, prefix_done = "", "", False
    for part in node.values:
        if isinstance(part, ast.Constant) and isinstance(part.value, str):
            rx += re.escape(part.value)
            if not prefix_done:
                prefix += part.value
        else:
            rx += r"[A-Za-z0-9_.:\-]+"
            prefix_done = True
    return rx, prefix


def walk_functions(tree: ast.AST):
    """Yield every FunctionDef/AsyncFunctionDef with its enclosing class
    name (or None)."""
    stack: List[Tuple[ast.AST, Optional[str]]] = [(tree, None)]
    while stack:
        node, cls = stack.pop()
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                stack.append((child, child.name))
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                stack.append((child, cls))
            else:
                stack.append((child, cls))


def filter_suppressed(violations: Sequence[Violation],
                      files: Dict[str, SourceFile]) -> List[Violation]:
    """Drop violations whose file carries a matching pragma."""
    out = []
    for v in violations:
        sf = files.get(v.path)
        if sf is not None and sf.suppressed(v.rule, v.line):
            continue
        out.append(v)
    return out
