"""Lifecycle checker: every ``UPDATE requests SET status=...`` site in
``runtime/state.py`` must instantiate a transition DECLARED in
``runtime/lifecycle.py`` — and the generated diagram in
``docs/robustness.md`` must match the table byte-for-byte.

Rules
-----
``lifecycle-undeclared``     a status write with no declared transition
                             (function + target status must match a row)
``lifecycle-guard``          the SQL WHERE constrains the source state
                             differently than the declared guard: a
                             ``where`` transition must name exactly the
                             declared source set, ``not-terminal`` must
                             exclude exactly the terminal states,
                             ``locked-select`` must sit under the store
                             lock next to a SELECT of the source state,
                             and ``none`` must not constrain status
``lifecycle-barrier``        durability mismatch: a ``barrier``
                             transition's UPDATE must flow through
                             ``Store._submit_write`` (the group-commit
                             durability barrier) and a ``sync-txn`` one
                             through a direct locked transaction
``lifecycle-attempts``       ``counts_attempt`` vs the presence of
                             ``attempts=attempts+1`` in the SQL disagree
``lifecycle-unused``         a declared (non-insert) transition with no
                             matching write site — table drift
``lifecycle-diagram-stale``  the marker-delimited block in
                             docs/robustness.md differs from
                             ``lifecycle.generated_block()`` (regenerate
                             with ``--write-lifecycle-diagram``)

How sites are found: the AST of state.py is scanned for string
constants (f-string constant parts included) containing
``UPDATE requests SET``; each is resolved to its enclosing function and
its delivery mechanism (``self._submit_write(...)`` argument vs
``self._db.execute/executemany`` under ``with self._lock`` vs
``self._exec``). Status literals are parsed out of the SET and WHERE
clauses textually — state.py writes statuses as SQL literals on
purpose, and a parameterized ``status=?`` would itself be flagged as
undeclared (the checker cannot prove it).
"""

from __future__ import annotations

import ast
import os
import re
from typing import List, Optional, Tuple

from .core import Ctx, SourceFile, Violation, dotted_name

_UPDATE_RE = re.compile(r"UPDATE\s+requests\s+SET", re.I)
_SET_STATUS_RE = re.compile(r"SET\s+status\s*=\s*'(\w+)'", re.I)
_WHERE_RE = re.compile(r"\bWHERE\b(.*)$", re.I | re.S)
_W_STATUS_EQ = re.compile(r"status\s*=\s*'(\w+)'", re.I)
_W_STATUS_NOTIN = re.compile(
    r"status\s+NOT\s+IN\s*\(([^)]*)\)", re.I)
_W_STATUS_IN = re.compile(r"status\s+IN\s*\(([^)]*)\)", re.I)
_ATTEMPTS_RE = re.compile(r"attempts\s*=\s*attempts\s*\+\s*1", re.I)
_QUOTED = re.compile(r"'(\w+)'")


class Site:
    """One UPDATE-requests write site resolved from the AST."""

    def __init__(self, sf: SourceFile, line: int, sql: str, fn: str,
                 mechanism: str, under_store_lock: bool,
                 fn_source: str):
        self.sf = sf
        self.line = line
        self.sql = sql
        self.fn = fn                    # enclosing function name
        self.mechanism = mechanism      # submit_write | db-direct | exec
        self.under_store_lock = under_store_lock
        self.fn_source = fn_source      # full source of the function

    @property
    def target(self) -> Optional[str]:
        m = _SET_STATUS_RE.search(self.sql)
        return m.group(1) if m else None

    def where_status(self) -> Tuple[str, frozenset]:
        """(kind, states) the WHERE clause constrains status to:
        ("eq", {s}) / ("in", {..}) / ("not-in", {..}) / ("none", {})."""
        m = _WHERE_RE.search(self.sql)
        if not m:
            return "none", frozenset()
        where = m.group(1)
        m = _W_STATUS_NOTIN.search(where)
        if m:
            return "not-in", frozenset(_QUOTED.findall(m.group(1)))
        m = _W_STATUS_IN.search(where)
        if m:
            return "in", frozenset(_QUOTED.findall(m.group(1)))
        m = _W_STATUS_EQ.search(where)
        if m:
            return "eq", frozenset([m.group(1)])
        return "none", frozenset()

    @property
    def counts_attempt(self) -> bool:
        return bool(_ATTEMPTS_RE.search(self.sql))


def _string_parts(node: ast.AST) -> Optional[str]:
    """Concatenated constant text of a Str / f-string / implicit-concat
    expression (formatted holes contribute nothing — the status and
    WHERE literals this checker reads are always in the constants)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        return "".join(p.value for p in node.values
                       if isinstance(p, ast.Constant)
                       and isinstance(p.value, str))
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        left, right = _string_parts(node.left), _string_parts(node.right)
        if left is not None and right is not None:
            return left + right
    return None


def _call_mechanism(call: ast.Call) -> Optional[str]:
    name = dotted_name(call.func) or ""
    if name.endswith("._submit_write"):
        return "submit_write"
    if name.endswith("._db.execute") or name.endswith("._db.executemany"):
        return "db-direct"
    if name.endswith("._exec"):
        return "exec"
    return None


def _with_holds_store_lock(with_node: ast.With) -> bool:
    for item in with_node.items:
        name = dotted_name(item.context_expr) or ""
        if name.endswith("._lock"):
            return True
    return False


def collect_sites(sf: SourceFile) -> List[Site]:
    """Every UPDATE-requests string in ``sf`` with its enclosing
    function, delivery call, and lock context."""
    sites: List[Site] = []
    if sf.tree is None:
        return sites

    class V(ast.NodeVisitor):
        def __init__(self):
            self.fn_stack: List[ast.AST] = []
            self.call_stack: List[ast.Call] = []
            self.with_stack: List[ast.With] = []

        def visit_FunctionDef(self, node):
            self.fn_stack.append(node)
            self.generic_visit(node)
            self.fn_stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_With(self, node):
            self.with_stack.append(node)
            self.generic_visit(node)
            self.with_stack.pop()

        def visit_Call(self, node):
            self.call_stack.append(node)
            self.generic_visit(node)
            self.call_stack.pop()

        def _note(self, node, text):
            mech = None
            for call in reversed(self.call_stack):
                mech = _call_mechanism(call)
                if mech is not None:
                    break
            under = any(_with_holds_store_lock(w)
                        for w in self.with_stack)
            fn = self.fn_stack[-1] if self.fn_stack else None
            sites.append(Site(
                sf, node.lineno, text,
                fn.name if fn is not None else "<module>",
                mech or "unknown", under,
                ast.get_source_segment(sf.text, fn) or "" if fn
                else sf.text))

        def visit_Constant(self, node):
            if isinstance(node.value, str) and _UPDATE_RE.search(
                    node.value):
                self._note(node, node.value)

        def visit_JoinedStr(self, node):
            text = _string_parts(node) or ""
            if _UPDATE_RE.search(text):
                self._note(node, text)
            # don't recurse: the constants inside would double-report

    V().visit(sf.tree)
    return sites


def _guard_violation(site: Site, t, terminal) -> Optional[str]:
    kind, states = site.where_status()
    declared = frozenset(t.source)
    if t.guard == "where":
        if kind == "eq" and states == declared:
            return None
        if kind == "in" and states == declared:
            return None
        return (f"declared guard 'where' over {sorted(declared)} but the "
                f"WHERE clause constrains status as {kind} "
                f"{sorted(states) or '(nothing)'}")
    if t.guard == "not-terminal":
        if kind == "not-in" and states == frozenset(terminal):
            return None
        return ("declared guard 'not-terminal' but the WHERE clause "
                f"constrains status as {kind} {sorted(states) or '∅'} "
                f"(want NOT IN {sorted(terminal)})")
    if t.guard == "locked-select":
        if kind != "none":
            return ("declared guard 'locked-select' but the UPDATE "
                    "itself constrains status — declare 'where' instead")
        if not site.under_store_lock:
            return ("declared guard 'locked-select' but the UPDATE does "
                    "not run under `with self._lock`")
        want = "|".join(sorted(declared))
        if not re.search(r"SELECT\b.*status\s*=\s*'(%s)'" % want,
                         site.fn_source, re.I | re.S):
            return ("declared guard 'locked-select' but no SELECT of "
                    f"status in {sorted(declared)} found in "
                    f"{site.fn}()")
        return None
    if t.guard == "none":
        if kind != "none":
            return (f"declared guard 'none' but the WHERE clause "
                    f"constrains status ({kind} {sorted(states)}) — "
                    "declare the guard")
        return None
    return f"unknown declared guard kind {t.guard!r}"


def _barrier_violation(site: Site, t) -> Optional[str]:
    if t.durability == "barrier":
        if site.mechanism != "submit_write":
            return (f"transition '{t.name}' declares the group-commit "
                    "durability barrier but the UPDATE is delivered via "
                    f"{site.mechanism!r}, not Store._submit_write")
        return None
    # sync-txn: a direct locked transaction (db-direct under the store
    # lock) or the _exec helper (which takes lock + txn itself)
    if site.mechanism == "exec":
        return None
    if site.mechanism == "db-direct" and site.under_store_lock:
        return None
    return (f"transition '{t.name}' declares sync-txn durability but "
            f"the UPDATE is delivered via {site.mechanism!r}"
            + ("" if site.under_store_lock
               else " outside `with self._lock`"))


def check_sites(state_sf: SourceFile, transitions,
                states=("pending", "processing", "completed", "failed"),
                terminal=("completed", "failed")) -> List[Violation]:
    """Core site check, unit-testable against fixture files/tables."""
    out: List[Violation] = []
    sites = collect_sites(state_sf)
    matched = set()
    for site in sites:
        target = site.target
        if target is None:
            # UPDATE requests that doesn't touch status (e.g. a future
            # cost-only write) is outside the machine
            continue
        if target not in states:
            out.append(Violation(
                "lifecycle-undeclared", state_sf.rel, site.line,
                f"status {target!r} written in {site.fn}() is not a "
                "declared lifecycle state"))
            continue
        cands = [t for t in transitions
                 if t.guard != "insert" and t.target == target
                 and t.fn == site.fn]
        if not cands:
            out.append(Violation(
                "lifecycle-undeclared", state_sf.rel, site.line,
                f"UPDATE in {site.fn}() sets status='{target}' but no "
                "declared transition covers (function, target) — add it "
                "to runtime/lifecycle.py TRANSITIONS or move the write"))
            continue
        # disambiguate recover_stale_processing's two writes by target;
        # (fn, target) is unique in the declared table by construction
        t = cands[0]
        matched.add(t.name)
        msg = _guard_violation(site, t, terminal)
        if msg:
            out.append(Violation("lifecycle-guard", state_sf.rel,
                                 site.line, msg))
        msg = _barrier_violation(site, t)
        if msg:
            out.append(Violation("lifecycle-barrier", state_sf.rel,
                                 site.line, msg))
        if t.counts_attempt != site.counts_attempt:
            out.append(Violation(
                "lifecycle-attempts", state_sf.rel, site.line,
                f"transition '{t.name}' declares "
                f"counts_attempt={t.counts_attempt} but the SQL "
                f"{'has' if site.counts_attempt else 'lacks'} "
                "attempts=attempts+1"))
    for t in transitions:
        if t.guard == "insert" or t.name in matched:
            continue
        out.append(Violation(
            "lifecycle-unused", state_sf.rel, 1,
            f"declared transition '{t.name}' ({'/'.join(t.source)} -> "
            f"{t.target} in {t.fn}()) matches no UPDATE site — stale "
            "table row?"))
    return out


def _extract_block(text: str, begin: str, end: str) -> Optional[str]:
    i = text.find(begin)
    if i < 0:
        return None
    j = text.find(end, i)
    if j < 0:
        return None
    return text[i:j + len(end)]


def check_diagram(robustness_md: str, lifecycle_mod) -> List[Violation]:
    rel = os.path.join("docs", "robustness.md")
    try:
        with open(robustness_md, encoding="utf-8") as f:
            text = f.read()
    except OSError as e:
        return [Violation("lifecycle-diagram-stale", rel, 1,
                          f"docs/robustness.md unreadable: {e}")]
    cur = _extract_block(text, lifecycle_mod.DOC_BEGIN,
                         lifecycle_mod.DOC_END)
    want = lifecycle_mod.generated_block()
    if cur is None:
        return [Violation(
            "lifecycle-diagram-stale", rel, 1,
            "generated lifecycle diagram block missing — run "
            "`python -m tools.dlilint --write-lifecycle-diagram`")]
    if cur != want:
        return [Violation(
            "lifecycle-diagram-stale", rel,
            text[:text.find(lifecycle_mod.DOC_BEGIN)].count("\n") + 1,
            "lifecycle diagram drifted from runtime/lifecycle.py — run "
            "`python -m tools.dlilint --write-lifecycle-diagram`")]
    return []


def write_lifecycle_diagram(robustness_md: str, lifecycle_mod) -> bool:
    """Regenerate the marker-delimited diagram block in place (appends
    the block if the markers are absent). Returns True if the file
    changed."""
    with open(robustness_md, encoding="utf-8") as f:
        text = f.read()
    want = lifecycle_mod.generated_block()
    cur = _extract_block(text, lifecycle_mod.DOC_BEGIN,
                         lifecycle_mod.DOC_END)
    if cur is None:
        new = text.rstrip("\n") + "\n\n" + want + "\n"
    elif cur == want:
        return False
    else:
        new = text.replace(cur, want)
    with open(robustness_md, "w", encoding="utf-8") as f:
        f.write(new)
    return True


def check(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    transitions = ctx.lifecycle_transitions
    if transitions is None:
        return out
    state_sf = next(
        (sf for sf in ctx.package_files
         if sf.rel.replace(os.sep, "/").endswith("runtime/state.py")),
        None)
    if state_sf is not None:
        out.extend(check_sites(state_sf, transitions))
    if ctx.robustness_md and ctx.lifecycle_mod is not None:
        out.extend(check_diagram(ctx.robustness_md, ctx.lifecycle_mod))
    files = {sf.rel: sf for sf in ctx.package_files}
    from .core import filter_suppressed
    return filter_suppressed(out, files)
