"""dlilint — repo-native static analysis for this codebase's invariants.

Eight PRs established invariants that only reviewer memory enforced:
metrics pre-registered at 0 (the PR 5 rule), every ``DLI_*`` knob
documented with a default, no host work inside jitted decode code, lock
discipline across 20+ runtime locks. dlilint machine-checks them as a
hard CI gate (``scripts/check.sh`` "dlilint" step):

==================  ===================================================
checker             rules
==================  ===================================================
knobs               knob-unregistered, knob-dead, knob-undocumented,
                    knob-doc-dead, knob-table-stale
metrics             metric-unregistered, metric-counter-no-total,
                    metric-not-preregistered
jit                 jit-impure, jit-in-loop
threads             lock-order-cycle, silent-except
rpc                 rpc-unknown-path, rpc-method-mismatch,
                    rpc-dead-route, rpc-quiet-unknown,
                    rpc-fault-unknown, rpc-body-unread,
                    rpc-body-unsent
lifecycle           lifecycle-undeclared, lifecycle-guard,
                    lifecycle-barrier, lifecycle-attempts,
                    lifecycle-unused, lifecycle-diagram-stale
events              event-undeclared, event-unemitted, event-undoc,
                    event-table-stale
time                time-direct
==================  ===================================================

Run: ``python -m tools.dlilint`` (exit 0 = clean). Suppress a reviewed
exception with ``# dlilint: disable=<rule>`` on (or right above) the
line. Full docs: docs/static_analysis.md. The dynamic twin of the
``threads`` checker is the ``DLI_LOCK_CHECK=1`` runtime watchdog in
``utils/locks.py``, armed during the chaos suite in CI.
"""

from __future__ import annotations

from typing import Dict, List

from . import (check_events, check_jit, check_knobs, check_lifecycle,
               check_metrics, check_rpc, check_threads, check_time)
from .core import Ctx, Violation

CHECKERS = {
    "knobs": check_knobs.check,
    "metrics": check_metrics.check,
    "jit": check_jit.check,
    "threads": check_threads.check,
    "rpc": check_rpc.check,
    "lifecycle": check_lifecycle.check,
    "events": check_events.check,
    "time": check_time.check,
}


def run_all(ctx: Ctx = None, only=None) -> Dict[str, List[Violation]]:
    """Run every checker (or the named subset) over ``ctx`` (defaults
    to the real repo). Returns checker -> violations."""
    if ctx is None:
        ctx = Ctx.for_repo()
    out: Dict[str, List[Violation]] = {}
    for name, fn in CHECKERS.items():
        if only and name not in only:
            continue
        out[name] = fn(ctx)
    return out
