"""Knobs checker: code env reads == ``utils/knobs.py`` == docs.

Rules:

- ``knob-unregistered``  — a ``DLI_*`` env read in code with no row in
  ``utils.knobs.KNOBS``.
- ``knob-dead``          — a registry row no code path reads.
- ``knob-undocumented``  — a registry row that never appears in
  ``docs/serving.md``.
- ``knob-doc-dead``      — a ``DLI_*`` token in ``docs/*.md`` that is in
  no registry row (documented knobs must exist).
- ``knob-table-stale``   — the generated table block in serving.md does
  not match ``knobs.generated_block()`` (regenerate with
  ``python -m tools.dlilint --write-knob-table``).

Env reads are found by AST: ``os.environ.get/ setdefault``,
``os.getenv``, ``os.environ[...]`` subscript loads, and calls to local
``_env*`` helper wrappers whose first argument is the var name. A name
given as a bare ``NAME`` is resolved through module-level string
constants. Names starting with ``_DLI`` are internal plumbing (private
env handshakes between a parent and its subprocess) and are exempt.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from .core import Ctx, SourceFile, Violation, const_str, dotted_name, \
    filter_suppressed

_KNOB_RE = re.compile(r"^DLI_[A-Z0-9_]+$")
_DOC_TOKEN_RE = re.compile(r"\bDLI_[A-Z0-9_]+\b")

RULES = ("knob-unregistered", "knob-dead", "knob-undocumented",
         "knob-doc-dead", "knob-table-stale")


def _env_read_name(call: ast.Call, consts: Dict[str, str]) -> Optional[str]:
    """The env-var name this Call reads, or None if it isn't a read."""
    fn = call.func
    dn = dotted_name(fn)
    is_env = False
    if dn in ("os.getenv", "getenv"):
        is_env = True
    elif isinstance(fn, ast.Attribute) and fn.attr in ("get", "setdefault"):
        base = dotted_name(fn.value)
        if base in ("os.environ", "environ"):
            is_env = True
    elif isinstance(fn, ast.Name) and fn.id.startswith("_env"):
        # local helper wrappers (e.g. tsdb._env_float) take the var name
        # as their first argument
        is_env = True
    if not is_env or not call.args:
        return None
    return _resolve_name(call.args[0], consts)


def _resolve_name(node: ast.AST, consts: Dict[str, str]) -> Optional[str]:
    s = const_str(node)
    if s is None and isinstance(node, ast.Name):
        s = consts.get(node.id)
    return s


def collect_env_reads(files) -> List[Tuple[SourceFile, int, str]]:
    """(file, line, name) for every DLI_* env read in ``files``."""
    out = []
    for sf in files:
        if sf.tree is None:
            continue
        consts = sf.module_constants()
        for node in ast.walk(sf.tree):
            name = None
            if isinstance(node, ast.Call):
                name = _env_read_name(node, consts)
            elif (isinstance(node, ast.Subscript)
                    and isinstance(node.ctx, ast.Load)
                    and dotted_name(node.value) in ("os.environ", "environ")):
                name = _resolve_name(node.slice, consts)
            if name and _KNOB_RE.match(name):
                out.append((sf, node.lineno, name))
    return out


# a shell READ is an expansion — ${DLI_X...} or $DLI_X — never the
# `DLI_X=...` assignment form check.sh uses to arm knobs for child
# processes (those are reads *by the child's python*, counted there)
_SHELL_READ_RE = re.compile(r"\$\{?(DLI_[A-Z0-9_]+)")


def collect_shell_reads(paths) -> List[Tuple[str, int, str]]:
    """(path, line, name) for DLI_* expansions in shell scripts —
    check.sh-only knobs (e.g. DLI_TSAN_FAST) are knobs too and belong
    in the registry + docs like any python-read knob."""
    out = []
    for path in paths:
        with open(path, encoding="utf-8", errors="replace") as f:
            for i, line in enumerate(f, 1):
                for m in _SHELL_READ_RE.finditer(line):
                    out.append((path, i, m.group(1)))
    return out


def check(ctx: Ctx) -> List[Violation]:
    violations: List[Violation] = []
    files = {sf.rel: sf for sf in ctx.package_files + ctx.gate_files}
    registry = ctx.knob_registry or {}

    reads = collect_env_reads(files.values())
    read_names = {}
    for sf, line, name in reads:
        read_names.setdefault(name, (sf.rel, line))
    for path, line, name in collect_shell_reads(ctx.shell_paths):
        rel = path[len(ctx.root) + 1:] if path.startswith(ctx.root) else path
        read_names.setdefault(name, (rel, line))
    # 1. every code read registered
    for name, (rel, line) in sorted(read_names.items()):
        if name not in registry:
            violations.append(Violation(
                "knob-unregistered", rel, line,
                f"env knob {name} read here but missing from "
                f"utils/knobs.py KNOBS"))
    # 2. every registry row read somewhere
    for name in sorted(registry):
        if name not in read_names:
            violations.append(Violation(
                "knob-dead", "distributed_llm_inferencing_tpu/utils/knobs.py",
                1, f"registered knob {name} has no env read in code"))

    # 3./4. docs parity
    serving_text = ""
    if ctx.serving_md:
        with open(ctx.serving_md, encoding="utf-8") as f:
            serving_text = f.read()
        for name in sorted(registry):
            if name not in serving_text:
                violations.append(Violation(
                    "knob-undocumented", "docs/serving.md", 1,
                    f"registered knob {name} missing from the "
                    f"docs/serving.md knob tables"))
    for path in ctx.doc_paths:
        with open(path, encoding="utf-8") as f:
            text = f.read()
        rel = path[len(ctx.root) + 1:] if path.startswith(ctx.root) else path
        for i, line in enumerate(text.splitlines(), 1):
            for tok in _DOC_TOKEN_RE.findall(line):
                if tok not in registry and not tok.startswith("_DLI"):
                    violations.append(Violation(
                        "knob-doc-dead", rel, i,
                        f"doc references {tok}, which is in no "
                        f"utils/knobs.py row (dead documented knob?)"))

    # 5. generated table freshness
    if ctx.serving_md and registry:
        from distributed_llm_inferencing_tpu.utils import knobs as knobs_mod
        if ctx.knob_registry is not None and \
                set(ctx.knob_registry) != set(knobs_mod.registry()):
            pass   # synthetic test registry: freshness check not meaningful
        else:
            block = _extract_block(serving_text, knobs_mod.DOC_BEGIN,
                                   knobs_mod.DOC_END)
            want = knobs_mod.generated_block()
            if block is None:
                violations.append(Violation(
                    "knob-table-stale", "docs/serving.md", 1,
                    "generated knob table markers missing — run "
                    "python -m tools.dlilint --write-knob-table"))
            elif block.strip() != want.strip():
                violations.append(Violation(
                    "knob-table-stale", "docs/serving.md", 1,
                    "generated knob table drifted from utils/knobs.py — "
                    "run python -m tools.dlilint --write-knob-table"))

    return filter_suppressed(violations, files)


def _extract_block(text: str, begin: str, end: str) -> Optional[str]:
    i = text.find(begin)
    j = text.find(end)
    if i < 0 or j < 0:
        return None
    return text[i:j + len(end)]


def write_knob_table(serving_md: str) -> bool:
    """Rewrite (or append) the generated block in ``serving_md``.
    Returns True when the file changed."""
    from distributed_llm_inferencing_tpu.utils import knobs as knobs_mod
    with open(serving_md, encoding="utf-8") as f:
        text = f.read()
    want = knobs_mod.generated_block()
    cur = _extract_block(text, knobs_mod.DOC_BEGIN, knobs_mod.DOC_END)
    if cur is None:
        new = text.rstrip("\n") + "\n\n## Appendix: full knob registry\n\n" \
            + want + "\n"
    elif cur == want:
        return False
    else:
        new = text.replace(cur, want)
    with open(serving_md, "w", encoding="utf-8") as f:
        f.write(new)
    return True
