"""RPC contract checker: the master↔worker wire protocol, statically.

The protocol between the master's RPC client (``_worker_get`` /
``_worker_post`` / ``_scrape_workers``), the kvwire peer-fetch client,
the bench/test/script HTTP drivers, and the services' ``Server.add``
route tables was enforced only by reviewer memory — a renamed path or a
method flip surfaced as a runtime 404 in chaos CI at best. This checker
cross-references every statically-visible call site against every
registered route.

Rules
-----
``rpc-unknown-path``      a call site names a path no service registers
``rpc-method-mismatch``   the path exists, but only under another method
``rpc-dead-route``        a registered route no caller, script, shell
                          fetcher, dashboard page, or doc reaches
``rpc-quiet-unknown``     an entry in httpd's QUIET_TRACE_PATHS open-set
                          matches no registered route (a typo there
                          silently un-quiets a poll path)
``rpc-fault-unknown``     a fault point armed in tests or docs matches
                          no live intercept site (route paths server-
                          side, ``rpc:<path>`` client-side)
``rpc-body-unread``       a master-side POST body key the handler (and
                          the helpers it hands the body to) never reads
``rpc-body-unsent``       a handler-read body key no caller, test,
                          bench, or doc ever mentions

Conservatism: only literal paths and literal/locally-built dict bodies
are checked; a dynamically computed path or a body that escapes into
unresolvable code is skipped, never guessed. Fully dynamic route
patterns (multihost's ``f"/{op}"`` rebinds) are ignored. Test files
contribute their own locally-registered routes to the match universe
(httpd unit tests register synthetic paths) but not to the dead-route
universe.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import (Ctx, SourceFile, Violation, dotted_name, const_str,
                   filter_suppressed)

_METHODS = ("GET", "POST", "PUT", "DELETE")
_HTTP_ATTRS = ("get", "post", "put", "delete")
# responses the tests drive through requests.request(...) etc are rare
# enough to skip; .get is also dict.get — a call only counts as HTTP
# when a path/URL literal is actually found in its arguments.

_PARAM_SEG = re.compile(r"^(<\w+>|\{\w*\}|\*)$")
_DOC_PATH_RE = re.compile(
    r"""(?:^|[\s"'`=(])(/[A-Za-z_][A-Za-z0-9_/<>{}*.-]*)""")
_DOC_POINT_RE = re.compile(r'"point"\s*:\s*"([^"]+)"')
_WORD_RE = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


def canon(path: str) -> Optional[Tuple[str, ...]]:
    """Path -> canonical segment tuple; param-ish segments become '*'.
    Returns None for paths that are not route-shaped."""
    path = path.partition("?")[0].strip()
    if not path.startswith("/"):
        return None
    segs = [s for s in path.split("/") if s]
    out = []
    for s in segs:
        out.append("*" if _PARAM_SEG.match(s) or "*" in s or "{" in s
                   or "<" in s else s)
    return tuple(out)


def _segs_match(a: Sequence[str], b: Sequence[str]) -> bool:
    return len(a) == len(b) and all(
        x == "*" or y == "*" or x == y for x, y in zip(a, b))


@dataclass
class RouteDef:
    method: str
    pattern: str                 # as registered
    segs: Optional[Tuple[str, ...]]   # None = fully dynamic (ignored)
    sf: SourceFile
    line: int
    handler: Optional[str]       # dotted handler expr ("self.health")


@dataclass
class CallSite:
    method: str                  # GET/POST/... or "" when unknowable
    path: str
    segs: Tuple[str, ...]
    sf: SourceFile
    line: int
    body: Optional[ast.expr] = None     # POST body expression
    fn: Optional[ast.AST] = None        # enclosing function node


# ---- route tables -----------------------------------------------------

def collect_routes(files) -> List[RouteDef]:
    out: List[RouteDef] = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            args = node.args
            if name.endswith(".add") and len(args) == 3:
                method, pattern = const_str(args[0]), args[1]
            elif name.endswith("_replace_route") and len(args) >= 4:
                method, pattern = const_str(args[1]), args[2]
            else:
                continue
            if method not in _METHODS:
                continue
            pat = const_str(pattern)
            if pat is None:
                # f-string pattern (multihost f"/{op}"): fully dynamic,
                # recorded as unmatched-anything (segs=None)
                out.append(RouteDef(method, "<dynamic>", None, sf,
                                    node.lineno,
                                    dotted_name(args[-1])))
                continue
            out.append(RouteDef(method, pat, canon(pat) or (), sf,
                                node.lineno, dotted_name(args[-1])))
    return out


# ---- call sites -------------------------------------------------------

def _joined_path(j: ast.JoinedStr) -> Optional[str]:
    parts: List[str] = []
    started = False
    for v in j.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            s = v.value
            if not started:
                if "://" in s:
                    s = s.split("://", 1)[1]
                    i = s.find("/")
                    if i < 0:
                        continue
                    s = s[i:]
                elif not s.startswith("/"):
                    continue
                started = True
            parts.append(s)
        elif started:
            parts.append("*")
    return "".join(parts) if parts else None


def _expr_path(node: ast.AST) -> Optional[str]:
    s = const_str(node)
    if s is not None:
        if "://" in s:
            s = s.split("://", 1)[1]
            i = s.find("/")
            return s[i:] if i >= 0 else None
        return s if s.startswith("/") else None
    if isinstance(node, ast.JoinedStr):
        return _joined_path(node)
    return None


def _call_path(call: ast.Call) -> Optional[str]:
    for arg in call.args[:1]:
        p = _expr_path(arg)
        if p is not None:
            return p
    # nested helper (_url(port, "/x")) or keyword url=...
    for sub in ast.walk(call):
        if sub is call:
            continue
        p = _expr_path(sub) if isinstance(
            sub, (ast.Constant, ast.JoinedStr)) else None
        if p is not None:
            return p
    return None


def _enclosing_functions(tree) -> List[Tuple[ast.AST, ast.AST]]:
    """(function_node, call_node) pairs are awkward with ast.walk; we
    instead map every node to its enclosing function via a visit."""
    pairs = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = []

        def visit_FunctionDef(self, node):
            self.stack.append(node)
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

        def visit_Call(self, node):
            pairs.append((self.stack[-1] if self.stack else None, node))
            self.generic_visit(node)

    V().visit(tree)
    return pairs


def collect_calls(files) -> List[CallSite]:
    """HTTP call sites: the master RPC client helpers
    (``_worker_get/_worker_post/_scrape_workers``) plus generic
    ``X.get/post/...`` calls with a literal path/URL (tests, bench,
    kvwire)."""
    out: List[CallSite] = []
    for sf in files:
        if sf.tree is None:
            continue
        for fn, call in _enclosing_functions(sf.tree):
            name = dotted_name(call.func) or ""
            short = name.rsplit(".", 1)[-1]
            method, path, body = None, None, None
            if short in ("_worker_get", "_worker_post") and call.args:
                if len(call.args) >= 2:
                    path = _expr_path(call.args[1]) or (
                        const_str(call.args[1]))
                method = "GET" if short == "_worker_get" else "POST"
                if method == "POST" and len(call.args) >= 3:
                    body = call.args[2]
            elif short == "_scrape_workers" and call.args:
                path = const_str(call.args[0])
                method = "GET"
            elif short in _HTTP_ATTRS and name != short:
                path = _call_path(call)
                method = short.upper()
                for kw in call.keywords:
                    if kw.arg == "json":
                        body = kw.value
            else:
                continue
            if path is None:
                continue
            segs = canon(path)
            if segs is None:
                continue
            out.append(CallSite(method, path.partition("?")[0], segs,
                                sf, call.lineno, body, fn))
    return out


# ---- reference universes ----------------------------------------------

def text_path_refs(text: str) -> Set[Tuple[str, ...]]:
    refs: Set[Tuple[str, ...]] = set()
    for m in _DOC_PATH_RE.finditer(text):
        tok = m.group(1).rstrip(".,;:)`'\"")
        c = canon(tok)
        if c:
            refs.add(c)
    return refs


def collect_quiet_set(files) -> List[Tuple[SourceFile, int, str]]:
    """QUIET_TRACE_PATHS literal entries (httpd's open-set of unrecorded
    poll paths)."""
    out = []
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "QUIET_TRACE_PATHS"):
                for sub in ast.walk(node.value):
                    s = const_str(sub)
                    if s is not None and s.startswith("/"):
                        out.append((sf, sub.lineno, s))
    return out


def collect_armed_points(test_files, doc_paths
                         ) -> List[Tuple[str, int, str]]:
    """Fault points armed in tests (dict literals with a "point" key)
    and in DLI_FAULTS examples in the docs. Returns (rel, line, point)
    — rel is repo-relative for the report."""
    out = []
    for sf in test_files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Dict):
                continue
            for k, v in zip(node.keys, node.values):
                if k is not None and const_str(k) == "point":
                    p = const_str(v)
                    if p:
                        out.append((sf.rel, v.lineno, p))
    for path in doc_paths:
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            continue
        rel = os.path.basename(os.path.dirname(path)) + "/" + \
            os.path.basename(path)
        for i, line in enumerate(text.splitlines(), 1):
            for m in _DOC_POINT_RE.finditer(line):
                out.append((rel, i, m.group(1)))
    return out


def collect_rpc_fault_sites(files) -> Set[str]:
    """Literals passed to ``_rpc_fault("<path>")`` — each is a live
    client-side intercept point ``rpc:<path>``."""
    sites: Set[str] = set()
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                if name.rsplit(".", 1)[-1] == "_rpc_fault" and node.args:
                    s = const_str(node.args[0])
                    if s:
                        sites.add("rpc:" + s)
    return sites


# ---- body-key analysis ------------------------------------------------

def _func_index(files) -> Dict[str, ast.AST]:
    """name -> FunctionDef for every function/method in the scanned
    files (methods indexed by bare name; the protocol surface has no
    colliding handler names across services that read bodies
    differently enough to matter — collisions mark the entry None and
    the checker skips, never guesses)."""
    idx: Dict[str, ast.AST] = {}
    dupes: Set[str] = set()
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if node.name in idx:
                    dupes.add(node.name)
                idx[node.name] = node
    for d in dupes:
        idx.pop(d, None)
    return idx


def built_keys(fn_node: ast.AST, var: Optional[str] = None
               ) -> Tuple[Set[str], bool]:
    """Literal keys a function assembles into the dict it returns (or
    into local ``var``): dict literals, ``x["k"] = ``, ``x.update(...)``
    with literal keys/kwargs, ``x.setdefault("k", ...)``. Returns
    (keys, complete) — complete=False when a ``**`` splat or an
    unresolvable update makes the set open."""
    keys: Set[str] = set()
    complete = True
    names = {var} if var else None

    def dict_keys(d: ast.Dict):
        nonlocal complete
        for k in d.keys:
            if k is None:
                complete = False
                continue
            s = const_str(k)
            if s is None:
                complete = False
            else:
                keys.add(s)

    for node in ast.walk(fn_node):
        if isinstance(node, ast.Return) and isinstance(node.value,
                                                       ast.Dict):
            dict_keys(node.value)
        elif isinstance(node, ast.Assign) and isinstance(node.value,
                                                         ast.Dict):
            for t in node.targets:
                if isinstance(t, ast.Name) and (names is None
                                                or t.id in names):
                    dict_keys(node.value)
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if (isinstance(t, ast.Subscript)
                        and isinstance(t.value, ast.Name)
                        and (names is None or t.value.id in names)):
                    s = const_str(t.slice)
                    if s is None:
                        complete = False
                    else:
                        keys.add(s)
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            base, _, attr = name.rpartition(".")
            if attr in ("update", "setdefault") and (
                    names is None or base in names):
                for kw in node.keywords:
                    if kw.arg is None:
                        complete = False
                    else:
                        keys.add(kw.arg)
                for a in node.args:
                    if isinstance(a, ast.Dict):
                        dict_keys(a)
                    elif attr == "setdefault" and const_str(a):
                        keys.add(const_str(a))
                        break
                    else:
                        complete = False
    return keys, complete


def resolve_body_keys(site: CallSite, funcs: Dict[str, ast.AST]
                      ) -> Tuple[Set[str], bool]:
    """Keys of a POST site's body expression. (keys, known)."""
    b = site.body
    if b is None:
        return set(), False
    if isinstance(b, ast.Dict):
        keys: Set[str] = set()
        for k in b.keys:
            s = const_str(k) if k is not None else None
            if s is None:
                return keys, False
            keys.add(s)
        return keys, True
    if isinstance(b, ast.Call):
        name = (dotted_name(b.func) or "").rsplit(".", 1)[-1]
        fn = funcs.get(name)
        if fn is not None:
            return built_keys(fn)
        return set(), False
    if isinstance(b, ast.Name) and site.fn is not None:
        keys, complete = built_keys(site.fn, var=b.id)
        # the var may have been seeded from a builder method:
        #   body = self._infer_body(req); body.update(...)
        for node in ast.walk(site.fn):
            if (isinstance(node, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == b.id
                            for t in node.targets)
                    and isinstance(node.value, ast.Call)):
                name = (dotted_name(node.value.func)
                        or "").rsplit(".", 1)[-1]
                fn = funcs.get(name)
                if fn is not None:
                    k2, c2 = built_keys(fn)
                    keys |= k2
                    complete = complete and c2
                elif name != "dict":
                    complete = False
        return keys, complete
    return set(), False


def handler_read_keys(handler: ast.AST, funcs: Dict[str, ast.AST],
                      depth: int = 4) -> Tuple[Set[str], bool]:
    """Literal body keys the handler reads, following the body object
    through same-module helper calls (``self._do_load(body)``,
    ``dict(body)`` copies, renames) up to ``depth`` hops. Returns
    (keys, complete): complete=False when the body escapes into code we
    can't see (the checker then skips unread-key reasoning)."""
    keys: Set[str] = set()
    complete = True
    seen: Set[str] = set()

    def body_param(fn: ast.AST) -> Optional[str]:
        args = [a.arg for a in fn.args.args if a.arg != "self"]
        return args[0] if args else None

    def walk_fn(fn: ast.AST, var: str, hops: int):
        nonlocal complete
        if fn.name in seen:
            return
        seen.add(fn.name)
        aliases = {var}
        for node in ast.walk(fn):
            # aliases: x = body / x = dict(body)
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                v = node.value
                if isinstance(v, ast.Name) and v.id in aliases:
                    aliases.add(node.targets[0].id)
                elif (isinstance(v, ast.Call)
                      and (dotted_name(v.func) or "") == "dict"
                      and v.args and isinstance(v.args[0], ast.Name)
                      and v.args[0].id in aliases):
                    aliases.add(node.targets[0].id)
        for node in ast.walk(fn):
            if isinstance(node, ast.Subscript) and \
                    isinstance(node.value, ast.Name) and \
                    node.value.id in aliases:
                s = const_str(node.slice)
                if s is not None:
                    keys.add(s)
            elif isinstance(node, ast.Compare) and node.ops and \
                    isinstance(node.ops[0], (ast.In, ast.NotIn)) and \
                    isinstance(node.comparators[0], ast.Name) and \
                    node.comparators[0].id in aliases:
                s = const_str(node.left)
                if s is not None:
                    keys.add(s)
            elif isinstance(node, ast.Call):
                name = dotted_name(node.func) or ""
                base, _, attr = name.rpartition(".")
                if not base and isinstance(node.func, ast.Attribute):
                    # the `(body or {}).get("k")` defensive idiom
                    recv = node.func.value
                    if isinstance(recv, ast.BoolOp):
                        for v in recv.values:
                            if isinstance(v, ast.Name) and \
                                    v.id in aliases:
                                base, attr = v.id, node.func.attr
                                break
                if base in aliases and attr in ("get", "pop",
                                                "setdefault"):
                    s = const_str(node.args[0]) if node.args else None
                    if s is not None:
                        keys.add(s)
                    continue
                # propagation: body handed to another callable
                passed = [
                    i for i, a in enumerate(node.args)
                    if (isinstance(a, ast.Name) and a.id in aliases)
                    or (isinstance(a, ast.Call)
                        and (dotted_name(a.func) or "") == "dict"
                        and a.args and isinstance(a.args[0], ast.Name)
                        and a.args[0].id in aliases)]
                if not passed:
                    continue
                callee_name = name.rsplit(".", 1)[-1]
                callee = funcs.get(callee_name)
                if callee_name == "dict" or base in aliases:
                    continue
                if callee is None or hops <= 0:
                    complete = False
                    continue
                callee_args = [a.arg for a in callee.args.args
                               if a.arg != "self"]
                idx = passed[0]
                if idx < len(callee_args):
                    walk_fn(callee, callee_args[idx], hops - 1)
                else:
                    complete = False

    var = body_param(handler)
    if var is None:
        return keys, False
    walk_fn(handler, var, depth)
    return keys, complete


# ---- the checker ------------------------------------------------------

def check(ctx: Ctx) -> List[Violation]:
    out: List[Violation] = []
    pkg = ctx.package_files
    routes = collect_routes(pkg)
    test_routes = collect_routes(ctx.test_files)
    static_routes = [r for r in routes if r.segs is not None]
    match_routes = static_routes + [r for r in test_routes
                                    if r.segs is not None]

    def find(segs) -> Tuple[bool, Set[str]]:
        methods: Set[str] = set()
        for r in match_routes:
            if _segs_match(segs, r.segs):
                methods.add(r.method)
        return bool(methods), methods

    # -- call sites vs routes ------------------------------------------
    rpc_calls = collect_calls(pkg)
    ext_calls = collect_calls(ctx.gate_files + ctx.test_files)
    for c in rpc_calls + ext_calls:
        known, methods = find(c.segs)
        if not known:
            out.append(Violation(
                "rpc-unknown-path", c.sf.rel, c.line,
                f"{c.method} {c.path}: no service registers this path"))
        elif c.method and c.method not in methods:
            out.append(Violation(
                "rpc-method-mismatch", c.sf.rel, c.line,
                f"{c.method} {c.path}: path is registered under "
                f"{'/'.join(sorted(methods))} only"))

    # -- dead routes ----------------------------------------------------
    refs: Set[Tuple[str, ...]] = set()
    for c in rpc_calls + ext_calls:
        refs.add(c.segs)
    text_sources: List[str] = []
    for p in list(ctx.doc_paths) + list(ctx.shell_paths):
        try:
            with open(p, encoding="utf-8") as f:
                text_sources.append(f.read())
        except OSError:
            pass
    if ctx.dashboard_file is not None:
        text_sources.append(ctx.dashboard_file.text)
    for text in text_sources:
        refs |= text_path_refs(text)
    for r in static_routes:
        if r.segs == ():       # the dashboard root page
            continue
        if any(_segs_match(r.segs, ref) for ref in refs):
            continue
        out.append(Violation(
            "rpc-dead-route", r.sf.rel, r.line,
            f"{r.method} {r.pattern}: no caller, test, bench, script, "
            "dashboard page, or doc reaches this route"))

    # -- quiet open-set -------------------------------------------------
    for sf, line, path in collect_quiet_set(pkg):
        c = canon(path)
        if c is None or not find(c)[0]:
            out.append(Violation(
                "rpc-quiet-unknown", sf.rel, line,
                f"QUIET_TRACE_PATHS entry {path!r} matches no "
                "registered route"))

    # -- fault points ----------------------------------------------------
    intercepts: Set[str] = {r.pattern for r in static_routes}
    intercepts |= {"rpc:" + c.path for c in rpc_calls}
    intercepts |= collect_rpc_fault_sites(pkg)
    for rel, line, point in collect_armed_points(ctx.test_files,
                                                 ctx.doc_paths):
        if any(fnmatch.fnmatchcase(site, point) for site in intercepts):
            continue
        out.append(Violation(
            "rpc-fault-unknown", rel, line,
            f"fault point {point!r} matches no live intercept site "
            "(route path or rpc:<path> client point)"))

    # -- body keys -------------------------------------------------------
    funcs = _func_index(pkg)
    handler_reads: Dict[Tuple[str, ...], Tuple[Set[str], bool, RouteDef]] = {}
    for r in static_routes:
        if r.method != "POST" or r.handler is None:
            continue
        h = funcs.get(r.handler.rsplit(".", 1)[-1])
        if h is None:
            continue
        keys, complete = handler_read_keys(h, funcs)
        prev = handler_reads.get(r.segs)
        if prev is not None:
            keys = keys | prev[0]
            complete = complete and prev[1]
        handler_reads[r.segs] = (keys, complete, r)

    mentions: Set[str] = set()
    for sf in list(ctx.test_files) + list(ctx.gate_files):
        mentions |= set(_WORD_RE.findall(sf.text))
    for text in text_sources:
        mentions |= set(_WORD_RE.findall(text))
    # package files count as protocol users too — a key the master
    # forwards by name (api_deploy_plan's tokenizer_path relay) is
    # sent, even though the relayed body itself is dynamic. Kept
    # per-file so a handler's OWN file never vouches for its reads.
    pkg_words: Dict[str, Set[str]] = {
        sf.rel: set(_WORD_RE.findall(sf.text)) for sf in pkg}

    sent_by_path: Dict[Tuple[str, ...], Set[str]] = {}
    for c in rpc_calls:
        if c.method != "POST" or c.body is None:
            continue
        entry = handler_reads.get(
            next((segs for segs in handler_reads
                  if _segs_match(c.segs, segs)), c.segs))
        keys, known = resolve_body_keys(c, funcs)
        if known:
            sent_by_path.setdefault(c.segs, set()).update(keys)
        if entry is None:
            continue
        reads, complete, _r = entry
        if known and complete:
            for k in sorted(keys - reads):
                out.append(Violation(
                    "rpc-body-unread", c.sf.rel, c.line,
                    f"POST {c.path}: body key {k!r} is sent but the "
                    "handler (and its helpers) never reads it"))

    for segs, (reads, complete, r) in sorted(handler_reads.items()):
        senders = set()
        for ssegs, keys in sent_by_path.items():
            if _segs_match(segs, ssegs):
                senders |= keys
        for rel, words in pkg_words.items():
            if rel != r.sf.rel:
                senders |= words
        for k in sorted(reads - senders - mentions):
            out.append(Violation(
                "rpc-body-unsent", r.sf.rel, r.line,
                f"POST {r.pattern}: handler reads body key {k!r} but "
                "no caller, test, bench, or doc ever mentions it"))

    files = {sf.rel: sf for sf in list(pkg) + list(ctx.test_files)
             + list(ctx.gate_files)}
    return filter_suppressed(out, files)
