"""Time hygiene: the runtime reads the clock only through the seam.

``utils/clock.py`` is the runtime's single source of time — the
interposition that lets tools/dlisim drive the real control plane
(scheduler, breaker, store, TSDB bucketing, lease monitor) on a virtual
clock, hours of cluster time in milliseconds, every timer deterministic.
One bare ``time.time()`` anywhere in ``runtime/`` punches a hole in
that seam: the simulator's timeline and the punched site's timeline
diverge silently, and the byte-identical-journal reproducibility gate
(tests/test_dlisim.py) rots into flakiness nobody can bisect.

- ``time-direct`` — a direct use of ``time.time``, ``time.monotonic``
  or ``time.sleep`` (called, referenced as a value, or imported via
  ``from time import ...``) inside a ``runtime/`` module. Use
  ``clock.now()`` / ``clock.monotonic()`` / ``clock.sleep()`` /
  ``clock.deadline()`` instead. ``time.perf_counter`` and
  ``time.time_ns`` stay legal: profiler deltas and RNG seeds measure
  the host, not the cluster timeline, and the simulator must not warp
  them. A reviewed exception (none exist today) carries
  ``# dlilint: disable=time-direct``.
"""

from __future__ import annotations

import ast
from typing import List

from .core import Ctx, Violation, dotted_name, filter_suppressed

RULES = ("time-direct",)

#: the seam-covered names; everything else on the time module is host
#: measurement (perf_counter, time_ns, strftime) and stays direct
_SEAMED = ("time", "monotonic", "sleep")


def check(ctx: Ctx) -> List[Violation]:
    violations: List[Violation] = []
    for sf in ctx.runtime_files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Attribute):
                # catches calls AND bare references (a
                # ``default_factory=time.time`` stamps rows just as
                # directly as a call does)
                if (dotted_name(node) or "") in \
                        tuple(f"time.{n}" for n in _SEAMED):
                    violations.append(Violation(
                        "time-direct", sf.rel, node.lineno,
                        f"direct `{dotted_name(node)}` in runtime/ "
                        f"bypasses the utils/clock.py seam — use "
                        f"`clock.{_seam_name(node.attr)}` so the "
                        f"simulator's virtual clock reaches this site"))
            elif isinstance(node, ast.ImportFrom) and node.module == "time":
                for alias in node.names:
                    if alias.name in _SEAMED:
                        violations.append(Violation(
                            "time-direct", sf.rel, node.lineno,
                            f"`from time import {alias.name}` in "
                            f"runtime/ bypasses the utils/clock.py "
                            f"seam — import utils.clock instead"))
    files = {sf.rel: sf for sf in ctx.runtime_files}
    return filter_suppressed(violations, files)


def _seam_name(attr: str) -> str:
    return {"time": "now()", "monotonic": "monotonic()",
            "sleep": "sleep()"}[attr]
