"""Thread hygiene: static lock-order graph + silent exception swallows.

The runtime holds 20+ locks across master/state/batcher/kvtier/tsdb/
worker, and every new background loop (telemetry scrape, group-commit
flusher, disagg threads — and next the AMP planner and live-migration
movers) threads through several of them. Two rules:

- ``lock-order-cycle`` — build the static acquisition graph: nodes are
  ``Class.attr`` lock attributes (``self._x = threading.Lock()`` or the
  ``utils.locks`` factories), edges ``A -> B`` when a ``with self._b:``
  (or a call to a method that takes it) appears inside a
  ``with self._a:`` body. Calls are followed one level: ``self.m()``
  into same-class methods, ``self.obj.m()`` into the class assigned to
  ``self.obj`` in ``__init__`` when resolvable. A cycle fails the
  build. The dynamic twin of this rule is ``utils/locks.py``
  (``DLI_LOCK_CHECK=1``), armed during the chaos suite.
- ``silent-except`` — an ``except``/``except Exception`` whose body is
  only ``pass`` inside the runtime modules swallows faults from
  scheduler/dispatcher/flusher threads with no trace. Log at least at
  warning level, or carry a ``# dlilint: disable=silent-except`` pragma
  with a justifying comment.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from .core import Ctx, SourceFile, Violation, dotted_name, filter_suppressed

RULES = ("lock-order-cycle", "silent-except")

_LOCK_CTORS = ("threading.Lock", "threading.RLock", "threading.Condition",
               "Lock", "RLock", "Condition",
               "locks.lock", "locks.rlock", "locks.condition")


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    dn = dotted_name(node.func) or ""
    return dn in _LOCK_CTORS or dn.endswith((".locks.lock", ".locks.rlock",
                                             ".locks.condition"))


class _ClassInfo:
    def __init__(self, name: str, sf: SourceFile, node: ast.ClassDef):
        self.name = name
        self.sf = sf
        self.node = node
        self.lock_attrs: Set[str] = set()
        self.attr_types: Dict[str, str] = {}    # self.x = ClassName(...)
        self.methods: Dict[str, ast.AST] = {}
        # method -> self-lock attrs it acquires anywhere in its body
        self.acquires: Dict[str, Set[str]] = {}


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"):
        return node.attr
    return None


def _collect_classes(files) -> Dict[str, _ClassInfo]:
    classes: Dict[str, _ClassInfo] = {}
    for sf in files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            ci = _ClassInfo(node.name, sf, node)
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    ci.methods[item.name] = item
            for meth in ci.methods.values():
                for sub in ast.walk(meth):
                    if isinstance(sub, ast.Assign) and len(sub.targets) == 1:
                        attr = _self_attr(sub.targets[0])
                        if attr is None:
                            continue
                        if _is_lock_ctor(sub.value):
                            ci.lock_attrs.add(attr)
                        elif isinstance(sub.value, ast.Call):
                            dn = dotted_name(sub.value.func)
                            if dn and dn[0].isupper():
                                ci.attr_types[attr] = dn.split(".")[-1]
            classes[ci.name] = ci
    for ci in classes.values():
        for mname, meth in ci.methods.items():
            acq = set()
            for sub in ast.walk(meth):
                if isinstance(sub, ast.With):
                    for item in sub.items:
                        attr = _self_attr(item.context_expr)
                        if attr in ci.lock_attrs:
                            acq.add(attr)
            ci.acquires[mname] = acq
    return classes


def _build_edges(classes: Dict[str, _ClassInfo]
                 ) -> Dict[Tuple[str, str], List]:
    """(A, B) -> [witness (file, line), ...] where B acquired under A."""
    edges: Dict[Tuple[str, str], List] = {}

    def note(a: str, b: str, sf: SourceFile, line: int):
        if a != b:
            edges.setdefault((a, b), []).append((sf.rel, line))

    for ci in classes.values():
        for meth in ci.methods.values():
            _walk_held(ci, meth, [], classes, note)
    return edges


def _walk_held(ci: _ClassInfo, node: ast.AST, held: List[str],
               classes, note):
    for child in ast.iter_child_nodes(node):
        if isinstance(child, ast.With):
            acquired = []
            for item in child.items:
                attr = _self_attr(item.context_expr)
                if attr in ci.lock_attrs:
                    name = f"{ci.name}.{attr}"
                    for h in held:
                        note(h, name, ci.sf, item.context_expr.lineno)
                    acquired.append(name)
            held.extend(acquired)
            _walk_held(ci, child, held, classes, note)
            del held[len(held) - len(acquired):]
        elif isinstance(child, ast.Call) and held:
            f = child.func
            # self.m() -> same-class method's acquisitions
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name) and f.value.id == "self":
                for attr in ci.acquires.get(f.attr, ()):
                    for h in held:
                        note(h, f"{ci.name}.{attr}", ci.sf, child.lineno)
                # self.obj.m() -> the attr's class, when its ctor was seen
            elif isinstance(f, ast.Attribute):
                oattr = _self_attr(f.value)
                if oattr is not None:
                    tcls = classes.get(ci.attr_types.get(oattr, ""))
                    if tcls is not None:
                        for attr in tcls.acquires.get(f.attr, ()):
                            for h in held:
                                note(h, f"{tcls.name}.{attr}",
                                     ci.sf, child.lineno)
            _walk_held(ci, child, held, classes, note)
        elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
            # nested defs run later, not under the current hold
            _walk_held(ci, child, [], classes, note)
        else:
            _walk_held(ci, child, held, classes, note)


def _find_cycles(edges: Dict[Tuple[str, str], List]) -> List[List[str]]:
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
    cycles, seen_keys = [], set()
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in sorted(graph.get(node, ())):
                if nxt == start:
                    cyc = path + [start]
                    key = frozenset(cyc)
                    if key not in seen_keys:
                        seen_keys.add(key)
                        cycles.append(cyc)
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))
    return cycles


def check(ctx: Ctx) -> List[Violation]:
    violations: List[Violation] = []
    files = {sf.rel: sf for sf in ctx.package_files}

    # ---- silent-except (runtime modules only) -------------------------
    for sf in ctx.runtime_files:
        if sf.tree is None:
            continue
        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or (
                isinstance(node.type, ast.Name)
                and node.type.id in ("Exception", "BaseException"))
            if broad and len(node.body) == 1 \
                    and isinstance(node.body[0], ast.Pass):
                violations.append(Violation(
                    "silent-except", sf.rel, node.lineno,
                    "bare `except Exception: pass` swallows faults from "
                    "runtime threads silently — log at warning (or carry "
                    "a justifying pragma)"))

    # ---- static lock-order graph --------------------------------------
    classes = _collect_classes(ctx.package_files)
    edges = _build_edges(classes)
    for cyc in _find_cycles(edges):
        a, b = cyc[0], cyc[1]
        rel, line = edges[(a, b)][0]
        violations.append(Violation(
            "lock-order-cycle", rel, line,
            "static lock-acquisition cycle: " + " -> ".join(cyc)))

    return filter_suppressed(violations, files)
