"""dliverify — an exhaustive-interleaving model checker for the
control plane's concurrency code.

The chaos suite (PR 2) exercises the breaker/idempotency/drain/claim
machinery under *some* interleavings — whichever the OS scheduler
happens to produce. dliverify removes the luck: the ``utils/locks.py``
factories (the narrow waist every runtime lock is born through, PR 9)
are interposed with scheduler-gated wrappers, the scenario's threads
are serialized so exactly one runs at a time, and a DFS explorer
enumerates every order in which the threads can pass their lock-
acquisition points — running the REAL master/worker/store code, not a
model of it — asserting machine-checked invariants after every step:

- ``single_claim``            no request claimed by two dispatchers
- ``single_terminal``         a terminal status, once observed, never
                              changes (no completed<->failed flip)
- ``half_open_single_probe``  a half-open breaker admits exactly one
                              in-flight probe
- ``inflight_nonnegative``    the master's per-node in-flight counts
                              never go negative
- ``tag_exactly_once``        one request_tag executes exactly once
                              (idempotent claim/join/replay)
- ``no_strand_on_drain``      drain never reports idle while an
                              admitted request is still running
- ``exclusion_honored``       a connection-faulted node is not
                              re-picked while an alternative exists

Granularity and soundness: threads yield at every runtime-lock
acquisition (and at explicit scenario markers); a step runs from one
yield point to the next. Sleep-set pruning (DPOR-style) skips
re-exploring orders of adjacent steps whose decision points touch
different locks — sound exactly when cross-thread shared state is
lock-protected, which is the discipline PR 9's checkers enforce; run
with ``prune=False`` for the unreduced tree. Unregistered threads
(store flushers, pool workers) pass through the instrumented locks
untouched and never create decision points, so schedule counts are
deterministic and reproducible.

Run: ``python -m tools.dliverify`` (exit 0 = every scenario explored
with zero violations). ``--mutate <name>`` re-arms a historical bug
(utils/faults.py MUTATIONS) and expects a counterexample — the
mutation gate proving the explorer can actually catch regressions.
Full docs: docs/static_analysis.md.
"""

from .sched import (Explorer, ExplorationResult, Scheduler,  # noqa: F401
                    Violation)
from .scenarios import SCENARIOS  # noqa: F401
