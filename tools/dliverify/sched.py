"""Deterministic cooperative scheduler + DFS schedule explorer.

The scheduler serializes a scenario's registered threads: each runs
until its next *yield point* (a runtime-lock acquisition through the
interposed ``utils/locks.py`` factories), then parks; the explorer
decides who runs next. One (prefix-replayed) run of the scenario = one
schedule; the explorer enumerates schedules breadth-first over the
divergence depth and re-runs the scenario from scratch per schedule
(stateless model checking — no snapshotting, the real code really
executes, and a found counterexample diverges from the default
schedule as early as possible).

Steps are coarse — run-to-next-lock-acquisition — so the default
exploration is the FULL tree (that is what "exhaustive" means in the
CI gate). The optional DPOR-style sleep-set pruning treats two pending
acquisitions of different lock roles as independent; that is sound
exactly when cross-thread shared state is lock-protected (the
discipline PR 9's checkers enforce) and is therefore offered as an
accelerator (``prune=True``), not the gate default.

Threads NOT spawned through ``Scheduler.spawn`` (group-commit
flushers, pool executors) acquire the instrumented locks directly and
never create decision points — they are environment, not model, and
schedule counts stay deterministic.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from distributed_llm_inferencing_tpu.utils import locks as locks_mod

# How long the explorer waits for the running thread to reach its next
# yield point before declaring the schedule hung. Generous: a step may
# legitimately block on environment threads (a group-commit barrier
# waits out a flush cycle).
_STEP_TIMEOUT_S = 30.0


@dataclass
class Violation:
    invariant: str
    detail: str
    schedule: Tuple[int, ...]
    trace: List[str]

    def render(self) -> str:
        lines = [f"INVARIANT VIOLATED: {self.invariant}",
                 f"  {self.detail}",
                 f"  schedule choices: {list(self.schedule)}",
                 "  counterexample trace (thread-step order):"]
        for i, step in enumerate(self.trace):
            lines.append(f"    {i:3d}. {step}")
        return "\n".join(lines)


@dataclass
class ExplorationResult:
    scenario: str
    schedules: int
    complete: bool           # False = stopped early (budget/violation)
    violation: Optional[Violation]
    hung: Optional[str]      # hang/deadlock description, if any
    elapsed_s: float
    decision_points: int     # max decision depth seen


class _ThreadState:
    __slots__ = ("name", "go", "parked", "action", "pending", "thread",
                 "done", "error", "held")

    def __init__(self, name: str):
        self.name = name
        self.go = threading.Event()
        self.parked = threading.Event()
        self.action: Tuple[str, Optional[str]] = ("start", None)
        self.pending: Optional["SchedLock"] = None
        self.thread: Optional[threading.Thread] = None
        self.done = False
        self.error: Optional[BaseException] = None
        self.held: List["SchedLock"] = []


class SchedLock:
    """Scheduler-gated lock handed out by the interposed factory.
    Registered threads park at blocking ``acquire`` (a decision
    point); unregistered threads use the underlying primitive
    directly. Quacks enough like a Lock for ``with``, the Condition
    fallback protocol, and non-blocking probes."""

    __slots__ = ("name", "_sched", "_reentrant", "_lk", "_owner",
                 "_count")

    def __init__(self, sched: "Scheduler", kind: str, name: str):
        self.name = name
        self._sched = sched
        self._reentrant = kind == "rlock"
        self._lk = (threading.RLock() if self._reentrant
                    else threading.Lock())
        self._owner: Optional[_ThreadState] = None
        self._count = 0

    def acquire(self, blocking: bool = True, timeout: float = -1):
        t = self._sched._current()
        if t is None or not blocking:
            got = self._lk.acquire(blocking, timeout)
            if got and t is not None:
                self._note_acquired(t)
            return got
        if self._reentrant and self._owner is t:
            # immediately grantable: not a branching point, so skipping
            # the park keeps the schedule tree at real decisions only
            self._lk.acquire()
            self._count += 1
            return True
        self._sched._yield_point(t, ("acquire", self.name), self)
        self._lk.acquire()
        self._note_acquired(t)
        return True

    def _note_acquired(self, t: _ThreadState):
        self._owner = t
        self._count += 1
        t.held.append(self)

    def release(self):
        t = self._sched._current()
        if t is not None and self._owner is t:
            self._count -= 1
            if self._count == 0:
                self._owner = None
            for i in range(len(t.held) - 1, -1, -1):
                if t.held[i] is self:
                    del t.held[i]
                    break
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        fn = getattr(self._lk, "locked", None)
        return fn() if fn is not None else self._owner is not None

    def __repr__(self):
        return f"<dliverify.SchedLock {self.name!r}>"


class Scheduler:
    """One scenario run under one prescribed choice prefix."""

    def __init__(self, choices: Tuple[int, ...] = ()):
        self._threads: List[_ThreadState] = []
        self._by_ident: Dict[int, _ThreadState] = {}
        self._choices = choices
        self.decisions: List[Tuple[int, int]] = []  # (n_enabled, chosen)
        self.enabled_log: List[List[Tuple[str, str, Optional[str]]]] = []
        self.trace: List[str] = []
        self.hung = False

    # ---- scenario-facing API -----------------------------------------

    def spawn(self, name: str, fn: Callable, *args, **kwargs):
        t = _ThreadState(name)

        def run():
            self._by_ident[threading.get_ident()] = t
            t.parked.set()          # parked at ("start", None)
            t.go.wait()
            t.go.clear()
            self.trace.append(f"{t.name}: start")
            try:
                fn(*args, **kwargs)
            except BaseException as e:     # surfaced by the explorer
                t.error = e
            finally:
                t.done = True
                t.parked.set()

        t.thread = threading.Thread(target=run, daemon=True,
                                    name=f"dliverify-{name}")
        self._threads.append(t)
        return t

    def mark(self, label: str):
        """Trace annotation from inside a scenario thread (NOT a yield
        point — it rides the current step)."""
        t = self._current()
        if t is not None:
            self.trace.append(f"{t.name}: {label}")

    def lock_factory(self, kind: str, name: str):
        return SchedLock(self, kind, name)

    # ---- internals ----------------------------------------------------

    def _current(self) -> Optional[_ThreadState]:
        return self._by_ident.get(threading.get_ident())

    def _yield_point(self, t: _ThreadState, action, lock: "SchedLock"):
        t.action = action
        t.pending = lock
        t.parked.set()
        t.go.wait()
        t.go.clear()
        t.pending = None
        self.trace.append(f"{t.name}: {action[0]} {action[1] or ''}"
                          .rstrip())

    def _runnable(self) -> List[_ThreadState]:
        out = []
        for t in self._threads:
            if t.done or not t.parked.is_set():
                continue
            lk = t.pending
            if lk is not None:
                owner = lk._owner
                if owner is not None:
                    # held by another registered thread — not runnable;
                    # held by t itself (non-reentrant re-acquire) — a
                    # guaranteed self-deadlock, also not runnable, and
                    # reported as a deadlock when nothing else can move
                    continue
            out.append(t)
        return out

    def run(self, step_hook: Optional[Callable[[], bool]] = None
            ) -> Optional[str]:
        """Drive the spawned threads to completion under the choice
        prefix (index 0 past the prefix). Returns an error string on
        hang/deadlock or a thread exception; ``step_hook`` returning
        True stops the run (an invariant fired mid-schedule)."""
        for t in self._threads:
            t.thread.start()
        for t in self._threads:
            if not t.parked.wait(_STEP_TIMEOUT_S):
                self.hung = True
                return f"thread {t.name} never reached its start point"
        depth = 0
        while True:
            live = [t for t in self._threads if not t.done]
            if not live:
                break
            runnable = self._runnable()
            if not runnable:
                self.hung = True
                return "deadlock: " + ", ".join(
                    f"{t.name} waiting on {t.action[1]}" for t in live)
            if len(runnable) > 1:
                chosen = (self._choices[depth]
                          if depth < len(self._choices) else 0)
                if chosen >= len(runnable):
                    # replay divergence: the parent run saw more enabled
                    # threads at this depth than this run does — the
                    # scenario is nondeterministic. Fail LOUDLY rather
                    # than clamp onto a different schedule and let the
                    # gate report a tree it never actually explored.
                    self.hung = True
                    return (f"replay diverged at decision {depth}: "
                            f"prescribed choice {chosen} but only "
                            f"{len(runnable)} thread(s) enabled — "
                            "scenario is nondeterministic")
                self.decisions.append((len(runnable), chosen))
                self.enabled_log.append(
                    [(x.name, x.action[0], x.action[1])
                     for x in runnable])
                depth += 1
            else:
                chosen = 0
            t = runnable[chosen]
            t.parked.clear()
            t.go.set()
            if not t.parked.wait(_STEP_TIMEOUT_S):
                self.hung = True
                return (f"schedule hung: {t.name} neither parked nor "
                        "finished within the step timeout")
            if step_hook is not None and step_hook():
                return None     # invariant violation captured by caller
        for t in self._threads:
            if t.error is not None:
                return (f"thread {t.name} raised "
                        f"{type(t.error).__name__}: {t.error}")
        return None


def _independent(a: Tuple[str, str, Optional[str]],
                 b: Tuple[str, str, Optional[str]]) -> bool:
    """Heuristic commutativity for the optional pruning: two pending
    decisions commute when both are lock acquisitions on DIFFERENT
    lock roles. Anything else (thread starts, same lock) is dependent."""
    _, ka, na = a
    _, kb, nb = b
    return (ka == "acquire" and kb == "acquire"
            and na is not None and nb is not None and na != nb)


@dataclass
class RunOutcome:
    decisions: List[Tuple[int, int]]
    enabled: List[List[Tuple[str, str, Optional[str]]]]
    violation: Optional[Violation]
    hung: bool = False
    error: Optional[str] = None
    trace: List[str] = field(default_factory=list)


class Explorer:
    """Stateless BFS/DFS over the schedule tree. ``make_run`` executes
    one schedule from scratch and reports its decision points."""

    def __init__(self, make_run: Callable[[Tuple[int, ...]], RunOutcome],
                 budget_s: float = 20.0, max_schedules: int = 100000,
                 prune: bool = False):
        self._make_run = make_run
        self._budget_s = budget_s
        self._max = max_schedules
        self._prune = prune

    def explore(self, scenario_name: str) -> ExplorationResult:
        t0 = time.monotonic()
        frontier: List[Tuple[int, ...]] = [()]
        schedules = 0
        max_depth = 0
        while frontier:
            if time.monotonic() - t0 > self._budget_s or \
                    schedules >= self._max:
                return ExplorationResult(
                    scenario_name, schedules, False, None, None,
                    time.monotonic() - t0, max_depth)
            prefix = frontier.pop(0)
            outcome = self._make_run(prefix)
            schedules += 1
            max_depth = max(max_depth, len(outcome.decisions))
            if outcome.hung:
                return ExplorationResult(
                    scenario_name, schedules, False, None,
                    outcome.error or "hang", time.monotonic() - t0,
                    max_depth)
            if outcome.violation is not None:
                outcome.violation.schedule = tuple(
                    c for _n, c in outcome.decisions)
                return ExplorationResult(
                    scenario_name, schedules, False, outcome.violation,
                    None, time.monotonic() - t0, max_depth)
            chosen = [c for _n, c in outcome.decisions]
            for d in range(len(outcome.decisions) - 1,
                           len(prefix) - 1, -1):
                n, _c = outcome.decisions[d]
                enabled = outcome.enabled[d]
                for alt in range(1, n):
                    if self._prune and all(
                            _independent(enabled[alt], enabled[j])
                            for j in range(alt)):
                        continue
                    frontier.append(tuple(chosen[:d]) + (alt,))
        return ExplorationResult(scenario_name, schedules, True, None,
                                 None, time.monotonic() - t0, max_depth)


def run_scenario_once(scenario, prefix: Tuple[int, ...]) -> RunOutcome:
    """Build a fresh Scheduler, interpose the locks factories, run the
    scenario from scratch under ``prefix``, check its invariants."""
    sched = Scheduler(choices=prefix)
    prev = locks_mod.set_factory_hook(sched.lock_factory)
    ctx = None
    step_bad: List[Tuple[str, str]] = []

    def hook() -> bool:
        bad = scenario.check_step(ctx)
        if bad is not None:
            step_bad.append(bad)
            return True
        return False

    try:
        ctx = scenario.build(sched)
        err = sched.run(step_hook=hook)
        violation = None
        if step_bad:
            inv, detail = step_bad[0]
            violation = Violation(inv, detail, prefix,
                                  list(sched.trace))
        elif err is not None and not sched.hung:
            violation = Violation("scenario-error", err, prefix,
                                  list(sched.trace))
        elif not sched.hung:
            bad = scenario.check_final(ctx)
            if bad is not None:
                inv, detail = bad
                violation = Violation(inv, detail, prefix,
                                      list(sched.trace))
        return RunOutcome(sched.decisions, sched.enabled_log, violation,
                          hung=sched.hung, error=err,
                          trace=list(sched.trace))
    finally:
        locks_mod.set_factory_hook(prev)
        if ctx is not None:
            try:
                scenario.cleanup(ctx)
            except Exception:
                pass
