"""Bounded scenarios the explorer enumerates — each drives the REAL
control-plane code (``runtime/master.py`` / ``runtime/worker.py`` /
``runtime/state.py``), not a model of it.

Determinism rules every scenario obeys:

- registered threads touch shared state only through code whose yield
  points are runtime-lock acquisitions (the interposed factories);
- no registered thread takes a branch on wall-clock or RNG state that
  changes its *lock-acquisition sequence* (backoff bases are pinned to
  0, claim delays to 0);
- master scenarios that exercise buffered status writes swap the
  group-commit store for a synchronous one (``group_commit=False``) —
  the write-behind flusher is an environment thread whose timing would
  otherwise make decision-point counts racy. The REAL requeue/claim/
  terminal SQL still runs; only the delivery is synchronous (the
  barrier semantics themselves are model-checked via ``terminal_once``
  ordering, and dynamically exercised by the chaos suite).

Each scenario declares the invariants it checks; ``check_step`` runs
after every scheduled step (all registered threads quiescent),
``check_final`` after the schedule completes. Returning
``(invariant, detail)`` aborts exploration with a counterexample
trace.
"""

from __future__ import annotations

import types
from typing import Optional, Tuple

Bad = Optional[Tuple[str, str]]


def _fresh_store(path=":memory:"):
    from distributed_llm_inferencing_tpu.runtime.state import Store
    return Store(path, group_commit=False)


def _fresh_master(**kw):
    from distributed_llm_inferencing_tpu.runtime.master import Master
    return Master(":memory:", **kw)


def _swap_sync_store(m):
    """Replace the master's group-commit store with a synchronous one
    (scenario determinism — see module docstring). Re-wires nothing
    else: the master holds the only reference."""
    from distributed_llm_inferencing_tpu.runtime.state import Store
    m.store.close()
    m.store = Store(":memory:", group_commit=False)
    return m.store


class Scenario:
    name = ""
    description = ""
    invariants: Tuple[str, ...] = ()
    threads = 0

    def build(self, sched):
        raise NotImplementedError

    def check_step(self, ctx) -> Bad:
        return None

    def check_final(self, ctx) -> Bad:
        return None

    def cleanup(self, ctx):
        m = getattr(ctx, "master", None)
        if m is not None:
            m.stop()
        s = getattr(ctx, "store", None)
        if s is not None:
            s.close()


def _inflight_bad(master) -> Bad:
    for nid, v in list(master._inflight.items()):
        if v < 0:
            return ("inflight_nonnegative",
                    f"node {nid} in-flight count is {v}")
    return None


class BreakerHalfOpenProbe(Scenario):
    """Two dispatchers race ``_pick_node(reserve=True)`` against one
    half-open node: the breaker must admit exactly one probe. The
    ``half_open_probe`` mutation (skip the probe_ok guard — the PR 2
    bug) makes both reservations succeed and is the first mutation-gate
    counterexample."""

    name = "breaker_half_open_probe"
    description = "half-open breaker admits exactly one probe"
    invariants = ("half_open_single_probe", "inflight_nonnegative")
    threads = 2

    def build(self, sched):
        m = _fresh_master(health_interval=0.05)
        nid = m.store.add_node("n1", "127.0.0.1", 9001, is_active=True)
        m.store.update_node(nid, breaker_state="half_open", is_active=1)
        rows = m.store.list_nodes(active_only=True)
        ctx = types.SimpleNamespace(master=m, nid=nid, picks=[],
                                    sched=sched)

        def probe(idx):
            node = m._pick_node(model=None, reserve=True,
                                nodes=[dict(r) for r in rows])
            got = node["id"] if node else None
            ctx.picks.append((idx, got))
            sched.mark(f"pick -> {got}")

        sched.spawn("probe-1", probe, 1)
        sched.spawn("probe-2", probe, 2)
        return ctx

    def check_step(self, ctx) -> Bad:
        n = ctx.master._inflight.get(ctx.nid, 0)
        if n > 1:
            return ("half_open_single_probe",
                    f"half-open node {ctx.nid} holds {n} concurrent "
                    "in-flight probes (must be exactly 1)")
        return _inflight_bad(ctx.master)

    def check_final(self, ctx) -> Bad:
        bad = self.check_step(ctx)
        if bad:
            return bad
        admitted = [i for i, got in ctx.picks if got == ctx.nid]
        if len(admitted) != 1:
            return ("half_open_single_probe",
                    f"{len(admitted)} of {len(ctx.picks)} probes were "
                    "admitted to the half-open node (want exactly 1)")
        return None


class RequeueExclusion(Scenario):
    """Two requests each fail on node A with a connection fault
    (`_fail_sub` — the real failover tail), are re-claimed, and
    re-picked: the pick must avoid the excluded node while node B
    exists. The ``requeue_exclusion`` mutation (drop excluded-node
    persistence — the PR 2 bug) routes the retry straight back to the
    faulted node and is the second mutation-gate counterexample."""

    name = "requeue_exclusion"
    description = "requeued request never returns to the faulted node"
    invariants = ("exclusion_honored", "inflight_nonnegative")
    threads = 2

    def build(self, sched):
        import requests as http
        m = _fresh_master(retry_backoff_base=0.0)
        _swap_sync_store(m)
        a = m.store.add_node("a", "127.0.0.1", 9001, is_active=True)
        b = m.store.add_node("b", "127.0.0.1", 9002, is_active=True)
        for rid_ in range(2):
            m.store.submit_request("m", "hello world")
        node_a = m.store.get_node(a)
        snapshot = m.store.list_nodes(active_only=True)
        ctx = types.SimpleNamespace(master=m, a=a, b=b, picks=[],
                                    failed_on_a=set(), sched=sched)

        def repick(req):
            node = m._reserve_node_for(req, nodes=[dict(r)
                                                   for r in snapshot])
            got = node["id"] if node else None
            ctx.picks.append((req["id"], req["excluded_nodes"], got))
            sched.mark(f"pick for {req['id']} -> {got}")

        def failing_dispatcher():
            req = m.store.claim_next_pending()
            if req is None:
                return
            sched.mark(f"claimed request {req['id']}")
            # attempt failed on node A with a connection-level fault;
            # the GROUND TRUTH of where it failed lives in the scenario
            # (failed_on_a), independent of what the store persisted —
            # that is exactly what the requeue_exclusion mutation lies
            # about
            req["node_id"] = a
            ctx.failed_on_a.add(req["id"])
            m._fail_sub(req, dict(node_a),
                        http.exceptions.ConnectionError(
                            "injected connection fault"),
                        nodes=snapshot)
            req2 = m.store.claim_next_pending()
            if req2 is None:
                return
            sched.mark(f"re-claimed request {req2['id']}")
            repick(req2)

        def contending_dispatcher():
            # a slim contender: its claim can intercept the requeued
            # request before the failing dispatcher's re-claim — and
            # whoever wins it must honor the exclusion. Kept to 2-3
            # lock points so the full tree stays exhaustively small.
            req = m.store.claim_next_pending()
            if req is None:
                return
            sched.mark(f"claimed request {req['id']}")
            repick(req)

        sched.spawn("disp-fail", failing_dispatcher)
        sched.spawn("disp-race", contending_dispatcher)
        return ctx

    def check_step(self, ctx) -> Bad:
        return _inflight_bad(ctx.master)

    def check_final(self, ctx) -> Bad:
        bad = _inflight_bad(ctx.master)
        if bad:
            return bad
        for rid, excluded, got in ctx.picks:
            if rid in ctx.failed_on_a and got == ctx.a:
                return ("exclusion_honored",
                        f"request {rid} re-picked node {ctx.a} right "
                        "after a connection fault there, while node "
                        f"{ctx.b} was schedulable "
                        f"(persisted exclusions: {excluded})")
        return None


class IdemTagRace(Scenario):
    """Three dispatch attempts race one request_tag through the
    worker's REAL idempotency plumbing (`_idem_claim`/`_idem_release`):
    exactly one may own the execution; late claims replay the cached
    result; a concurrent claim joins. The generation must run exactly
    once no matter the order."""

    name = "idem_tag_race"
    description = "one request_tag executes exactly once"
    invariants = ("tag_exactly_once",)
    threads = 3

    def build(self, sched):
        from distributed_llm_inferencing_tpu.runtime.worker import (
            WorkerAgent)
        w = WorkerAgent(auth_key=None)
        ctx = types.SimpleNamespace(worker=w, executions=[], joins=[],
                                    replays=[], sched=sched)

        def attempt(idx):
            kind, obj = w._idem_claim("tag-1")
            sched.mark(f"claim -> {kind}")
            if kind == "own":
                # the "generation": exactly-once is the whole point
                ctx.executions.append(idx)
                w._idem_release("tag-1", obj,
                                {"status": "success", "result": "r"})
            elif kind == "join":
                ctx.joins.append(idx)
            else:
                ctx.replays.append(idx)

        for i in range(3):
            sched.spawn(f"attempt-{i + 1}", attempt, i + 1)
        return ctx

    def check_step(self, ctx) -> Bad:
        if len(ctx.executions) > 1:
            return ("tag_exactly_once",
                    f"tag executed {len(ctx.executions)} times "
                    f"(threads {ctx.executions})")
        return None

    def check_final(self, ctx) -> Bad:
        if len(ctx.executions) != 1:
            return ("tag_exactly_once",
                    f"tag executed {len(ctx.executions)} times across "
                    "3 racing attempts (want exactly 1; "
                    f"joins={ctx.joins} replays={ctx.replays})")
        return None


class DrainNoStrand(Scenario):
    """One request races the worker's drain: whatever the order, drain
    must never report idle (``drained=True``) while a request it
    admitted is still running — the check-and-increment in
    ``_try_begin_inference`` shares one lock with the drain flag, and
    this proves that fence under every interleaving."""

    name = "drain_no_strand"
    description = "drain never strands an admitted request"
    invariants = ("no_strand_on_drain",)
    threads = 2

    def build(self, sched):
        from distributed_llm_inferencing_tpu.runtime.worker import (
            WorkerAgent)
        w = WorkerAgent(auth_key=None)
        ctx = types.SimpleNamespace(worker=w, events=[], sched=sched)

        def request():
            if w._try_begin_inference():
                ctx.events.append(("admitted", None))
                sched.mark("admitted")
                w._end_inference()
                ctx.events.append(("ended", None))
                sched.mark("ended")
            else:
                ctx.events.append(("refused", None))
                sched.mark("refused (draining)")

        def drainer():
            res = w.drain({"timeout": 0})
            ctx.events.append(("drain", res))
            sched.mark(f"drain -> drained={res['drained']} "
                       f"in_flight={res['in_flight']}")

        sched.spawn("request", request)
        sched.spawn("drainer", drainer)
        return ctx

    def check_final(self, ctx) -> Bad:
        open_reqs = 0
        for kind, payload in ctx.events:
            if kind == "admitted":
                open_reqs += 1
            elif kind == "ended":
                open_reqs -= 1
            elif kind == "drain" and payload["drained"] and \
                    payload["in_flight"] == 0 and open_reqs > 0:
                return ("no_strand_on_drain",
                        "drain reported idle while an admitted "
                        "request had not finished")
        return None


class ClaimOnce(Scenario):
    """Two dispatchers race ``claim_next_pending_many`` over three
    pending rows: the locked SELECT + executemany flip must hand out
    disjoint claims covering every due row exactly once."""

    name = "claim_once"
    description = "concurrent claims are disjoint and complete"
    invariants = ("single_claim",)
    threads = 2

    def build(self, sched):
        s = _fresh_store()
        ids = [s.submit_request("m", f"p{i}") for i in range(3)]
        ctx = types.SimpleNamespace(store=s, ids=ids, claims={},
                                    sched=sched)

        def dispatcher(idx):
            got = s.claim_next_pending_many(2)
            ctx.claims[idx] = [r["id"] for r in got]
            sched.mark(f"claimed {[r['id'] for r in got]}")

        sched.spawn("disp-1", dispatcher, 1)
        sched.spawn("disp-2", dispatcher, 2)
        return ctx

    def check_final(self, ctx) -> Bad:
        a = ctx.claims.get(1, [])
        b = ctx.claims.get(2, [])
        dup = set(a) & set(b)
        if dup:
            return ("single_claim",
                    f"requests {sorted(dup)} claimed by BOTH "
                    f"dispatchers (claims: {a} / {b})")
        if sorted(a + b) != sorted(ctx.ids):
            return ("single_claim",
                    f"claims {a}+{b} do not cover the 3 due rows "
                    f"{ctx.ids} exactly once")
        return None


class TerminalOnce(Scenario):
    """A completion races a failure (the user-cancel-vs-finish race)
    on one claimed request: whichever terminal write lands first must
    WIN — the row's terminal status, once observable, never changes.
    This is the race the ``NOT IN ('completed','failed')`` guards on
    ``mark_completed``/``mark_failed`` close; removing either guard
    makes this scenario produce a counterexample."""

    name = "terminal_once"
    description = "a request reaches exactly one terminal state"
    invariants = ("single_terminal",)
    threads = 2

    def build(self, sched):
        s = _fresh_store()
        rid = s.submit_request("m", "p")
        s.claim_next_pending()
        ctx = types.SimpleNamespace(store=s, rid=rid, observed=[],
                                    sched=sched)

        def completer():
            s.mark_completed(rid, "out", 1, 0.1, 1.0)
            st = s.get_request(rid)["status"]
            ctx.observed.append(st)
            sched.mark(f"completed write; row now {st}")

        def failer():
            s.mark_failed(rid, "cancelled by user")
            st = s.get_request(rid)["status"]
            ctx.observed.append(st)
            sched.mark(f"failed write; row now {st}")

        sched.spawn("completer", completer)
        sched.spawn("failer", failer)
        return ctx

    def check_final(self, ctx) -> Bad:
        terminal = None
        for st in ctx.observed:
            if st in ("completed", "failed"):
                if terminal is None:
                    terminal = st
                elif st != terminal:
                    return ("single_terminal",
                            f"request {ctx.rid} observed in terminal "
                            f"state {terminal!r} and LATER in "
                            f"{st!r} — a terminal verdict flipped")
        final = ctx.store.get_request(ctx.rid)["status"]
        if final not in ("completed", "failed"):
            return ("single_terminal",
                    f"request {ctx.rid} ended non-terminal ({final!r}) "
                    "despite two terminal writes")
        return None


class MigrateVsComplete(Scenario):
    """A live-migration handoff (``requeue_migrated`` — the worker's
    303) races the dispatch's completion on one claimed request.
    Whichever lands first decides: a completion first must STICK —
    ``requeue_migrated``'s WHERE status='processing' guard makes the
    late handoff a no-op instead of resurrecting a finished row — and
    a handoff first puts the row back to pending with its resume
    record, after which the (still-valid: output is a pure function of
    (params, prompt, seed)) completion may finish it. Either way the
    row ends ``completed`` exactly once and a terminal verdict never
    flips back to live."""

    name = "migrate_vs_complete"
    description = "a migration handoff never resurrects a terminal row"
    invariants = ("migrate_never_resurrects",)
    threads = 2

    def build(self, sched):
        s = _fresh_store()
        rid = s.submit_request("m", "p")
        s.claim_next_pending()
        ctx = types.SimpleNamespace(store=s, rid=rid, observed=[],
                                    sched=sched)

        def completer():
            s.mark_completed(rid, "out", 1, 0.1, 1.0)
            st = s.get_request(rid)["status"]
            ctx.observed.append(st)
            sched.mark(f"completed write; row now {st}")

        def migrator():
            # the REAL handoff write, exclusion read-modify-write and
            # resume/kv_source persistence included
            s.requeue_migrated(rid,
                               resume={"tokens": [1, 2], "seed": 7},
                               kv_source={"url": "http://w0",
                                          "model": "m"},
                               excluded_node_id=1)
            st = s.get_request(rid)["status"]
            ctx.observed.append(st)
            sched.mark(f"migrate requeue; row now {st}")

        sched.spawn("completer", completer)
        sched.spawn("migrator", migrator)
        return ctx

    def check_final(self, ctx) -> Bad:
        terminal = None
        for st in ctx.observed:
            if terminal is not None and st not in ("completed", "failed"):
                return ("migrate_never_resurrects",
                        f"request {ctx.rid} observed terminal "
                        f"{terminal!r} and LATER live {st!r} — the "
                        "migration handoff resurrected a finished row")
            if st in ("completed", "failed"):
                terminal = st
        final = ctx.store.get_request(ctx.rid)["status"]
        if final != "completed":
            return ("migrate_never_resurrects",
                    f"request {ctx.rid} ended {final!r} — the "
                    "completion must land in every interleaving "
                    "(a handoff never makes the row terminal)")
        return None


class LeaseTakeover(Scenario):
    """A paused-then-revived old leader's dispatch races a standby's
    lease takeover for one claimed request, through the worker's REAL
    lease fence (``note_master_term``) + idempotency plumbing and the
    store's REAL recovery/claim/terminal SQL. The cluster tag is
    SHARED (replicated meta), so whatever the interleaving the
    generation runs exactly once and the row reaches exactly one
    terminal state; the worker-side fence additionally guarantees that
    once term 2 has been seen, a term-1 dispatch never proceeds — the
    invariant the ``stale_term_check`` mutation (skip the fence, the
    revived-old-leader double-dispatch hazard) must break with a
    printed counterexample."""

    name = "lease_takeover"
    description = ("old leader paused mid-dispatch vs standby lease "
                   "takeover: exactly-once, single terminal, stale "
                   "terms fenced")
    invariants = ("tag_exactly_once", "single_terminal",
                  "stale_term_fenced")
    threads = 2

    def build(self, sched):
        from distributed_llm_inferencing_tpu.runtime.worker import (
            WorkerAgent)
        w = WorkerAgent(auth_key=None)
        s = _fresh_store()
        rid = s.submit_request("m", "p")
        s.claim_next_pending()          # the old leader's claim
        tag = f"cluster:{rid}"          # the replicated tag nonce
        ctx = types.SimpleNamespace(worker=w, store=s, rid=rid,
                                    executions=[], joins=[],
                                    stale_proceeded=[], observed=[],
                                    fenced_term=0, sched=sched)

        def run_tag(who):
            kind, obj = w._idem_claim(tag)
            sched.mark(f"{who} idem claim -> {kind}")
            if kind == "own":
                ctx.executions.append(who)
                w._idem_release(tag, obj,
                                {"status": "success", "result": "r"})
            elif kind == "join":
                # the real join waits the running execution out; for
                # the model the claim outcome is what matters (the
                # IdemTagRace pattern — waiting on the peer's Event
                # would block outside a scheduler yield point)
                ctx.joins.append(who)
            s.mark_completed(rid, "r", 1, 0.1, 1.0)
            st = s.get_request(rid)["status"]
            ctx.observed.append(st)
            sched.mark(f"{who} terminal write; row now {st}")

        def old_leader():
            # The paused dispatch revives and reaches the worker at
            # its OLD term. The fence ground truth rides
            # ctx.fenced_term: the standby publishes it in the SAME
            # scheduler step as its own term fence (no lock op in
            # between), and this thread reads it in the same step as
            # its admission decision — so "admitted while term 2 was
            # already fenced" is exact, and the benign interleaving
            # (admitted at term 1, takeover strictly after) never
            # false-positives.
            ok = w.note_master_term("nonce-A", 1)
            if ok and ctx.fenced_term > 1:
                ctx.stale_proceeded.append(ctx.fenced_term)
            if not ok:
                sched.mark("old leader fenced (409) — steps down, "
                           "writes nothing")
                return
            sched.mark("old leader term-1 dispatch admitted")
            run_tag("old")

        def standby():
            # takeover: fence term 2 at the worker, recover the dead
            # leader's in-flight claim, re-claim, re-dispatch with the
            # SAME replicated tag
            w.note_master_term("nonce-B", 2)
            ctx.fenced_term = 2        # same atomic step as the fence
            sched.mark("standby takes the lease at term 2")
            s.recover_stale_processing()
            req = s.claim_next_pending()
            if req is None:
                sched.mark("nothing to re-claim (completion won)")
                return
            run_tag("new")

        sched.spawn("old-leader", old_leader)
        sched.spawn("standby", standby)
        return ctx

    def check_step(self, ctx) -> Bad:
        if len(ctx.executions) > 1:
            return ("tag_exactly_once",
                    f"tag executed {len(ctx.executions)} times "
                    f"({ctx.executions})")
        if ctx.stale_proceeded:
            return ("stale_term_fenced",
                    "a term-1 dispatch proceeded past worker "
                    f"validation although term {ctx.stale_proceeded[0]} "
                    "had already been fenced — the revived old leader "
                    "double-dispatched")
        return None

    def check_final(self, ctx) -> Bad:
        bad = self.check_step(ctx)
        if bad:
            return bad
        if len(ctx.executions) != 1:
            return ("tag_exactly_once",
                    f"tag executed {len(ctx.executions)} times across "
                    "the takeover race (want exactly 1; joins="
                    f"{ctx.joins})")
        terminal = None
        for st in ctx.observed:
            if st in ("completed", "failed"):
                if terminal is None:
                    terminal = st
                elif st != terminal:
                    return ("single_terminal",
                            f"request {ctx.rid} observed terminal "
                            f"{terminal!r} and LATER {st!r} — the "
                            "takeover flipped a verdict")
            elif terminal is not None:
                return ("single_terminal",
                        f"request {ctx.rid} observed live {st!r} after "
                        f"terminal {terminal!r}")
        final = ctx.store.get_request(ctx.rid)["status"]
        if final != "completed":
            return ("single_terminal",
                    f"request {ctx.rid} ended {final!r} despite a "
                    "completed generation")
        return None


class ShedVsSubmit(Scenario):
    """A submit races the overload ladder's shed-threshold crossing
    (``_overload_sweep`` escalating 0 -> 1 sheds batch-class work): in
    EVERY interleaving the submit is either admitted — its row exists,
    pending, owed an answer — or refused with an honest 429 carrying a
    Retry-After header and NO row. The hazard either way is a lie at
    the front door: admitted-then-dropped (row missing after a success
    ack) or refused-but-enqueued (a 429'd client retries into a
    duplicate). Pressure signals are pinned (no TSDB/wall-clock reads
    on a registered thread — determinism rules) and the queue-only
    ladder (burn threshold 0) with hold 0 makes the sweep's one rung
    step unconditional."""

    name = "shed_vs_submit"
    description = ("submit racing a shed crossing is admitted-and-owed "
                   "or honestly 429'd — never silently dropped")
    invariants = ("shed_honest",)
    threads = 2

    def build(self, sched):
        m = _fresh_master(overload_burn=0.0, overload_queue=1.0,
                          overload_hold_s=0.0)
        _swap_sync_store(m)
        m._overload_signals = lambda: (None, 10.0)
        ctx = types.SimpleNamespace(master=m, resp=[], sched=sched)

        def submitter():
            r = m.api_submit({"model_name": "m", "prompt": "p",
                              "slo_class": "batch"})
            ctx.resp.append(r)
            sched.mark("submit -> "
                       f"{r[0] if isinstance(r, tuple) else 'admitted'}")

        def shedder():
            m._overload_sweep()
            sched.mark(f"ladder at level {m._overload_level}")

        sched.spawn("submitter", submitter)
        sched.spawn("shedder", shedder)
        return ctx

    def check_final(self, ctx) -> Bad:
        if not ctx.resp:
            return ("shed_honest", "submit thread never resolved")
        r = ctx.resp[0]
        if isinstance(r, tuple):
            if r[0] != 429:
                return ("shed_honest",
                        f"refusal status {r[0]} (want an honest 429)")
            if len(r) < 3 or "Retry-After" not in (r[2] or {}):
                return ("shed_honest",
                        "429 without a Retry-After header — the client "
                        "cannot back off honestly")
            if ctx.master.store.recent_requests(10):
                return ("shed_honest",
                        "a 429'd submit still enqueued a row — the "
                        "refused client's retry would duplicate it")
            return None
        rid = r.get("request_id")
        row = ctx.master.store.get_request(rid) if rid else None
        if row is None:
            return ("shed_honest",
                    f"success ack for request {rid} but no row exists "
                    "— admitted-and-dropped")
        if row["status"] != "pending":
            return ("shed_honest",
                    f"admitted request {rid} is {row['status']!r} with "
                    "no dispatcher running — the shed touched an "
                    "admitted row")
        return None


class PriorityAging(Scenario):
    """Two dispatchers claim one request each from three pending rows
    (latency / throughput / batch) where the batch row has waited past
    the full priority span (>= 2 x DLI_SCHED_AGING_S): its aged
    effective priority now outranks every fresh submit, so it MUST be
    among the claimed set — the deadline-style-aging anti-starvation
    bound the claim ORDER BY encodes (state.py _SLO_PRIORITY_SQL)."""

    name = "priority_aging"
    description = ("an aged batch request outranks fresh latency work "
                   "(deadline-style aging anti-starvation)")
    invariants = ("no_starvation", "single_claim")
    threads = 2

    def build(self, sched):
        from distributed_llm_inferencing_tpu.runtime import state
        s = _fresh_store()
        s.submit_request("m", "p-lat", slo_class="latency")
        s.submit_request("m", "p-thr", slo_class="throughput")
        old = s.submit_request("m", "p-old", slo_class="batch")
        # backdate the batch row well past 2x the aging constant (the
        # point where no later submit can sort ahead of it) — direct
        # SQL because created_at is claim-visible state, not API state
        with s._lock, s._db:
            s._db.execute(
                "UPDATE requests SET created_at=created_at-? WHERE id=?",
                (10 * max(state.CLAIM_AGING_S, 1.0), old))
        ctx = types.SimpleNamespace(store=s, old=old, claims={},
                                    sched=sched)

        def dispatcher(idx):
            got = s.claim_next_pending_many(1)
            ctx.claims[idx] = [r["id"] for r in got]
            sched.mark(f"claimed {[r['id'] for r in got]}")

        sched.spawn("disp-1", dispatcher, 1)
        sched.spawn("disp-2", dispatcher, 2)
        return ctx

    def check_final(self, ctx) -> Bad:
        from distributed_llm_inferencing_tpu.runtime import state
        a = ctx.claims.get(1, [])
        b = ctx.claims.get(2, [])
        if set(a) & set(b):
            return ("single_claim",
                    f"rows {sorted(set(a) & set(b))} claimed by BOTH "
                    f"dispatchers (claims: {a} / {b})")
        if state.CLAIM_AGING_S <= 0:
            return None     # aging disabled by env — bound not claimed
        claimed = a + b
        if len(claimed) == 2 and ctx.old not in claimed:
            return ("no_starvation",
                    f"aged batch request {ctx.old} passed over by both "
                    f"claims ({claimed}) although it outranks every "
                    "fresh row after 2x DLI_SCHED_AGING_S")
        return None


SCENARIOS = {s.name: s for s in (
    BreakerHalfOpenProbe(), RequeueExclusion(), IdemTagRace(),
    DrainNoStrand(), ClaimOnce(), TerminalOnce(), MigrateVsComplete(),
    LeaseTakeover(), ShedVsSubmit(), PriorityAging())}

# which scenario proves which re-armed historical bug (the mutation
# gate): utils/faults.py MUTATIONS -> scenario name
MUTATION_SCENARIOS = {
    "half_open_probe": "breaker_half_open_probe",
    "requeue_exclusion": "requeue_exclusion",
    "stale_term_check": "lease_takeover",
}
