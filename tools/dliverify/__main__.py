"""CLI: ``python -m tools.dliverify [--scenario s] [--budget S]
[--mutate name] [--prune] [--list]``.

Exit 0: every selected scenario fully explored, zero violations (or,
with ``--mutate``, a counterexample was produced — the mutation gate
PASSES by finding the bug). Exit 1: an invariant violation (or a
mutation the explorer failed to catch). Exit 2: usage / hang.

Budget: ``--budget`` seconds per scenario (default: the
``DLI_VERIFY_BUDGET`` knob, 20). Exploration stopped by the budget is
reported loudly (explored N schedules, INCOMPLETE) and fails the run —
a bounded gate must either finish or say so, never silently pass on a
truncated tree.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.dliverify",
        description="Exhaustive-interleaving model checker for the "
                    "control plane (docs/static_analysis.md)")
    ap.add_argument("--scenario", default="",
                    help="comma list of scenarios (default: all)")
    ap.add_argument("--budget", type=float,
                    default=float(os.environ.get("DLI_VERIFY_BUDGET",
                                                 20)),
                    help="seconds of exploration per scenario")
    ap.add_argument("--mutate", default="",
                    help="arm a historical bug (utils/faults.py "
                         "MUTATIONS) and REQUIRE a counterexample")
    ap.add_argument("--prune", action="store_true",
                    help="DPOR-style sleep-set pruning (heuristic "
                         "accelerator; the CI gate runs the full tree)")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and invariants, then exit")
    args = ap.parse_args(argv)

    # scenario threads log expected failures (injected faults) loudly;
    # the explorer's report is the artifact, not the log stream. The
    # env default covers the not-yet-configured case (setup_logging
    # honors it at first import), the setLevel the already-configured
    # one (an earlier import in the same process).
    os.environ.setdefault("DLI_LOG_LEVEL", "ERROR")
    logging.getLogger("dli_tpu").setLevel(logging.ERROR)

    from . import SCENARIOS
    from .scenarios import MUTATION_SCENARIOS
    from .sched import Explorer, run_scenario_once

    if args.list:
        for s in SCENARIOS.values():
            print(f"{s.name}: {s.description} "
                  f"[{', '.join(s.invariants)}; {s.threads} threads]")
        return 0

    if args.mutate:
        from distributed_llm_inferencing_tpu.utils.faults import (
            MUTATIONS)
        if args.mutate not in MUTATIONS:
            print(f"dliverify: unknown mutation {args.mutate!r} "
                  f"(known: {', '.join(MUTATIONS)})", file=sys.stderr)
            return 2
        names = [MUTATION_SCENARIOS[args.mutate]]
    elif args.scenario:
        names = [s.strip() for s in args.scenario.split(",")
                 if s.strip()]
        bad = sorted(set(names) - set(SCENARIOS))
        if bad:
            print(f"dliverify: unknown scenario(s): {', '.join(bad)}",
                  file=sys.stderr)
            return 2
    else:
        names = list(SCENARIOS)

    prev_env = os.environ.get("DLI_VERIFY_MUTATIONS")
    if args.mutate:
        os.environ["DLI_VERIFY_MUTATIONS"] = args.mutate
    failed = False
    try:
        for name in names:
            scenario = SCENARIOS[name]
            exp = Explorer(
                lambda prefix, s=scenario: run_scenario_once(s, prefix),
                budget_s=args.budget, prune=args.prune)
            res = exp.explore(name)
            tag = (f"{res.schedules} schedule(s), "
                   f"{res.decision_points} max decision points, "
                   f"{res.elapsed_s:.2f}s")
            if args.mutate:
                if res.violation is not None:
                    print(f"dliverify {name} [mutation {args.mutate}]: "
                          f"counterexample found as required ({tag})")
                    print(res.violation.render())
                else:
                    print(f"dliverify {name} [mutation {args.mutate}]: "
                          f"NO counterexample ({tag}) — the explorer "
                          "failed to catch the re-armed bug",
                          file=sys.stderr)
                    failed = True
                continue
            if res.violation is not None:
                print(f"dliverify {name}: FAIL ({tag})")
                print(res.violation.render())
                failed = True
            elif res.hung is not None:
                print(f"dliverify {name}: HANG — {res.hung} ({tag})",
                      file=sys.stderr)
                failed = True
            elif not res.complete:
                print(f"dliverify {name}: INCOMPLETE — budget "
                      f"exhausted after {tag}; raise "
                      "DLI_VERIFY_BUDGET or bound the scenario",
                      file=sys.stderr)
                failed = True
            else:
                print(f"dliverify {name}: exhaustively explored, "
                      f"0 violations ({tag})")
    finally:
        if args.mutate:
            if prev_env is None:
                os.environ.pop("DLI_VERIFY_MUTATIONS", None)
            else:
                os.environ["DLI_VERIFY_MUTATIONS"] = prev_env
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
