"""Ring-decode vs dense-GSPMD decode step: evidence on a virtual mesh.

parallel/ring.py routes sp-sharded decode through an explicit
flash-decoding combine (per-shard online-softmax partials + one
pmax/psum of O(B*H*hd) bytes). One real chip can't host an sp mesh, so
this harness compares the full ``transformer.decode_step`` with the ring
path against the dense-under-GSPMD fallback on an
``--xla_force_host_platform_device_count`` CPU mesh, reporting compiled
collective bytes (the traffic that would ride ICI) plus relative
wall-clock and output equality.

MEASURED FINDING (recorded so the ring.py claim stays honest): at the
scales this harness can run, XLA's partitioner discovers an equivalent
combine-of-partials pattern for the dense formulation — collective
traffic parity and bit-identical outputs. The explicit ring-decode
path's value is therefore the *guarantee* of that communication shape
(GSPMD's choice is heuristic and scale/layout-dependent), not a measured
win over it; wall-clock on CPU memcpy collectives is noise either way.

Usage: python benchmarks/ring_decode_bench.py [S] [sp]
Prints one JSON line with ring_ms / dense_ms / *_collective_bytes /
speedup / max_abs_diff.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(seq_len: int = 32768, sp: int = 8):
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={sp}").strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        # this environment's sitecustomize imports jax at interpreter
        # startup (TPU plugin), so env vars alone are too late — flip the
        # config before the first backend query (same as tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from distributed_llm_inferencing_tpu.parallel.mesh import (
        MeshSpec, create_mesh)

    import numpy as np
    from distributed_llm_inferencing_tpu.models import transformer
    from distributed_llm_inferencing_tpu.models.params import init_params
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
    from distributed_llm_inferencing_tpu.parallel import sharding as shd

    # The claim under test lives in the FULL decode step (ring.py:20-26):
    # in isolation GSPMD already partitions a lone attention well, but
    # inside the real program (cache scatter + QKV/O matmuls around it)
    # the dense fallback's resharding shows up. Same model step, same
    # sp-sharded cache; only mesh= routing differs.
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    B = 1
    spec = MeshSpec(sp=sp)
    mesh = create_mesh(spec)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    with mesh:
        params = shd.shard_params(params, mesh, cfg, spec)
        cache = init_cache(cfg, B, seq_len, dtype=jnp.float32)
        cache = jax.device_put(cache,
                               shd.named(mesh, shd.cache_specs(cfg, spec)))
        # pretend the cache is full to seq_len - 1 (realistic long decode)
        cache = cache._replace(
            lengths=jnp.full((B,), seq_len - 1, jnp.int32))
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (B, 1)),
            jnp.int32)

        ring = jax.jit(lambda p, t, c: transformer.decode_step(
            p, cfg, t, c, mesh=mesh)[0])
        dense = jax.jit(lambda p, t, c: transformer.decode_step(
            p, cfg, t, c, mesh=None)[0])   # GSPMD dense fallback

        def collective_bytes(fn):
            """Bytes produced by cross-device collectives in the compiled
            HLO — the traffic that would ride ICI on a real slice. This is
            the number the ring claim is about: the dense formulation
            gathers cache shards; the ring combines O(B*H*hd) partials."""
            import re
            txt = fn.lower(params, tokens, cache).compile().as_text()
            dt_bytes = {"f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4,
                        "s8": 1, "u8": 1, "pred": 1, "f64": 8}
            total = 0
            for m in re.finditer(
                    r"=\s+(?:\([^)]*\)\s+)?(\w+)\[([\d,]*)\][^=]*"
                    r"(all-gather|all-reduce|collective-permute|"
                    r"reduce-scatter|all-to-all)\(", txt):
                dt, shape = m.group(1), m.group(2)
                n = 1
                for d in filter(None, shape.split(",")):
                    n *= int(d)
                total += n * dt_bytes.get(dt, 4)
            # tuple-shaped collectives: count their tuple elements too
            for m in re.finditer(
                    r"=\s+\(([^)]+)\)\s+(?:all-gather|all-reduce|"
                    r"collective-permute|reduce-scatter|all-to-all)\(", txt):
                for el in m.group(1).split(", "):
                    em = re.match(r"(\w+)\[([\d,]*)\]", el.strip())
                    if em:
                        n = 1
                        for d in filter(None, em.group(2).split(",")):
                            n *= int(d)
                        total += n * dt_bytes.get(em.group(1), 4)
            return total

        def best(fn, n=5):
            jax.block_until_ready(fn(params, tokens, cache))
            t_best = 1e9
            for _ in range(n):
                t0 = time.perf_counter()
                jax.block_until_ready(fn(params, tokens, cache))
                t_best = min(t_best, time.perf_counter() - t0)
            return t_best * 1e3

        ring_ms, dense_ms = best(ring), best(dense)
        out_r = ring(params, tokens, cache)
        out_d = dense(params, tokens, cache)
        err = float(jnp.max(jnp.abs(out_r - out_d)))
        rb, db = collective_bytes(ring), collective_bytes(dense)
        print(json.dumps({
            "seq_len": seq_len, "sp": sp, "batch": B, "model": cfg.name,
            "ring_ms": round(ring_ms, 2), "dense_ms": round(dense_ms, 2),
            "ring_collective_bytes": rb,
            "dense_collective_bytes": db,
            "collective_traffic_ratio": round(db / rb, 1) if rb else None,
            "speedup": round(dense_ms / ring_ms, 2) if ring_ms else None,
            "max_abs_diff": err,
            "note": "virtual CPU mesh: wall-clock is relative evidence "
                    "only; collective bytes are what would ride ICI",
        }))


if __name__ == "__main__":
    s = int(sys.argv[1]) if len(sys.argv) > 1 else 32768
    sp = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    main(s, sp)
