#!/usr/bin/env bash
# Tier-1 gate: byte-compile everything, then run the ROADMAP.md tier-1
# verify command. Later PRs run this in CI (.github/workflows/tier1.yml)
# so "no worse than seed" is checked automatically.
set -uo pipefail

cd "$(dirname "$0")/.."

# ---- optional mode: bash scripts/check.sh --tsan ----------------------
# ThreadSanitizer pass over the native RowPool (docs/static_analysis.md
# "TSan wiring"): builds qgemv.cc with -fsanitize=thread -g into a
# separate libdli_qgemv_tsan.so, then (1) hammers the pool's every
# concurrency edge from ctypes — no jax import, seconds — and
# (2) reruns the full threaded-GEMV suite under the instrumented lib.
# Known-benign suppressions (uninstrumented python/numpy internals) live
# in scripts/tsan.supp; finished-python-thread "leaks" are disabled via
# report_thread_leaks=0 (the RowPool's detached workers are by design).
if [[ "${1:-}" == "--tsan" ]]; then
    TSAN_LIB=$(g++ -print-file-name=libtsan.so)
    if [[ "$TSAN_LIB" != /* || ! -e "$TSAN_LIB" ]]; then
        echo "FAIL: libtsan.so not found (install gcc's tsan runtime)" >&2
        exit 1
    fi
    TSAN_OPTS="suppressions=$PWD/scripts/tsan.supp exitcode=66"
    TSAN_OPTS="$TSAN_OPTS report_thread_leaks=0"
    echo "== tsan build (qgemv.cc -fsanitize=thread -g) =="
    JAX_PLATFORMS=cpu python scripts/tsan_gemv_driver.py --build-only \
        || exit 1
    echo "== tsan stage 1: ctypes RowPool hammer (dispatch x resize) =="
    env LD_PRELOAD="$TSAN_LIB" TSAN_OPTIONS="$TSAN_OPTS" \
        python scripts/tsan_gemv_driver.py || exit 1
    if [[ "${DLI_TSAN_FAST:-}" == "1" ]]; then
        # CI budget mode: TSan's interception makes anything that jits
        # brutally slow; the ctypes hammer above already covers every
        # RowPool concurrency edge, so the bounded tier-1 job stops
        # here. Run without DLI_TSAN_FAST locally / nightly for the
        # pytest rerun too.
        echo "tsan: clean (stage 2 skipped under DLI_TSAN_FAST=1)"
        exit 0
    fi
    echo "== tsan stage 2: threaded-GEMV suite under the instrumented lib =="
    # Default: the thread-relevant subset (env parse, set_threads
    # roundtrip, the threaded-dispatch-inside-jit reentrancy test). The
    # parity sweeps add dozens of XLA compiles whose extra TSan value
    # over the ctypes hammer is nil but which put the rerun far past a
    # 30-min budget — DLI_TSAN_FULL=1 runs everything anyway.
    K='configured or set_threads or inside_jit'
    [[ "${DLI_TSAN_FULL:-}" == "1" ]] && K=''
    timeout -k 10 1800 env LD_PRELOAD="$TSAN_LIB" DLI_NATIVE_TSAN=1 \
        JAX_PLATFORMS=cpu TSAN_OPTIONS="$TSAN_OPTS" \
        python -m pytest tests/test_gemv_threads.py -q ${K:+-k "$K"} \
        -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
    echo "tsan: clean"
    exit 0
fi

echo "== compileall =="
python -m compileall -q distributed_llm_inferencing_tpu tests bench.py \
    benchmarks tools || exit 1

echo "== dlilint (repo-native invariant checkers) =="
# AST-checked invariants (docs/static_analysis.md): metrics registered +
# pre-registered at 0, DLI_* knobs in code == utils/knobs.py == docs,
# no host work inside jitted code, no silent except-pass in runtime
# threads, no static lock-order cycles — plus the protocol half
# (dliproto): every master->worker RPC path/method/body-key against the
# route tables, every fault point against a live intercept site, and
# every request-status write against the declared lifecycle machine
# (runtime/lifecycle.py, with the byte-checked diagram in
# docs/robustness.md). Prints per-checker counts; any violation fails
# the build here.
python -m tools.dlilint || exit 1

echo "== dliverify (exhaustive-interleaving model checker) =="
# Deterministic-scheduler exploration of the REAL breaker/idempotency/
# drain/claim code over every thread interleaving of its bounded
# scenarios (docs/static_analysis.md "dliverify"): half-open admits one
# probe, a tag executes once, claims are disjoint, terminal states
# never flip, drain strands nothing, exclusions are honored. The
# mutation gate then re-arms two historical bugs and REQUIRES a
# counterexample trace for each — proving the explorer still catches
# regressions. Seconds-scale; budget per scenario via DLI_VERIFY_BUDGET.
# The outer timeout scales with the budget (10 scenarios + import slack)
# so a raised budget can't be SIGTERMed into a diagnostic-free exit 124
# before the explorer's own INCOMPLETE reporting fires.
VB="${DLI_VERIFY_BUDGET:-20}"
VT=$(python -c "print(int(float('$VB') * 12 + 180))")
timeout -k 10 "$VT" env JAX_PLATFORMS=cpu \
    python -m tools.dliverify --budget "$VB" || exit 1
timeout -k 10 "$VT" env JAX_PLATFORMS=cpu \
    python -m tools.dliverify --mutate half_open_probe --budget "$VB" \
    || exit 1
timeout -k 10 "$VT" env JAX_PLATFORMS=cpu \
    python -m tools.dliverify --mutate requeue_exclusion --budget "$VB" \
    || exit 1
timeout -k 10 "$VT" env JAX_PLATFORMS=cpu \
    python -m tools.dliverify --mutate stale_term_check --budget "$VB" \
    || exit 1

echo "== native kernels (threaded GEMV/GEMM must build; no silent fallback) =="
# The decode hot path leans on the -pthread row-pool kernel
# (native/src/qgemv.cc via ops/cpu_gemv.py). A build regression must fail
# HERE, loudly — not degrade every int8 matmul to the XLA dequant path.
JAX_PLATFORMS=cpu python - <<'PY' || exit 1
from distributed_llm_inferencing_tpu.native import configured_threads
from distributed_llm_inferencing_tpu.ops import cpu_gemv
assert cpu_gemv.available(), (
    "native qgemv failed to build/register -- the threaded decode hot "
    "path would silently fall back to the XLA dequant matmul")
print(f"qgemv ready: {cpu_gemv.get_threads()} threads "
      f"(configured default {configured_threads()})")
PY

echo "== perf hot-path suites (threaded GEMV + adaptive speculation) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_gemv_threads.py tests/test_adaptive_spec.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly || exit 1

echo "== wave speculation + pallas kernel parity + decode-speed smoke =="
# Wave-level batched speculation (per-slot draft widths, per-request
# controllers — docs/serving.md "Wave-level speculation") and the
# interpret-mode differential suite pinning every pallas kernel — incl.
# the fused dequant-GEMV->RoPE->paged-attention decode step behind
# DLI_FUSED_DECODE — against its XLA oracle; the smoke gates the
# per-slot tokens-per-weight-pass amortization and the single-stream
# spec-vs-plain regression (BENCH_r05's inversion must stay gone)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_spec_wave.py tests/test_pallas_parity.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario decode_speed --smoke || exit 1

echo "== control-plane suite + saturation smoke (batched dispatch) =="
# Multiplexed batched dispatch, pooled RPC, queue-aware scheduling
# (docs/serving.md "Control plane"); the smoke drives a live
# master + in-proc worker and gates on zero failures + connection reuse
timeout -k 10 600 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    python -m pytest tests/test_dispatch_batch.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario control_plane --smoke || exit 1

echo "== prefix-cache tier suite + shared-prefix smoke (kv offload + affinity) =="
# Host-RAM KV offload arena + prefix-digest advertisement + affinity
# routing (docs/serving.md "Prefix-cache tier"); the smoke drives a live
# master + 2 in-proc workers over a shared-system-prompt workload and
# gates on zero failures + affinity picks + cached-prefill fraction
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_kvtier.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario prefix_cache --smoke || exit 1

echo "== multi-LoRA adapter serving suite + routed smoke =="
# Paged host adapter store, per-slot batched gathered application
# (mixed-adapter waves bitwise vs dedicated batchers), adapter-affinity
# routing with the convoy guard, loud load-failure semantics
# (docs/serving.md "Multi-LoRA adapter serving"); the smoke drives a
# live master + 2 in-proc workers over interleaved base/adapter traffic
# and gates zero failures, lazy dispatch-time loads, affinity picks,
# and the adapter-loaded trail in /api/events (JSON at
# /tmp/dli_bench_multi_lora.json for the CI artifact)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_lora.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario multi_lora --smoke || exit 1

echo "== disaggregated prefill/decode + KV transfer suite + smoke =="
# Role-split pools, /kv_fetch wire, bitwise transferred-decode, chaos on
# the transfer (docs/architecture.md "Disaggregation"); the smoke drives
# a live master + prefill/decode worker pair and gates on zero failures
# plus at least one real cross-node KV transfer
timeout -k 10 600 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    python -m pytest tests/test_disagg.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
# int8 KV tier differential suite: per-(layer, head) quantize/dequant
# bounds, wire-frame corruption rejection, arena byte honesty, and the
# greedy-match gate for decode continued from quantized transferred KV
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_kvblock_quant.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario disagg --smoke || exit 1

echo "== live migration + elastic rebalancing suite + smoke =="
# Mid-generation KV snapshot + bitwise resume, /migrate_out + 303
# handoff, role flips, rebalancer policy (docs/robustness.md "Live
# in-flight migration"); the smoke drives a live master + role-split
# fleet and gates one proactive role flip on a uniform mix plus
# kill-mid-wave recovery with zero lost/duplicated tokens (the bench
# JSON lands at /tmp/dli_bench_rebalance.json for the CI artifact)
timeout -k 10 600 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    python -m pytest tests/test_migration.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario rebalance --smoke || exit 1

echo "== replicated control plane suite + kill-the-leader chaos smoke =="
# Leader-leased master pair over op-log replication (docs/robustness.md
# "Replicated control plane"): the suite covers the op-log capture/
# apply path, lease validation, redirects, and the barrier degradation;
# the smoke runs a LIVE 2-master/2-worker fleet, SIGKILLs the leader
# subprocess mid-wave, and gates standby takeover within 2 lease
# intervals, zero lost/duplicated requests (idempotency-tag
# accounting), survivor dashboard reads clean throughout, and the
# takeover reconstructable from the replicated event journal (JSON at
# /tmp/dli_bench_ha.json for the CI artifact; leader subprocess log at
# /tmp/dli_ha_leader.log)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_ha.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python bench.py --scenario ha --smoke || exit 1

echo "== telemetry plane + flight recorder (TSDB + cost ledger + SLO + events) =="
# Time-series retention, per-request cost ledger, SLO accounting, decode
# profiler (docs/observability.md "Telemetry plane"), and the flight
# recorder (durable event journal + request journeys + TSDB
# snapshot/restore, docs/observability.md "Flight recorder"); the smoke
# drives a live master + in-proc worker, waits two scrape intervals,
# asserts /api/timeseries serves multi-sample series + the cost ledger
# round-trips + events flow into /api/events + the journey endpoint
# returns a connected timeline, and leaves a debug bundle at
# /tmp/dli_debug_bundle.tar.gz (uploaded as a CI artifact on tier-1
# failure, together with the /tmp/dli_events.json journal export)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_tsdb.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 900 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    python -m pytest tests/test_events.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/telemetry_smoke.py || exit 1

echo "== cluster observatory (virtual-clock sim: scale + calibration gates) =="
# Trace-calibrated discrete-event simulator (docs/simulator.md): the
# suites pin the clock seam (utils/clock.py) and the sim harness
# (tools/dlisim drives the REAL _pick_node/breaker/Store on a
# VirtualClock); the scale gate pushes 100k requests through a
# 1000-node fleet in <120s wall with a deterministic decision journal
# and sub-linear per-pick cost; the calibration gate replays a live
# smoke run's own arrival trace through the fitted worker model and
# fails on sim-vs-real divergence beyond the documented tolerances
# (artifacts: /tmp/dli_bench_sim.json, /tmp/dli_sim_calibration.json)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_clock.py tests/test_dlisim.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario sim_scale --smoke || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario sim_calibrate --smoke || exit 1

echo "== overload front door (admission + priority + shedding ladder) =="
# SLO-class admission control, per-tenant token buckets, priority claims
# with anti-starvation aging, and the burn-rate degradation ladder
# (docs/robustness.md "Overload control"); the smoke drives an open-loop
# diurnal storm to ~4x measured capacity against a live master + warm
# in-proc worker and gates honest 429s (Retry-After on every refusal),
# zero admitted failures, a full ladder walk up AND back reconstructable
# from /api/events, then replays the same policy deterministically in
# the virtual-clock sim and asserts the anti-starvation wave bound
# (JSON at /tmp/dli_bench_overload.json for the CI artifact)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_admission.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python bench.py --scenario overload --smoke || exit 1

echo "== auto-parallelism planner (cost model + sim sweep + live smoke) =="
# Heterogeneity-aware plan search (docs/architecture.md "Auto-
# parallelism planner"): analytic cost model over fleet-fitted node
# classes, (mesh x role split) enumeration under memory feasibility,
# decision records persisted in the replicated meta table. The sim
# sweep replays a 120-node two-class fleet through tools/dlisim and
# fails if the planner's top choice falls outside DLI_PLANNER_TOLERANCE
# of the sim-measured best split; the smoke drives a live 3-worker
# fleet with one fault-throttled node and gates the full
# decision->persistence->rebalancer-steering path (JSON artifacts:
# /tmp/dli_planner_sweep.json, /tmp/dli_bench_plan.json)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_planner.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m tools.dlisim --planner-sweep --nodes 120 --requests 2000 \
    --duration 200 --seed 42 --out /tmp/dli_planner_sweep.json || exit 1
timeout -k 10 900 env JAX_PLATFORMS=cpu \
    python bench.py --scenario plan --smoke || exit 1

echo "== chaos suite (fault injection + self-healing dispatch + lock watchdog) =="
# Deterministic fault schedules: a failure here reproduces locally with
#   DLI_FAULTS_SEED=0 JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q
# (see docs/robustness.md for the fault-point spec / runbook)
# DLI_LOCK_CHECK=1 arms the runtime lock-order watchdog (utils/locks.py)
# for the whole chaos run: every runtime lock becomes an instrumented
# wrapper recording per-thread acquisition order, and the conftest
# session gate fails the suite on ANY lock-order cycle — dynamic
# inversions fail the build here, not production.
timeout -k 10 600 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    DLI_FAULTS_SEED=0 DLI_LOCK_CHECK=1 \
    python -m pytest tests/test_chaos.py tests/test_node_lifecycle.py \
    tests/test_locks.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1

echo "== tier-1 tests (ROADMAP.md verify command) =="
# (the chaos/lifecycle and perf hot-path suites already ran above —
#  skipped here so check.sh doesn't pay for them twice; the bare ROADMAP
#  command still collects them)
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly \
    --ignore=tests/test_chaos.py --ignore=tests/test_node_lifecycle.py \
    --ignore=tests/test_locks.py \
    --ignore=tests/test_gemv_threads.py \
    --ignore=tests/test_adaptive_spec.py \
    --ignore=tests/test_spec_wave.py \
    --ignore=tests/test_pallas_parity.py \
    --ignore=tests/test_dispatch_batch.py \
    --ignore=tests/test_kvtier.py \
    --ignore=tests/test_lora.py \
    --ignore=tests/test_disagg.py \
    --ignore=tests/test_kvblock_quant.py \
    --ignore=tests/test_migration.py \
    --ignore=tests/test_tsdb.py \
    --ignore=tests/test_events.py \
    --ignore=tests/test_ha.py \
    --ignore=tests/test_clock.py \
    --ignore=tests/test_dlisim.py \
    --ignore=tests/test_admission.py \
    --ignore=tests/test_planner.py \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
