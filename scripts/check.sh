#!/usr/bin/env bash
# Tier-1 gate: byte-compile everything, then run the ROADMAP.md tier-1
# verify command. Later PRs run this in CI (.github/workflows/tier1.yml)
# so "no worse than seed" is checked automatically.
set -uo pipefail

cd "$(dirname "$0")/.."

echo "== compileall =="
python -m compileall -q distributed_llm_inferencing_tpu tests bench.py \
    benchmarks || exit 1

echo "== native kernels (threaded GEMV/GEMM must build; no silent fallback) =="
# The decode hot path leans on the -pthread row-pool kernel
# (native/src/qgemv.cc via ops/cpu_gemv.py). A build regression must fail
# HERE, loudly — not degrade every int8 matmul to the XLA dequant path.
JAX_PLATFORMS=cpu python - <<'PY' || exit 1
from distributed_llm_inferencing_tpu.native import configured_threads
from distributed_llm_inferencing_tpu.ops import cpu_gemv
assert cpu_gemv.available(), (
    "native qgemv failed to build/register -- the threaded decode hot "
    "path would silently fall back to the XLA dequant matmul")
print(f"qgemv ready: {cpu_gemv.get_threads()} threads "
      f"(configured default {configured_threads()})")
PY

echo "== perf hot-path suites (threaded GEMV + adaptive speculation) =="
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_gemv_threads.py tests/test_adaptive_spec.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly || exit 1

echo "== wave speculation + pallas kernel parity + decode-speed smoke =="
# Wave-level batched speculation (per-slot draft widths, per-request
# controllers — docs/serving.md "Wave-level speculation") and the
# interpret-mode differential suite pinning every pallas kernel — incl.
# the fused dequant-GEMV->RoPE->paged-attention decode step behind
# DLI_FUSED_DECODE — against its XLA oracle; the smoke gates the
# per-slot tokens-per-weight-pass amortization and the single-stream
# spec-vs-plain regression (BENCH_r05's inversion must stay gone)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_spec_wave.py tests/test_pallas_parity.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario decode_speed --smoke || exit 1

echo "== control-plane suite + saturation smoke (batched dispatch) =="
# Multiplexed batched dispatch, pooled RPC, queue-aware scheduling
# (docs/serving.md "Control plane"); the smoke drives a live
# master + in-proc worker and gates on zero failures + connection reuse
timeout -k 10 600 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    python -m pytest tests/test_dispatch_batch.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario control_plane --smoke || exit 1

echo "== prefix-cache tier suite + shared-prefix smoke (kv offload + affinity) =="
# Host-RAM KV offload arena + prefix-digest advertisement + affinity
# routing (docs/serving.md "Prefix-cache tier"); the smoke drives a live
# master + 2 in-proc workers over a shared-system-prompt workload and
# gates on zero failures + affinity picks + cached-prefill fraction
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_kvtier.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario prefix_cache --smoke || exit 1

echo "== disaggregated prefill/decode + KV transfer suite + smoke =="
# Role-split pools, /kv_fetch wire, bitwise transferred-decode, chaos on
# the transfer (docs/architecture.md "Disaggregation"); the smoke drives
# a live master + prefill/decode worker pair and gates on zero failures
# plus at least one real cross-node KV transfer
timeout -k 10 600 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    python -m pytest tests/test_disagg.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python bench.py --scenario disagg --smoke || exit 1

echo "== telemetry plane (TSDB + cost ledger + SLO + profiler) =="
# Time-series retention, per-request cost ledger, SLO accounting, decode
# profiler (docs/observability.md "Telemetry plane"); the smoke drives a
# live master + in-proc worker, waits two scrape intervals, asserts
# /api/timeseries serves multi-sample series + the cost ledger
# round-trips, and leaves a debug bundle at /tmp/dli_debug_bundle.tar.gz
# (uploaded as a CI artifact on tier-1 failure)
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_tsdb.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1
timeout -k 10 600 env JAX_PLATFORMS=cpu \
    python scripts/telemetry_smoke.py || exit 1

echo "== chaos suite (fault injection + self-healing dispatch) =="
# Deterministic fault schedules: a failure here reproduces locally with
#   DLI_FAULTS_SEED=0 JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q
# (see docs/robustness.md for the fault-point spec / runbook)
timeout -k 10 600 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    DLI_FAULTS_SEED=0 \
    python -m pytest tests/test_chaos.py tests/test_node_lifecycle.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1

echo "== tier-1 tests (ROADMAP.md verify command) =="
# (the chaos/lifecycle and perf hot-path suites already ran above —
#  skipped here so check.sh doesn't pay for them twice; the bare ROADMAP
#  command still collects them)
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly \
    --ignore=tests/test_chaos.py --ignore=tests/test_node_lifecycle.py \
    --ignore=tests/test_gemv_threads.py \
    --ignore=tests/test_adaptive_spec.py \
    --ignore=tests/test_spec_wave.py \
    --ignore=tests/test_pallas_parity.py \
    --ignore=tests/test_dispatch_batch.py \
    --ignore=tests/test_kvtier.py \
    --ignore=tests/test_disagg.py \
    --ignore=tests/test_tsdb.py \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
