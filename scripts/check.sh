#!/usr/bin/env bash
# Tier-1 gate: byte-compile everything, then run the ROADMAP.md tier-1
# verify command. Later PRs run this in CI (.github/workflows/tier1.yml)
# so "no worse than seed" is checked automatically.
set -uo pipefail

cd "$(dirname "$0")/.."

echo "== compileall =="
python -m compileall -q distributed_llm_inferencing_tpu tests bench.py \
    benchmarks || exit 1

echo "== chaos suite (fault injection + self-healing dispatch) =="
# Deterministic fault schedules: a failure here reproduces locally with
#   DLI_FAULTS_SEED=0 JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q
# (see docs/robustness.md for the fault-point spec / runbook)
timeout -k 10 600 env JAX_PLATFORMS=cpu DLI_FAULTS_ENABLE=1 \
    DLI_FAULTS_SEED=0 \
    python -m pytest tests/test_chaos.py tests/test_node_lifecycle.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit 1

echo "== tier-1 tests (ROADMAP.md verify command) =="
# (the chaos/lifecycle suites already ran above with the seeded env —
#  skipped here so check.sh doesn't pay for them twice; the bare ROADMAP
#  command still collects them)
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly \
    --ignore=tests/test_chaos.py --ignore=tests/test_node_lifecycle.py \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
