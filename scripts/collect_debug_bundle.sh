#!/usr/bin/env bash
# Snapshot a live master's observability surfaces into one tarball for
# bug reports (docs/robustness.md "Fault runbook"): retained time-series
# history, the cluster trace export, decode-profiler readout, SLO
# rollup, node/breaker state, cluster metrics, and recent request rows.
#
# Usage: scripts/collect_debug_bundle.sh [MASTER_URL] [OUT_TARBALL]
#   MASTER_URL   default http://127.0.0.1:8000
#   OUT_TARBALL  default dli-debug-bundle-<timestamp>.tar.gz
# Honors DLI_MASTER_AUTH_KEY for a bearer-authed master and
# DLI_BUNDLE_TIMEOUT (seconds per fetch, default 30). Each fetch is
# best-effort: an unreachable surface records its error in place instead
# of sinking the whole bundle.
set -uo pipefail

MASTER="${1:-http://127.0.0.1:8000}"
OUT="${2:-dli-debug-bundle-$(date +%Y%m%d-%H%M%S).tar.gz}"
TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

HDR=()
if [ -n "${DLI_MASTER_AUTH_KEY:-}" ]; then
    HDR=(-H "Authorization: Bearer $DLI_MASTER_AUTH_KEY")
fi

fetch() {  # fetch <path> <outfile>
    # ${HDR[@]+...}: an empty array under `set -u` is an unbound-variable
    # abort on bash < 4.4 (macOS /bin/bash 3.2) — expand only when set
    if ! curl -fsS --max-time "${DLI_BUNDLE_TIMEOUT:-30}" \
            ${HDR[@]+"${HDR[@]}"} \
            "$MASTER$1" -o "$TMP/$2" 2>"$TMP/$2.err"; then
        printf '{"error": "fetch %s failed: %s"}\n' \
            "$1" "$(tr -d '"\n' < "$TMP/$2.err" | head -c 200)" > "$TMP/$2"
    fi
    rm -f "$TMP/$2.err"
}

fetch /api/timeseries timeseries_catalog.json
for m in tokens_generated batcher_queue_depth batcher_free_kv_blocks \
         prefix_hit_ratio breaker_state slo_attainment slo_burn_rate \
         requests_completed; do
    fetch "/api/timeseries?metric=$m" "timeseries_$m.json"
done
fetch /api/trace trace.json              # open in Perfetto
fetch /api/profile profile.json          # decode-profiler readout
fetch /api/slo slo.json
fetch /api/nodes/status nodes_status.json
fetch /api/cluster_metrics cluster_metrics.json
fetch /api/inference/recent recent_requests.json
fetch /api/events events.json            # flight-recorder journal
fetch /metrics master_metrics.prom

# Journey of the worst recent SLO-missing request: a terminal failure
# is an SLO miss by definition; with none in the recent window, take
# the slowest completion (the likeliest TTFT/ITL violator). Best-effort
# like every other fetch -- no python3, no journey, bundle still lands.
RID=$(python3 - "$TMP/recent_requests.json" <<'EOF' 2>/dev/null
import json, sys
try:
    rows = json.load(open(sys.argv[1])).get("requests") or []
except Exception:
    rows = []
bad = [r for r in rows if r.get("status") == "failed"]
if not bad:
    bad = sorted((r for r in rows if r.get("status") == "completed"),
                 key=lambda r: -(r.get("execution_time") or 0))[:1]
if bad:
    print(bad[0]["id"])
EOF
)
if [ -n "${RID:-}" ]; then
    fetch "/api/requests/$RID/journey" worst_request_journey.json
    fetch "/api/events?request=$RID" worst_request_events.json
fi

{
    echo "collected_at: $(date -u +%Y-%m-%dT%H:%M:%SZ)"
    echo "master: $MASTER"
} > "$TMP/MANIFEST"

tar -czf "$OUT" -C "$TMP" .
echo "$OUT"
