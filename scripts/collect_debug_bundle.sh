#!/usr/bin/env bash
# Snapshot a live control plane's observability surfaces into one
# tarball for bug reports (docs/robustness.md "Fault runbook"):
# retained time-series history, the cluster trace export,
# decode-profiler readout, SLO rollup, node/breaker state, cluster
# metrics, recent request rows, the flight-recorder journal, and — on
# an HA pair (docs/robustness.md "Replicated control plane") — the
# replication/lease state of EVERY configured master, so a failover
# postmortem has both sides' view of the lease and the op-log.
#
# Usage: scripts/collect_debug_bundle.sh [MASTER_URLS] [OUT_TARBALL]
#   MASTER_URLS  comma list of master base URLs
#                (default http://127.0.0.1:8000; an HA pair passes
#                 "http://m1:8000,http://m2:8000" — each master gets
#                 its own master_<n>/ directory in the bundle)
#   OUT_TARBALL  default dli-debug-bundle-<timestamp>.tar.gz
# Honors DLI_MASTER_AUTH_KEY for a bearer-authed master and
# DLI_BUNDLE_TIMEOUT (seconds per fetch, default 30). Each fetch is
# best-effort: an unreachable surface (or a whole dead master) records
# its error in place instead of sinking the bundle.
set -uo pipefail

MASTERS="${1:-http://127.0.0.1:8000}"
OUT="${2:-dli-debug-bundle-$(date +%Y%m%d-%H%M%S).tar.gz}"
TMP="$(mktemp -d)"
trap 'rm -rf "$TMP"' EXIT

HDR=()
if [ -n "${DLI_MASTER_AUTH_KEY:-}" ]; then
    HDR=(-H "Authorization: Bearer $DLI_MASTER_AUTH_KEY")
fi

fetch() {  # fetch <master> <dir> <path> <outfile>
    # ${HDR[@]+...}: an empty array under `set -u` is an unbound-variable
    # abort on bash < 4.4 (macOS /bin/bash 3.2) — expand only when set
    if ! curl -fsS --max-time "${DLI_BUNDLE_TIMEOUT:-30}" \
            ${HDR[@]+"${HDR[@]}"} \
            "$1$3" -o "$TMP/$2/$4" 2>"$TMP/$2/$4.err"; then
        printf '{"error": "fetch %s failed: %s"}\n' \
            "$3" "$(tr -d '"\n' < "$TMP/$2/$4.err" | head -c 200)" \
            > "$TMP/$2/$4"
    fi
    rm -f "$TMP/$2/$4.err"
}

collect_master() {  # collect_master <master> <dir>
    local M="$1" D="$2"
    mkdir -p "$TMP/$D"
    fetch "$M" "$D" /api/timeseries timeseries_catalog.json
    for m in tokens_generated batcher_queue_depth batcher_free_kv_blocks \
             prefix_hit_ratio breaker_state slo_attainment slo_burn_rate \
             requests_completed; do
        fetch "$M" "$D" "/api/timeseries?metric=$m" "timeseries_$m.json"
    done
    fetch "$M" "$D" /api/trace trace.json        # open in Perfetto
    fetch "$M" "$D" /api/profile profile.json    # decode-profiler readout
    fetch "$M" "$D" /api/slo slo.json
    fetch "$M" "$D" /api/nodes/status nodes_status.json
    fetch "$M" "$D" /api/cluster_metrics cluster_metrics.json
    fetch "$M" "$D" /api/inference/recent recent_requests.json
    fetch "$M" "$D" /api/events events.json      # flight-recorder journal
    # Workload capture (docs/simulator.md): the request-submitted rows
    # are the replayable arrival trace — feed this file straight to
    #   python -m tools.dlisim --trace workload_capture.json
    # to re-drive the incident's exact workload through the simulator.
    fetch "$M" "$D" "/api/events?type=request-submitted&limit=2000" \
        workload_capture.json
    fetch "$M" "$D" /api/ha ha_status.json       # lease/replication state
    fetch "$M" "$D" /api/leader leader.json      # who this master follows
    fetch "$M" "$D" /metrics master_metrics.prom

    # Journey of the worst recent SLO-missing request: a terminal
    # failure is an SLO miss by definition; with none in the recent
    # window, take the slowest completion (the likeliest TTFT/ITL
    # violator). Best-effort like every other fetch — no python3, no
    # journey, bundle still lands.
    local RID
    RID=$(python3 - "$TMP/$D/recent_requests.json" <<'EOF' 2>/dev/null
import json, sys
try:
    rows = json.load(open(sys.argv[1])).get("requests") or []
except Exception:
    rows = []
bad = [r for r in rows if r.get("status") == "failed"]
if not bad:
    bad = sorted((r for r in rows if r.get("status") == "completed"),
                 key=lambda r: -(r.get("execution_time") or 0))[:1]
if bad:
    print(bad[0]["id"])
EOF
)
    if [ -n "${RID:-}" ]; then
        fetch "$M" "$D" "/api/requests/$RID/journey" \
            worst_request_journey.json
        fetch "$M" "$D" "/api/events?request=$RID" \
            worst_request_events.json
    fi
}

{
    echo "collected_at: $(date -u +%Y-%m-%dT%H:%M:%SZ)"
    echo "masters: $MASTERS"
} > "$TMP/MANIFEST"

i=0
IFS=',' read -ra URLS <<< "$MASTERS"
for M in "${URLS[@]}"; do
    M="$(echo "$M" | tr -d '[:space:]')"
    [ -n "$M" ] || continue
    i=$((i + 1))
    D="master_$i"
    echo "master_$i: $M" >> "$TMP/MANIFEST"
    collect_master "$M" "$D"
done

tar -czf "$OUT" -C "$TMP" .
echo "$OUT"
