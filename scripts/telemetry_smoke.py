"""Telemetry-plane CI smoke: start a live master + one in-proc batched
worker, run a short batched wave, wait out two TSDB scrape intervals,
and assert the retention layer actually retained:

- ``GET /api/timeseries`` serves multi-sample per-node series for tok/s
  (counter->rate) and queue depth after the run;
- a completed request's cost record round-trips through the worker
  response, the master row, and ``GET /api/requests/<id>/cost``, with
  its phases summing to ~the e2e window;
- the SLO evaluator saw every completed request;
- flight-recorder events flow end-to-end (emit -> group-commit store ->
  ``GET /api/events``, type filter honored) and
  ``GET /api/requests/<id>/journey`` returns one connected, time-ordered
  timeline with the cost phases attached (journal exported to
  /tmp/dli_events.json for the CI failure artifact).

Always finishes by collecting a debug bundle from the live cluster into
/tmp/dli_debug_bundle.tar.gz — on a later tier-1 failure the workflow
uploads it as the postmortem artifact (scripts/collect_debug_bundle.sh).
"""

import json
import os
import subprocess
import sys
import time

# runnable as `python scripts/telemetry_smoke.py` from the repo root
# (sys.path[0] is scripts/ then, and the package wouldn't resolve)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import requests

STEP_S = 0.5


def main():
    from distributed_llm_inferencing_tpu.runtime.master import Master
    from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent

    agent = WorkerAgent()
    wsrv = agent.serve("127.0.0.1", 0, background=True)
    wport = wsrv.server_address[1]
    r = requests.post(f"http://127.0.0.1:{wport}/load_model", json={
        "model_name": "tiny-llama", "allow_random_init": True,
        "dtype": "float32", "serving": "batched", "slots": 4,
        "kv_blocks": 128, "kv_block_size": 8, "max_seq": 64}, timeout=600)
    assert r.status_code == 200, r.text

    m = Master(":memory:", health_interval=1.0, tsdb_step_s=STEP_S)
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{msrv.server_address[1]}"
    rc = 1
    try:
        r = requests.post(f"{base}/api/nodes/add", json={
            "name": "w0", "host": "127.0.0.1", "port": wport}).json()
        assert r["status"] == "success", r
        m.start_background()

        rids = []
        for i in range(6):
            rids.append(requests.post(f"{base}/api/inference/submit", json={
                "model_name": "tiny-llama", "prompt": f"telemetry {i}",
                "max_new_tokens": 8,
                "sampling": {"do_sample": False,
                             "allow_random_init": True}}).json()
                ["request_id"])
        deadline = time.time() + 300
        rows = {}
        while time.time() < deadline and len(rows) < len(rids):
            for rid in rids:
                if rid in rows:
                    continue
                st = requests.get(
                    f"{base}/api/inference/status/{rid}").json()["request"]
                if st["status"] in ("completed", "failed"):
                    rows[rid] = st
            time.sleep(0.2)
        assert len(rows) == len(rids), f"only {len(rows)} finished"
        failed = [r for r in rows.values() if r["status"] != "completed"]
        assert not failed, failed

        # two scrape intervals so the tok/s rate series has >= 2 samples
        time.sleep(4 * STEP_S)

        for metric, min_points in (("tokens_generated", 2),
                                   ("batcher_queue_depth", 2)):
            ts = requests.get(f"{base}/api/timeseries",
                              params={"metric": metric}).json()
            series = [s for s in ts["series"] if s["node"] == "w0"]
            assert series, f"no {metric} series for w0: {ts}"
            pts = series[0]["points"]
            assert len(pts) >= min_points, (metric, pts)
        # the rate series must have seen the run's tokens move
        ts = requests.get(f"{base}/api/timeseries",
                          params={"metric": "tokens_generated"}).json()
        assert any(v > 0 for s in ts["series"] for _, v in s["points"]), ts

        # cost ledger round-trip + phase-sum sanity
        rid = rids[0]
        c = requests.get(f"{base}/api/requests/{rid}/cost").json()
        assert c["status"] == "success", c
        cost = c["cost"]
        phase_sum = (cost["queue_ms"] + cost["prefill_ms"]
                     + cost["decode_ms"])
        # phases sum exactly to the batcher's e2e span; the worker's
        # execution_time adds only handler overhead around that span,
        # while the master's e2e_ms also adds dispatch overhead (a fixed
        # ~10ms that dwarfs a warm millisecond-scale request) — so gate
        # tightly against the worker window, loosely against the master
        e2e = c["e2e_ms"]
        exec_ms = c["execution_time"] * 1e3
        assert 0.85 * exec_ms <= phase_sum <= min(1.02 * exec_ms,
                                                  1.02 * e2e), (phase_sum,
                                                                exec_ms, c)
        assert cost["decode_tokens"] == 8, cost
        # SLO evaluator saw every completed request
        slo = requests.get(f"{base}/api/slo").json()
        assert slo["requests_total"] >= len(rids), slo
        # decode profiler surface answers (disabled by default)
        prof = requests.get(f"{base}/api/profile").json()
        assert prof["nodes"]["w0"]["tiny-llama"]["summary"][
            "enabled"] is False, prof

        # flight recorder: events flow end-to-end (emit -> group-commit
        # store -> /api/events) and the type filter works
        ev = requests.get(f"{base}/api/events").json()
        assert ev["status"] == "success" and ev["events"], ev
        types = {e["type"] for e in ev["events"]}
        assert "node-added" in types, types
        flt = requests.get(f"{base}/api/events",
                           params={"type": "node-added"}).json()
        assert flt["events"] and all(e["type"] == "node-added"
                                     for e in flt["events"]), flt
        assert flt["events"][0].get("node") == "w0", flt
        with open("/tmp/dli_events.json", "w") as f:
            json.dump(ev, f, indent=1)
        # journey endpoint returns one CONNECTED timeline: starts at
        # submission, contains the terminal transition, time-ordered,
        # with the cost phases partitioning the tail
        jr = requests.get(f"{base}/api/requests/{rid}/journey").json()
        assert jr["status"] == "success" and jr["connected"], jr
        entry_ts = [e["t"] for e in jr["entries"]]
        assert entry_ts == sorted(entry_ts), jr["entries"]
        life = [e["name"] for e in jr["entries"]
                if e["kind"] == "lifecycle"]
        assert life[0] == "submitted" and "completed" in life, life
        assert [p["phase"] for p in jr["phases"]] == [
            "queue", "prefill", "decode"], jr["phases"]

        out = subprocess.run(
            ["bash", "scripts/collect_debug_bundle.sh", base,
             "/tmp/dli_debug_bundle.tar.gz"],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        print("telemetry smoke ok:",
              json.dumps({"series_points": len(pts),
                          "phase_sum_ms": round(phase_sum, 1),
                          "e2e_ms": e2e,
                          "slo_requests": slo["requests_total"],
                          "events": len(ev["events"]),
                          "journey_entries": len(jr["entries"]),
                          "bundle": out.stdout.strip()}),
              file=sys.stderr)
        rc = 0
    finally:
        m.stop()
        agent.service.shutdown()
    return rc


if __name__ == "__main__":
    sys.exit(main())
