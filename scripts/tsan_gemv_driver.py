"""ThreadSanitizer harness for the native RowPool (qgemv.cc).

Run by ``scripts/check.sh --tsan`` as:

    DLI_NATIVE_TSAN=1 python scripts/tsan_gemv_driver.py --build-only
    LD_PRELOAD=$(g++ -print-file-name=libtsan.so) \\
        TSAN_OPTIONS="suppressions=scripts/tsan.supp exitcode=66" \\
        python scripts/tsan_gemv_driver.py

The build step runs WITHOUT the TSan runtime preloaded (it only needs
g++ and the XLA FFI headers off a normal-speed jax import); the run
step loads the instrumented library through ctypes — never importing
jax — because a TSan-intercepted process pays minutes per heavyweight
import while numpy+ctypes stay in seconds.

What it exercises (every concurrency edge the pool has):

- concurrent GEMV dispatches from many python threads (the pool
  serializes them on ``api_mu_`` — a regression there is exactly what
  TSan exists to catch),
- runtime pool resizes (``DliGemvSetThreads``) racing those dispatches,
  including mid-run worker spawns picking up the current generation,
- every kernel shape class: M == 1 (fused path), M in 2..4 (register
  block), M > 4 (blocked fallback), int8 and f32 weight formats,
- a numerical cross-check against numpy per thread, so the harness
  also fails on data corruption, not just on TSan reports.

Exit codes: 0 clean, 1 harness failure (wrong numerics / lib missing),
66 TSan report (set via TSAN_OPTIONS exitcode — TSan exits the process
itself when a race is found and ``halt_on_error=1``).
"""

import argparse
import ctypes
import os
import sys
import threading

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LIB = os.path.join(ROOT, "distributed_llm_inferencing_tpu", "native",
                   "libdli_qgemv_tsan.so")


def build() -> int:
    os.environ["DLI_NATIVE_TSAN"] = "1"
    sys.path.insert(0, ROOT)
    from distributed_llm_inferencing_tpu.ops import cpu_gemv
    path = cpu_gemv._build()
    print(f"tsan build: {path}")
    return 0 if os.path.exists(path) else 1


def run(threads: int = 8, iters: int = 200) -> int:
    import numpy as np
    if not os.path.exists(LIB):
        print(f"tsan lib missing ({LIB}); run --build-only first",
              file=sys.stderr)
        return 1
    lib = ctypes.CDLL(LIB)
    i64 = ctypes.c_int64
    lib.DliGemvI8Direct.argtypes = [ctypes.c_void_p] * 4 + [i64] * 3
    lib.DliGemvF32Direct.argtypes = [ctypes.c_void_p] * 3 + [i64] * 3
    lib.DliGemvSetThreads.argtypes = [ctypes.c_int]
    lib.DliGemvGetThreads.restype = ctypes.c_int

    k, n = 384, 512
    rng = np.random.default_rng(0)
    w = rng.standard_normal((n, k), dtype=np.float32)
    wq = np.clip(np.round(w * 16), -127, 127).astype(np.int8)
    scale = np.full((n,), 1 / 16, np.float32)
    failures = []

    def hammer(tid: int):
        r = np.random.default_rng(tid)
        for i in range(iters):
            m = int(r.integers(1, 9))      # 1 / 2-4 / blocked paths
            x = r.standard_normal((m, k), dtype=np.float32)
            y = np.empty((m, n), np.float32)
            if i % 2 == 0:
                lib.DliGemvI8Direct(
                    x.ctypes.data, wq.ctypes.data, scale.ctypes.data,
                    y.ctypes.data, m, k, n)
                want = x @ (wq.astype(np.float32).T * scale)
            else:
                lib.DliGemvF32Direct(
                    x.ctypes.data, w.ctypes.data, y.ctypes.data, m, k, n)
                want = x @ w.T
            if not np.allclose(y, want, rtol=2e-3, atol=2e-3):
                failures.append((tid, i, float(np.abs(y - want).max())))
                return

    def resizer():
        r = np.random.default_rng(99)
        for _ in range(iters // 2):
            lib.DliGemvSetThreads(int(r.integers(1, 7)))
        lib.DliGemvSetThreads(0)            # restore the default

    ts = [threading.Thread(target=hammer, args=(t,))
          for t in range(threads)] + [threading.Thread(target=resizer)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if failures:
        print(f"numerical mismatches under concurrency: {failures[:5]}",
              file=sys.stderr)
        return 1
    print(f"tsan harness clean: {threads} threads x {iters} dispatches, "
          f"pool now {lib.DliGemvGetThreads()} threads")
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--build-only", action="store_true")
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--iters", type=int, default=200)
    a = ap.parse_args()
    sys.exit(build() if a.build_only
             else run(threads=a.threads, iters=a.iters))
