"""Integration tests: worker agent + master control plane over localhost HTTP.

Reproduces the reference's primary call stack (SURVEY.md §3.1) — submit →
queue → dispatch → worker load+infer → poll result — against real sockets,
plus the failure-handling upgrades (retry/failover, strikes, reactivation).
"""

import json
import time

import pytest
import requests

from distributed_llm_inferencing_tpu.runtime.master import Master
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent


@pytest.fixture(scope="module")
def worker():
    agent = WorkerAgent()
    srv = agent.serve(host="127.0.0.1", port=0, background=True)
    port = srv.server_address[1]
    yield agent, port
    agent.service.shutdown()


@pytest.fixture()
def master():
    m = Master(":memory:", dispatcher_threads=2, health_interval=0.5)
    m.start_background()
    srv = m.service.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    yield m, port
    m.stop()


def _url(port, path):
    return f"http://127.0.0.1:{port}{path}"


def _wait_status(port, req_id, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = requests.get(_url(port, f"/api/inference/status/{req_id}")).json()
        if r["request"]["status"] in ("completed", "failed"):
            return r["request"]
        time.sleep(0.2)
    raise TimeoutError("request never finished")


# ---- worker alone ----------------------------------------------------

def test_worker_health(worker):
    _, port = worker
    r = requests.get(_url(port, "/health")).json()
    assert r["status"] == "online"
    assert r["resources"]["devices"]
    assert isinstance(r["loaded_models"], list)


def test_worker_load_requires_checkpoint_or_optin(worker):
    _, port = worker
    r = requests.post(_url(port, "/load_model"),
                      json={"model_name": "tiny-gpt2"})
    assert r.status_code == 400
    assert "allow_random_init" in r.json()["message"]


def test_worker_load_infer_unload(worker):
    _, port = worker
    r = requests.post(_url(port, "/load_model"), json={
        "model_name": "tiny-gpt2", "allow_random_init": True,
        "dtype": "float32", "max_seq": 64})
    assert r.status_code == 200, r.text
    # idempotent second load (reference worker/app.py:106-110)
    r2 = requests.post(_url(port, "/load_model"), json={
        "model_name": "tiny-gpt2", "allow_random_init": True})
    assert "already loaded" in r2.json()["message"]

    r = requests.post(_url(port, "/inference"), json={
        "model_name": "tiny-gpt2", "prompt_tokens": [1, 2, 3],
        "max_new_tokens": 5, "sampling": {"do_sample": False}})
    assert r.status_code == 200, r.text
    data = r.json()
    assert data["status"] == "success"
    assert len(data["tokens"]) == 5
    assert data["execution_time"] > 0

    r = requests.post(_url(port, "/unload_model"),
                      json={"model_name": "tiny-gpt2"})
    assert r.json()["status"] == "success"
    r = requests.post(_url(port, "/unload_model"),
                      json={"model_name": "tiny-gpt2"})
    assert r.status_code == 404


def test_worker_streaming(worker):
    _, port = worker
    requests.post(_url(port, "/load_model"), json={
        "model_name": "tiny-gpt2", "allow_random_init": True,
        "dtype": "float32", "max_seq": 64})
    with requests.post(_url(port, "/inference_stream"), json={
            "model_name": "tiny-gpt2", "prompt_tokens": [4, 5],
            "max_new_tokens": 4, "sampling": {"do_sample": False}},
            stream=True) as r:
        assert r.status_code == 200
        events = []
        for line in r.iter_lines():
            if line.startswith(b"data: "):
                events.append(json.loads(line[6:]))
    kinds = [e["event"] for e in events]
    assert kinds.count("token") == 4
    assert kinds[-1] == "done"
    requests.post(_url(port, "/unload_model"), json={"model_name": "tiny-gpt2"})


def test_worker_auth():
    agent = WorkerAgent(auth_key="sekrit")
    srv = agent.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    try:
        assert requests.get(_url(port, "/health")).status_code == 401
        r = requests.get(_url(port, "/health"),
                         headers={"Authorization": "Bearer sekrit"})
        assert r.status_code == 200
    finally:
        agent.service.shutdown()


# ---- master + worker end-to-end --------------------------------------

def test_end_to_end_submit_poll(worker, master):
    _, wport = worker
    m, mport = master
    r = requests.post(_url(mport, "/api/nodes/add"), json={
        "name": "w1", "host": "127.0.0.1", "port": wport}).json()
    assert r["status"] == "success", r

    req = requests.post(_url(mport, "/api/inference/submit"), json={
        "model_name": "tiny-gpt2", "prompt": "hi",
        "max_new_tokens": 4,
        "sampling": {"do_sample": False, "allow_random_init": True},
    }).json()
    assert req["status"] == "success"
    done = _wait_status(mport, req["request_id"])
    assert done["status"] == "completed", done
    assert done["node_id"] is not None
    assert done["execution_time"] > 0

    recent = requests.get(_url(mport, "/api/inference/recent")).json()
    assert recent["counts"]["completed"] >= 1

    # pages render
    for path in ("/", "/nodes", "/inference"):
        page = requests.get(_url(mport, path))
        assert page.status_code == 200
        assert "<html" in page.text

    # node status shows the worker with the loaded model
    ns = requests.get(_url(mport, "/api/nodes/status")).json()
    assert ns["nodes"][0]["is_active"]


def test_master_rejects_unreachable_node(master):
    _, mport = master
    r = requests.post(_url(mport, "/api/nodes/add"), json={
        "name": "ghost", "host": "127.0.0.1", "port": 1})
    assert r.status_code == 502


def test_master_plan_api(master):
    _, mport = master
    r = requests.post(_url(mport, "/api/plans/create"), json={
        "model_name": "llama-3-8b", "mesh": {"tp": 4}}).json()
    assert r["status"] == "success"
    assert r["plan"]["num_devices"] == 4
    plans = requests.get(_url(mport, "/api/plans")).json()
    assert len(plans["plans"]) == 1


def test_plan_create_and_deploy_ui_flow(worker, master):
    """The nodes page's plan mutation surface end-to-end: the same
    create → deploy POSTs the dashboard form/button issue (the reference
    kept this mutation surface in Django admin only, admin.py:4-19, and
    never actually called /load_shard, SURVEY.md §3.2)."""
    _, wport = worker
    m, mport = master
    requests.post(_url(mport, "/api/nodes/add"), json={
        "name": "wplan", "host": "127.0.0.1", "port": wport})
    r = requests.post(_url(mport, "/api/plans/create"), json={
        "model_name": "tiny-gpt2", "mesh": {"tp": 1}, "max_seq": 64}).json()
    assert r["status"] == "success", r
    pid = r["plan_id"]
    d = requests.post(_url(mport, f"/api/plans/deploy/{pid}"), json={
        "allow_random_init": True, "dtype": "float32"}).json()
    assert d["status"] == "success", d
    plans = requests.get(_url(mport, "/api/plans")).json()["plans"]
    mine = [p for p in plans if p["id"] == pid]
    assert mine and mine[0]["is_loaded"] and mine[0]["node_id"] is not None
    # the worker really holds the model now
    h = requests.get(_url(wport, "/health")).json()
    assert any(mdl["name"] == "tiny-gpt2" for mdl in h["loaded_models"])
    requests.post(_url(wport, "/unload_model"),
                  json={"model_name": "tiny-gpt2"})
    # the page ships the mutation form + deploy wiring
    page = requests.get(_url(mport, "/nodes")).text
    assert "Create Placement Plan" in page
    assert "deployPlan" in page and "/api/plans/deploy/" in page
    assert "/api/plans/create" in page


def test_user_error_does_not_strike_node(worker, master):
    """An unknown model name must fail the request immediately without
    deactivating the (healthy) node."""
    _, wport = worker
    m, mport = master
    requests.post(_url(mport, "/api/nodes/add"), json={
        "name": "w1", "host": "127.0.0.1", "port": wport})
    req = requests.post(_url(mport, "/api/inference/submit"), json={
        "model_name": "no-such-model", "prompt": "x",
        "sampling": {"allow_random_init": True}}).json()
    done = _wait_status(mport, req["request_id"], timeout=20)
    assert done["status"] == "failed"
    assert "rejected" in done["error"]
    ns = requests.get(_url(mport, "/api/nodes/status")).json()
    assert ns["nodes"][0]["is_active"], "healthy node was struck offline"


def test_max_length_reference_semantics(worker, master):
    """max_length counts prompt+new tokens (reference views.py:351)."""
    _, wport = worker
    m, mport = master
    requests.post(_url(mport, "/api/nodes/add"), json={
        "name": "w1", "host": "127.0.0.1", "port": wport})
    # ByteTokenizer: "hello" -> BOS + 5 bytes = 6 tokens; max_length=10 -> 4 new
    req = requests.post(_url(mport, "/api/inference/submit"), json={
        "model_name": "tiny-gpt2", "prompt": "hello", "max_length": 10,
        "sampling": {"do_sample": False, "allow_random_init": True}}).json()
    done = _wait_status(mport, req["request_id"])
    assert done["status"] == "completed", done
    assert done["max_length"] == 10


def test_failed_request_after_node_death(worker, master):
    """Kill the only node → request fails with a real error after retries
    (reference: mark_failed with no retry, views.py:364-378)."""
    m, mport = master
    # add a node then kill it by pointing at a dead port
    agent = WorkerAgent()
    srv = agent.serve("127.0.0.1", 0, background=True)
    dead_port = srv.server_address[1]
    requests.post(_url(mport, "/api/nodes/add"), json={
        "name": "dying", "host": "127.0.0.1", "port": dead_port})
    agent.service.shutdown()  # node is now dead

    req = requests.post(_url(mport, "/api/inference/submit"), json={
        "model_name": "tiny-gpt2", "prompt": "x",
        "sampling": {"allow_random_init": True}}).json()
    done = _wait_status(mport, req["request_id"], timeout=30)
    assert done["status"] == "failed"
    assert done["error"]


def test_ssh_setup_parity(worker):
    """Reference worker/app.py:374-413: /ssh_setup probes a connection.
    paramiko is optional here (the reference used-but-never-declared it,
    SURVEY.md §5.9), and the endpoint refuses to exist without worker
    auth — it is an SSRF primitive otherwise."""
    _, port = worker
    # unauthenticated worker: hard 403 regardless of body
    r = requests.post(_url(port, "/ssh_setup"),
                      json={"host": "127.0.0.1", "username": "u",
                            "password": "p", "port": 1})
    assert r.status_code == 403

    agent = WorkerAgent(auth_key="s3")
    srv = agent.serve("127.0.0.1", 0, background=True)
    aport = srv.server_address[1]
    try:
        hdr = {"Authorization": "Bearer s3"}
        r = requests.post(_url(aport, "/ssh_setup"), headers=hdr,
                          json={"host": "127.0.0.1", "username": "u",
                                "password": "p", "port": 1})
        try:
            import paramiko  # noqa: F401
            assert r.status_code == 502      # closed port -> connect fails
            r2 = requests.post(_url(aport, "/ssh_setup"), headers=hdr,
                               json={"host": "x"})
            assert r2.status_code == 400     # missing username
        except ImportError:
            assert r.status_code == 501
            assert "paramiko" in r.json()["message"]
    finally:
        agent.service.shutdown()


def test_admin_cli(worker, master):
    """The admin CLI drives the master API end-to-end (≙ Django admin)."""
    import io
    from contextlib import redirect_stdout
    from distributed_llm_inferencing_tpu.__main__ import main as cli

    _, wport = worker
    _, mport = master
    base = f"http://127.0.0.1:{mport}"

    def run(*argv):
        buf = io.StringIO()
        with redirect_stdout(buf):
            cli(["admin", "--master", base, *argv])
        return json.loads(buf.getvalue())

    out = run("add-node", "--name", "adm1", "--node_host", "127.0.0.1",
              "--node_port", str(wport))
    assert out["status"] == "success"
    nodes = run("nodes")
    assert any(n["name"] == "adm1" for n in nodes["nodes"])
    out = run("load-model", "--model_name", "tiny-gpt2",
              "--allow_random_init")
    assert out["status"] == "success", out
    reqs = run("requests")
    assert "counts" in reqs
    node_id = [n["id"] for n in nodes["nodes"] if n["name"] == "adm1"][0]
    out = run("remove-node", "--node_id", str(node_id))
    assert out["status"] == "success"


def test_master_cancel_frees_worker_slot(master):
    """Master-side cancel reaches the worker's batcher and frees the slot
    (VERDICT round-1 item 7 done-condition)."""
    m, mport = master
    agent = WorkerAgent()
    srv = agent.serve(host="127.0.0.1", port=0, background=True)
    wport = srv.server_address[1]
    try:
        r = requests.post(_url(wport, "/load_model"), json={
            "model_name": "tiny-llama", "allow_random_init": True,
            "serving": "batched", "kv_blocks": 64, "kv_block_size": 8,
            "slots": 2, "max_seq": 128, "dtype": "float32",
        }, timeout=300)
        assert r.status_code == 200, r.text
        r = requests.post(_url(mport, "/api/nodes/add"), json={
            "name": "cancel-node", "host": "127.0.0.1", "port": wport,
        }, timeout=30)
        assert r.status_code == 200, r.text

        r = requests.post(_url(mport, "/api/inference/submit"), json={
            "model_name": "tiny-llama", "prompt": "hello world",
            "max_new_tokens": 110,
        }, timeout=30)
        req_id = r.json()["request_id"]

        # wait until it's actually running on the worker, then cancel
        deadline = time.time() + 60
        cancelled = False
        while time.time() < deadline and not cancelled:
            c = requests.post(
                _url(mport, f"/api/inference/cancel/{req_id}"), timeout=30)
            if c.status_code == 200 and "relayed" in c.json()["message"]:
                cancelled = True
            elif c.status_code == 409 and "already" in c.json()["message"]:
                raise AssertionError(f"finished before cancel: {c.json()}")
            time.sleep(0.1)
        assert cancelled

        req = _wait_status(mport, req_id)
        assert req["status"] == "failed"
        assert "cancel" in req["error"]

        deadline = time.time() + 30
        while time.time() < deadline:
            st = requests.get(_url(wport, "/health")).json()[
                "loaded_models"][0]["scheduler"]
            if st["active"] == 0:
                break
            time.sleep(0.2)
        assert st["active"] == 0, st
    finally:
        agent.service.shutdown()


def test_dashboard_pages_surface_serving_internals(master):
    """The three pages render, and the round-2 additions are present:
    batcher stats on the dashboard, placement plans on the nodes page
    (≙ reference node_management.html:154-171 shard table)."""
    _, mport = master
    dash = requests.get(_url(mport, "/")).text
    assert "Batched Serving" in dash and "Prefix hit rate" in dash
    nodes = requests.get(_url(mport, "/nodes")).text
    assert "Placement Plans" in nodes and "/api/plans" in nodes
    inf = requests.get(_url(mport, "/inference")).text
    assert "Run Inference" in inf


def test_worker_streaming_speculative(worker):
    """SSE streaming with speculative decoding on: every token arrives as
    its own event (chunk-verified tokens are re-serialized per token) and
    the stream matches the non-streaming result."""
    _, wport = worker
    requests.post(_url(wport, "/load_model"), json={
        "model_name": "tiny-gpt2", "allow_random_init": True,
        "dtype": "float32", "max_seq": 128})
    body = {"model_name": "tiny-gpt2", "prompt_tokens": [7, 3] * 6,
            "max_new_tokens": 18, "sampling": {"do_sample": False},
            "speculative": "ngram", "spec_gamma": 4}
    import json as _json
    with requests.post(_url(wport, "/inference_stream"), json=body,
                       stream=True, timeout=300) as r:
        assert r.status_code == 200
        events = [_json.loads(l[6:]) for l in r.iter_lines()
                  if l.startswith(b"data: ")]
    toks = [e["token"] for e in events if e["event"] == "token"]
    assert events[-1]["event"] == "done"
    plain = requests.post(_url(wport, "/inference"), json=body,
                          timeout=300).json()
    assert toks == plain["tokens"] and len(toks) == 18
    requests.post(_url(wport, "/unload_model"),
                  json={"model_name": "tiny-gpt2"})


def test_worker_serves_deepseek_moe(worker):
    """The flagship MLA + MoE family through the worker's HTTP surface:
    load (random-init registry model), infer, unload — the same wire
    protocol the reference exposes for any model (reference
    worker/app.py:49-330), exercised on a mixed dense-prefix MLA stack
    with the latent KV cache auto-enabled by the engine underneath."""
    _, port = worker
    r = requests.post(_url(port, "/load_model"), json={
        "model_name": "tiny-deepseek", "allow_random_init": True,
        "dtype": "float32", "max_seq": 64})
    assert r.status_code == 200, r.text

    r = requests.post(_url(port, "/inference"), json={
        "model_name": "tiny-deepseek", "prompt_tokens": [4, 9, 2, 7],
        "max_new_tokens": 6, "sampling": {"do_sample": False}})
    assert r.status_code == 200, r.text
    data = r.json()
    assert data["status"] == "success" and len(data["tokens"]) == 6

    r = requests.post(_url(port, "/unload_model"),
                      json={"model_name": "tiny-deepseek"})
    assert r.json()["status"] == "success"
