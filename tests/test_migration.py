"""Live in-flight migration + elastic rebalancing.

Covers the acceptance-critical invariants:
- a mid-generation migration is BITWISE invisible to the client: the
  resumed continuation emits exactly the tokens the unmigrated run
  would have (greedy AND sampled — the position-keyed PRNG continues
  the same stream), with zero duplicated and zero lost stream tokens
  (the source's stream cursor + the destination's new tokens partition
  the full output exactly),
- the migrate-vs-complete race is safe at every layer: the batcher
  answers None/409 when the request finished first, and a handoff can
  never resurrect a terminal row (the dliverify ``migrate_vs_complete``
  scenario model-checks the store's side),
- role is mutable worker state: POST /role flips it, /health and the
  numeric ``dli_worker_role`` gauge re-advertise it,
- master-driven migration end-to-end: draining a node live-migrates
  its in-flight request (303 handoff -> requeue_migrated -> resume on
  a peer with a real cross-node KV transfer) with an identical result,
- chaos: killing a worker mid-stream loses nothing — the failover
  retry completes the request with identical output, and a
  disaggregated request's persisted kv_source makes the recovery a
  re-fetch, not a re-prefill (FailSafe),
- the rebalancer's decision function: flips toward the starving pool
  on sustained TSDB divergence, honors the per-node cooldown, never
  empties the decode-capable pool, and migrates in-flight work off
  draining nodes.
"""

import json
import threading
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import requests as rq

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher
from distributed_llm_inferencing_tpu.runtime.master import Master
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)

LONG_PROMPT = "The quick brown fox jumps over the lazy dog. " * 2 + "Go."
PROMPT_TOKS = list(range(7, 7 + 21))   # 21 tokens: several full 8-blocks


# ---- batcher-level: snapshot + resume ----------------------------------

def _mk_batcher(**kw):
    kw.setdefault("num_blocks", 64)
    kw.setdefault("block_size", 8)
    kw.setdefault("slots", 4)
    kw.setdefault("max_seq", 128)
    kw.setdefault("kv_host_mb", 8)
    # small decode chunks so a migration request lands mid-stream, not
    # after the whole budget ran inside one chunk
    kw.setdefault("decode_chunk_cap", 4)
    return ContinuousBatcher(CFG, PARAMS, **kw)


def _wait_tokens(req, n, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if len(req.tokens) >= n or req.done.is_set():
            return
        time.sleep(0.002)
    raise AssertionError(f"never reached {n} tokens "
                         f"({len(req.tokens)} emitted)")


@pytest.mark.parametrize("do_sample", [False, True],
                         ids=["greedy", "sampled"])
def test_batcher_migrate_stream_zero_dup_zero_loss(do_sample):
    """The headline guarantee at the batcher layer: source stream +
    destination stream partition the unmigrated run's exact token
    sequence — nothing duplicated, nothing lost, bitwise identical."""
    sp = SamplingParams(temperature=0.8, top_k=20, do_sample=do_sample)
    ref_b = _mk_batcher()
    ref_b.start()
    try:
        ref = ref_b.submit(PROMPT_TOKS, max_new_tokens=40, sampling=sp,
                           seed=5).wait(timeout=120)
    finally:
        ref_b.stop()

    src = _mk_batcher()
    src.start()
    s1 = []
    req = src.submit(PROMPT_TOKS, max_new_tokens=40, sampling=sp,
                     stream_cb=s1.append, seed=5)
    _wait_tokens(req, 6)
    rec = src.migrate_out(req)
    src.stop()
    assert rec is not None and req._migrated
    # the resume record IS the stream cursor: exactly what streamed
    assert rec["tokens"] == s1 and 0 < len(s1) < 40
    assert rec["seed"] == 5 and rec["steps"] == len(s1)

    dst = _mk_batcher()
    dst.start()
    try:
        # host-arena handover (the HTTP twin — /kv_fetch — is pinned in
        # test_disagg and the worker-level test below)
        for d in list(src.kvtier.arena._entries):
            dst.kvtier.arena.put(d, src.kvtier.arena.peek_pages(d),
                                 count_offload=False)
        s2 = []
        req2 = dst.submit(rec["prompt_tokens"],
                          max_new_tokens=rec["max_new_tokens"],
                          sampling=sp, stream_cb=s2.append,
                          eos_token_id=rec["eos_token_id"], resume=rec)
        full = req2.wait(timeout=120)
    finally:
        dst.stop()
    assert s1 + s2 == full == ref
    # the snapshot was actually used: the destination restored blocks
    # from the migrated KV instead of re-prefilling everything
    c = dst.metrics.snapshot()["counters"]
    assert c.get("kvtier_restored_blocks", 0) > 0


def test_batcher_migrate_races_completion_returns_none():
    b = _mk_batcher()
    b.start()
    try:
        req = b.submit(PROMPT_TOKS, max_new_tokens=2,
                       sampling=SamplingParams.greedy())
        req.wait(timeout=60)
        assert b.migrate_out(req, timeout=2.0) is None
        assert not req._migrated and not req.error
    finally:
        b.stop()


def test_batcher_migrate_queued_request():
    """A request still in the queue migrates by resume record alone
    (nothing on device yet)."""
    b = _mk_batcher(slots=1)
    b.start()
    try:
        hog = b.submit(PROMPT_TOKS, max_new_tokens=60,
                       sampling=SamplingParams.greedy())
        _wait_tokens(hog, 2)
        queued = b.submit(list(range(40, 55)), max_new_tokens=20,
                          sampling=SamplingParams.greedy(), seed=3)
        rec = b.migrate_out(queued, timeout=30)
        assert rec is not None and rec["tokens"] == []
        assert rec["prompt_tokens"] == list(range(40, 55))
        hog.cancel()
    finally:
        b.stop()


def test_migrated_accounting_not_failed():
    """A handoff is not a failure: it lands in
    batcher_requests_migrated, and submitted reconciles with
    completed + failed + migrated."""
    b = _mk_batcher()
    b.start()
    try:
        req = b.submit(PROMPT_TOKS, max_new_tokens=40,
                       sampling=SamplingParams.greedy())
        _wait_tokens(req, 4)
        assert b.migrate_out(req) is not None
        c = b.metrics.snapshot()["counters"]
        assert c["batcher_requests_migrated"] == 1
        assert c["batcher_requests_submitted"] == (
            c.get("batcher_requests_completed", 0)
            + c.get("batcher_requests_failed", 0)
            + c["batcher_requests_migrated"])
    finally:
        b.stop()


def test_resume_record_spec_state_roundtrip():
    """The spec-controller's request-owned policy state survives an
    export/load cycle (gamma, mode, acceptance window)."""
    from distributed_llm_inferencing_tpu.ops.speculative import (
        AdaptiveSpecController)
    a = AdaptiveSpecController(8)
    a.gamma = 2
    a.mode = "plain"
    a._accept.extend([(1, 4), (0, 4)])
    b = AdaptiveSpecController(8)
    b.load_state(a.export_state())
    assert b.gamma == 2 and b.mode == "plain"
    assert list(b._accept) == [(1, 4), (0, 4)]
    # malformed state is ignored field-by-field, never raises
    c = AdaptiveSpecController(8)
    c.load_state({"gamma": "x", "mode": "bogus", "accept": [[1]]})
    assert c.gamma == 8 and c.mode == "spec"


# ---- worker-level: /migrate_out, /role, cross-node resume ---------------

def _mk_worker(role="mixed", **load_kw):
    agent = WorkerAgent(role=role)
    srv = agent.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    body = {"model_name": "tiny-llama", "allow_random_init": True,
            "dtype": "float32", "serving": "batched", "slots": 4,
            "kv_blocks": 64, "kv_block_size": 8, "max_seq": 128,
            "decode_chunk_cap": 4}
    body.update(load_kw)
    r = rq.post(f"http://127.0.0.1:{port}/load_model", json=body,
                timeout=600)
    assert r.status_code == 200, r.text
    return agent, port


def _infer(port, max_new=24, seed=11, do_sample=False, **extra):
    body = {"model_name": "tiny-llama", "prompt": LONG_PROMPT,
            "max_new_tokens": max_new, "seed": seed,
            "sampling": {"do_sample": do_sample, "temperature": 0.8,
                         "top_k": 20}}
    body.update(extra)
    return rq.post(f"http://127.0.0.1:{port}/inference", json=body,
                   timeout=600)


@pytest.fixture(scope="module")
def worker_pair():
    a = _mk_worker()
    b = _mk_worker()
    yield a, b
    for agent, _ in (a, b):
        agent.service.shutdown()


def test_role_flip_endpoint(worker_pair):
    (agent, port), _ = worker_pair
    assert rq.get(f"http://127.0.0.1:{port}/health").json()[
        "role"] == "mixed"
    r = rq.post(f"http://127.0.0.1:{port}/role",
                json={"role": "decode"}, timeout=10)
    assert r.status_code == 200
    assert r.json() == {"status": "success", "role": "decode",
                        "previous": "mixed"}
    h = rq.get(f"http://127.0.0.1:{port}/health").json()
    assert h["role"] == "decode"
    snap = agent.metrics.snapshot()
    assert snap["gauges"]["worker_role"] == 2.0
    assert snap["counters"]["role_flips"] == 1
    assert rq.post(f"http://127.0.0.1:{port}/role",
                   json={"role": "gpu"}, timeout=10).status_code == 400
    rq.post(f"http://127.0.0.1:{port}/role", json={"role": "mixed"},
            timeout=10)


def test_migrate_out_validation(worker_pair):
    (_, port), _ = worker_pair
    url = f"http://127.0.0.1:{port}/migrate_out"
    assert rq.post(url, json={}, timeout=10).status_code == 400
    assert rq.post(url, json={"request_tag": "ghost"},
                   timeout=10).status_code == 404


@pytest.mark.parametrize("do_sample", [False, True],
                         ids=["greedy", "sampled"])
def test_worker_migrate_resume_bitwise(worker_pair, do_sample):
    """Cross-node migration over the real wire: /migrate_out snapshot
    on A, 303 handoff with the resume record, resume on B pulling the
    mid-generation KV over /kv_fetch — final output bitwise identical
    to an unmigrated run."""
    (a, pa), (b, pb) = worker_pair
    seed = 21 if do_sample else 22
    ref = _infer(pb, seed=seed, do_sample=do_sample).json()["tokens"]

    tag = f"mig-{seed}"
    out = {}

    def run():
        out["r"] = _infer(pa, seed=seed, do_sample=do_sample,
                          request_tag=tag, timeout=120)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    deadline = time.time() + 60
    breq = None
    while time.time() < deadline:
        breq = a._tagged.get(tag)
        if breq is not None and len(breq.tokens) >= 5:
            break
        time.sleep(0.002)
    assert breq is not None and len(breq.tokens) >= 5
    r = rq.post(f"http://127.0.0.1:{pa}/migrate_out",
                json={"request_tag": tag, "model_name": "tiny-llama"},
                timeout=30)
    assert r.status_code == 200, r.text
    t.join(timeout=60)
    resp = out["r"]
    assert resp.status_code == 303, resp.text
    rec = resp.json()["resume"]
    assert 5 <= len(rec["tokens"]) < 24

    before = b.metrics.snapshot()["counters"].get("kv_transfer_blocks", 0)
    got = _infer(pb, seed=seed, do_sample=do_sample, resume=rec,
                 kv_source={"url": f"http://127.0.0.1:{pa}",
                            "model": "tiny-llama"}).json()
    assert got["tokens"] == ref
    after = b.metrics.snapshot()["counters"].get("kv_transfer_blocks", 0)
    assert after > before      # the resume actually fetched KV from A
    assert a.metrics.snapshot()["counters"]["requests_migrated_out"] >= 1


# ---- master-level: drain migration + chaos ------------------------------

def _cluster(roles, load_kw=None, **master_kw):
    workers = [_mk_worker(role=r, **(load_kw or {})) for r in roles]
    master_kw.setdefault("health_interval", 0.5)
    master_kw.setdefault("disagg", False)
    m = Master(":memory:", **master_kw)
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{msrv.server_address[1]}"
    for i, (_, port) in enumerate(workers):
        r = rq.post(f"{base}/api/nodes/add",
                    json={"name": f"w{i}", "host": "127.0.0.1",
                          "port": port}, timeout=30).json()
        assert r["status"] == "success", r
    m.start_background()
    return m, base, workers


def _submit(base, max_new=30, prompt=LONG_PROMPT):
    return rq.post(f"{base}/api/inference/submit", json={
        "model_name": "tiny-llama", "prompt": prompt,
        "max_new_tokens": max_new,
        "sampling": {"do_sample": False, "allow_random_init": True}},
        timeout=30).json()["request_id"]


def _wait_req(base, rid, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = rq.get(f"{base}/api/inference/status/{rid}",
                    timeout=30).json()["request"]
        if st["status"] in ("completed", "failed"):
            return st
        time.sleep(0.05)
    raise TimeoutError(f"request {rid} never finished")


def test_master_drain_migrates_inflight_live():
    """Draining a node live-migrates its in-flight request within one
    rebalancer sweep: 303 handoff -> requeue_migrated -> resume on the
    peer, identical result, zero attempts burned."""
    # Single-slot workers + hog requests: a warm tiny-llama decodes 100
    # tokens in ~0.3s, far faster than any realistic drain -> health
    # sweep -> rebalancer chain — so the measured request must WAIT
    # behind hogs on its node (one slot each), which holds it in the
    # batcher (worker-side queued or early-stream, _tagged either way)
    # long enough for the drain chain to land deterministically.
    m, base, workers = _cluster(
        ["mixed", "mixed"], load_kw={"slots": 1},
        rebalance=True, rebalance_interval_s=0.05,
        rebalance_sustain_s=0.5, health_interval=0.1)
    prompt, budget = "please continue the story", 100
    try:
        time.sleep(0.5)          # one health sweep: runtime roles fresh
        ref = _wait_req(base, _submit(base, max_new=budget,
                                      prompt=prompt))
        assert ref["status"] == "completed", ref

        hogs = [_submit(base, max_new=budget,
                        prompt=f"hog {i} holds the single slot")
                for i in range(4)]
        rid = _submit(base, max_new=budget, prompt=prompt)
        # drain the node the moment the request is dispatched AND
        # registered with the worker's batcher (queued behind a hog or
        # already streaming — migrate_out handles both)
        tag = m._tag(rid)
        node = breq = None
        deadline = time.time() + 30
        while time.time() < deadline:
            node = m._processing.get(rid)
            breq = next((w._tagged.get(tag) for w, _ in workers
                         if w._tagged.get(tag) is not None), None)
            if node is not None and breq is not None:
                break
            time.sleep(0.002)
        assert node is not None and breq is not None
        threading.Thread(
            target=lambda: rq.post(
                f"http://127.0.0.1:{node['port']}/drain",
                json={"timeout": 30}, timeout=60),
            daemon=True).start()
        st = _wait_req(base, rid)
        assert st["status"] == "completed", st
        assert st["result"] == ref["result"]
        assert st["attempts"] == 0     # a handoff is not a failure
        for h in hogs:                 # nothing lost in the shuffle
            assert _wait_req(base, h)["status"] == "completed"
        mc = m.metrics.snapshot()["counters"]
        assert mc["requests_migrated"] >= 1
        assert mc["rebalancer_migrations"] >= 1
    finally:
        m.stop()
        for agent, _ in workers:
            agent.service.shutdown()


def test_chaos_kill_worker_mid_stream_recovers_via_kv_fetch():
    """FailSafe: kill the decode node mid-request. The failover retry
    re-dispatches with the PERSISTED kv_source hint, so the surviving
    decode node recovers by fetching the prompt's KV from the prefill
    peer — identical output, zero failures, and the recovery shows
    cached/transferred prefill instead of a full re-prefill."""
    m, base, workers = _cluster(
        ["prefill", "decode", "decode"], disagg=True,
        disagg_min_prompt=64, infer_timeout=20)
    (pre, _), (d1, p1), (d2, p2) = workers
    try:
        time.sleep(0.8)
        ref = _wait_req(base, _submit(base))
        assert ref["status"] == "completed", ref

        rid = _submit(base)
        victim = None
        deadline = time.time() + 30
        while time.time() < deadline and victim is None:
            node = m._processing.get(rid)
            if node is not None and node["port"] in (p1, p2):
                victim = node
            time.sleep(0.002)
        assert victim is not None, "request never landed on a decode node"
        killed = d1 if victim["port"] == p1 else d2
        survivor = d2 if killed is d1 else d1
        # hard kill: stop serving AND sever the keep-alive sockets the
        # master would otherwise keep writing into
        killed.service.shutdown()
        st = _wait_req(base, rid, timeout=120)
        assert st["status"] == "completed", st
        assert st["result"] == ref["result"]
        assert st["attempts"] >= 1       # a real failover, not a no-op
        # recovery was a fetch/restore, not a cold re-prefill: the
        # surviving decode node pulled KV or the cost ledger shows
        # cached prefill tokens on the recovered attempt
        sc = survivor.metrics.snapshot()["counters"]
        cost = st.get("cost")
        if isinstance(cost, str):
            cost = json.loads(cost)
        assert (sc.get("kv_transfer_blocks", 0) > 0
                or (cost or {}).get("prefill_cached_tokens", 0) > 0)
    finally:
        m.stop()
        for agent, _ in workers:
            try:
                agent.service.shutdown()
            except Exception:
                pass


# ---- rebalancer decision units ------------------------------------------

class _Resp:
    def __init__(self, status_code=200, body=None):
        self.status_code = status_code
        self._body = body or {"status": "success"}
        self.text = json.dumps(self._body)

    def json(self):
        return self._body


def _decision_master(roles, queues, *, sustain=60.0, ratio=3.0):
    """Master with synthetic nodes + seeded TSDB queue-depth series —
    no live workers, no background threads; sweeps run by hand."""
    m = Master(":memory:", dispatcher_threads=0, rebalance=False,
               rebalance_sustain_s=sustain, rebalance_ratio=ratio)
    now = time.time()
    for i, (role, q) in enumerate(zip(roles, queues)):
        nid = m.store.add_node(f"n{i}", "127.0.0.1", 9000 + i,
                               is_active=True)
        m.store.update_node(nid, info={"role": role, "loaded_models": []})
        m._node_runtime[nid] = {"queue": q, "free_blocks": 10,
                                "arena_occ": 0.1, "role": role,
                                "at": now, "models": {}}
        for k in range(4):
            # sustained: 4 points inside the window, spread wider than
            # the TSDB's fine-bucket width so they stay distinct samples
            m.tsdb.record(f"n{i}", "batcher_queue_depth", q,
                          t=now - sustain + 1 + k * (m.tsdb.step_s + 1))
    m._flips = []
    m._worker_post = lambda node, path, body, timeout, stream=False: (
        m._flips.append((node["id"], path, dict(body))) or _Resp())
    m._refresh_node = lambda node: None
    return m


def test_rebalancer_flips_idle_prefill_to_decode():
    """The BENCH_r07 uniform-mix fix: decode pool starving, prefill
    idle -> flip the prefill node into the decode pool (the strict
    prefill pool MAY empty)."""
    m = _decision_master(["prefill", "decode"], [0, 6])
    try:
        m._maybe_flip_roles()
        assert m._flips == [(1, "/role", {"role": "decode"})]
        assert m.metrics.snapshot()["counters"][
            "rebalancer_role_flips"] == 1
    finally:
        m.stop()


def test_rebalancer_flips_spare_decode_to_prefill_never_last():
    # prefill drowning, two decode-capable nodes: flip the idler one
    m = _decision_master(["prefill", "decode", "mixed"], [8, 1, 0])
    try:
        m._maybe_flip_roles()
        assert m._flips == [(3, "/role", {"role": "prefill"})]
    finally:
        m.stop()
    # ...but NEVER the last decode-capable node, however loaded the
    # prefill pool is (every full request needs one)
    m = _decision_master(["prefill", "decode"], [8, 0])
    try:
        m._maybe_flip_roles()
        assert m._flips == []
    finally:
        m.stop()


def test_rebalancer_recreates_prefill_pool_on_disagg_demand():
    """Flip-back path: after the rebalancer emptied the strict prefill
    pool, disagg-eligible demand arriving with nowhere to prefill (the
    scheduler_disagg_no_prefill_pool counter) re-creates the pool from
    a decode-capable spare — emptying the pool must never disable
    disaggregation for the master's lifetime."""
    m = _decision_master(["decode", "decode", "mixed"], [1, 3, 2])
    try:
        m._maybe_flip_roles()
        assert m._flips == []          # no demand signal yet
        m.metrics.inc("scheduler_disagg_no_prefill_pool", 3)
        m._maybe_flip_roles()
        assert m._flips == [(1, "/role", {"role": "prefill"})]
        # the signal was consumed: a quiet next sweep flips nothing
        m._node_runtime[1]["role"] = "decode"   # pretend flip not seen
        m._flips.clear()
        m._maybe_flip_roles()
        assert m._flips == []
    finally:
        m.stop()
    # never down to the last decode-capable node, demand or not
    m = _decision_master(["decode"], [5])
    try:
        m.metrics.inc("scheduler_disagg_no_prefill_pool", 5)
        m._maybe_flip_roles()
        assert m._flips == []
    finally:
        m.stop()


def test_rebalancer_migrate_retries_after_transient_404():
    """A 404 from /migrate_out is transient (the tag registers with
    the batcher only after the submit-time prefetch): the request must
    NOT be poisoned out of future sweeps."""
    m = _decision_master(["mixed", "mixed"], [1, 1])
    try:
        rid = m.store.submit_request("mod", "hello")
        req = m.store.claim_next_pending()
        node = m.store.get_node(1)
        m.store.update_node(1, draining=1)
        m._processing[req["id"]] = node
        answers = [404, 200]
        m._worker_post = lambda *a, **k: (
            m._flips.append(a[1]) or _Resp(answers[len(m._flips) - 1]))
        m._migrate_inflight_off_hot()
        assert m._flips == ["/migrate_out"] and rid not in m._migrated_reqs
        m._migrate_inflight_off_hot()      # retried, 200 settles it
        assert m._flips == ["/migrate_out"] * 2
        assert rid in m._migrated_reqs
        m._migrate_inflight_off_hot()
        assert len(m._flips) == 2          # settled: no third POST
    finally:
        m.stop()


def test_rebalancer_flip_cooldown_and_sustain_requirement():
    m = _decision_master(["prefill", "decode"], [0, 6])
    try:
        m._maybe_flip_roles()
        assert len(m._flips) == 1
        # the flipped node's runtime role changed; make the divergence
        # persist artificially and sweep again: cooldown blocks a
        # re-flip of the same node, and no OTHER candidate exists
        m._node_runtime[1]["role"] = "prefill"   # pretend still split
        m._maybe_flip_roles()
        assert len(m._flips) == 1
    finally:
        m.stop()
    # no sustained data (a single TSDB point) -> no decision
    m = _decision_master(["prefill", "decode"], [0, 6])
    try:
        m.tsdb = type(m.tsdb)(window_s=60, step_s=1)   # wipe history
        m._maybe_flip_roles()
        assert m._flips == []
    finally:
        m.stop()


def test_rebalancer_migrates_off_draining_node():
    m = _decision_master(["mixed", "mixed"], [1, 1])
    try:
        rid = m.store.submit_request("mod", "hello")
        req = m.store.claim_next_pending()
        node = m.store.get_node(1)
        m.store.update_node(1, draining=1)
        m._processing[req["id"]] = node
        m._migrate_inflight_off_hot()
        assert (1, "/migrate_out",
                {"request_tag": m._tag(rid), "model_name": "mod"}) \
            in m._flips
        assert m.metrics.snapshot()["counters"][
            "rebalancer_migrations"] == 1
        # once per request: a second sweep does not re-POST
        m._flips.clear()
        m._migrate_inflight_off_hot()
        assert m._flips == []
    finally:
        m.stop()


def test_requeue_migrated_persists_resume_and_guards_terminal():
    from distributed_llm_inferencing_tpu.runtime.state import Store
    s = Store(":memory:")
    rid = s.submit_request("m", "p")
    s.claim_next_pending()
    s.requeue_migrated(rid, resume={"tokens": [1, 2, 3], "seed": 9},
                       kv_source={"url": "http://w0", "model": "m"},
                       excluded_node_id=4)
    r = s.get_request(rid)
    assert r["status"] == "pending" and r["attempts"] == 0
    assert r["resume"] == {"tokens": [1, 2, 3], "seed": 9}
    assert r["kv_source"] == {"url": "http://w0", "model": "m"}
    assert r["excluded_nodes"] == [4] and r["node_id"] is None
    # the re-claim carries the parsed resume/kv_source along
    row = s.claim_next_pending()
    assert row["resume"]["seed"] == 9 and row["kv_source"]["model"] == "m"
    # a terminal row never resurrects
    s.mark_completed(rid, "out", 1, 0.1, 1.0)
    s.requeue_migrated(rid, resume={"tokens": [9]})
    assert s.get_request(rid)["status"] == "completed"


def test_infer_body_carries_resume_and_persisted_kv_source():
    m = Master(":memory:", dispatcher_threads=0, rebalance=False)
    try:
        req = {"id": 1, "model_name": "m", "prompt": "p", "sampling": {},
               "max_new_tokens": 8, "max_length": None,
               "resume": {"tokens": [1], "seed": 2},
               "kv_source": {"url": "http://w0", "model": "m"}}
        body = m._infer_body(req)
        assert body["resume"] == {"tokens": [1], "seed": 2}
        assert body["kv_source"] == {"url": "http://w0", "model": "m"}
        # in-memory hint (same-dispatch disagg) still wins over the row
        req["_kv_source"] = {"url": "http://w1", "model": "m"}
        assert m._infer_body(req)["kv_source"]["url"] == "http://w1"
    finally:
        m.stop()
