"""Multi-host lockstep serving over a real 2-process jax.distributed
cluster (CPU transport — the same code path as multi-host TPU).

Two worker processes each own ONE device; the tp=2 mesh spans both, so
every jitted step's collectives cross the process boundary. The leader
mirrors ops to the follower via /lockstep; a greedy generation must
complete AND match the single-process oracle.
"""

import json
import subprocess
import sys
import time

import numpy as np
import pytest
import requests

RUNNER = r"""
import os, sys
proc, wport, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
followers = sys.argv[4] if len(sys.argv) > 4 else ""
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_llm_inferencing_tpu.runtime.multihost import (
    LockstepFollower, LockstepLeader, init_multihost)
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
if coord == "nodist":
    # control-plane-only slice: no jax.distributed job (used by the
    # control-plane elastic-recovery test)
    pid = proc
elif coord == "latejoin":
    # restarted host: its old coordinator epoch is gone — record the
    # distributed identity and wait for the leader's recovery to order
    # a fresh join (/lockstep/reinit_dist)
    from distributed_llm_inferencing_tpu.runtime.multihost import (
        configure_multihost)
    configure_multihost(2, proc)
    pid = proc
else:
    pid, n = init_multihost(coord, 2, proc)
agent = WorkerAgent()
if pid == 0:
    LockstepLeader(agent, [f for f in followers.split(",") if f])
else:
    LockstepFollower(agent)
print("READY", flush=True)
agent.serve("127.0.0.1", wport)
"""


from distributed_llm_inferencing_tpu.utils.platform import \
    free_port as _free_port  # noqa: E402


@pytest.fixture(scope="module")
def slice2():
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    lport, fport = _free_port(), _free_port()
    script = RUNNER.format(repo=repo)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen([sys.executable, "-c", script, "0", str(lport),
                          coord, f"127.0.0.1:{fport}"],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env),
        subprocess.Popen([sys.executable, "-c", script, "1", str(fport),
                          coord],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env),
    ]
    # wait for both HTTP servers
    deadline = time.time() + 120
    for port in (lport, fport):
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs):
                outs = [p.communicate()[0][-2000:] for p in procs]
                raise RuntimeError(f"worker died during startup: {outs}")
            try:
                requests.get(f"http://127.0.0.1:{port}/health", timeout=2)
                break
            except requests.ConnectionError:
                time.sleep(0.5)
        else:
            raise TimeoutError("slice did not come up")
    yield lport, fport
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def test_lockstep_load_and_infer(slice2):
    lport, fport = slice2
    url = f"http://127.0.0.1:{lport}"
    r = requests.post(url + "/load_model", json={
        "model_name": "tiny-llama", "allow_random_init": True,
        "dtype": "float32", "max_seq": 64, "mesh": {"tp": 2}}, timeout=300)
    assert r.status_code == 200, r.text

    prompt = np.random.default_rng(0).integers(0, 256, 9).tolist()
    r = requests.post(url + "/inference", json={
        "model_name": "tiny-llama", "prompt_tokens": prompt,
        "max_new_tokens": 8, "sampling": {"do_sample": False}},
        timeout=300)
    assert r.status_code == 200, r.text
    got = r.json()["tokens"]
    assert len(got) == 8
    # a second identical request must reproduce exactly (the slice stays in
    # lockstep; sequence numbers advance on both hosts). Value-correctness
    # of tp-sharded vs unsharded compute is pinned by test_sharding.py with
    # float tolerances — exact token equality vs a tp=1 oracle would be
    # flaky on argmax ties under collective reduction-order noise.
    r2 = requests.post(url + "/inference", json={
        "model_name": "tiny-llama", "prompt_tokens": prompt,
        "max_new_tokens": 8, "sampling": {"do_sample": False}}, timeout=300)
    assert r2.json()["tokens"] == got


def test_lockstep_streaming(slice2):
    lport, _ = slice2
    url = f"http://127.0.0.1:{lport}"
    prompt = [3, 1, 4, 1, 5]
    with requests.post(url + "/inference_stream", json={
            "model_name": "tiny-llama", "prompt_tokens": prompt,
            "max_new_tokens": 6, "sampling": {"do_sample": False}},
            stream=True, timeout=300) as r:
        assert r.status_code == 200
        events = [json.loads(l[6:]) for l in r.iter_lines()
                  if l.startswith(b"data: ")]
    kinds = [e["event"] for e in events]
    assert kinds.count("token") >= 1 and kinds[-1] == "done"


def test_follower_rejects_direct_calls(slice2):
    _, fport = slice2
    r = requests.post(f"http://127.0.0.1:{fport}/inference", json={
        "model_name": "tiny-llama", "prompt_tokens": [1],
        "max_new_tokens": 2}, timeout=30)
    assert r.status_code == 409
    assert "leader" in r.json()["message"]


@pytest.fixture()
def slice2_nodist():
    """Control-plane-only 2-host slice (no jax.distributed job) whose
    follower can be killed and respawned — the elastic-recovery scenario.
    On a real TPU slice the restarted host additionally rejoins
    jax.distributed before serving; the recovery protocol under test
    (epoch reset + state replay) is identical."""
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    lport, fport = _free_port(), _free_port()
    script = RUNNER.format(repo=repo)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

    def spawn(proc_id, port, followers=None):
        argv = [sys.executable, "-c", script, str(proc_id), str(port),
                "nodist"]
        if followers:
            argv.append(followers)
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True, env=env)

    procs = [spawn(0, lport, f"127.0.0.1:{fport}"), spawn(1, fport)]

    def wait_up(port, deadline=120):
        end = time.time() + deadline
        while time.time() < end:
            try:
                requests.get(f"http://127.0.0.1:{port}/health", timeout=2)
                return
            except requests.ConnectionError:
                time.sleep(0.5)
        raise TimeoutError(f"worker on {port} did not come up")

    wait_up(lport)
    wait_up(fport)
    yield lport, fport, procs, spawn, wait_up
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def test_elastic_recovery_after_follower_restart(slice2_nodist):
    """Round-3: kill a follower mid-service, restart it, and the leader's
    auto-recovery (epoch reset + model replay) resumes serving without
    manual surgery — replacing round-2's permanent degradation."""
    lport, fport, procs, spawn, wait_up = slice2_nodist
    url = f"http://127.0.0.1:{lport}"
    r = requests.post(url + "/load_model", json={
        "model_name": "tiny-llama", "allow_random_init": True,
        "dtype": "float32", "max_seq": 64}, timeout=300)
    assert r.status_code == 200, r.text
    body = {"model_name": "tiny-llama", "prompt_tokens": [2, 7, 1, 8],
            "max_new_tokens": 6, "seed": 5}
    want = requests.post(url + "/inference", json=body, timeout=300).json()
    assert want["status"] == "success", want

    procs[1].kill()
    procs[1].wait(timeout=10)
    # first mirrored op after the kill degrades the slice -> fast 503
    r = requests.post(url + "/inference", json=body, timeout=60)
    assert r.status_code == 503, (r.status_code, r.text)
    st = requests.get(url + "/lockstep/status", timeout=30).json()
    assert st["degraded"]

    procs[1] = spawn(1, fport)   # operator/daemon restarts the follower
    wait_up(fport)
    # auto-recovery polls the follower back in, replays the model load,
    # and serving resumes with identical output (pure fn of params/seed)
    deadline = time.time() + 180
    got = None
    while time.time() < deadline:
        r = requests.post(url + "/inference", json=body, timeout=120)
        if r.status_code == 200:
            got = r.json()
            break
        time.sleep(2)
    assert got is not None, "serving did not resume after follower restart"
    assert got["tokens"] == want["tokens"]
    # the replay rebuilt the follower's model too (its lockstep executor
    # drains asynchronously — poll rather than racing it)
    end = time.time() + 60
    while time.time() < end:
        fst = requests.get(f"http://127.0.0.1:{fport}/lockstep/status",
                           timeout=30).json()
        if fst["loaded"] == ["tiny-llama"]:
            break
        time.sleep(1)
    assert fst["loaded"] == ["tiny-llama"] and fst["epoch"] >= 1, fst
    lst = requests.get(url + "/lockstep/status", timeout=30).json()
    assert not lst["degraded"]

    # operator escape hatch: recover is a no-op when healthy unless forced
    r = requests.post(url + "/lockstep/recover", json={}, timeout=60).json()
    assert "nothing to recover" in r["message"]
    r = requests.post(url + "/lockstep/recover", json={"force": True},
                      timeout=300).json()
    assert r["status"] == "success" and r["epoch"] > fst["epoch"], r
    got2 = requests.post(url + "/inference", json=body, timeout=300).json()
    assert got2["tokens"] == want["tokens"]


@pytest.fixture()
def slice2_dist_restartable():
    """A REAL 2-process jax.distributed slice (CPU transport) whose
    follower can be killed and respawned — the full elastic-recovery
    scenario including re-forming the distributed runtime."""
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    lport, fport = _free_port(), _free_port()
    script = RUNNER.format(repo=repo)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}

    def spawn(proc_id, port, coord_arg, followers=None):
        argv = [sys.executable, "-c", script, str(proc_id), str(port),
                coord_arg]
        if followers:
            argv.append(followers)
        return subprocess.Popen(argv, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True, env=env)

    procs = [spawn(0, lport, coord, f"127.0.0.1:{fport}"),
             spawn(1, fport, coord)]

    def wait_up(port, deadline=120):
        end = time.time() + deadline
        while time.time() < end:
            try:
                requests.get(f"http://127.0.0.1:{port}/health", timeout=2)
                return
            except requests.ConnectionError:
                time.sleep(0.5)
        raise TimeoutError(f"worker on {port} did not come up")

    wait_up(lport)
    wait_up(fport)
    yield lport, fport, procs, spawn, wait_up
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def test_elastic_recovery_reforms_distributed_runtime(
        slice2_dist_restartable):
    """Round-4 (VERDICT ask #7): elastic recovery on a REAL
    jax.distributed slice. The tp=2 model's collectives span both
    processes, so serving after the restart is only possible if the
    restarted follower actually rejoined a fresh distributed job AND
    re-sharded params onto it — the epoch-reset control protocol alone
    cannot fake this."""
    lport, fport, procs, spawn, wait_up = slice2_dist_restartable
    url = f"http://127.0.0.1:{lport}"
    r = requests.post(url + "/load_model", json={
        "model_name": "tiny-llama", "allow_random_init": True,
        "dtype": "float32", "max_seq": 64, "mesh": {"tp": 2}}, timeout=300)
    assert r.status_code == 200, r.text
    body = {"model_name": "tiny-llama", "prompt_tokens": [2, 7, 1, 8],
            "max_new_tokens": 6, "seed": 5}
    want = requests.post(url + "/inference", json=body, timeout=300).json()
    assert want["status"] == "success", want

    procs[1].kill()
    procs[1].wait(timeout=10)
    r = requests.post(url + "/inference", json=body, timeout=60)
    assert r.status_code == 503, (r.status_code, r.text)

    # the restarted follower has no coordinator to join — it comes up in
    # late-join mode and waits for the leader's recovery to order it
    procs[1] = spawn(1, fport, "latejoin")
    wait_up(fport)
    deadline = time.time() + 300
    got = None
    while time.time() < deadline:
        try:
            r = requests.post(url + "/inference", json=body, timeout=120)
            if r.status_code == 200:
                got = r.json()
                break
        except requests.RequestException:
            pass
        time.sleep(2)
    assert got is not None, "serving did not resume after dist restart"
    # pure fn of (params, prompt, seed): the re-formed slice reproduces
    assert got["tokens"] == want["tokens"]
    end = time.time() + 60   # the follower's executor drains async
    while time.time() < end:
        fst = requests.get(f"http://127.0.0.1:{fport}/lockstep/status",
                           timeout=30).json()
        if fst["loaded"] == ["tiny-llama"]:
            break
        time.sleep(1)
    assert fst["loaded"] == ["tiny-llama"], fst
    assert fst["dist"]["joined"] and fst["dist"]["error"] is None, fst
    lst = requests.get(url + "/lockstep/status", timeout=30).json()
    assert not lst["degraded"]


def test_batched_serving_on_multihost(slice2):
    """Round-2: batched serving spans the slice — the tp=2 mesh covers
    both processes, so every batcher program's collectives cross hosts;
    completion is only possible if the follower replays each leader
    program (a missing partner deadlocks the collective). Requests also
    reproduce exactly, proving the slice stays in lockstep."""
    import threading
    lport, _ = slice2
    url = f"http://127.0.0.1:{lport}"
    r = requests.post(url + "/load_model", json={
        "model_name": "tiny-gpt2", "allow_random_init": True,
        "serving": "batched", "kv_blocks": 32, "kv_block_size": 8,
        "slots": 2, "max_seq": 64, "dtype": "float32",
        "mesh": {"tp": 2}}, timeout=300)
    assert r.status_code == 200, r.text

    prompts = [[3, 5, 7], [2, 4, 6, 8]]
    results = {}

    def go(i):
        results[i] = requests.post(url + "/inference", json={
            "model_name": "tiny-gpt2", "prompt_tokens": prompts[i],
            "max_new_tokens": 6, "seed": 11 + i}, timeout=300).json()

    threads = [threading.Thread(target=go, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    for i in range(2):
        assert results[i]["status"] == "success", results[i]
        assert len(results[i]["tokens"]) == 6

    # identical request ⇒ identical tokens (pure fn of params/prompt/seed)
    r2 = requests.post(url + "/inference", json={
        "model_name": "tiny-gpt2", "prompt_tokens": prompts[0],
        "max_new_tokens": 6, "seed": 11}, timeout=300).json()
    assert r2["tokens"] == results[0]["tokens"]


def test_batched_mirror_amortized(slice2):
    """Round-3: the lockstep mirror broadcasts one op per admission wave /
    decode chunk, not one per token — a 40-token batched generation must
    cost the follower far fewer /lockstep POSTs than tokens (the round-2
    per-token mirror was the multi-host serving ceiling). Counted via the
    follower's monotone lockstep sequence number."""
    lport, fport = slice2
    url = f"http://127.0.0.1:{lport}"
    # batched model from the previous test (idempotent re-load keeps this
    # test self-sufficient; the duplicate load consumes one seq)
    r = requests.post(url + "/load_model", json={
        "model_name": "tiny-gpt2", "allow_random_init": True,
        "serving": "batched", "kv_blocks": 32, "kv_block_size": 8,
        "slots": 2, "max_seq": 64, "dtype": "float32",
        "mesh": {"tp": 2}}, timeout=300)
    assert r.status_code == 200, r.text

    before = requests.get(f"http://127.0.0.1:{fport}/lockstep/status",
                          timeout=30).json()["next_seq"]
    r = requests.post(url + "/inference", json={
        "model_name": "tiny-gpt2", "prompt_tokens": [5, 3, 1],
        "max_new_tokens": 40, "seed": 42}, timeout=300).json()
    assert r["status"] == "success" and len(r["tokens"]) == 40, r

    deadline = time.time() + 60   # followers drain asynchronously
    while time.time() < deadline:
        after = requests.get(f"http://127.0.0.1:{fport}/lockstep/status",
                             timeout=30).json()["next_seq"]
        if after > before:
            time.sleep(1.0)   # settle: no more ops in flight
            again = requests.get(
                f"http://127.0.0.1:{fport}/lockstep/status",
                timeout=30).json()["next_seq"]
            if again == after:
                break
            after = again
    mirrored = after - before
    # 1 admit + ~5 decode chunks (39 remaining = 32+4+2+1) ≪ 40 tokens
    assert 1 <= mirrored <= 10, (before, after)


# NOTE: runs LAST among the slice2 tests — it consumes the follower's next
# expected seq directly (the leader never learns about it), so any later
# mirrored op against this slice would collide and degrade it.
def test_follower_rejects_stale_duplicate_or_gapped_seq(slice2):
    """Bad sequence numbers must be refused at the door: duplicates would
    wedge or desync the ordered executor, and a GAP proves this follower
    missed forwards (e.g. it restarted) — accepting would enqueue an op
    that can never execute. The gap 409 is what makes the leader degrade
    and run recovery instead of silently diverging."""
    _, fport = slice2
    url = f"http://127.0.0.1:{fport}"
    nxt = requests.get(url + "/lockstep/status",
                       timeout=30).json()["last_recv"] + 1
    # consecutive arrival: accepted
    r = requests.post(url + "/lockstep", json={
        "seq": nxt, "op": "noop", "body": {}}, timeout=30)
    assert r.status_code == 200
    # exact replay of an already-received seq
    r = requests.post(url + "/lockstep", json={
        "seq": nxt, "op": "unload_model", "body": {"model_name": "x"}},
        timeout=30)
    assert r.status_code == 409
    # far-future seq = a gap: this follower missed ops -> refuse
    r = requests.post(url + "/lockstep", json={
        "seq": nxt + 999_983, "op": "noop", "body": {}}, timeout=30)
    assert r.status_code == 409
    assert "gap" in r.json()["message"]
    r = requests.post(url + "/lockstep", json={
        "seq": "nope", "op": "inference", "body": {}}, timeout=30)
    assert r.status_code == 400
    r = requests.post(url + "/lockstep", json={
        "seq": -3, "op": "noop", "body": {}}, timeout=30)
    assert r.status_code == 400
