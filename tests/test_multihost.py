"""Multi-host lockstep serving over a real 2-process jax.distributed
cluster (CPU transport — the same code path as multi-host TPU).

Two worker processes each own ONE device; the tp=2 mesh spans both, so
every jitted step's collectives cross the process boundary. The leader
mirrors ops to the follower via /lockstep; a greedy generation must
complete AND match the single-process oracle.
"""

import json
import socket
import subprocess
import sys
import time

import numpy as np
import pytest
import requests

RUNNER = r"""
import os, sys
proc, wport, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
followers = sys.argv[4] if len(sys.argv) > 4 else ""
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
sys.path.insert(0, {repo!r})
import jax
jax.config.update("jax_platforms", "cpu")
from distributed_llm_inferencing_tpu.runtime.multihost import (
    LockstepFollower, LockstepLeader, init_multihost)
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
pid, n = init_multihost(coord, 2, proc)
agent = WorkerAgent()
if pid == 0:
    LockstepLeader(agent, [f for f in followers.split(",") if f])
else:
    LockstepFollower(agent)
print("READY", flush=True)
agent.serve("127.0.0.1", wport)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


@pytest.fixture(scope="module")
def slice2():
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    lport, fport = _free_port(), _free_port()
    script = RUNNER.format(repo=repo)
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen([sys.executable, "-c", script, "0", str(lport),
                          coord, f"127.0.0.1:{fport}"],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env),
        subprocess.Popen([sys.executable, "-c", script, "1", str(fport),
                          coord],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         text=True, env=env),
    ]
    # wait for both HTTP servers
    deadline = time.time() + 120
    for port in (lport, fport):
        while time.time() < deadline:
            if any(p.poll() is not None for p in procs):
                outs = [p.communicate()[0][-2000:] for p in procs]
                raise RuntimeError(f"worker died during startup: {outs}")
            try:
                requests.get(f"http://127.0.0.1:{port}/health", timeout=2)
                break
            except requests.ConnectionError:
                time.sleep(0.5)
        else:
            raise TimeoutError("slice did not come up")
    yield lport, fport
    for p in procs:
        p.terminate()
    for p in procs:
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()


def test_lockstep_load_and_infer(slice2):
    lport, fport = slice2
    url = f"http://127.0.0.1:{lport}"
    r = requests.post(url + "/load_model", json={
        "model_name": "tiny-llama", "allow_random_init": True,
        "dtype": "float32", "max_seq": 64, "mesh": {"tp": 2}}, timeout=300)
    assert r.status_code == 200, r.text

    prompt = np.random.default_rng(0).integers(0, 256, 9).tolist()
    r = requests.post(url + "/inference", json={
        "model_name": "tiny-llama", "prompt_tokens": prompt,
        "max_new_tokens": 8, "sampling": {"do_sample": False}},
        timeout=300)
    assert r.status_code == 200, r.text
    got = r.json()["tokens"]
    assert len(got) == 8
    # a second identical request must reproduce exactly (the slice stays in
    # lockstep; sequence numbers advance on both hosts). Value-correctness
    # of tp-sharded vs unsharded compute is pinned by test_sharding.py with
    # float tolerances — exact token equality vs a tp=1 oracle would be
    # flaky on argmax ties under collective reduction-order noise.
    r2 = requests.post(url + "/inference", json={
        "model_name": "tiny-llama", "prompt_tokens": prompt,
        "max_new_tokens": 8, "sampling": {"do_sample": False}}, timeout=300)
    assert r2.json()["tokens"] == got


def test_lockstep_streaming(slice2):
    lport, _ = slice2
    url = f"http://127.0.0.1:{lport}"
    prompt = [3, 1, 4, 1, 5]
    with requests.post(url + "/inference_stream", json={
            "model_name": "tiny-llama", "prompt_tokens": prompt,
            "max_new_tokens": 6, "sampling": {"do_sample": False}},
            stream=True, timeout=300) as r:
        assert r.status_code == 200
        events = [json.loads(l[6:]) for l in r.iter_lines()
                  if l.startswith(b"data: ")]
    kinds = [e["event"] for e in events]
    assert kinds.count("token") >= 1 and kinds[-1] == "done"


def test_follower_rejects_direct_calls(slice2):
    _, fport = slice2
    r = requests.post(f"http://127.0.0.1:{fport}/inference", json={
        "model_name": "tiny-llama", "prompt_tokens": [1],
        "max_new_tokens": 2}, timeout=30)
    assert r.status_code == 409
    assert "leader" in r.json()["message"]


def test_follower_rejects_stale_or_duplicate_seq(slice2):
    """A replayed or stale sequence number must be refused at the door —
    accepted duplicates would wedge or desync the ordered executor.
    Self-contained: uses a far-future noop seq so it neither depends on
    earlier tests having consumed seqs nor perturbs slice state."""
    _, fport = slice2
    far = 999_983
    r = requests.post(f"http://127.0.0.1:{fport}/lockstep", json={
        "seq": far, "op": "noop", "body": {}}, timeout=30)
    assert r.status_code == 200
    # exact replay of an already-received seq
    r = requests.post(f"http://127.0.0.1:{fport}/lockstep", json={
        "seq": far, "op": "unload_model", "body": {"model_name": "x"}},
        timeout=30)
    assert r.status_code == 409
    r = requests.post(f"http://127.0.0.1:{fport}/lockstep", json={
        "seq": "nope", "op": "inference", "body": {}}, timeout=30)
    assert r.status_code == 400
    r = requests.post(f"http://127.0.0.1:{fport}/lockstep", json={
        "seq": -3, "op": "noop", "body": {}}, timeout=30)
    assert r.status_code == 400


def test_batched_serving_on_multihost(slice2):
    """Round-2: batched serving spans the slice — the tp=2 mesh covers
    both processes, so every batcher program's collectives cross hosts;
    completion is only possible if the follower replays each leader
    program (a missing partner deadlocks the collective). Requests also
    reproduce exactly, proving the slice stays in lockstep."""
    import threading
    lport, _ = slice2
    url = f"http://127.0.0.1:{lport}"
    r = requests.post(url + "/load_model", json={
        "model_name": "tiny-gpt2", "allow_random_init": True,
        "serving": "batched", "kv_blocks": 32, "kv_block_size": 8,
        "slots": 2, "max_seq": 64, "dtype": "float32",
        "mesh": {"tp": 2}}, timeout=300)
    assert r.status_code == 200, r.text

    prompts = [[3, 5, 7], [2, 4, 6, 8]]
    results = {}

    def go(i):
        results[i] = requests.post(url + "/inference", json={
            "model_name": "tiny-gpt2", "prompt_tokens": prompts[i],
            "max_new_tokens": 6, "seed": 11 + i}, timeout=300).json()

    threads = [threading.Thread(target=go, args=(i,)) for i in range(2)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)
    for i in range(2):
        assert results[i]["status"] == "success", results[i]
        assert len(results[i]["tokens"]) == 6

    # identical request ⇒ identical tokens (pure fn of params/prompt/seed)
    r2 = requests.post(url + "/inference", json={
        "model_name": "tiny-gpt2", "prompt_tokens": prompts[0],
        "max_new_tokens": 6, "seed": 11}, timeout=300).json()
    assert r2["tokens"] == results[0]["tokens"]
