"""Tracer unit tests (span nesting, ring-buffer eviction, Chrome export)
plus cross-process propagation: one trace id must link master submit →
worker infer spans over real localhost HTTP.
"""

import json
import time

import pytest
import requests

from distributed_llm_inferencing_tpu.utils import trace
from distributed_llm_inferencing_tpu.utils.trace import SpanCtx, Tracer


# ---- span model -------------------------------------------------------

def test_span_nesting_and_ids():
    tr = Tracer(service="t")
    with tr.span("outer") as outer:
        with tr.span("inner") as inner:
            with tr.span("leaf"):
                pass
    spans = {s.name: s for s in tr.spans()}
    assert set(spans) == {"outer", "inner", "leaf"}
    assert spans["outer"].parent_id is None
    assert spans["inner"].parent_id == spans["outer"].span_id
    assert spans["leaf"].parent_id == spans["inner"].span_id
    # one trace id across the whole tree; unique span ids
    assert len({s.trace_id for s in spans.values()}) == 1
    assert len({s.span_id for s in spans.values()}) == 3
    # children finish before parents, every span has a real duration
    assert spans["leaf"].end <= spans["inner"].end <= spans["outer"].end
    assert all(s.end >= s.start for s in spans.values())
    assert outer.ctx().trace_id == inner.ctx().trace_id


def test_span_contextvar_restored_and_error_attr():
    tr = Tracer()
    assert trace.current() is None
    with pytest.raises(RuntimeError):
        with tr.span("boom"):
            assert trace.current() is not None
            raise RuntimeError("x")
    assert trace.current() is None
    (sp,) = tr.spans()
    assert "RuntimeError" in sp.attrs["error"]
    assert sp.end >= sp.start   # recorded despite the exception


def test_explicit_parent_crosses_threads():
    """parent= adopts a remote/cross-thread ctx; parent=None roots fresh."""
    tr = Tracer()
    ctx = SpanCtx(trace_id="feedbeef00000000", span_id="ab" * 8)
    with tr.span("child", parent=ctx):
        pass
    with tr.span("fresh", parent=None):
        pass
    child, fresh = tr.spans()
    assert child.trace_id == "feedbeef00000000"
    assert child.parent_id == "ab" * 8
    assert fresh.trace_id != "feedbeef00000000" and fresh.parent_id is None


def test_record_retroactive():
    tr = Tracer()
    t0 = time.time() - 1.0
    g = tr.record("root", t0, t0 + 0.5, attrs={"k": 1})
    tr.record("sub", t0, t0 + 0.2, parent=g)
    root, sub = tr.spans()
    assert sub.trace_id == root.trace_id == g.trace_id
    assert sub.parent_id == root.span_id
    assert abs(root.duration_ms - 500) < 1


def test_ring_buffer_eviction():
    tr = Tracer(capacity=8)
    for i in range(20):
        tr.record(f"s{i}", 0.0, 1.0)
    names = [s.name for s in tr.spans()]
    assert len(names) == 8
    assert names == [f"s{i}" for i in range(12, 20)]  # oldest evicted


# ---- header propagation ----------------------------------------------

def test_inject_extract_roundtrip():
    ctx = SpanCtx(trace_id="11" * 8, span_id="22" * 8)
    h = trace.inject({}, ctx)
    assert h[trace.TRACE_HEADER] == "11" * 8
    assert h[trace.PARENT_HEADER] == "22" * 8
    back = trace.extract(h)
    assert back == ctx
    assert trace.extract({}) is None
    assert trace.inject({}) == {}   # nothing current -> no-op


# ---- Chrome trace-event export ---------------------------------------

def test_chrome_export_schema():
    tr = Tracer(service="unit")
    with tr.span("a", attrs={"n": 3}):
        with tr.span("b"):
            pass
    doc = tr.chrome_trace()
    # valid JSON end to end (what /api/trace serves)
    doc = json.loads(json.dumps(doc))
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    spans = [e for e in evs if e["ph"] == "X"]
    # process_name carries host:pid (export pid is synthetic — real pids
    # collide across containers that all run as PID 1)
    assert meta and meta[0]["args"]["name"].startswith("unit")
    assert len(spans) == 2
    for e in spans:
        for key in ("name", "cat", "ph", "ts", "dur", "pid", "tid", "args"):
            assert key in e, f"missing {key}"
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert e["dur"] >= 0
        assert e["args"]["trace_id"] and e["args"]["span_id"]
    b = next(e for e in spans if e["name"] == "b")
    a = next(e for e in spans if e["name"] == "a")
    assert b["args"]["parent_id"] == a["args"]["span_id"]
    assert a["args"]["n"] == 3


def test_chrome_export_merge_dedupes():
    tr = Tracer()
    with tr.span("x"):
        pass
    evs = tr.chrome_events()
    doc = tr.chrome_trace(extra_events=evs)   # merge our own export back
    span_evs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    assert len(span_evs) == 1


# ---- cross-process propagation over HTTP -----------------------------

@pytest.fixture()
def cluster():
    from distributed_llm_inferencing_tpu.runtime.master import Master
    from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
    agent = WorkerAgent()
    wsrv = agent.serve(host="127.0.0.1", port=0, background=True)
    m = Master(":memory:", dispatcher_threads=2, health_interval=0.5)
    m.start_background()
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    yield (agent, wsrv.server_address[1], m, msrv.server_address[1])
    m.stop()
    agent.service.shutdown()


def _url(port, path):
    return f"http://127.0.0.1:{port}{path}"


def test_one_trace_links_master_submit_to_worker_infer(cluster):
    """Acceptance: a single end-to-end request yields one connected trace
    — shared trace id, >= 6 spans spanning both the master's and the
    worker's process roles — exportable as Chrome trace JSON."""
    agent, wport, m, mport = cluster
    r = requests.post(_url(mport, "/api/nodes/add"), json={
        "name": "tw", "host": "127.0.0.1", "port": wport})
    assert r.status_code == 200, r.text

    tid = "a1b2c3d4e5f60718"
    sub = requests.post(
        _url(mport, "/api/inference/submit"),
        headers={trace.TRACE_HEADER: tid, trace.PARENT_HEADER: "00" * 8},
        json={"model_name": "tiny-gpt2", "prompt": "hi",
              "max_new_tokens": 4,
              "sampling": {"do_sample": False, "allow_random_init": True}})
    assert sub.status_code == 200, sub.text
    # the response names the trace it belongs to
    assert sub.headers.get(trace.TRACE_HEADER) == tid
    req_id = sub.json()["request_id"]

    deadline = time.time() + 60
    while time.time() < deadline:
        st = requests.get(
            _url(mport, f"/api/inference/status/{req_id}")).json()
        if st["request"]["status"] in ("completed", "failed"):
            break
        time.sleep(0.2)
    assert st["request"]["status"] == "completed", st

    doc = requests.get(_url(mport, "/api/trace")).json()
    evs = [e for e in doc["traceEvents"] if e.get("ph") == "X"
           and e.get("args", {}).get("trace_id") == tid]
    names = [e["name"] for e in evs]
    assert len(evs) >= 6, names
    # master-side stages
    assert "http POST /api/inference/submit" in names
    assert "master.execute" in names and "master.queued" in names
    assert "master.dispatch" in names
    # worker-side stages, linked by the SAME trace id via the headers the
    # master injected on its /inference call
    assert "http POST /inference" in names
    assert "worker.inference" in names
    assert "engine.generate" in names
    assert "engine.prefill" in names and "engine.decode" in names
    # parent links form one connected tree (every non-root parent exists)
    ids = {e["args"]["span_id"] for e in evs}
    roots = [e for e in evs if "parent_id" not in e["args"]
             or e["args"]["parent_id"] not in ids]
    # the submit span's parent is the client's fake span id -> one root
    assert len(roots) <= 2, [(e["name"], e["args"].get("parent_id"))
                             for e in roots]

    # the worker's own /api/trace also serves valid Chrome JSON with the
    # linked spans
    wdoc = requests.get(_url(wport, "/api/trace")).json()
    wnames = [e["name"] for e in wdoc["traceEvents"]
              if e.get("ph") == "X"
              and e.get("args", {}).get("trace_id") == tid]
    assert "worker.inference" in wnames


def test_error_response_carries_trace_headers(cluster):
    _, wport, _, mport = cluster
    tid = "0102030405060708"
    r = requests.post(_url(wport, "/inference"),
                      headers={trace.TRACE_HEADER: tid},
                      json={"model_name": "not-loaded", "prompt": "x"})
    assert r.status_code == 400
    assert r.headers.get(trace.TRACE_HEADER) == tid
    assert r.headers.get(trace.SPAN_HEADER)
    # 404s too (deliberate unknown path)
    # dlilint: disable=rpc-unknown-path
    r = requests.get(_url(mport, "/no/such/path"),
                     headers={trace.TRACE_HEADER: tid})
    assert r.status_code == 404
    assert r.headers.get(trace.TRACE_HEADER) == tid


def test_405_wrong_method_gets_allow_header(cluster):
    _, wport, _, mport = cluster
    # /health is GET-only on the worker (deliberate wrong method)
    # dlilint: disable=rpc-method-mismatch
    r = requests.post(_url(wport, "/health"), json={})
    assert r.status_code == 405
    assert "GET" in r.headers.get("Allow", "")
    assert r.json()["status"] == "error"
    # /api/inference/submit is POST-only on the master
    # dlilint: disable=rpc-method-mismatch
    r = requests.get(_url(mport, "/api/inference/submit"))
    assert r.status_code == 405
    assert "POST" in r.headers.get("Allow", "")
    # unregistered path still 404s
    # dlilint: disable=rpc-unknown-path
    assert requests.get(_url(wport, "/nope")).status_code == 404
