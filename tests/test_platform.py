"""Platform selection robustness (utils/platform.py).

Round-1 failure mode: the TPU backend hung/errored at init and took the
bench + CLI down with it (BENCH_r01 rc=1). These tests pin the contract:
explicit request wins, probe failure degrades to cpu, and the probe is a
subprocess with a hard timeout so a hang cannot propagate.
"""

import subprocess
import sys

from distributed_llm_inferencing_tpu.utils import platform as plat


def test_explicit_request_is_not_degraded(monkeypatch):
    monkeypatch.delenv("DLI_PLATFORM", raising=False)
    info = plat.ensure_backend("cpu")
    assert (info["platform"], info["degraded"]) == ("cpu", False)
    assert info["probe_last_error"] is None


def test_env_request_wins(monkeypatch):
    monkeypatch.setenv("DLI_PLATFORM", "cpu")
    info = plat.ensure_backend()
    assert (info["platform"], info["degraded"]) == ("cpu", False)


def test_probe_failure_degrades_to_cpu(monkeypatch):
    monkeypatch.delenv("DLI_PLATFORM", raising=False)
    monkeypatch.setattr(plat, "probe_default_backend_ex",
                        lambda timeout: (None, "boom"))
    info = plat.ensure_backend(attempts=2, backoff_s=0.0)
    assert (info["platform"], info["degraded"]) == ("cpu", True)
    # a degraded result must carry the WHY for the bench artifact
    assert info["probe_attempts"] == 2
    assert info["probe_last_error"] == "boom"


def test_probe_success_is_used(monkeypatch):
    monkeypatch.delenv("DLI_PLATFORM", raising=False)
    monkeypatch.setattr(plat, "probe_default_backend_ex",
                        lambda timeout: ("tpu", None))
    info = plat.ensure_backend()
    assert (info["platform"], info["degraded"]) == ("tpu", False)
    assert info["probe_attempts"] == 1


def test_probe_timeout_kills_hung_init(monkeypatch):
    # a probe command that hangs forever must return None at the timeout,
    # not hang the caller — and report the hang as the probe error
    real_run = subprocess.run

    def hang_run(cmd, **kw):
        return real_run([sys.executable, "-c", "import time; time.sleep(60)"],
                        **kw)

    monkeypatch.setattr(plat.subprocess, "run", hang_run)
    assert plat.probe_default_backend(timeout=1.0) is None
    p, err = plat.probe_default_backend_ex(timeout=1.0)
    assert p is None and "timeout" in err
