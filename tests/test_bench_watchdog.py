"""Bench hang-proofing: the probe's compute canary and the mid-run
stall watchdog (bench.py).

Observed failure mode (round 5, tunnel-attached v5e): the remote chip
answers device enumeration from cached topology while its first
executable dispatch blocks FOREVER without raising. A devices()-only
probe passes, the bench enters the TPU path, and the except-branch CPU
fallback can never fire because nothing raises — the driver gets no
line at all. Two defenses, each pinned here:

- the probe subprocess runs a tiny jit and blocks on its result, so a
  compute-wedged chip fails the probe at the hard timeout
  (utils/platform._PROBE_SRC);
- a watchdog thread re-execs the bench on CPU when no heartbeat lands
  for DLI_BENCH_STALL_S seconds, parking already-captured TPU partials
  first (bench._start_stall_watchdog).
"""

import json
import os
import time
import types

import bench
from distributed_llm_inferencing_tpu.utils import platform as plat


def test_probe_source_contains_compute_canary():
    # devices() alone is NOT a health check — pin the canary's presence
    assert "jax.jit" in plat._PROBE_SRC
    assert "block_until_ready" in plat._PROBE_SRC or "float(v)" in plat._PROBE_SRC


def test_probe_canary_executes_on_cpu(monkeypatch):
    # The probe deliberately targets the TRUE default backend, which on
    # a TPU host may be the (possibly wedged) axon plugin — env vars
    # cannot pin it to cpu (sitecustomize registers the plugin before
    # user code; jax.config is the only reliable switch, see conftest).
    # Pin the probe SOURCE to cpu so the test exercises the real
    # subprocess + canary machinery hermetically.
    monkeypatch.setattr(
        plat, "_PROBE_SRC",
        "import jax\njax.config.update('jax_platforms', 'cpu')\n"
        + plat._PROBE_SRC)
    p, err = plat.probe_default_backend_ex(timeout=120.0)
    assert p == "cpu" and err is None


def test_watchdog_fires_parks_partials_and_reexecs(monkeypatch, tmp_path):
    calls = {}
    partial = tmp_path / "BENCH_PARTIAL.json"
    partial.write_text("{\"k\": 1}")
    monkeypatch.setattr(bench, "_PARTIAL_PATH", str(partial))
    monkeypatch.setenv("DLI_BENCH_STALL_S", "0.2")

    def fake_run(cmd, env=None, **kw):
        calls["env"] = env
        return types.SimpleNamespace(returncode=7)

    def fake_exit(rc):
        calls["rc"] = rc

    monkeypatch.setattr(bench.subprocess, "run", fake_run)
    monkeypatch.setattr(bench.os, "_exit", fake_exit)
    bench._beat("test-start")
    bench._HEARTBEAT["t"] = time.time() - 60  # already stale
    bench._start_stall_watchdog(attempts=3)
    deadline = time.time() + 5
    while "rc" not in calls and time.time() < deadline:
        time.sleep(0.05)
    assert calls.get("rc") == 7
    env = calls["env"]
    assert env[bench._FALLBACK_ENV] == "1"
    assert env["DLI_PLATFORM"] == "cpu"
    info = json.loads(env[bench._FALLBACK_INFO_ENV])
    assert info["probe_attempts"] == 3
    assert "mid-run TPU stall" in info["probe_last_error"]
    # captured TPU keys were parked, not clobbered, for the CPU child
    assert not partial.exists()
    assert os.path.exists(str(partial) + ".tpu")
    bench._beat("test-end")  # leave a fresh heartbeat for other tests


def test_watchdog_disabled_by_zero(monkeypatch):
    monkeypatch.setenv("DLI_BENCH_STALL_S", "0")
    before = {t.name for t in bench.threading.enumerate()}
    bench._start_stall_watchdog(attempts=0)
    after = {t.name for t in bench.threading.enumerate()}
    assert after == before
