"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip behavior (tp/dp/pp/sp/ep shardings, collectives) is tested on
host CPU devices exactly as SURVEY.md §4 prescribes — set BEFORE jax
initializes anything.
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
