"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip behavior (tp/dp/pp/sp/ep shardings, collectives) is tested on
host CPU devices exactly as SURVEY.md §4 prescribes.

Note: this environment's sitecustomize imports jax at interpreter startup
(axon TPU plugin), so env vars alone are too late — we must also flip
``jax.config`` before the first backend query.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
