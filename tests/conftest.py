"""Test configuration: force an 8-device virtual CPU mesh.

Multi-chip behavior (tp/dp/pp/sp/ep shardings, collectives) is tested on
host CPU devices exactly as SURVEY.md §4 prescribes.

Note: this environment's sitecustomize imports jax at interpreter startup
(axon TPU plugin), so env vars alone are too late — we must also flip
``jax.config`` before the first backend query.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def tiny_gpt_oss_model(seed=60):
    """Tiny randomized HF gpt-oss (sinks randomized — HF init may leave
    them empty/zero, and all-zero sinks are invisible to sharding and
    parity tests alike). One definition shared by the numerics and
    sharding suites."""
    import torch
    import transformers
    cfg = transformers.GptOssConfig(
        vocab_size=128, hidden_size=32, intermediate_size=16,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, num_local_experts=4, num_experts_per_tok=2,
        sliding_window=4, layer_types=["sliding_attention",
                                       "full_attention"],
        max_position_embeddings=64, rope_scaling=None,
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(seed)
    model = transformers.GptOssForCausalLM(cfg).eval()
    with torch.no_grad():
        for lyr in model.model.layers:
            lyr.self_attn.sinks.normal_(0.0, 1.0)
    return model


def tiny_glm45_moe_model(seed=58):
    """Tiny randomized HF GLM-4.5 MoE (q/k norms and the router
    correction bias perturbed away from their invariant inits)."""
    import torch
    import transformers
    cfg = transformers.Glm4MoeConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2, head_dim=16,
        partial_rotary_factor=0.5, use_qk_norm=True,
        n_routed_experts=8, n_shared_experts=1, num_experts_per_tok=2,
        n_group=2, topk_group=1, routed_scaling_factor=1.5,
        norm_topk_prob=True, first_k_dense_replace=1,
        max_position_embeddings=64, tie_word_embeddings=False,
        pad_token_id=0)
    torch.manual_seed(seed)
    model = transformers.Glm4MoeForCausalLM(cfg).eval()
    with torch.no_grad():
        for lyr in model.model.layers:
            lyr.self_attn.q_norm.weight.mul_(
                torch.rand_like(lyr.self_attn.q_norm.weight) + 0.5)
            lyr.self_attn.k_norm.weight.mul_(
                torch.rand_like(lyr.self_attn.k_norm.weight) + 0.5)
            if hasattr(lyr.mlp, "gate"):
                lyr.mlp.gate.e_score_correction_bias.uniform_(0.0, 0.2)
    return model


# ---- lock-order watchdog gate (utils/locks.py) ------------------------
# When the suite runs with DLI_LOCK_CHECK=1 (scripts/check.sh arms it
# for the chaos suite), every runtime lock is instrumented and a
# dynamic lock-order inversion anywhere in the run must fail the build.
# The deliberate-inversion tests in tests/test_locks.py reset the
# watchdog behind themselves, so any report left at session end is real.

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _lock_watchdog_gate():
    yield
    from distributed_llm_inferencing_tpu.utils import locks
    if locks.enabled():
        reports = locks.cycle_reports()
        assert not reports, (
            "lock-order watchdog detected potential deadlocks during "
            f"the run: {reports}")
