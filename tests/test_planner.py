"""Heterogeneity-aware auto-parallelism planner suite
(parallel/planner.py, docs/architecture.md "Auto-parallelism
planner").

Covers the analytic cost model's basic sanity properties
(monotonicity under perfect scaling, memory-feasibility rejection),
the search's behavior on a measured two-class fleet (quarantine the
SLO-violating class), the decision-record contract (schema, journal
reconstructability, replicated-meta persistence across restart), the
``plan_from_json`` round-trip over the whole model registry, and a
small sim-agreement sweep against tools/dlisim ground truth.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import pytest

from distributed_llm_inferencing_tpu.models.registry import (get_config,
                                                             list_models)
from distributed_llm_inferencing_tpu.parallel import planner
from distributed_llm_inferencing_tpu.parallel.mesh import (MeshSpec,
                                                           validate_spec)
from distributed_llm_inferencing_tpu.parallel.plan import (PLAN_KEYS,
                                                           make_plan,
                                                           plan_from_json,
                                                           plan_to_json)

MESH1 = {"dp": 1, "pp": 1, "sp": 1, "tp": 1, "ep": 1}


def _klass(n_nodes=2, device_count=1, decode_tok_s=50.0,
           latency_ms=None, measured=True, key="k",
           memory_bytes=16 << 30, first_id=1):
    return planner.NodeClass(
        key=key, kind="test", device_count=device_count,
        memory_bytes=memory_bytes,
        node_ids=tuple(range(first_id, first_id + n_nodes)),
        decode_tok_s=decode_tok_s, latency_ms=latency_ms,
        measured=measured)


def _views(n_fast=8, n_slow=4, slow_x=24.0):
    """Two-class fleet: ``n_slow`` throttled nodes first (the id
    ordering the sim's speeds list uses), then ``n_fast`` healthy."""
    views = []
    for i in range(n_slow + n_fast):
        x = slow_x if i < n_slow else 1.0
        views.append({
            "id": i + 1, "name": f"n{i}",
            "devices": [{"kind": "tpu", "memory_bytes": 16 << 30}],
            "decode_tok_s": round(1000.0 / (18.0 * x), 3),
            "latency_ms": 8.0 * x})
    return views


# ---- cost model -------------------------------------------------------

def test_more_devices_never_worse_under_perfect_scaling():
    """Monotonicity: with zero collective overhead (perfect scaling),
    adding devices to a class never lowers the scored goodput, for
    every mesh shape that fits the smaller class."""
    inputs = planner.CostInputs(coll_overhead_per_way=0.0)
    for mesh in ({"tp": 1}, {"tp": 2}, {"dp": 2}, {"tp": 2, "dp": 2}):
        mesh = dict(MESH1, **mesh)
        prev = None
        for d in (1, 2, 4, 8, 16):
            k = _klass(device_count=d)
            s = score = planner.score_candidate(mesh, {}, [k], inputs)
            if not s["feasible"]:
                continue   # mesh larger than the class: skip, not worse
            if prev is not None:
                assert score["goodput_req_s"] >= prev - 1e-9, \
                    (mesh, d, score, prev)
            prev = score["goodput_req_s"]


def test_rates_scale_linearly_with_replicas():
    inputs = planner.CostInputs(coll_overhead_per_way=0.0)
    r1 = planner.class_rates(MESH1, _klass(device_count=1), inputs)
    r4 = planner.class_rates(MESH1, _klass(device_count=4), inputs)
    assert r4["replicas"] == 4
    assert r4["decode_tok_s"] == pytest.approx(4 * r1["decode_tok_s"])


def test_pipeline_bubble_penalizes_pp():
    inputs = planner.CostInputs(coll_overhead_per_way=0.0,
                                bubble_microbatches=4)
    k = _klass(device_count=2)
    r_tp = planner.class_rates(dict(MESH1, tp=2), k, inputs)
    r_pp = planner.class_rates(dict(MESH1, pp=2), k, inputs)
    # same device budget: the pp=2 pipeline pays the GPipe bubble
    # mb/(mb+pp-1) = 4/5, tp=2 at zero overhead does not
    assert r_pp["decode_tok_s"] == pytest.approx(
        0.8 * r_tp["decode_tok_s"])


def test_memory_infeasible_rejected():
    """A class whose per-device memory cannot hold even tiny-llama
    yields no mesh candidate: search reports no feasible candidate."""
    k = _klass(memory_bytes=1)   # 1 byte of HBM
    decision = planner.search("tiny-llama", [k])
    assert "chosen" not in decision
    assert decision["error"] == "no feasible candidate"
    assert decision["scored"] == 0


def test_all_prefill_split_infeasible():
    k = _klass(n_nodes=2)
    s = planner.score_candidate(MESH1, {k.key: 2}, [k],
                                planner.CostInputs())
    assert not s["feasible"]
    assert s["goodput_req_s"] == 0.0


# ---- two-class fleet --------------------------------------------------

def test_two_class_fleet_quarantines_measured_slow_class():
    """The heterogeneous case the planner exists for: a throttled
    class whose estimated ITL violates the SLO is steered into the
    strict prefill pool (zero goodput AND wasted dispatch concurrency
    if it stays mixed); the healthy class keeps serving."""
    views = _views(n_fast=8, n_slow=4, slow_x=24.0)
    classes = planner.fit_node_classes(views)
    assert len(classes) == 2
    inputs = planner.CostInputs(est_prompt_tokens=64,
                                est_decode_tokens=16,
                                slo_itl_ms=250.0)
    decision = planner.search("tiny-llama", classes, inputs)
    chosen = decision["chosen"]
    # the slow nodes (ids 1..4) — and only them — go prefill
    assert chosen["prefill_nodes"] == [1, 2, 3, 4]
    ranked = decision["ranked"]
    assert ranked[0]["goodput_req_s"] >= ranked[-1]["goodput_req_s"]


def test_fit_node_classes_splits_identical_hardware_by_rate():
    """Same device inventory, 24x measured-rate gap: two classes (the
    throttled-host case device info alone cannot see)."""
    views = _views(n_fast=2, n_slow=2)
    classes = planner.fit_node_classes(views)
    assert len(classes) == 2
    assert {len(c.node_ids) for c in classes} == {2}


def test_unmeasured_fleet_prices_at_priors():
    classes = planner.fit_node_classes(
        [{"id": 1, "devices": [{"kind": "tpu"}]}])
    assert len(classes) == 1
    assert not classes[0].measured
    assert classes[0].decode_tok_s == planner.PRIOR_DECODE_TOK_S


# ---- decision record --------------------------------------------------

def test_decision_record_schema_and_json_clean():
    views = _views(n_fast=4, n_slow=2)
    classes = planner.fit_node_classes(views)
    decision = planner.search("tiny-llama", classes,
                              planner.CostInputs(slo_itl_ms=250.0),
                              now=123.0)
    for key in ("version", "model", "at", "chosen", "candidates",
                "scored", "ranked", "inputs", "budget", "tolerance"):
        assert key in decision, key
    chosen = decision["chosen"]
    for key in ("mesh", "role_split", "prefill_nodes",
                "score_goodput_req_s", "plan"):
        assert key in chosen, key
    assert set(chosen["plan"]) >= PLAN_KEYS
    assert decision["at"] == 123.0
    # the inputs block alone must reconstruct the choice: re-scoring
    # the chosen candidate from the recorded classes + inputs lands on
    # the recorded score (flight-recorder discipline)
    rec = decision["inputs"]
    classes2 = [planner.NodeClass(**dict(c, node_ids=tuple(c["node_ids"])))
                for c in rec["classes"]]
    inputs2 = planner.CostInputs(**{
        f.name: rec[f.name]
        for f in planner.CostInputs.__dataclass_fields__.values()})
    s = planner.score_candidate(chosen["mesh"], chosen["role_split"],
                                classes2, inputs2)
    assert s["goodput_req_s"] == pytest.approx(
        chosen["score_goodput_req_s"])
    # survives a JSON round-trip bitwise (what the meta row stores)
    text = json.dumps(decision, sort_keys=True)
    assert json.dumps(json.loads(text), sort_keys=True) == text


def test_search_deterministic():
    views = _views()
    a = planner.search("tiny-llama", planner.fit_node_classes(views),
                       planner.CostInputs(slo_itl_ms=250.0), now=1.0)
    b = planner.search("tiny-llama", planner.fit_node_classes(views),
                       planner.CostInputs(slo_itl_ms=250.0), now=1.0)
    assert json.dumps(a, sort_keys=True) == json.dumps(b, sort_keys=True)


# ---- plan_from_json round-trip over the registry ----------------------

def _mesh_candidates():
    for n in (1, 2, 4, 8):
        yield from planner._factor_assignments(n)


def test_plan_from_json_roundtrip_every_model_and_mesh():
    """Property test: every registry model x every mesh factorization
    of 1/2/4/8 devices that validate_spec accepts survives
    plan -> JSON -> plan_from_json -> JSON bitwise."""
    rounds = 0
    for name in list_models():
        cfg = get_config(name)
        for mesh in _mesh_candidates():
            spec = MeshSpec.from_dict(mesh)
            try:
                validate_spec(spec, cfg)
            except (ValueError, NotImplementedError):
                continue
            plan = make_plan(cfg, spec, max_seq=128)
            text = plan_to_json(plan)
            back = plan_from_json(text)
            assert back == plan, (name, mesh)
            assert plan_to_json(back) == text, (name, mesh)
            rounds += 1
    assert rounds > len(list_models())   # the loop really exercised


def test_plan_from_json_rejects_truncated_payload():
    plan = make_plan(get_config("tiny-llama"),
                     MeshSpec.from_dict({"tp": 1}), max_seq=128)
    broken = {k: v for k, v in plan.items() if k != "partition_specs"}
    with pytest.raises(ValueError, match="partition_specs"):
        plan_from_json(json.dumps(broken))
    with pytest.raises(ValueError, match="object"):
        plan_from_json("[1, 2]")


# ---- master integration: persistence + journal ------------------------

def _seed_nodes(m, n=2):
    for i in range(n):
        nid = m.store.add_node(f"pn{i}", "127.0.0.1", 9000 + i,
                               is_active=True)
        m.store.update_node(nid, info={
            "resources": {"devices": [{"kind": "tpu",
                                       "memory_bytes": 16 << 30}]}})
    m.store.flush()


def test_api_plan_auto_persists_and_survives_restart(tmp_path):
    """The deploy-time contract: one /api/plans/auto call persists the
    chosen plan (plans table) AND the decision record (replicated meta
    row), journals `plan-chosen` with the full inputs, and a fresh
    master over the same database reloads the decision — the
    rebalancer's steering target survives restart/failover."""
    from distributed_llm_inferencing_tpu.runtime.master import Master
    db = str(tmp_path / "planner.sqlite3")
    m = Master(db)
    try:
        _seed_nodes(m)
        r = m.api_plan_auto({"model_name": "tiny-llama",
                             "est_prompt_tokens": 8,
                             "est_decode_tokens": 8})
        assert r["status"] == "success", r
        plan_id = r["plan_id"]
        decision = r["decision"]
        assert decision["plan_id"] == plan_id
        # persisted plan row round-trips through plan_from_json
        row = next(p for p in m.store.list_plans()
                   if p["id"] == plan_id)
        raw = row["plan"]
        plan = plan_from_json(raw if isinstance(raw, str)
                              else json.dumps(raw))
        assert plan["model"] == "tiny-llama"
        # journal: decision reconstructable from the event alone
        evs = [e for e in m.events.tail(50) if e["type"] == "plan-chosen"]
        assert len(evs) == 1
        data = evs[0]["data"]
        for key in ("model", "plan_id", "mesh", "role_split",
                    "prefill_nodes", "candidates", "scored", "score",
                    "classes", "est_prompt_tokens", "est_decode_tokens",
                    "prefill_ewma_ms_per_tok",
                    "decode_tokens_per_weight_pass", "reason"):
            assert key in data, key
        # metrics moved off their pre-registered zeros
        snap = m.metrics.snapshot()
        assert snap["counters"]["planner_searches"] == 1
        assert snap["counters"]["planner_candidates"] >= 1
        assert snap["gauges"]["planner_chosen_score"] > 0
        # cooldown: an identical ask inside the window is served from
        # the persisted decision, not a re-search
        r2 = m.api_plan_auto({"model_name": "tiny-llama"})
        assert r2.get("cached") is True
        assert r2["plan_id"] == plan_id
        assert m.metrics.snapshot()["counters"]["planner_searches"] == 1
        # force re-plans
        r3 = m.api_plan_auto({"model_name": "tiny-llama", "force": True})
        assert r3.get("cached") is None
        assert m.metrics.snapshot()["counters"]["planner_searches"] == 2
    finally:
        m.stop()
    m2 = Master(db)
    try:
        dec = m2._planner_decision
        assert dec is not None
        assert dec["model"] == "tiny-llama"
        assert dec["chosen"]["prefill_nodes"] == \
            decision["chosen"]["prefill_nodes"]
    finally:
        m2.stop()


def test_planner_metrics_preregistered_at_zero():
    from distributed_llm_inferencing_tpu.runtime.master import Master
    m = Master(":memory:")
    try:
        snap = m.metrics.snapshot()
        assert snap["counters"]["planner_searches"] == 0
        assert snap["counters"]["planner_candidates"] == 0
        assert snap["gauges"]["planner_chosen_score"] == 0.0
    finally:
        m.stop()


def test_plan_auto_requires_model_and_nodes():
    from distributed_llm_inferencing_tpu.runtime.master import Master
    m = Master(":memory:")
    try:
        code, body = m.api_plan_auto({})
        assert code == 400
        code, body = m.api_plan_auto({"model_name": "tiny-llama"})
        assert code == 503   # empty fleet: nothing to plan over
    finally:
        m.stop()


def test_planner_steer_targets_decision_split():
    """The rebalancer reads the planner's split as its role target:
    given a persisted decision quarantining node 1, the steer loop
    flips node 1 to prefill (and leaves the rest mixed)."""
    from distributed_llm_inferencing_tpu.runtime.master import Master
    m = Master(":memory:")
    try:
        _seed_nodes(m, n=2)
        flips = []
        m._flip_role = lambda node, role, reason=None: flips.append(
            (node["id"], role, reason))
        m._planner_decision = {
            "model": "tiny-llama",
            "chosen": {"prefill_nodes": [1], "role_split": {}}}
        nodes = m.store.list_nodes(active_only=True)
        assert m._planner_steer(nodes, now=1000.0) is True
        assert flips == [(1, "prefill", "planner-target")]
        # converged fleet: steer still owns the policy (returns True,
        # keeping the divergence heuristic out) but flips nothing
        m._node_role = lambda n: ("prefill" if n["id"] == 1
                                  else "mixed")
        flips.clear()
        assert m._planner_steer(nodes, now=2000.0) is True
        assert flips == []
    finally:
        m.stop()


# ---- sim agreement ----------------------------------------------------

def test_sim_sweep_agrees_with_planner_choice():
    """Small instance of the `--planner-sweep` gate: the planner's top
    choice must land within DLI_PLANNER_TOLERANCE of the sim-measured
    best goodput over the candidate quarantine sizes."""
    from tools.dlisim.planner import sweep
    r = sweep(nodes=18, requests=240, duration_s=90.0, seed=11)
    assert r["ok"], r
    assert r["planner"]["prefill_nodes"] == r["slow_nodes"]
    hashes = {c["journal_hash"] for c in r["candidates"]}
    assert len(hashes) == len(r["candidates"])   # distinct topologies
