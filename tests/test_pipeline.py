"""Pipelined execution tests: GPipe schedule over pp on the CPU mesh.

Golden property (the one the reference conspicuously never checked,
SURVEY.md §4): pipelined output == single-device output, through both
prefill and the full generate loop.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.parallel import pipeline, sharding as shd
from distributed_llm_inferencing_tpu.parallel.mesh import (
    MeshSpec, create_mesh, validate_spec)
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine


@pytest.mark.parametrize("spec,n_micro", [
    (MeshSpec(pp=2), 2),
    (MeshSpec(pp=4), 1),
    (MeshSpec(pp=4), 4),
    (MeshSpec(pp=2, tp=2), 2),
    (MeshSpec(dp=2, pp=2, tp=2), 4),
])
def test_pipelined_prefill_matches_reference(spec, n_micro):
    cfg = get_config("tiny-llama").replace(dtype="float32")
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    B, S = 4, 8
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S)),
        jnp.int32)
    lengths = jnp.asarray([S, S - 2, 3, S], jnp.int32)

    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    ref, ref_cache = transformer.prefill(params, cfg, tokens, lengths, cache)

    mesh = create_mesh(spec)
    with mesh:
        pparams = shd.shard_params(params, mesh, cfg, spec)
        cache = jax.device_put(init_cache(cfg, B, S, dtype=jnp.float32),
                               shd.named(mesh, shd.cache_specs(cfg, spec)))
        got, got_cache = jax.jit(lambda p, t, l, c: pipeline.pipelined_prefill(
            p, cfg, t, l, c, mesh=mesh, n_micro=n_micro)
        )(pparams, tokens, lengths, cache)

    pos = np.arange(S)[None, :]
    valid = (pos < np.asarray(lengths)[:, None])[..., None]
    np.testing.assert_allclose(np.where(valid, np.asarray(got), 0),
                               np.where(valid, np.asarray(ref), 0),
                               atol=2e-4, rtol=2e-4)
    # the KV cache written by the pipeline must match the reference cache
    # (valid slots only) — this is what decode correctness rests on
    vmask = valid[None, :, :, None]  # [1,B,S,1,1]-ish broadcast over L,Hkv,hd
    np.testing.assert_allclose(
        np.where(vmask, np.asarray(got_cache.k), 0),
        np.where(vmask, np.asarray(ref_cache.k), 0), atol=2e-4, rtol=2e-4)


def test_pipelined_engine_generate_matches_single_device():
    cfg = get_config("tiny-llama").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(7)
    prompts = [rng.integers(1, cfg.vocab_size, n).tolist()
               for n in (11, 5, 19, 8)]
    g = SamplingParams.greedy()
    pp_eng = InferenceEngine(cfg, params, mesh_spec=MeshSpec(pp=4),
                             max_seq=64)
    ref_eng = InferenceEngine(cfg, params, max_seq=64)
    got = pp_eng.generate(prompts, max_new_tokens=12, sampling=g)
    ref = ref_eng.generate(prompts, max_new_tokens=12, sampling=g)
    assert got.tokens == ref.tokens


def test_pick_n_micro():
    assert pipeline.pick_n_micro(8, 4) == 8
    assert pipeline.pick_n_micro(6, 2) == 3   # largest divisor of 6 <= 4
    assert pipeline.pick_n_micro(1, 4) == 1
    assert pipeline.pick_n_micro(8, 4, requested=2) == 2
    # non-dividing request clamps (live requests must not hard-fail)
    assert pipeline.pick_n_micro(8, 4, requested=3) == 1
    assert pipeline.pick_n_micro(12, 4, requested=8) == 4


def test_moe_pipelined():
    """MoE layers run through the pipeline too (pp x ep composition)."""
    cfg = get_config("tiny-mixtral").replace(dtype="float32")
    spec = MeshSpec(pp=2, ep=2)
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    B, S = 2, 8
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (B, S)),
        jnp.int32)
    lengths = jnp.full((B,), S, jnp.int32)

    ref, _ = transformer.prefill(params, cfg, tokens, lengths,
                                 init_cache(cfg, B, S, dtype=jnp.float32))
    mesh = create_mesh(spec)
    with mesh:
        pparams = shd.shard_params(params, mesh, cfg, spec)
        cache = jax.device_put(init_cache(cfg, B, S, dtype=jnp.float32),
                               shd.named(mesh, shd.cache_specs(cfg, spec)))
        got, _ = jax.jit(lambda p, t, l, c: pipeline.pipelined_prefill(
            p, cfg, t, l, c, mesh=mesh, n_micro=2))(pparams, tokens,
                                                    lengths, cache)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)


def test_pipelined_per_layer_windows_match_single_device():
    """Per-layer attention windows (gpt-neo topology) through pp: the [L]
    ``attn_window`` leaf shards over the pp axis like every stacked leaf,
    so each stage masks with its OWN layers' windows."""
    cfg = get_config("tiny-llama").replace(
        dtype="float32", sliding_window=None,
        attn_windows=(None, 3, None, 3))
    assert cfg.num_layers == 4, "tiny-llama layer count changed"
    spec = MeshSpec(pp=2)
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    B, S = 2, 8
    tokens = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (B, S)),
        jnp.int32)
    lengths = jnp.asarray([S, S - 3], jnp.int32)

    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    ref, _ = transformer.prefill(params, cfg, tokens, lengths, cache)
    # sanity: the window must actually bind (global-only result differs)
    cfg_g = cfg.replace(attn_windows=None)
    params_g = dict(params, layers={
        k: v for k, v in params["layers"].items() if k != "attn_window"})
    glob, _ = transformer.prefill(params_g, cfg_g, tokens, lengths,
                                  init_cache(cfg_g, B, S,
                                             dtype=jnp.float32))
    assert not np.allclose(np.asarray(ref)[0, :8], np.asarray(glob)[0, :8],
                           atol=1e-5)

    mesh = create_mesh(spec)
    with mesh:
        pparams = shd.shard_params(params, mesh, cfg, spec)
        pcache = jax.device_put(init_cache(cfg, B, S, dtype=jnp.float32),
                                shd.named(mesh, shd.cache_specs(cfg, spec)))
        got, _ = jax.jit(lambda p, t, l, c: pipeline.pipelined_prefill(
            p, cfg, t, l, c, mesh=mesh, n_micro=2)
        )(pparams, tokens, lengths, pcache)

    pos = np.arange(S)[None, :]
    valid = (pos < np.asarray(lengths)[:, None])[..., None]
    np.testing.assert_allclose(np.where(valid, np.asarray(got), 0),
                               np.where(valid, np.asarray(ref), 0),
                               atol=2e-4, rtol=2e-4)
