"""Runtime lock-order watchdog suite (utils/locks.py).

The watchdog learns the fleet-wide acquisition-order graph from real
executions: edge A -> B when some thread acquired B while holding A, a
cycle = a potential deadlock even if this run never interleaved badly
enough to hang. ``DLI_LOCK_CHECK=1`` arms it (scripts/check.sh does for
the chaos suite; the conftest session gate fails the run on any cycle
report). Every test here resets the watchdog behind itself so the
deliberate inversions can't leak into that gate.
"""

import os
import threading
import time

import pytest

from distributed_llm_inferencing_tpu.utils import locks


@pytest.fixture(autouse=True)
def _armed(monkeypatch):
    # save-around, NOT reset: when check.sh runs this file in the same
    # pytest session as the chaos suite, reports a real chaos inversion
    # accumulated must survive for the conftest session gate — only the
    # deliberate inversions seeded HERE may be discarded
    monkeypatch.setenv("DLI_LOCK_CHECK", "1")
    saved = locks.watchdog().snapshot()
    locks.watchdog().reset()
    yield
    locks.watchdog().restore(saved)


def _run(*fns):
    """Run each callable in its own thread, strictly one after another
    (inversions are detected from the learned graph — the threads never
    need to actually contend, and must not, or the test would deadlock
    for real)."""
    for fn in fns:
        t = threading.Thread(target=fn)
        t.start()
        t.join(timeout=10)
        assert not t.is_alive()


def test_deliberate_inversion_produces_exactly_one_report():
    a, b = locks.lock("inv.A"), locks.lock("inv.B")

    def order_ab():
        with a:
            with b:
                pass

    def order_ba():
        with b:
            with a:
                pass

    _run(order_ab, order_ba)
    reports = locks.cycle_reports()
    assert len(reports) == 1, reports
    (rep,) = reports
    assert rep["kind"] == "lock_order_cycle"
    assert set(rep["edge"]) == {"inv.A", "inv.B"}
    assert rep["cycle"][0] == rep["cycle"][-1]          # a closed loop
    assert set(rep["cycle"]) == {"inv.A", "inv.B"}
    # the witness names the thread that established the opposite order
    assert rep["witness"] is not None


def test_consistent_order_stays_silent():
    a, b, c = (locks.lock("ok.A"), locks.lock("ok.B"), locks.lock("ok.C"))

    def nested():
        with a:
            with b:
                with c:
                    pass

    _run(*[nested] * 4)
    assert locks.cycle_reports() == []
    # the graph learned the edges all the same
    edges = locks.watchdog().edges()
    assert "ok.B" in edges["ok.A"] and "ok.C" in edges["ok.B"]


def test_three_lock_cycle_detected():
    a, b, c = (locks.lock("tri.A"), locks.lock("tri.B"), locks.lock("tri.C"))
    _run(lambda: _nest(a, b), lambda: _nest(b, c), lambda: _nest(c, a))
    reports = locks.cycle_reports()
    assert len(reports) == 1, reports
    assert set(reports[0]["cycle"]) == {"tri.A", "tri.B", "tri.C"}


def _nest(outer, inner):
    with outer:
        with inner:
            pass


def test_same_name_different_instances_not_a_cycle():
    # two arenas (one per model) legitimately nest under a fleet sweep;
    # same ROLE nesting across instances must not read as A -> A
    outer = locks.lock("multi.sweep")
    a1, a2 = locks.lock("multi.arena"), locks.lock("multi.arena")
    with outer:
        with a1:
            with a2:
                pass
    assert locks.cycle_reports() == []


def test_rlock_reentrant_acquire_is_not_a_self_deadlock():
    r = locks.rlock("re.R")
    with r:
        with r:
            pass
    assert locks.cycle_reports() == []


def test_blocking_reacquire_of_plain_lock_reported():
    lk = locks.lock("dead.L")
    reported = threading.Event()

    def victim():
        lk.acquire()
        # the watchdog must report BEFORE this blocks for real
        lk.acquire()
        lk.release()

    t = threading.Thread(target=victim, daemon=True)
    t.start()
    for _ in range(100):
        if locks.watchdog().reports("self_deadlock"):
            reported.set()
            break
        time.sleep(0.02)
    assert reported.is_set()
    # threading.Lock may be released from any thread: free the victim
    lk.release()
    t.join(timeout=5)
    assert not t.is_alive()


def test_condition_wait_notify_clean():
    cv = locks.condition("cv.test")
    got = []

    def waiter():
        with cv:
            cv.wait(timeout=5)
            got.append(1)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cv:
        cv.notify_all()
    t.join(timeout=5)
    assert got == [1]
    assert locks.cycle_reports() == []


def test_held_too_long_reported(monkeypatch):
    monkeypatch.setenv("DLI_LOCK_HELD_WARN_MS", "10")
    lk = locks.lock("slow.L")
    with lk:
        time.sleep(0.05)
    reps = locks.watchdog().reports("held_too_long")
    assert reps and reps[0]["lock"] == "slow.L"
    assert reps[0]["held_ms"] >= 10
    # advisory only: never part of the cycle gate
    assert locks.cycle_reports() == []


def test_disabled_returns_stock_primitives(monkeypatch):
    monkeypatch.delenv("DLI_LOCK_CHECK", raising=False)
    assert isinstance(locks.lock("x"), type(threading.Lock()))
    assert isinstance(locks.rlock("x"), type(threading.RLock()))
    assert isinstance(locks.condition("x"), threading.Condition)


def test_runtime_store_creates_instrumented_locks_when_armed():
    # integration: the runtime factories actually flow through
    # utils/locks — a group-commit Store exercises lock + rlock +
    # condition across its flusher thread with zero reports
    from distributed_llm_inferencing_tpu.runtime.state import Store
    st = Store(":memory:", group_commit=True)
    try:
        assert isinstance(st._lock, locks._Instrumented)
        assert isinstance(st._gc_flush_lock, locks._Instrumented)
        nid = st.add_node("n0", "127.0.0.1", 1234)
        rid = st.submit_request("tiny", "hello")
        st.mark_completed(rid, "out", nid, 0.01, 10.0, barrier=True)
        assert st.get_request(rid)["status"] == "completed"
        assert st.get_node(nid)["name"] == "n0"
    finally:
        st.close()
    assert locks.cycle_reports() == []
