"""Flight recorder suite (runtime/events.py, docs/observability.md
"Flight recorder").

Covers the acceptance-critical invariants:
- the declared registry is self-consistent and the journal enforces it
  at emit time (undeclared types raise; the module helper never does),
- the in-memory ring is bounded and the durable half persists through
  the Store group-commit path with working type/node/request/since
  filters and retention pruning,
- events AND TSDB series survive a master restart on the same sqlite
  file (a series queried after restart spans samples from before it),
- TSDB snapshot/restore serves byte-equivalent points and continues
  counter rates across the restart without a spike,
- the journey endpoint merges lifecycle + events + node-scoped context
  + cost phases into one connected, time-ordered view — over a LIVE
  disagg + chaos run, the persisted journal alone reconstructs the
  recovery (breaker open -> requeue -> resume) linked to the affected
  request's journey,
- decision-site units: breaker transitions, drain changes, parks, and
  SLO burn crossings each journal exactly once per transition.
"""

import json
import os
import time

import pytest
import requests as rq

from distributed_llm_inferencing_tpu.runtime import events as events_mod
from distributed_llm_inferencing_tpu.runtime.master import (
    MAX_ATTEMPTS, Master)
from distributed_llm_inferencing_tpu.runtime.state import Store
from distributed_llm_inferencing_tpu.runtime.tsdb import TSDB
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent

# char-level tiny-llama tokenizer + the workers' max_seq=128: the
# prompt must stay under ~98 tokens with 30 new, while clearing the
# 64-char disagg floor
LONG_PROMPT = "The quick brown fox jumps over the lazy dog. " * 2 + "Go."


# ---- registry + journal units ------------------------------------------

def test_registry_is_self_consistent():
    reg = events_mod.registry()
    assert len(reg) == len(events_mod.EVENT_TYPES)
    for t in events_mod.EVENT_TYPES:
        assert t.severity in events_mod.SEVERITIES
        assert t.doc.strip(), t.name
        assert isinstance(t.fields, tuple)
        assert len(t.fields) == len(set(t.fields)), t.name
    assert events_mod.get("breaker-open").severity == "warning"


def test_emit_validates_and_shapes():
    j = events_mod.EventJournal(ring=8)
    ev = j.emit("breaker-open", node_id=3, strikes=2,
                prev_state="closed", ghost=None)
    assert ev["type"] == "breaker-open" and ev["severity"] == "warning"
    assert ev["node_id"] == 3 and ev["request_id"] is None
    assert ev["data"] == {"strikes": 2, "prev_state": "closed"}
    ev2 = j.emit("migrate-anomaly", severity="info", status=409)
    assert ev2["severity"] == "info"
    with pytest.raises(ValueError):
        j.emit("not-a-declared-type")
    with pytest.raises(ValueError):
        j.emit("breaker-open", severity="fatal")


def test_ring_is_bounded():
    j = events_mod.EventJournal(ring=4)
    for i in range(10):
        j.emit("node-drain", node_id=i, draining=True)
    tail = j.tail(100)
    assert len(tail) == 4
    assert [e["node_id"] for e in tail] == [6, 7, 8, 9]
    c = j.counts()
    assert c["emitted"] == 10 and c["ring_cap"] == 4


def test_module_helper_never_raises():
    j = events_mod.EventJournal(ring=4)
    assert events_mod.emit("node-drain") is None   # none installed
    events_mod.set_journal(j)
    try:
        assert events_mod.emit("node-drain", draining=True) is not None
        # an undeclared type through the helper logs, never raises
        assert events_mod.emit("definitely-not-declared") is None
        other = events_mod.EventJournal(ring=4)
        events_mod.clear_journal(other)   # not installed: no-op
        assert events_mod.get_journal() is j
    finally:
        events_mod.clear_journal(j)
    assert events_mod.get_journal() is None


# ---- durable persistence through the Store -----------------------------

def test_store_persistence_and_filters():
    st = Store(":memory:", group_commit=True)
    try:
        j = events_mod.EventJournal(store=st, ring=64)
        t0 = time.time()
        j.emit("breaker-open", node_id=1, strikes=3, prev_state="closed")
        j.emit("breaker-open", node_id=2, strikes=3, prev_state="closed")
        j.emit("request-requeued", node_id=1, request_id=7,
               error="boom", attempts=0)
        j.emit("node-drain", node_id=1, draining=True, t=t0 + 100)
        st.flush()
        assert st.count_events() == 4
        rows = st.query_events()
        assert [r["type"] for r in rows] == [
            "breaker-open", "breaker-open", "request-requeued",
            "node-drain"]
        assert [r["type"] for r in st.query_events(etype="breaker-open")
                ] == ["breaker-open"] * 2
        assert [r["node_id"] for r in st.query_events(node_id=1)
                ] == [1, 1, 1]
        byreq = st.query_events(request_id=7)
        assert len(byreq) == 1 and byreq[0]["data"]["error"] == "boom"
        assert [r["type"] for r in st.query_events(since=t0 + 50)
                ] == ["node-drain"]
        # bounded window: BOTH ends are server-side filters, so the
        # newest-N page can never cut in-window rows (the journey's
        # node-context merge depends on this)
        assert [r["type"] for r in st.query_events(until=t0 + 50)] == [
            "breaker-open", "breaker-open", "request-requeued"]
        assert [r["type"] for r in st.query_events(
            since=t0 + 50, until=t0 + 200)] == ["node-drain"]
        # limit keeps the NEWEST matches, served oldest-first
        assert [r["type"] for r in st.query_events(limit=2)] == [
            "request-requeued", "node-drain"]
    finally:
        st.close()


def test_retention_prunes_the_table():
    st = Store(":memory:", group_commit=True)
    try:
        j = events_mod.EventJournal(store=st, ring=8, retain=10)
        n = events_mod.EventJournal._PRUNE_EVERY + 8
        for i in range(n):
            j.emit("node-drain", node_id=i, draining=bool(i % 2))
        st.flush()
        # prune fired once at _PRUNE_EVERY: the table holds the retained
        # window plus whatever landed after the prune op in the buffer
        assert st.count_events() <= 10 + 8
        newest = st.query_events(limit=1)[0]
        assert newest["node_id"] == n - 1
    finally:
        st.close()


def test_events_survive_store_restart(tmp_path):
    db = str(tmp_path / "m.sqlite3")
    st = Store(db, group_commit=True)
    j = events_mod.EventJournal(store=st)
    j.emit("role-flip", node_id=4, role="decode", prev_role="prefill",
           reason="divergence")
    st.flush()
    st.close()
    st2 = Store(db)
    try:
        rows = st2.query_events(etype="role-flip")
        assert len(rows) == 1
        assert rows[0]["data"] == {"role": "decode",
                                   "prev_role": "prefill",
                                   "reason": "divergence"}
        assert rows[0]["node_id"] == 4
    finally:
        st2.close()


# ---- TSDB snapshot/restore ---------------------------------------------

def _filled_tsdb(now):
    t = TSDB(window_s=40.0, step_s=0.5)
    for i in range(120):   # long enough that history downsampled into
        ts = now - 60 + i * 0.5   # the coarse ring is exercised too
        t.record("w0", "tok", 50.0 * i, kind="counter", t=ts)
        t.record("w0", "q", float(i % 7), kind="gauge", t=ts)
        t.record("w1", "q", float(i % 3), kind="gauge", t=ts)
    return t


def test_tsdb_snapshot_restore_byte_equivalent():
    now = time.time()
    t = _filled_tsdb(now)
    snap = json.loads(json.dumps(t.dump()))   # through the wire format
    t2 = TSDB(window_s=40.0, step_s=0.5)
    assert t2.restore(snap) == 3
    for metric in ("tok", "q"):
        for window in (5.0, 40.0):
            a = json.dumps(t.query(metric, window=window, now=now))
            b = json.dumps(t2.query(metric, window=window, now=now))
            assert a == b, (metric, window)
    assert t2.catalog() == t.catalog()


def test_tsdb_restore_continues_counter_rate_without_spike():
    now = time.time()
    t = _filled_tsdb(now)
    t2 = TSDB(window_s=40.0, step_s=0.5)
    t2.restore(t.dump())
    # next cumulative sample after the "restart": the restored baseline
    # keeps rating from the pre-restart value — a fresh series would
    # need two samples, and a zeroed baseline would spike to v/dt
    t2.record("w0", "tok", 50.0 * 121, kind="counter", t=now + 0.5)
    pts = [p for s in t2.query("tok", now=now + 1.0) for p in s["points"]]
    assert pts, "restored counter series vanished"
    last = pts[-1][1]
    assert 0 < last < 1000, last


def test_tsdb_restore_refuses_step_mismatch():
    t = _filled_tsdb(time.time())
    other = TSDB(window_s=40.0, step_s=1.0)
    assert other.restore(t.dump()) == 0
    assert other.restore({"v": 2}) == 0
    assert other.restore("garbage") == 0


# ---- master decision-site units ----------------------------------------

def _types(m, **kw):
    m.store.flush()
    return [e["type"] for e in m.store.query_events(**kw)]


def test_master_breaker_and_park_events():
    m = Master(":memory:", rebalance=False)
    try:
        nid = m.store.add_node("w0", "127.0.0.1", 1, is_active=True)
        node = m.store.get_node(nid)
        for _ in range(3):
            m._node_failure(node)
        assert _types(m, etype="breaker-open", node_id=nid) == [
            "breaker-open"]
        ev = m.store.query_events(etype="breaker-open")[0]
        assert ev["data"]["strikes"] == 3
        # half-open probe success closes -> breaker-closed event
        m.store.update_node(nid, breaker_state="half_open")
        m._node_success(m.store.get_node(nid))
        assert _types(m, etype="breaker-closed", node_id=nid) == [
            "breaker-closed"]

        # no schedulable node: park (non-terminal), then terminal fail
        rid = m.store.submit_request("tiny-llama", "p")
        m.store.update_node(nid, is_active=0)
        req = m.store.claim_next_pending()
        assert m._reserve_node_for(req) is None
        m.store.flush()
        parks = m.store.query_events(etype="request-park",
                                     request_id=rid)
        assert len(parks) == 1 and parks[0]["data"]["terminal"] is False
        req["attempts"] = MAX_ATTEMPTS - 1
        assert m._reserve_node_for(req) is None
        m.store.flush()
        parks = m.store.query_events(etype="request-park",
                                     request_id=rid)
        assert [p["data"]["terminal"] for p in parks] == [False, True]
        assert parks[-1]["severity"] == "error"
    finally:
        m.stop()


class _Resp:
    def __init__(self, body):
        self._body = body

    def json(self):
        return self._body


def test_master_drain_transition_events():
    m = Master(":memory:", rebalance=False)
    try:
        nid = m.store.add_node("w0", "127.0.0.1", 1, is_active=True)

        def sweep(status):
            node = m.store.get_node(nid)
            m._scrape_workers = lambda path, nodes=None: [
                (node, _Resp({"status": status}), None)]
            m._health_sweep()

        sweep("online")                    # no change: no event
        sweep("draining")                  # off -> on
        sweep("draining")                  # steady: no event
        sweep("online")                    # on -> off
        m.store.flush()
        evs = m.store.query_events(etype="node-drain", node_id=nid)
        assert [e["data"]["draining"] for e in evs] == [True, False]
    finally:
        m.stop()


def test_master_burn_crossing_hysteresis():
    m = Master(":memory:", rebalance=False)
    try:
        m._note_burn(0.5)
        m._note_burn(2.0)     # crossing up
        m._note_burn(5.0)     # still above: silent
        m._note_burn(0.3)     # crossing down
        m._note_burn(0.1)     # still below: silent
        m.store.flush()
        evs = m.store.query_events(etype="slo-burn")
        assert [e["data"]["direction"] for e in evs] == ["above",
                                                         "below"]
        assert evs[0]["severity"] == "warning"
        assert evs[1]["severity"] == "info"
    finally:
        m.stop()


def test_fault_arm_emits_event():
    m = Master(":memory:", rebalance=False)
    try:
        m.service.faults.arm([{"point": "/inference", "mode": "error",
                               "times": 1}])
        assert _types(m, etype="fault-armed") == ["fault-armed"]
        ev = m.store.query_events(etype="fault-armed")[0]
        assert ev["data"]["points"] == ["/inference"]
        assert ev["data"]["service"] == "master"
    finally:
        m.stop()


def test_api_events_filters_and_validation():
    m = Master(":memory:", rebalance=False)
    try:
        nid = m.store.add_node("w0", "127.0.0.1", 1, is_active=True)
        m.events.emit("breaker-open", node_id=nid, strikes=3,
                      prev_state="closed")
        m.events.emit("node-drain", node_id=nid, draining=True)
        out = m.api_events({})
        assert out["count"] == 2
        assert out["events"][0].get("node") == "w0"
        out = m.api_events({"type": "node-drain"})
        assert [e["type"] for e in out["events"]] == ["node-drain"]
        status, body = m.api_events({"type": "no-such-type"})
        assert status == 400, body
        status, body = m.api_events({"node": "notanint"})
        assert status == 400, body
    finally:
        m.stop()


def test_journey_merges_events_phases_and_node_context():
    m = Master(":memory:", rebalance=False)
    try:
        nid = m.store.add_node("w0", "127.0.0.1", 1, is_active=True)
        rid = m.store.submit_request("tiny-llama", "p")
        req = m.store.claim_next_pending()
        assert req["id"] == rid
        # node-scoped context inside the window (no request id)...
        m.events.emit("breaker-open", node_id=nid, strikes=3,
                      prev_state="closed")
        # ...a request-tagged event on the same node...
        m.events.emit("request-requeued", request_id=rid, node_id=nid,
                      error="boom", attempts=0)
        # ...and an unrelated node's event that must NOT merge
        other = m.store.add_node("w9", "127.0.0.1", 2, is_active=True)
        m.events.emit("breaker-open", node_id=other, strikes=3,
                      prev_state="closed")
        cost = {"queue_ms": 10.0, "prefill_ms": 30.0, "decode_ms": 60.0}
        m.store.mark_completed(rid, "out", nid, 0.1, 80.0, cost=cost)
        out = m.api_request_journey({}, str(rid))
        assert out["status"] == "success" and out["connected"], out
        names = [(e["kind"], e["name"]) for e in out["entries"]]
        assert ("lifecycle", "submitted") in names
        assert ("lifecycle", "claimed") in names
        assert ("lifecycle", "completed") in names
        assert ("event", "request-requeued") in names
        assert ("node-event", "breaker-open") in names
        # the unrelated node's trip stays out
        merged_nodes = {e.get("node_id") for e in out["entries"]
                        if e["name"] == "breaker-open"}
        assert merged_nodes == {nid}
        ts = [e["t"] for e in out["entries"]]
        assert ts == sorted(ts)
        # phases partition backward from completion and abut exactly
        assert [p["phase"] for p in out["phases"]] == [
            "queue", "prefill", "decode"]
        q, pf, dc = out["phases"]
        assert q["end"] == pf["start"] and pf["end"] == dc["start"]
        # epoch-magnitude floats: ~1e-7 s absolute precision, so gate
        # the 100ms span at 0.01 ms
        assert abs((dc["end"] - q["start"]) * 1e3 - 100.0) < 0.01
        # 404/400 shapes
        assert m.api_request_journey({}, "999999")[0] == 404
        assert m.api_request_journey({}, "notanint")[0] == 400
    finally:
        m.stop()


# ---- master restart: TSDB + journal durability -------------------------

def test_master_restart_restores_tsdb_and_journal(tmp_path):
    db = str(tmp_path / "m.sqlite3")
    m = Master(db, rebalance=False, tsdb_step_s=0.2, tsdb_snapshot_s=0.1)
    m.metrics.inc("requests_submitted", 5)
    for _ in range(3):
        m._telemetry_sweep()
        time.sleep(0.25)
    m.events.emit("node-drain", node_id=1, draining=True)
    before = m.tsdb.query("requests_submitted", node="master")
    assert before and len(before[0]["points"]) >= 2, before
    m.stop()   # final snapshot + flush

    m2 = Master(db, rebalance=False, tsdb_step_s=0.2, tsdb_snapshot_s=0)
    try:
        # restored series serves the pre-restart points...
        after = m2.tsdb.query("requests_submitted", node="master")
        assert after and after[0]["points"] == before[0]["points"]
        # ...and a post-restart sweep extends the SAME series: one
        # query spans samples from both runs. The restored fine-ring
        # samples survive verbatim; only the in-progress coarse
        # accumulator's preview may re-average as new samples join its
        # bucket — exactly as it would WITHOUT a restart.
        pre_fine = [tuple(p) for p in m.tsdb.dump()["nodes"]["master"]
                    ["requests_submitted"]["fine"]]
        time.sleep(0.25)
        m2.metrics.inc("requests_submitted", 2)
        m2._telemetry_sweep()
        s2 = m2.tsdb._series["master"]["requests_submitted"]
        assert list(s2.fine)[:len(pre_fine)] == pre_fine
        spanned = m2.tsdb.query("requests_submitted", node="master")
        assert len(spanned[0]["points"]) > len(before[0]["points"])
        pre_last = max(t for t, _ in before[0]["points"])
        assert spanned[0]["points"][-1][0] > pre_last
        # the journal survived too
        evs = m2.store.query_events(etype="node-drain")
        assert len(evs) == 1 and evs[0]["data"]["draining"] is True
    finally:
        m2.stop()


def test_master_snapshot_disabled_writes_nothing(tmp_path):
    db = str(tmp_path / "m.sqlite3")
    m = Master(db, rebalance=False, tsdb_step_s=0.2, tsdb_snapshot_s=0)
    m._telemetry_sweep()
    m.stop()
    st = Store(db)
    try:
        assert st.get_meta("tsdb_snapshot") is None
    finally:
        st.close()


# ---- live e2e: the chaos gate ------------------------------------------

def _mk_worker(role="mixed", **load_kw):
    agent = WorkerAgent(role=role)
    srv = agent.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    body = {"model_name": "tiny-llama", "allow_random_init": True,
            "dtype": "float32", "serving": "batched", "slots": 4,
            "kv_blocks": 64, "kv_block_size": 8, "max_seq": 128,
            "decode_chunk_cap": 4}
    body.update(load_kw)
    r = rq.post(f"http://127.0.0.1:{port}/load_model", json=body,
                timeout=600)
    assert r.status_code == 200, r.text
    return agent, port


def _cluster(roles, **master_kw):
    workers = [_mk_worker(role=r) for r in roles]
    master_kw.setdefault("health_interval", 0.5)
    master_kw.setdefault("rebalance", False)
    m = Master(":memory:", **master_kw)
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{msrv.server_address[1]}"
    for i, (_, port) in enumerate(workers):
        r = rq.post(f"{base}/api/nodes/add",
                    json={"name": f"w{i}", "host": "127.0.0.1",
                          "port": port}, timeout=30).json()
        assert r["status"] == "success", r
    m.start_background()
    return m, base, workers


def _wait_req(base, rid, timeout=120):
    deadline = time.time() + timeout
    while time.time() < deadline:
        st = rq.get(f"{base}/api/inference/status/{rid}",
                    timeout=30).json()["request"]
        if st["status"] in ("completed", "failed"):
            return st
        time.sleep(0.05)
    raise TimeoutError(f"request {rid} never finished")


def test_chaos_kill_decode_node_journal_reconstructs_recovery():
    """The ISSUE 13 chaos gate: kill a decode worker mid-request and
    reconstruct the whole recovery from the persisted journal alone —
    disagg verdict -> breaker open -> failover requeue -> recovery —
    with every event linked into the affected request's journey, which
    shows one connected cross-node timeline."""
    m, base, workers = _cluster(["prefill", "decode", "decode"],
                                disagg=True, disagg_min_prompt=64,
                                infer_timeout=20)
    (pre, _), (d1, p1), (d2, p2) = workers
    try:
        time.sleep(0.8)   # one health sweep: runtime roles fresh
        ref = _wait_req(base, rq.post(
            f"{base}/api/inference/submit", json={
                "model_name": "tiny-llama", "prompt": LONG_PROMPT,
                "max_new_tokens": 30,
                "sampling": {"do_sample": False,
                             "allow_random_init": True}},
            timeout=30).json()["request_id"])
        assert ref["status"] == "completed", ref

        rid = rq.post(f"{base}/api/inference/submit", json={
            "model_name": "tiny-llama", "prompt": LONG_PROMPT,
            "max_new_tokens": 30,
            "sampling": {"do_sample": False,
                         "allow_random_init": True}},
            timeout=30).json()["request_id"]
        victim = None
        deadline = time.time() + 30
        while time.time() < deadline and victim is None:
            node = m._processing.get(rid)
            if node is not None and node["port"] in (p1, p2):
                victim = node
            time.sleep(0.002)
        assert victim is not None, "request never landed on decode"
        killed = d1 if victim["port"] == p1 else d2
        killed.service.shutdown()
        st = _wait_req(base, rid, timeout=120)
        assert st["status"] == "completed", st
        assert st["result"] == ref["result"]
        assert st["attempts"] >= 1

        # ---- the journal alone reconstructs the recovery ----
        m.store.flush()
        plan = m.store.query_events(etype="disagg-plan", request_id=rid)
        assert plan and plan[0]["data"]["verdict"] == "transfer", plan
        assert plan[0]["data"]["prefill_pool"] == 1
        assert plan[0]["data"]["est_tokens"] > 0
        trips = m.store.query_events(etype="breaker-open",
                                     node_id=victim["id"])
        assert trips, "victim's breaker trip not journaled"
        requeues = m.store.query_events(etype="request-requeued",
                                        request_id=rid)
        assert requeues and requeues[0]["node_id"] == victim["id"]
        # chronology: verdict -> trip/requeue -> completion
        assert plan[0]["ts"] <= requeues[0]["ts"]
        assert requeues[0]["ts"] <= st["completed_at"]

        # ---- and every event links into the request's journey ----
        jr = rq.get(f"{base}/api/requests/{rid}/journey",
                    timeout=30).json()
        assert jr["status"] == "success" and jr["connected"], jr
        names = [(e["kind"], e["name"]) for e in jr["entries"]]
        assert ("event", "disagg-plan") in names
        assert ("event", "request-requeued") in names
        assert ("node-event", "breaker-open") in names
        ts = [e["t"] for e in jr["entries"]]
        assert ts == sorted(ts)
        # cross-node: the journey's records name BOTH sides of the
        # disagg split (prefill node + the decode nodes involved)
        nodes_seen = {e.get("node_id") for e in jr["entries"]
                      if e.get("node_id") is not None}
        assert victim["id"] in nodes_seen
        assert plan[0]["data"]["prefill_node"] in nodes_seen \
            or len(nodes_seen) >= 2
        assert jr["trace_id"], jr
    finally:
        m.stop()
        for agent, _ in workers:
            try:
                agent.service.shutdown()
            except Exception:
                pass
        # stop the batcher scheduler threads too (the killed worker's
        # keeps decoding for nobody otherwise): a daemon thread still
        # dispatching XLA work during interpreter teardown is the
        # known-flaky exit crash this container shows at seed
        for agent, _ in workers:
            for lm in list(getattr(agent, "models", {}).values()):
                if lm.batcher is not None:
                    try:
                        lm.batcher.stop()
                    except Exception:
                        pass
