"""Speculative decoding (ops/speculative.py + engine integration).

The contract is output EQUIVALENCE: greedy speculative decode must be
bit-identical to plain greedy decode (acceptance keeps exactly the tokens
argmax would have produced), and sampling mode must preserve the target
distribution (delta-draft leave-one-out rejection). Speed is asserted
only structurally — fewer dispatched steps than emitted tokens on a
draft-friendly (repetitive) input.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.ops.speculative import propose_ngram
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(0)


def test_propose_ngram():
    hist = [1, 2, 3, 4, 9, 9, 1, 2]
    # trailing bigram (1,2) occurred at 0 -> continuation 3, 4, 9...
    assert propose_ngram(hist, 3) == [3, 4, 9]
    # continuation shorter than gamma -> padded with its last token
    assert propose_ngram([5, 6, 7, 5, 6], 4) == [7, 5, 6, 6]
    assert propose_ngram([1, 2, 3], 4) is None          # no earlier hit
    assert propose_ngram([1, 2], 4) is None             # too short


def _engine():
    return InferenceEngine(CFG, PARAMS, max_seq=128)


def test_greedy_speculative_matches_plain_repetitive():
    """Repetitive prompt = high draft acceptance; output must still be
    bit-identical to plain greedy decode."""
    pattern = RNG.integers(0, CFG.vocab_size, 5).tolist()
    prompt = (pattern * 4)[:18]
    eng = _engine()
    plain = eng.generate([prompt], max_new_tokens=24,
                         sampling=SamplingParams.greedy())
    spec = eng.generate([prompt], max_new_tokens=24,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=4)
    assert spec.tokens[0] == plain.tokens[0]


def test_greedy_speculative_matches_plain_random():
    """Random prompt = few/no draft hits; correctness must not depend on
    acceptance rate."""
    prompt = RNG.integers(0, CFG.vocab_size, 13).tolist()
    eng = _engine()
    plain = eng.generate([prompt], max_new_tokens=16,
                         sampling=SamplingParams.greedy())
    spec = eng.generate([prompt], max_new_tokens=16,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=3)
    assert spec.tokens[0] == plain.tokens[0]


def test_speculative_fewer_steps_on_acceptance():
    """Tiny random-init models repeat themselves under greedy decode, so
    the n-gram draft should land accepts — fewer verify dispatches than
    tokens. (Structural speed proxy; wall-clock is hardware-dependent.)"""
    pattern = RNG.integers(0, CFG.vocab_size, 4).tolist()
    prompt = (pattern * 5)[:19]
    eng = _engine()
    spec = eng.generate([prompt], max_new_tokens=30,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=4)
    assert len(spec.tokens[0]) == 30
    assert spec.steps < 30, spec.steps


def test_speculative_eos_and_seeding():
    prompt = RNG.integers(0, CFG.vocab_size, 9).tolist()
    eng = _engine()
    full = eng.generate([prompt], max_new_tokens=12,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram").tokens[0]
    eos = full[5]
    want = full[:5] if eos not in full[:5] else None
    got = eng.generate([prompt], max_new_tokens=12,
                       sampling=SamplingParams.greedy(),
                       speculative="ngram", eos_token_id=eos).tokens[0]
    if want is not None:
        assert got == want
    assert eos not in got
    # sampling mode: deterministic given the seed
    sp = SamplingParams(temperature=0.9, top_k=40, top_p=0.9)
    a = eng.generate([prompt], max_new_tokens=15, sampling=sp, seed=7,
                     speculative="ngram").tokens[0]
    b = eng.generate([prompt], max_new_tokens=15, sampling=sp, seed=7,
                     speculative="ngram").tokens[0]
    assert a == b and len(a) == 15


def test_speculative_sampling_distribution_preserved():
    """Delta-draft rejection must keep the target distribution: with a
    sharply peaked next-token distribution and an adversarial draft, the
    emitted first token's empirical frequencies must match plain decode's
    across seeds."""
    prompt = (RNG.integers(0, CFG.vocab_size, 4).tolist() * 5)[:18]
    eng = _engine()
    sp = SamplingParams(temperature=1.2, top_k=8, top_p=0.95)
    plain_counts: dict = {}
    spec_counts: dict = {}
    n = 120
    for seed in range(n):
        p = eng.generate([prompt], max_new_tokens=2, sampling=sp,
                         seed=seed).tokens[0]
        s = eng.generate([prompt], max_new_tokens=2, sampling=sp, seed=seed,
                         speculative="ngram", spec_gamma=2).tokens[0]
        # token 0 comes from the same prefill+sample path in both modes —
        # compare token 1, the first speculative-verified position
        plain_counts[p[1]] = plain_counts.get(p[1], 0) + 1
        spec_counts[s[1]] = spec_counts.get(s[1], 0) + 1
    support = set(plain_counts) | set(spec_counts)
    tv = sum(abs(plain_counts.get(t, 0) - spec_counts.get(t, 0))
             for t in support) / (2 * n)
    # total-variation distance between the two empirical distributions;
    # ~sqrt(k/n) noise floor — generous bound catches real skew
    assert tv < 0.25, (tv, plain_counts, spec_counts)
