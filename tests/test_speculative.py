"""Speculative decoding (ops/speculative.py + engine integration).

The contract is output EQUIVALENCE: greedy speculative decode must be
bit-identical to plain greedy decode (acceptance keeps exactly the tokens
argmax would have produced), and sampling mode must preserve the target
distribution (delta-draft leave-one-out rejection). Speed is asserted
only structurally — fewer dispatched steps than emitted tokens on a
draft-friendly (repetitive) input.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.ops.speculative import propose_ngram
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(0)


def test_propose_ngram():
    hist = [1, 2, 3, 4, 9, 9, 1, 2]
    # trailing bigram (1,2) occurred at 0 -> continuation 3, 4, 9...
    assert propose_ngram(hist, 3) == [3, 4, 9]
    # continuation shorter than gamma -> padded with its last token
    assert propose_ngram([5, 6, 7, 5, 6], 4) == [7, 5, 6, 6]
    assert propose_ngram([1, 2, 3], 4) is None          # no earlier hit
    assert propose_ngram([1, 2], 4) is None             # too short


def _engine():
    return InferenceEngine(CFG, PARAMS, max_seq=128)


def test_greedy_speculative_matches_plain_repetitive():
    """Repetitive prompt = high draft acceptance; output must still be
    bit-identical to plain greedy decode."""
    pattern = RNG.integers(0, CFG.vocab_size, 5).tolist()
    prompt = (pattern * 4)[:18]
    eng = _engine()
    plain = eng.generate([prompt], max_new_tokens=24,
                         sampling=SamplingParams.greedy())
    spec = eng.generate([prompt], max_new_tokens=24,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=4)
    assert spec.tokens[0] == plain.tokens[0]


def test_greedy_speculative_matches_plain_random():
    """Random prompt = few/no draft hits; correctness must not depend on
    acceptance rate."""
    prompt = RNG.integers(0, CFG.vocab_size, 13).tolist()
    eng = _engine()
    plain = eng.generate([prompt], max_new_tokens=16,
                         sampling=SamplingParams.greedy())
    spec = eng.generate([prompt], max_new_tokens=16,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=3)
    assert spec.tokens[0] == plain.tokens[0]


def test_speculative_fewer_steps_on_acceptance():
    """Tiny random-init models repeat themselves under greedy decode, so
    the n-gram draft should land accepts — fewer verify dispatches than
    tokens. (Structural speed proxy; wall-clock is hardware-dependent.)"""
    pattern = RNG.integers(0, CFG.vocab_size, 4).tolist()
    prompt = (pattern * 5)[:19]
    eng = _engine()
    spec = eng.generate([prompt], max_new_tokens=30,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=4)
    assert len(spec.tokens[0]) == 30
    assert spec.steps < 30, spec.steps


def test_speculative_eos_and_seeding():
    prompt = RNG.integers(0, CFG.vocab_size, 9).tolist()
    eng = _engine()
    full = eng.generate([prompt], max_new_tokens=12,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram").tokens[0]
    eos = full[5]
    want = full[:5] if eos not in full[:5] else None
    got = eng.generate([prompt], max_new_tokens=12,
                       sampling=SamplingParams.greedy(),
                       speculative="ngram", eos_token_id=eos).tokens[0]
    if want is not None:
        assert got == want
    assert eos not in got
    # sampling mode: deterministic given the seed
    sp = SamplingParams(temperature=0.9, top_k=40, top_p=0.9)
    a = eng.generate([prompt], max_new_tokens=15, sampling=sp, seed=7,
                     speculative="ngram").tokens[0]
    b = eng.generate([prompt], max_new_tokens=15, sampling=sp, seed=7,
                     speculative="ngram").tokens[0]
    assert a == b and len(a) == 15


def test_speculative_sampling_distribution_preserved():
    """Delta-draft rejection must keep the target distribution: with a
    sharply peaked next-token distribution and an adversarial draft, the
    emitted first token's empirical frequencies must match plain decode's
    across seeds."""
    prompt = (RNG.integers(0, CFG.vocab_size, 4).tolist() * 5)[:18]
    eng = _engine()
    sp = SamplingParams(temperature=1.2, top_k=8, top_p=0.95)
    plain_counts: dict = {}
    spec_counts: dict = {}
    n = 120
    for seed in range(n):
        p = eng.generate([prompt], max_new_tokens=2, sampling=sp,
                         seed=seed).tokens[0]
        s = eng.generate([prompt], max_new_tokens=2, sampling=sp, seed=seed,
                         speculative="ngram", spec_gamma=2).tokens[0]
        # token 0 comes from the same prefill+sample path in both modes —
        # compare token 1, the first speculative-verified position
        plain_counts[p[1]] = plain_counts.get(p[1], 0) + 1
        spec_counts[s[1]] = spec_counts.get(s[1], 0) + 1
    support = set(plain_counts) | set(spec_counts)
    tv = sum(abs(plain_counts.get(t, 0) - spec_counts.get(t, 0))
             for t in support) / (2 * n)
    # total-variation distance between the two empirical distributions;
    # ~sqrt(k/n) noise floor — generous bound catches real skew
    assert tv < 0.25, (tv, plain_counts, spec_counts)


# ---------------- on-device drafting ----------------

def test_propose_ngram_device_matches_host():
    """Differential: the vectorized device proposer must agree with the
    host propose_ngram on random histories (where the host finds a
    draft), and report has_draft=False exactly when the host returns
    None."""
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.ops.speculative import (
        propose_ngram, propose_ngram_device)
    rng = np.random.default_rng(0)
    H, R, G = 48, 16, 4
    hist = np.zeros((R, H), np.int32)
    lens = np.zeros((R,), np.int32)
    rows = []
    for r in range(R):
        n = int(rng.integers(3, H))
        # small vocab => plenty of repeated bigrams
        row = rng.integers(0, 5, n).tolist()
        rows.append(row)
        hist[r, :n] = row
        lens[r] = n
    drafts, has = propose_ngram_device(
        jnp.asarray(hist), jnp.asarray(lens), G)
    drafts, has = np.asarray(drafts), np.asarray(has)
    for r in range(R):
        want = propose_ngram(rows[r], G)
        assert has[r] == (want is not None), (r, rows[r])
        if want is not None:
            assert drafts[r].tolist() == want, (r, rows[r],
                                                drafts[r].tolist(), want)


def test_propose_ngram_device_short_histories():
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.ops.speculative import (
        propose_ngram_device)
    hist = jnp.asarray([[7, 0, 0, 0], [7, 7, 0, 0]], jnp.int32)
    drafts, has = propose_ngram_device(hist, jnp.asarray([1, 2]), 3)
    assert not bool(has[0]) and not bool(has[1])
    # fallback drafts repeat the current token
    assert np.asarray(drafts).tolist() == [[7, 7, 7], [7, 7, 7]]


def _paged_setup(prompts, cfg, num_blocks=64, bs=8, mb=8):
    """Prefill prompts into a fresh paged cache via the admission path;
    returns (paged, block_tables, context_lens, tokens=last prompt tok)."""
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models import transformer
    from distributed_llm_inferencing_tpu.models.params import init_params
    from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
        init_paged_cache)
    import jax
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    paged = init_paged_cache(cfg, num_blocks, bs)
    r = len(prompts)
    t = max(len(p) for p in prompts)
    t = -(-t // bs) * bs
    toks = np.zeros((r, t), np.int32)
    tail_len = np.zeros((r,), np.int32)
    tail_blocks = np.zeros((r, t // bs), np.int32)
    nb = 1   # block 0 = dummy
    tables = np.zeros((r, mb), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p) - 1] = p[:-1]
        tail_len[i] = len(p) - 1
        nblk = t // bs
        tail_blocks[i] = np.arange(nb, nb + nblk)
        tables[i, :nblk] = tail_blocks[i]
        # growth blocks for decode
        tables[i, nblk:] = np.arange(nb + nblk, nb + mb)
        nb += mb
    _, paged = transformer.paged_prefill_tail(
        params, cfg, jnp.asarray(toks), jnp.asarray(tail_len),
        jnp.asarray(tail_blocks), jnp.zeros((r, 1), jnp.int32),
        jnp.zeros((r,), jnp.int32), paged)
    cur = np.asarray([p[-1] for p in prompts], np.int32)
    cl = np.asarray([len(p) - 1 for p in prompts], np.int32)
    return params, paged, jnp.asarray(tables), jnp.asarray(cl), \
        jnp.asarray(cur)


def test_paged_speculative_chunk_matches_plain_chunk():
    """Greedy rows: bit-identical tokens to the plain decode chunk (the
    acceptance rule only skips ahead). The sampling row runs exact
    rejection sampling — trajectory diverges from plain by design, but
    must be budget-exact and deterministic given its seed. Exercised
    with a repetitive prompt so drafts actually accept."""
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models import transformer
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(0)
    base = rng.integers(0, 256, 6).tolist()
    prompts = [(base * 4)[:20],                      # repetitive: drafts hit
               rng.integers(0, 256, 9).tolist(),     # arbitrary
               (base * 3)[:14]]                      # repetitive + sampled
    params, paged0, tables, cl0, cur0 = _paged_setup(prompts, cfg)

    n_new = 12
    seeds = jnp.asarray([11, 22, 33], jnp.int32)
    steps0 = jnp.zeros((3,), jnp.int32)
    temps = jnp.asarray([1.0, 1.0, 0.8], jnp.float32)
    tks = jnp.asarray([0, 0, 40], jnp.int32)
    tps = jnp.asarray([1.0, 1.0, 0.9], jnp.float32)
    ds = jnp.asarray([False, False, True])
    budget = jnp.full((3,), n_new, jnp.int32)
    eos = jnp.full((3,), -1, jnp.int32)

    ptoks, pemits, _ = transformer.paged_decode_chunk(
        params, cfg, n_new, cur0, paged0, tables, cl0, seeds, steps0,
        temps, tks, tps, ds, budget, eos, dummy_block=0)
    plain = [[int(ptoks[t, r]) for t in range(n_new) if bool(pemits[t, r])]
             for r in range(3)]

    def run_spec():
        stoks, keeps, _, _ = transformer.paged_speculative_chunk(
            params, cfg, 12, 3, cur0, _hist(prompts, 64), paged0, tables,
            cl0, seeds, steps0, temps, tks, tps, ds, budget, eos,
            dummy_block=0)
        out = [[], [], []]
        for t in range(12):
            for r in range(3):
                out[r].extend(int(x) for x in
                              np.asarray(stoks[t, r, :int(keeps[t, r])]))
        return out

    spec = run_spec()
    assert spec[0] == plain[0], (spec[0], plain[0])   # greedy: bit-identical
    assert spec[1] == plain[1], (spec[1], plain[1])
    assert len(spec[2]) == n_new                      # sampled: budget exact
    assert run_spec()[2] == spec[2]                   # and seed-deterministic


def _hist(prompts, h):
    import jax.numpy as jnp
    r = len(prompts)
    out = np.zeros((r, h), np.int32)
    for i, p in enumerate(prompts):
        out[i, :len(p)] = p
    return jnp.asarray(out)


def test_paged_speculative_chunk_eos_and_budget():
    """Per-slot eos inside an accepted run truncates at it; budgets are
    exact (never exceeded even when a full gamma+1 run would)."""
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models import transformer
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(1)
    base = rng.integers(0, 256, 5).tolist()
    prompts = [(base * 5)[:22], (base * 5)[:22]]
    params, paged0, tables, cl0, cur0 = _paged_setup(prompts, cfg)

    seeds = jnp.zeros((2,), jnp.int32)
    steps0 = jnp.zeros((2,), jnp.int32)
    ones = jnp.ones((2,), jnp.float32)
    ds = jnp.zeros((2,), bool)
    # row 0: tiny budget; row 1: eos = its first plain-decode token
    ptoks, pemits, _ = transformer.paged_decode_chunk(
        params, cfg, 4, cur0, paged0, tables, cl0, seeds, steps0, ones,
        jnp.zeros((2,), jnp.int32), ones, ds, jnp.full((2,), 4, jnp.int32),
        jnp.full((2,), -1, jnp.int32), dummy_block=0)
    first_tok = int(ptoks[1, 1]) if bool(pemits[1, 1]) else int(ptoks[0, 1])

    budget = jnp.asarray([3, 10], jnp.int32)
    eos = jnp.asarray([-1, first_tok], jnp.int32)
    stoks, keeps, eos_seen, _ = transformer.paged_speculative_chunk(
        params, cfg, 8, 3, cur0, _hist(prompts, 64), paged0, tables,
        cl0, seeds, steps0, ones, jnp.zeros((2,), jnp.int32), ones, ds,
        budget, eos, dummy_block=0)
    out = [[], []]
    for t in range(8):
        for r in range(2):
            out[r].extend(int(x) for x in
                          np.asarray(stoks[t, r, :int(keeps[t, r])]))
    assert len(out[0]) == 3                     # budget exact
    assert first_tok not in out[1]              # eos never emitted
    eos_seen = np.asarray(eos_seen)
    assert not eos_seen[-1, 0]                  # budget death, not eos
    assert eos_seen[-1, 1]                      # eos reported to the host


def test_batcher_speculative_matches_plain():
    """Batched speculative serving: greedy requests produce bit-identical
    outputs to the plain batcher (exact acceptance); the sampled request
    runs exact rejection sampling — right length, deterministic given its
    seed — and draft tokens were accepted on the repetitive prompts."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(0)
    base = rng.integers(0, 256, 6).tolist()
    rep = (base * 4)[:20]
    arb = rng.integers(0, 256, 9).tolist()

    def run(spec):
        b = ContinuousBatcher(
            cfg, num_blocks=96, block_size=8, slots=3, max_seq=128, seed=0,
            speculative="ngram" if spec else None, spec_gamma=3)
        reqs = [
            b.submit(rep, max_new_tokens=14, sampling=SamplingParams.greedy(),
                     seed=1),
            b.submit(arb, max_new_tokens=10, sampling=SamplingParams.greedy(),
                     seed=2),
            b.submit(rep, max_new_tokens=12,
                     sampling=SamplingParams(temperature=0.8, top_k=40),
                     seed=3),
        ]
        for _ in range(120):
            b.step()
            if all(r.done.is_set() for r in reqs):
                break
        return [r.wait() for r in reqs], b.stats()

    plain, _ = run(False)
    spec, st = run(True)
    assert spec[0] == plain[0], (spec[0], plain[0])
    assert spec[1] == plain[1], (spec[1], plain[1])
    assert len(spec[2]) == 12
    spec2, _ = run(True)
    assert spec2[2] == spec[2]          # sampled: seed-deterministic
    assert st["spec_accepted_tokens"] >= 1, st


def test_batcher_speculative_sampled_accepts_drafts():
    """do_sample requests must get real accepted-draft speedups (VERDICT
    round-3 ask #3): a lone sampled request on a highly repetitive prompt
    accepts at least one draft token."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(3)
    base = rng.integers(0, 256, 4).tolist()
    prompt = (base * 6)[:22]
    b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                          max_seq=128, seed=0, speculative="ngram",
                          spec_gamma=3)
    # low temperature peaks the target distribution, so in-pattern drafts
    # carry high acceptance probability (the tiny random-init model's
    # sampled trajectories wander; near-greedy keeps them on-pattern)
    r = b.submit(prompt, max_new_tokens=48,
                 sampling=SamplingParams(temperature=0.05, top_k=20), seed=5)
    for _ in range(120):
        b.step()
        if r.done.is_set():
            break
    assert len(r.wait()) == 48
    assert b.stats()["spec_accepted_tokens"] >= 1, b.stats()


def test_batcher_speculative_lockstep_hist_delta():
    """The lockstep broadcast must NOT carry the full drafting history:
    spec_decode args ship per-slot deltas (non-empty only right after an
    admission), and a follower replaying the JSON'd programs reconstructs
    the leader's history rows and cache evolution exactly."""
    import json
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(2)
    base = rng.integers(0, 256, 5).tolist()
    prompts = [(base * 5)[:20], rng.integers(0, 256, 7).tolist()]

    mk = lambda: ContinuousBatcher(  # noqa: E731
        cfg, num_blocks=64, block_size=8, slots=2, max_seq=96, seed=0,
        speculative="ngram", spec_gamma=3)
    leader, follower = mk(), mk()
    spec_payloads = []

    def hook(kind, args, run):
        wire = json.loads(json.dumps(args))   # prove JSON-safety
        if kind == "spec_decode":
            assert "hist" not in wire, "full history must not broadcast"
            spec_payloads.append(wire)
        follower.replay(kind, wire)
        return run()

    leader.program_hook = hook
    reqs = [leader.submit(p, max_new_tokens=12,
                          sampling=SamplingParams.greedy(), seed=9 + i)
            for i, p in enumerate(prompts)]
    for _ in range(60):
        leader.step()
        if all(r.done.is_set() for r in reqs):
            break
    outs = [r.wait() for r in reqs]
    assert all(len(o) == 12 for o in outs)

    assert spec_payloads, "speculative chunks must have been dispatched"
    # delta amortization: only the first chunk after admission syncs rows
    assert spec_payloads[0]["hist_delta"], spec_payloads[0]
    for p in spec_payloads[1:]:
        assert p["hist_delta"] == [], p["hist_delta"]
    # follower reconstructed the leader's history exactly
    np.testing.assert_array_equal(follower._hist, leader._hist)


def test_accept_rejection_batch_matches_analytic_probability():
    """The acceptance math itself, against closed form: with a fixed
    peaked distribution and the draft equal to the favored token, the
    expected accepted count is p + p^2 + ... + p^G for
    p = exp(l)/(exp(l) + (k-1)) under temp-1 top-k warping. Empirical
    mean over seeds must land on it; and rejected-position residuals must
    never re-emit the rejected draft."""
    import jax
    from distributed_llm_inferencing_tpu.ops.speculative import (
        accept_rejection_batch)
    G, V, L = 3, 64, 5.0
    logits = np.zeros((1, G + 1, V), np.float32)
    logits[..., 7] = L
    drafts = np.full((1, G), 7, np.int32)
    args = dict(temps=jnp.asarray([1.0], jnp.float32),
                top_ks=jnp.asarray([20], jnp.int32),
                top_ps=jnp.asarray([0.95], jnp.float32),
                ds=jnp.asarray([True]))
    fn = jax.jit(lambda s: accept_rejection_batch(
        jnp.asarray(logits), jnp.asarray(drafts), s,
        jnp.zeros((1,), jnp.int32), **args))
    n_accs, toks = [], []
    for s in range(400):
        t, n_emit = fn(jnp.asarray([s], jnp.int32))
        n_accs.append(int(n_emit[0]) - 1)
        toks.append(np.asarray(t[0]))
    p = np.exp(L) / (np.exp(L) + 19)   # top-20 keeps 19 competitors
    want = sum(p ** i for i in range(1, G + 1))        # ~2.37
    got = np.mean(n_accs)
    assert abs(got - want) < 0.12, (got, want)
    # rejection residuals exclude the rejected draft
    for n_acc, t in zip(n_accs, toks):
        if n_acc < G:
            assert t[n_acc] != 7, (n_acc, t)


def test_batcher_speculative_sampling_distribution_preserved():
    """Exact rejection sampling at the batcher level: across many seeds,
    the speculative-verified tokens' empirical distribution must match
    the plain batcher's. The distributions are conditional mixtures over
    the admission token, so the pass bound is CALIBRATED against the
    plain-vs-plain sampling noise floor at the same sample size instead
    of a fixed constant."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(0)
    prompt = (rng.integers(0, 256, 4).tolist() * 5)[:18]
    sp = SamplingParams(temperature=1.2, top_k=8, top_p=0.95)
    n = 120

    def collect(spec, seed0):
        b = ContinuousBatcher(cfg, num_blocks=256, block_size=8, slots=8,
                              max_seq=64, seed=0,
                              speculative="ngram" if spec else None,
                              spec_gamma=2)
        reqs = [b.submit(prompt, max_new_tokens=3, sampling=sp,
                         seed=seed0 + s) for s in range(n)]
        for _ in range(600):
            b.step()
            if all(r.done.is_set() for r in reqs):
                break
        counts: dict = {}
        for r in reqs:
            toks = r.wait()
            # token 0 is the admission sample (same path in both modes);
            # positions 1 and 2 are speculative-verified
            for pos in (1, 2):
                key = (pos, toks[pos])
                counts[key] = counts.get(key, 0) + 1
        return counts

    def tv(a, b):
        support = set(a) | set(b)
        return sum(abs(a.get(t, 0) - b.get(t, 0))
                   for t in support) / (2 * 2 * n)

    plain_a = collect(False, 0)
    plain_b = collect(False, 5000)     # same dist, fresh seeds: noise floor
    spec_a = collect(True, 0)
    tv_null = tv(plain_a, plain_b)
    tv_spec = tv(spec_a, plain_a)
    assert tv_spec < 1.5 * tv_null + 0.08, (tv_spec, tv_null)


def test_batcher_speculative_eos_and_stream():
    """eos cuts a speculative run mid-chunk; streamed tokens match kept
    tokens in order."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(1)
    base = rng.integers(0, 256, 5).tolist()
    prompt = (base * 4)[:18]

    plain = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                              max_seq=128, seed=0)
    r0 = plain.submit(prompt, max_new_tokens=10,
                      sampling=SamplingParams.greedy())
    for _ in range(40):
        plain.step()
        if r0.done.is_set():
            break
    full = r0.wait()
    eos = full[4]
    want = full[:4] if eos not in full[:4] else None

    b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                          max_seq=128, seed=0, speculative="ngram",
                          spec_gamma=3)
    seen = []
    r = b.submit(prompt, max_new_tokens=10,
                 sampling=SamplingParams.greedy(), eos_token_id=eos,
                 stream_cb=seen.append)
    for _ in range(40):
        b.step()
        if r.done.is_set():
            break
    got = r.wait()
    if want is not None:
        assert got == want, (got, want)
    assert seen == got
    assert eos not in got
