"""Speculative decoding (ops/speculative.py + engine integration).

The contract is output EQUIVALENCE: greedy speculative decode must be
bit-identical to plain greedy decode (acceptance keeps exactly the tokens
argmax would have produced), and sampling mode must preserve the target
distribution (delta-draft leave-one-out rejection). Speed is asserted
only structurally — fewer dispatched steps than emitted tokens on a
draft-friendly (repetitive) input.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.ops.speculative import propose_ngram
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(0)


def test_propose_ngram():
    hist = [1, 2, 3, 4, 9, 9, 1, 2]
    # trailing bigram (1,2) occurred at 0 -> continuation 3, 4, 9...
    assert propose_ngram(hist, 3) == [3, 4, 9]
    # continuation shorter than gamma -> padded with its last token
    assert propose_ngram([5, 6, 7, 5, 6], 4) == [7, 5, 6, 6]
    assert propose_ngram([1, 2, 3], 4) is None          # no earlier hit
    assert propose_ngram([1, 2], 4) is None             # too short


def _engine():
    return InferenceEngine(CFG, PARAMS, max_seq=128)


def test_greedy_speculative_matches_plain_repetitive():
    """Repetitive prompt = high draft acceptance; output must still be
    bit-identical to plain greedy decode."""
    pattern = RNG.integers(0, CFG.vocab_size, 5).tolist()
    prompt = (pattern * 4)[:18]
    eng = _engine()
    plain = eng.generate([prompt], max_new_tokens=24,
                         sampling=SamplingParams.greedy())
    spec = eng.generate([prompt], max_new_tokens=24,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=4)
    assert spec.tokens[0] == plain.tokens[0]


def test_greedy_speculative_matches_plain_random():
    """Random prompt = few/no draft hits; correctness must not depend on
    acceptance rate."""
    prompt = RNG.integers(0, CFG.vocab_size, 13).tolist()
    eng = _engine()
    plain = eng.generate([prompt], max_new_tokens=16,
                         sampling=SamplingParams.greedy())
    spec = eng.generate([prompt], max_new_tokens=16,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=3)
    assert spec.tokens[0] == plain.tokens[0]


def test_speculative_fewer_steps_on_acceptance():
    """Tiny random-init models repeat themselves under greedy decode, so
    the n-gram draft should land accepts — fewer verify dispatches than
    tokens. (Structural speed proxy; wall-clock is hardware-dependent.)"""
    pattern = RNG.integers(0, CFG.vocab_size, 4).tolist()
    prompt = (pattern * 5)[:19]
    eng = _engine()
    spec = eng.generate([prompt], max_new_tokens=30,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram", spec_gamma=4)
    assert len(spec.tokens[0]) == 30
    assert spec.steps < 30, spec.steps


def test_speculative_eos_and_seeding():
    prompt = RNG.integers(0, CFG.vocab_size, 9).tolist()
    eng = _engine()
    full = eng.generate([prompt], max_new_tokens=12,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram").tokens[0]
    eos = full[5]
    want = full[:5] if eos not in full[:5] else None
    got = eng.generate([prompt], max_new_tokens=12,
                       sampling=SamplingParams.greedy(),
                       speculative="ngram", eos_token_id=eos).tokens[0]
    if want is not None:
        assert got == want
    assert eos not in got
    # sampling mode: deterministic given the seed
    sp = SamplingParams(temperature=0.9, top_k=40, top_p=0.9)
    a = eng.generate([prompt], max_new_tokens=15, sampling=sp, seed=7,
                     speculative="ngram").tokens[0]
    b = eng.generate([prompt], max_new_tokens=15, sampling=sp, seed=7,
                     speculative="ngram").tokens[0]
    assert a == b and len(a) == 15


def test_speculative_sampling_distribution_preserved():
    """Delta-draft rejection must keep the target distribution: with a
    sharply peaked next-token distribution and an adversarial draft, the
    emitted first token's empirical frequencies must match plain decode's
    across seeds."""
    prompt = (RNG.integers(0, CFG.vocab_size, 4).tolist() * 5)[:18]
    eng = _engine()
    sp = SamplingParams(temperature=1.2, top_k=8, top_p=0.95)
    plain_counts: dict = {}
    spec_counts: dict = {}
    n = 120
    for seed in range(n):
        p = eng.generate([prompt], max_new_tokens=2, sampling=sp,
                         seed=seed).tokens[0]
        s = eng.generate([prompt], max_new_tokens=2, sampling=sp, seed=seed,
                         speculative="ngram", spec_gamma=2).tokens[0]
        # token 0 comes from the same prefill+sample path in both modes —
        # compare token 1, the first speculative-verified position
        plain_counts[p[1]] = plain_counts.get(p[1], 0) + 1
        spec_counts[s[1]] = spec_counts.get(s[1], 0) + 1
    support = set(plain_counts) | set(spec_counts)
    tv = sum(abs(plain_counts.get(t, 0) - spec_counts.get(t, 0))
             for t in support) / (2 * n)
    # total-variation distance between the two empirical distributions;
    # ~sqrt(k/n) noise floor — generous bound catches real skew
    assert tv < 0.25, (tv, plain_counts, spec_counts)


# ---------------- on-device drafting ----------------

def test_propose_ngram_device_matches_host():
    """Differential: the vectorized device proposer must agree with the
    host propose_ngram on random histories (where the host finds a
    draft), and report has_draft=False exactly when the host returns
    None."""
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.ops.speculative import (
        propose_ngram, propose_ngram_device)
    rng = np.random.default_rng(0)
    H, R, G = 48, 16, 4
    hist = np.zeros((R, H), np.int32)
    lens = np.zeros((R,), np.int32)
    rows = []
    for r in range(R):
        n = int(rng.integers(3, H))
        # small vocab => plenty of repeated bigrams
        row = rng.integers(0, 5, n).tolist()
        rows.append(row)
        hist[r, :n] = row
        lens[r] = n
    drafts, has = propose_ngram_device(
        jnp.asarray(hist), jnp.asarray(lens), G)
    drafts, has = np.asarray(drafts), np.asarray(has)
    for r in range(R):
        want = propose_ngram(rows[r], G)
        assert has[r] == (want is not None), (r, rows[r])
        if want is not None:
            assert drafts[r].tolist() == want, (r, rows[r],
                                                drafts[r].tolist(), want)


def test_propose_ngram_device_short_histories():
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.ops.speculative import (
        propose_ngram_device)
    hist = jnp.asarray([[7, 0, 0, 0], [7, 7, 0, 0]], jnp.int32)
    drafts, has = propose_ngram_device(hist, jnp.asarray([1, 2]), 3)
    assert not bool(has[0]) and not bool(has[1])
    # fallback drafts repeat the current token
    assert np.asarray(drafts).tolist() == [[7, 7, 7], [7, 7, 7]]


def _paged_setup(prompts, cfg, num_blocks=64, bs=8, mb=8):
    """Prefill prompts into a fresh paged cache via the admission path;
    returns (paged, block_tables, context_lens, tokens=last prompt tok)."""
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models import transformer
    from distributed_llm_inferencing_tpu.models.params import init_params
    from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
        init_paged_cache)
    import jax
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    paged = init_paged_cache(cfg, num_blocks, bs)
    r = len(prompts)
    t = max(len(p) for p in prompts)
    t = -(-t // bs) * bs
    toks = np.zeros((r, t), np.int32)
    tail_len = np.zeros((r,), np.int32)
    tail_blocks = np.zeros((r, t // bs), np.int32)
    nb = 1   # block 0 = dummy
    tables = np.zeros((r, mb), np.int32)
    for i, p in enumerate(prompts):
        toks[i, :len(p) - 1] = p[:-1]
        tail_len[i] = len(p) - 1
        nblk = t // bs
        tail_blocks[i] = np.arange(nb, nb + nblk)
        tables[i, :nblk] = tail_blocks[i]
        # growth blocks for decode
        tables[i, nblk:] = np.arange(nb + nblk, nb + mb)
        nb += mb
    _, paged = transformer.paged_prefill_tail(
        params, cfg, jnp.asarray(toks), jnp.asarray(tail_len),
        jnp.asarray(tail_blocks), jnp.zeros((r, 1), jnp.int32),
        jnp.zeros((r,), jnp.int32), paged)
    cur = np.asarray([p[-1] for p in prompts], np.int32)
    cl = np.asarray([len(p) - 1 for p in prompts], np.int32)
    return params, paged, jnp.asarray(tables), jnp.asarray(cl), \
        jnp.asarray(cur)


def test_paged_speculative_chunk_matches_plain_chunk():
    """Greedy rows: bit-identical tokens to the plain decode chunk (the
    acceptance rule only skips ahead); a sampling row: bit-identical too
    (spec emits one sample/iter from the same per-row stream). Exercised
    with a repetitive prompt so drafts actually accept."""
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models import transformer
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(0)
    base = rng.integers(0, 256, 6).tolist()
    prompts = [(base * 4)[:20],                      # repetitive: drafts hit
               rng.integers(0, 256, 9).tolist(),     # arbitrary
               (base * 3)[:14]]                      # repetitive + sampled
    params, paged0, tables, cl0, cur0 = _paged_setup(prompts, cfg)

    n_new = 12
    seeds = jnp.asarray([11, 22, 33], jnp.int32)
    steps0 = jnp.zeros((3,), jnp.int32)
    temps = jnp.asarray([1.0, 1.0, 0.8], jnp.float32)
    tks = jnp.asarray([0, 0, 40], jnp.int32)
    tps = jnp.asarray([1.0, 1.0, 0.9], jnp.float32)
    ds = jnp.asarray([False, False, True])
    budget = jnp.full((3,), n_new, jnp.int32)
    eos = jnp.full((3,), -1, jnp.int32)

    ptoks, pemits, _ = transformer.paged_decode_chunk(
        params, cfg, n_new, cur0, paged0, tables, cl0, seeds, steps0,
        temps, tks, tps, ds, budget, eos, dummy_block=0)
    plain = [[int(ptoks[t, r]) for t in range(n_new) if bool(pemits[t, r])]
             for r in range(3)]

    stoks, keeps, alive, _ = transformer.paged_speculative_chunk(
        params, cfg, 12, 3, cur0, _hist(prompts, 64), paged0, tables,
        cl0, seeds, steps0, temps, tks, tps, ds, budget, eos,
        dummy_block=0)
    spec = [[], [], []]
    for t in range(12):
        for r in range(3):
            spec[r].extend(int(x) for x in
                           np.asarray(stoks[t, r, :int(keeps[t, r])]))
    assert spec == plain, (spec, plain)


def _hist(prompts, h):
    import jax.numpy as jnp
    r = len(prompts)
    out = np.zeros((r, h), np.int32)
    for i, p in enumerate(prompts):
        out[i, :len(p)] = p
    return jnp.asarray(out)


def test_paged_speculative_chunk_eos_and_budget():
    """Per-slot eos inside an accepted run truncates at it; budgets are
    exact (never exceeded even when a full gamma+1 run would)."""
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models import transformer
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(1)
    base = rng.integers(0, 256, 5).tolist()
    prompts = [(base * 5)[:22], (base * 5)[:22]]
    params, paged0, tables, cl0, cur0 = _paged_setup(prompts, cfg)

    seeds = jnp.zeros((2,), jnp.int32)
    steps0 = jnp.zeros((2,), jnp.int32)
    ones = jnp.ones((2,), jnp.float32)
    ds = jnp.zeros((2,), bool)
    # row 0: tiny budget; row 1: eos = its first plain-decode token
    ptoks, pemits, _ = transformer.paged_decode_chunk(
        params, cfg, 4, cur0, paged0, tables, cl0, seeds, steps0, ones,
        jnp.zeros((2,), jnp.int32), ones, ds, jnp.full((2,), 4, jnp.int32),
        jnp.full((2,), -1, jnp.int32), dummy_block=0)
    first_tok = int(ptoks[1, 1]) if bool(pemits[1, 1]) else int(ptoks[0, 1])

    budget = jnp.asarray([3, 10], jnp.int32)
    eos = jnp.asarray([-1, first_tok], jnp.int32)
    stoks, keeps, eos_seen, _ = transformer.paged_speculative_chunk(
        params, cfg, 8, 3, cur0, _hist(prompts, 64), paged0, tables,
        cl0, seeds, steps0, ones, jnp.zeros((2,), jnp.int32), ones, ds,
        budget, eos, dummy_block=0)
    out = [[], []]
    for t in range(8):
        for r in range(2):
            out[r].extend(int(x) for x in
                          np.asarray(stoks[t, r, :int(keeps[t, r])]))
    assert len(out[0]) == 3                     # budget exact
    assert first_tok not in out[1]              # eos never emitted
    eos_seen = np.asarray(eos_seen)
    assert not eos_seen[-1, 0]                  # budget death, not eos
    assert eos_seen[-1, 1]                      # eos reported to the host


def test_batcher_speculative_matches_plain():
    """Batched speculative serving: greedy AND sampled requests produce
    bit-identical outputs to the plain batcher (greedy via exact
    acceptance; sampled via the shared per-row stream), and at least one
    draft token was accepted on the repetitive prompt."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(0)
    base = rng.integers(0, 256, 6).tolist()
    rep = (base * 4)[:20]
    arb = rng.integers(0, 256, 9).tolist()

    def run(spec):
        b = ContinuousBatcher(
            cfg, num_blocks=96, block_size=8, slots=3, max_seq=128, seed=0,
            speculative="ngram" if spec else None, spec_gamma=3)
        reqs = [
            b.submit(rep, max_new_tokens=14, sampling=SamplingParams.greedy(),
                     seed=1),
            b.submit(arb, max_new_tokens=10, sampling=SamplingParams.greedy(),
                     seed=2),
            b.submit(rep, max_new_tokens=12,
                     sampling=SamplingParams(temperature=0.8, top_k=40),
                     seed=3),
        ]
        for _ in range(120):
            b.step()
            if all(r.done.is_set() for r in reqs):
                break
        return [r.wait() for r in reqs], b.stats()

    plain, _ = run(False)
    spec, st = run(True)
    assert spec == plain, (spec, plain)
    assert st["spec_accepted_tokens"] >= 1, st


def test_batcher_speculative_eos_and_stream():
    """eos cuts a speculative run mid-chunk; streamed tokens match kept
    tokens in order."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(1)
    base = rng.integers(0, 256, 5).tolist()
    prompt = (base * 4)[:18]

    plain = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                              max_seq=128, seed=0)
    r0 = plain.submit(prompt, max_new_tokens=10,
                      sampling=SamplingParams.greedy())
    for _ in range(40):
        plain.step()
        if r0.done.is_set():
            break
    full = r0.wait()
    eos = full[4]
    want = full[:4] if eos not in full[:4] else None

    b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                          max_seq=128, seed=0, speculative="ngram",
                          spec_gamma=3)
    seen = []
    r = b.submit(prompt, max_new_tokens=10,
                 sampling=SamplingParams.greedy(), eos_token_id=eos,
                 stream_cb=seen.append)
    for _ in range(40):
        b.step()
        if r.done.is_set():
            break
    got = r.wait()
    if want is not None:
        assert got == want, (got, want)
    assert seen == got
    assert eos not in got
