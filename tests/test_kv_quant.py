"""int8 KV cache (cfg.kv_quant): storage halves, outputs stay close.

Per-token-per-head symmetric int8 (ops/kvcache.py quant_kv) bounds the
per-element quantization error at ~0.4% of the head's max |value|, so
logits drift but distributions stay close — the standard serving trade.
Tests pin: (a) relaxed-tolerance logits equivalence vs the bf16/f32 cache
on dense and paged paths, (b) end-to-end generation through engine and
batcher, (c) the memory halving that is the feature's point.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.kvcache import (
    dequant_kv, init_cache, quant_kv)
from distributed_llm_inferencing_tpu.ops.paged_kvcache import init_paged_cache
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
QCFG = CFG.replace(kv_quant="int8")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(0)


def test_quant_roundtrip_error_bound():
    x = jnp.asarray(RNG.normal(size=(4, 7, 2, 16)), jnp.float32)
    q, s = quant_kv(x)
    back = dequant_kv(q, s, jnp.float32)
    # symmetric int8: error <= scale/2 = max|x| per head / 254
    err = np.abs(np.asarray(back - x))
    bound = np.asarray(jnp.max(jnp.abs(x), axis=-1) / 127.0)[..., None]
    assert (err <= bound * 0.5 + 1e-7).all()


def test_cache_memory_halves():
    full = init_cache(CFG, 2, 64, dtype=jnp.float32)
    q = init_cache(QCFG, 2, 64)
    fb = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(full))
    qb = sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(q))
    assert q.k.dtype == jnp.int8 and q.quantized
    # f32 baseline: int8 + one f32 scale per hd-vector -> (1 + 4/hd)/4
    expected = (1 + 4 / CFG.head_dim) / 4
    assert qb < expected * fb * 1.05
    # at serving head dims (>=64) that is ~0.26x f32 / ~0.52x bf16
    assert CFG.head_dim < 64 or qb < 0.27 * fb


def test_dense_prefill_decode_close_to_full_precision():
    B, S = 2, 24
    toks = jnp.asarray(RNG.integers(0, CFG.vocab_size, (B, S)), jnp.int32)
    lens = jnp.asarray([S, S - 5], jnp.int32)

    logits_f, cache_f = transformer.prefill(
        PARAMS, CFG, toks, lens, init_cache(CFG, B, 48, dtype=jnp.float32))
    logits_q, cache_q = transformer.prefill(
        PARAMS, QCFG, toks, lens, init_cache(QCFG, B, 48))
    # prefill attends fresh K/V only -> logits should match tightly
    np.testing.assert_allclose(np.asarray(logits_q), np.asarray(logits_f),
                               atol=1e-4, rtol=1e-4)

    nxt = jnp.argmax(logits_f[:, -1], -1).astype(jnp.int32)[:, None]
    d_f, _ = transformer.decode_step(PARAMS, CFG, nxt, cache_f)
    d_q, _ = transformer.decode_step(PARAMS, QCFG, nxt, cache_q)
    # decode reads the quantized cache -> relaxed tolerance
    f, q = np.asarray(d_f[:, 0]), np.asarray(d_q[:, 0])
    assert np.abs(q - f).max() < 0.15 * np.abs(f).max()
    # distributions nearly identical
    pf = jax.nn.softmax(jnp.asarray(f), axis=-1)
    pq = jax.nn.softmax(jnp.asarray(q), axis=-1)
    assert float(jnp.abs(pf - pq).sum(-1).max()) < 0.1


def test_engine_generates_with_kv_int8():
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine
    prompt = RNG.integers(0, CFG.vocab_size, 11).tolist()
    full = InferenceEngine(CFG, PARAMS, max_seq=64).generate(
        [prompt], max_new_tokens=12, sampling=SamplingParams.greedy())
    q = InferenceEngine(QCFG, PARAMS, max_seq=64).generate(
        [prompt], max_new_tokens=12, sampling=SamplingParams.greedy())
    assert len(q.tokens[0]) == 12
    # greedy trajectories usually agree on a tiny model; require a shared
    # prefix so gross corruption can't pass
    shared = sum(1 for a, b in zip(full.tokens[0], q.tokens[0]) if a == b)
    assert shared >= 6, (full.tokens[0], q.tokens[0])


def test_batcher_paged_kv_int8_end_to_end():
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    b = ContinuousBatcher(QCFG, PARAMS, num_blocks=64, block_size=8,
                          slots=2, max_seq=64)
    assert b.paged.quantized and b.paged.k.dtype == jnp.int8
    sys_prompt = RNG.integers(0, CFG.vocab_size, 16).tolist()
    prompts = [sys_prompt + RNG.integers(0, CFG.vocab_size, 3).tolist(),
               sys_prompt + RNG.integers(0, CFG.vocab_size, 5).tolist()]
    reqs = [b.submit(p, max_new_tokens=10, sampling=SamplingParams.greedy())
            for p in prompts]
    for _ in range(60):
        b.step()
        if all(r.done.is_set() for r in reqs):
            break
    for r in reqs:
        assert r.error is None and len(r.wait()) == 10
    # prefix reuse works over the quantized pool too
    assert b.pool.stats()["prefix_hits"] >= 1
    # quantized-vs-full trajectories stay mostly aligned (greedy, tiny model)
    fb = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                           slots=2, max_seq=64)
    fr = fb.submit(prompts[0], max_new_tokens=10,
                   sampling=SamplingParams.greedy())
    for _ in range(60):
        fb.step()
        if fr.done.is_set():
            break
    shared = sum(1 for a, c in zip(fr.wait(), reqs[0].wait()) if a == c)
    assert shared >= 5, (fr.tokens, reqs[0].tokens)


def test_paged_decode_step_kv_int8_matches_dense():
    """Stepwise paged decode over an int8 pool vs the int8 DENSE cache:
    the same quantization scheme on both sides should land on the same
    greedy tokens for a short trajectory."""
    paged = init_paged_cache(QCFG, 16, 8)
    prompt = RNG.integers(0, CFG.vocab_size, 9).tolist()
    # paged admission via prefill tail (no prefix)
    toks = np.zeros((1, 16), np.int32)
    toks[0, :9] = prompt
    last, paged = jax.jit(
        transformer.paged_prefill_tail, static_argnums=(1,))(
        PARAMS, QCFG, jnp.asarray(toks), jnp.asarray([9], jnp.int32),
        jnp.asarray([1, 2], jnp.int32), jnp.zeros((1, 1), jnp.int32),
        jnp.asarray([0], jnp.int32), paged)
    bt = np.zeros((1, 4), np.int32)
    bt[0, :2] = [1, 2]
    cur = int(jnp.argmax(last[0]))
    out_paged = [cur]
    cl = 9
    for _ in range(5):
        logits, paged = jax.jit(
            transformer.paged_decode_step, static_argnums=(1,))(
            PARAMS, QCFG, jnp.asarray([cur], jnp.int32), paged,
            jnp.asarray(bt), jnp.asarray([cl], jnp.int32))
        cur = int(jnp.argmax(logits[0]))
        out_paged.append(cur)
        cl += 1

    cache = init_cache(QCFG, 1, 32)
    logits, cache = transformer.prefill(
        PARAMS, QCFG, jnp.asarray([prompt], jnp.int32),
        jnp.asarray([9], jnp.int32), cache)
    cur = int(jnp.argmax(logits[0, 8]))
    out_dense = [cur]
    for _ in range(5):
        logits, cache = transformer.decode_step(
            PARAMS, QCFG, jnp.asarray([[cur]], jnp.int32), cache)
        cur = int(jnp.argmax(logits[0, 0]))
        out_dense.append(cur)
    assert out_paged == out_dense


def test_kv_int8_with_sequence_parallel_ring():
    """kv_quant composes with sp (ring prefill + flash-decoding combine):
    the ring decode path receives the dequantized cache view."""
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine
    prompt = RNG.integers(0, CFG.vocab_size, 12).tolist()
    eng = InferenceEngine(QCFG, PARAMS, mesh_spec=MeshSpec(sp=2), max_seq=64)
    out = eng.generate([prompt], max_new_tokens=8,
                       sampling=SamplingParams.greedy())
    assert len(out.tokens[0]) == 8
    # trajectories track the unsharded kv-int8 engine closely
    ref = InferenceEngine(QCFG, PARAMS, max_seq=64).generate(
        [prompt], max_new_tokens=8, sampling=SamplingParams.greedy())
    shared = sum(1 for a, b in zip(out.tokens[0], ref.tokens[0]) if a == b)
    assert shared >= 5, (out.tokens[0], ref.tokens[0])
