"""Pallas kernel numerics vs the XLA reference attention (interpret mode).

The reference framework never checked kernel numerics at all (its attention
was vendored torch inside ``generate()``, SURVEY.md §2.5); here every
masking regime of both kernels is pinned against ops/attention.py.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_llm_inferencing_tpu.ops.attention import (
    attend, attend_decode, attend_prefill)
from distributed_llm_inferencing_tpu.ops.pallas import (
    flash_attention, flash_decode)


def _rand(rng, *shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


@pytest.mark.parametrize("B,S,H,Hkv,hd", [
    (2, 64, 8, 4, 64),     # GQA
    (1, 32, 4, 4, 32),     # MHA, small head_dim
    (2, 128, 8, 1, 64),    # MQA
])
def test_flash_prefill_matches_reference(B, S, H, Hkv, hd):
    rng = np.random.default_rng(0)
    q, k, v = _rand(rng, B, S, H, hd), _rand(rng, B, S, Hkv, hd), _rand(rng, B, S, Hkv, hd)
    ref = attend_prefill(q, k, v, backend="xla")
    out = flash_attention(q, k, v, block_q=32, block_kv=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_prefill_sliding_window():
    rng = np.random.default_rng(1)
    B, S, H, Hkv, hd = 2, 64, 4, 2, 32
    q, k, v = _rand(rng, B, S, H, hd), _rand(rng, B, S, Hkv, hd), _rand(rng, B, S, Hkv, hd)
    ref = attend_prefill(q, k, v, sliding_window=16, backend="xla")
    out = flash_attention(q, k, v, sliding_window=16,
                          block_q=16, block_kv=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_prefill_uneven_blocks():
    # block sizes that don't divide the default targets
    rng = np.random.default_rng(2)
    B, S, H, Hkv, hd = 1, 48, 2, 2, 16
    q, k, v = _rand(rng, B, S, H, hd), _rand(rng, B, S, Hkv, hd), _rand(rng, B, S, Hkv, hd)
    ref = attend_prefill(q, k, v, backend="xla")
    # S=48: _pick_block falls back to a divisor (16)
    out = flash_attention(q, k, v, block_q=32, block_kv=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("lengths", [[37, 90], [1, 128], [128, 64]])
def test_flash_decode_matches_reference(lengths):
    rng = np.random.default_rng(3)
    B, S, H, Hkv, hd = 2, 128, 8, 4, 64
    q = _rand(rng, B, 1, H, hd)
    k, v = _rand(rng, B, S, Hkv, hd), _rand(rng, B, S, Hkv, hd)
    lens = jnp.asarray(lengths, jnp.int32)
    ref = attend_decode(q, k, v, lens, backend="xla")
    out = flash_decode(q, k, v, lens, block_kv=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_decode_sliding_window():
    rng = np.random.default_rng(4)
    B, S, H, Hkv, hd = 2, 64, 4, 2, 32
    q = _rand(rng, B, 1, H, hd)
    k, v = _rand(rng, B, S, Hkv, hd), _rand(rng, B, S, Hkv, hd)
    lens = jnp.asarray([50, 20], jnp.int32)
    ref = attend_decode(q, k, v, lens, sliding_window=8, backend="xla")
    out = flash_decode(q, k, v, lens, sliding_window=8,
                       block_kv=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_flash_prefill_bf16():
    rng = np.random.default_rng(5)
    B, S, H, Hkv, hd = 1, 64, 4, 2, 64
    q = _rand(rng, B, S, H, hd).astype(jnp.bfloat16)
    k = _rand(rng, B, S, Hkv, hd).astype(jnp.bfloat16)
    v = _rand(rng, B, S, Hkv, hd).astype(jnp.bfloat16)
    ref = attend_prefill(q, k, v, backend="xla")
    out = flash_attention(q, k, v, block_q=32, block_kv=32, interpret=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        rtol=3e-2, atol=3e-2)


def test_engine_end_to_end_with_pallas_interpret():
    """Greedy generation must be token-identical across backends."""
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

    sp = SamplingParams(temperature=0.0)  # greedy
    prompt = list(range(1, 12))
    outs = {}
    for backend in ("xla", "pallas_interpret"):
        cfg = get_config("tiny-llama").replace(
            dtype="float32", attn_backend=backend)
        eng = InferenceEngine(cfg, max_seq=64, seed=0)
        outs[backend] = eng.generate([prompt], max_new_tokens=8,
                                     sampling=sp).tokens[0]
    assert outs["xla"] == outs["pallas_interpret"]


def test_flash_prefill_alibi_matches_reference():
    """ALiBi rides the prefill kernel as an in-tile bias (SMEM slope per
    head) — must match the xla formulation's slope*(kv-q) arithmetic."""
    from distributed_llm_inferencing_tpu.ops.attention import alibi_slopes
    rng = np.random.default_rng(4)
    B, S, H, Hkv, hd = 2, 64, 4, 4, 32
    q, k, v = (_rand(rng, B, S, H, hd), _rand(rng, B, S, Hkv, hd),
               _rand(rng, B, S, Hkv, hd))
    sl = alibi_slopes(H)
    ref = attend_prefill(q, k, v, backend="xla", alibi=sl)
    out = flash_attention(q, k, v, alibi=sl, block_q=16, block_kv=16,
                          interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("H,Hkv", [(4, 4), (8, 2)])   # MHA + grouped
def test_flash_decode_alibi_matches_reference(H, Hkv):
    from distributed_llm_inferencing_tpu.ops.attention import alibi_slopes
    rng = np.random.default_rng(5)
    B, S, hd = 2, 128, 32
    q = _rand(rng, B, 1, H, hd)
    k, v = _rand(rng, B, S, Hkv, hd), _rand(rng, B, S, Hkv, hd)
    lens = jnp.asarray([37, 101], jnp.int32)
    sl = alibi_slopes(H)
    ref = attend_decode(q, k, v, lens, backend="xla", alibi=sl)
    out = flash_decode(q, k, v, lens, alibi=sl, block_kv=32,
                       interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_alibi_engine_pallas_interpret_matches_xla():
    """Whole-model: a BLOOM-style (ALiBi) tiny engine on the pallas
    interpret backend decodes identically to the xla backend — the
    fast path the ALiBi families previously silently forfeited."""
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

    base = get_config("tiny-llama").replace(
        dtype="float32", position_embedding="alibi", name="tiny-alibi")
    prompt = [3, 17, 52, 9, 1, 30]

    def run(backend):
        eng = InferenceEngine(base.replace(attn_backend=backend),
                              max_seq=64, seed=0)
        return eng.generate([prompt], max_new_tokens=10,
                            sampling=SamplingParams.greedy()).tokens[0]

    assert run("pallas_interpret") == run("xla")
