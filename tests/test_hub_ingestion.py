"""Opt-in HF-hub model ingestion (models/convert.py load_hf_model).

Offline-by-default is the framework's posture; DLI_ALLOW_DOWNLOAD=1
restores the reference's download-any-model-by-name capability
(reference worker/app.py:117-121, cache at worker/app.py:19-20).
All tests run offline against a mocked ``from_pretrained``.
"""

import numpy as np
import pytest
import transformers

from distributed_llm_inferencing_tpu.models import convert


class _Captured(Exception):
    def __init__(self, kwargs):
        self.kwargs = kwargs


@pytest.fixture()
def capture_from_pretrained(monkeypatch):
    calls = {}

    def fake(name, **kw):
        calls["name"] = name
        calls.update(kw)
        raise _Captured(kw)

    monkeypatch.setattr(transformers.AutoModelForCausalLM, "from_pretrained",
                        staticmethod(fake))
    return calls


def test_offline_by_default(monkeypatch, capture_from_pretrained):
    monkeypatch.delenv("DLI_ALLOW_DOWNLOAD", raising=False)
    assert not convert.allow_download()
    with pytest.raises(_Captured) as e:
        convert.load_hf_model("gpt2")
    assert e.value.kwargs["local_files_only"] is True


def test_env_gate_enables_hub_download(monkeypatch, capture_from_pretrained):
    monkeypatch.setenv("DLI_ALLOW_DOWNLOAD", "1")
    monkeypatch.setenv("DLI_MODEL_CACHE", "/tmp/dli-test-cache")
    with pytest.raises(_Captured) as e:
        convert.load_hf_model("gpt2")
    assert e.value.kwargs["local_files_only"] is False
    assert e.value.kwargs["cache_dir"] == "/tmp/dli-test-cache"


def test_local_dir_stays_local_even_when_enabled(
        monkeypatch, tmp_path, capture_from_pretrained):
    monkeypatch.setenv("DLI_ALLOW_DOWNLOAD", "1")
    with pytest.raises(_Captured) as e:
        convert.load_hf_model(str(tmp_path))
    assert e.value.kwargs["local_files_only"] is True
    assert "cache_dir" not in e.value.kwargs


def test_in_memory_model_unaffected(monkeypatch):
    """The in-memory path never touches from_pretrained (used by tests and
    the numerics oracle)."""
    monkeypatch.delenv("DLI_ALLOW_DOWNLOAD", raising=False)
    import torch
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=97, n_positions=32, n_embd=16, n_layer=2, n_head=2)).eval()
    cfg, params = convert.load_hf_model(hf)
    assert cfg.vocab_size == 97
    assert params["embed"]["tokens"].shape == (97, 16)
