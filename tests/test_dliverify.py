"""dliverify suite: scheduler determinism, schedule-count
reproducibility under a fixed bound, the invariant catalog, and the
mutation gate — BOTH re-armed historical bugs must produce a
counterexample trace, proving the explorer can actually catch
regressions (not just bless correct code).

The explorations here run the REAL master/worker/store code per
schedule; scenarios are bounded small (hundreds of schedules at most)
so the whole suite stays seconds-scale.
"""

import logging
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.dliverify import SCENARIOS
from tools.dliverify.scenarios import MUTATION_SCENARIOS
from tools.dliverify.sched import (Explorer, Scheduler,
                                   run_scenario_once)

logging.getLogger("dli_tpu").setLevel(logging.ERROR)

BUDGET_S = 120.0     # generous: a loaded CI box must not flake


def _explore(name, prune=False, max_schedules=100000):
    scenario = SCENARIOS[name]
    exp = Explorer(lambda prefix: run_scenario_once(scenario, prefix),
                   budget_s=BUDGET_S, max_schedules=max_schedules,
                   prune=prune)
    return exp.explore(name)


# ---- the catalog: every scenario explores exhaustively and clean -----

@pytest.mark.parametrize("name", sorted(SCENARIOS))
def test_scenario_exhaustive_and_clean(name):
    res = _explore(name)
    assert res.hung is None, res.hung
    assert res.violation is None, res.violation.render()
    assert res.complete, (
        f"{name} did not finish within {BUDGET_S}s / "
        f"{res.schedules} schedules — the scenario is no longer "
        "bounded small")
    assert res.schedules >= 1


def test_catalog_covers_declared_invariants():
    declared = set()
    for s in SCENARIOS.values():
        assert s.invariants, f"{s.name} declares no invariants"
        declared |= set(s.invariants)
    assert {"single_claim", "single_terminal", "half_open_single_probe",
            "inflight_nonnegative", "tag_exactly_once",
            "no_strand_on_drain", "exclusion_honored"} <= declared


# ---- determinism ------------------------------------------------------

def test_schedule_count_reproducible():
    """Same scenario, same bound -> byte-identical exploration stats.
    Environment threads (store flushers) must not leak decision
    points."""
    for name in ("claim_once", "terminal_once", "requeue_exclusion"):
        a = _explore(name)
        b = _explore(name)
        assert (a.schedules, a.decision_points) == \
            (b.schedules, b.decision_points), name


def test_single_schedule_replay_is_deterministic():
    """Replaying one choice prefix twice takes the identical
    decision sequence and trace."""
    scenario = SCENARIOS["terminal_once"]
    o1 = run_scenario_once(scenario, (1,))
    o2 = run_scenario_once(scenario, (1,))
    assert o1.decisions == o2.decisions
    assert o1.trace == o2.trace
    assert not o1.hung and o1.violation is None


def test_interleavings_actually_differ():
    """The explorer must drive real divergence: across the schedules of
    terminal_once, both terminal orders (completed-first and
    failed-first) must occur — otherwise we are re-running one
    interleaving N times."""
    scenario = SCENARIOS["terminal_once"]
    finals = set()
    # (): completer runs first; (1, 1): the failer both starts AND
    # passes its store acquisition first (yields sit BEFORE acquires)
    for prefix in ((), (1, 1)):
        ctx_final = []

        class Spy:
            def build(self, sched):
                c = scenario.build(sched)
                ctx_final.append(c)
                return c

            def check_step(self, ctx):
                return scenario.check_step(ctx)

            def check_final(self, ctx):
                bad = scenario.check_final(ctx)
                finals.add(ctx.store.get_request(ctx.rid)["status"])
                return bad

            def cleanup(self, ctx):
                scenario.cleanup(ctx)

        out = run_scenario_once(Spy(), prefix)
        assert out.violation is None and not out.hung
    assert finals == {"completed", "failed"}


# ---- scheduler unit behavior -----------------------------------------

def test_scheduler_serializes_and_traces():
    from distributed_llm_inferencing_tpu.utils import locks as locks_mod
    sched = Scheduler(choices=())
    prev = locks_mod.set_factory_hook(sched.lock_factory)
    try:
        lk = locks_mod.lock("t.shared")
        log = []

        def worker(tag):
            with lk:
                log.append(tag)

        sched.spawn("w1", worker, "a")
        sched.spawn("w2", worker, "b")
        err = sched.run()
    finally:
        locks_mod.set_factory_hook(prev)
    assert err is None and not sched.hung
    assert sorted(log) == ["a", "b"]
    assert any("acquire t.shared" in t for t in sched.trace)


def test_scheduler_reports_deadlock():
    from distributed_llm_inferencing_tpu.utils import locks as locks_mod
    sched = Scheduler(choices=())
    prev = locks_mod.set_factory_hook(sched.lock_factory)
    try:
        a = locks_mod.lock("t.a")
        b = locks_mod.lock("t.b")

        def one_way():
            with a:
                with b:
                    pass

        def other_way():
            with b:
                with a:
                    pass

        sched.spawn("w1", one_way)
        sched.spawn("w2", other_way)
        # drive the inversion (yields sit BEFORE acquires): w1 starts
        # and passes acquire-a, then w2 starts and passes acquire-b —
        # now each wants the other's lock
        sched._choices = (0, 0, 1, 1)
        err = sched.run()
    finally:
        locks_mod.set_factory_hook(prev)
    assert sched.hung and err is not None and "deadlock" in err


def test_unregistered_threads_pass_through():
    """A lock created under the hook but used from an unregistered
    thread must behave like a plain lock (environment threads are not
    modeled)."""
    import threading

    from distributed_llm_inferencing_tpu.utils import locks as locks_mod
    sched = Scheduler(choices=())
    prev = locks_mod.set_factory_hook(sched.lock_factory)
    try:
        lk = locks_mod.lock("t.env")
    finally:
        locks_mod.set_factory_hook(prev)
    hits = []

    def env():
        with lk:
            hits.append(1)

    t = threading.Thread(target=env)
    t.start()
    t.join(5)
    assert hits == [1]


# ---- the mutation gate ------------------------------------------------

@pytest.mark.parametrize("mutation", sorted(MUTATION_SCENARIOS))
def test_mutation_produces_counterexample(mutation, monkeypatch):
    """Re-arm a historical bug behind its test-only flag: the explorer
    MUST find a counterexample, and the trace must be a readable
    thread-step list."""
    monkeypatch.setenv("DLI_VERIFY_MUTATIONS", mutation)
    res = _explore(MUTATION_SCENARIOS[mutation])
    assert res.violation is not None, (
        f"mutation {mutation} re-armed but the explorer found no "
        f"counterexample in {res.schedules} schedules")
    rendered = res.violation.render()
    assert "INVARIANT VIOLATED" in rendered
    assert "counterexample trace" in rendered
    assert len(res.violation.trace) >= 2


def test_mutations_off_means_clean(monkeypatch):
    """The same two scenarios are clean with the flags off — the gate
    measures the mutation, not scenario noise."""
    monkeypatch.delenv("DLI_VERIFY_MUTATIONS", raising=False)
    for name in set(MUTATION_SCENARIOS.values()):
        res = _explore(name)
        assert res.violation is None, res.violation.render()
        assert res.complete


def test_mutation_flag_is_off_by_default():
    from distributed_llm_inferencing_tpu.utils.faults import (
        MUTATIONS, mutation_enabled)
    assert os.environ.get("DLI_VERIFY_MUTATIONS") is None
    for m in MUTATIONS:
        assert not mutation_enabled(m)


# ---- CLI --------------------------------------------------------------

def test_cli_list_and_clean_exit():
    from tools.dliverify.__main__ import main
    assert main(["--list"]) == 0
    # one cheap scenario end-to-end through the CLI
    assert main(["--scenario", "claim_once"]) == 0


def test_cli_mutation_gate_exit_codes():
    from tools.dliverify.__main__ import main
    assert main(["--mutate", "half_open_probe"]) == 0   # found = pass
    assert main(["--mutate", "no-such-mutation"]) == 2
