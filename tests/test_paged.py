"""Paged KV cache correctness: paged serving path ≡ dense path.

The golden property (the one the reference never checked for its shards,
SURVEY.md §4): a sequence decoded through paged blocks — including via a
shared cached prefix — produces the same tokens/logits as the dense-cache
path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
from distributed_llm_inferencing_tpu.ops.paged_kvcache import init_paged_cache

BS = 8  # block size for tests


def _cfg(name):
    return get_config(name).replace(dtype="float32", attn_backend="xla")


def _dense_greedy(cfg, params, prompt, n_new):
    """Reference trajectory via the dense cache."""
    s0 = 32
    cache = init_cache(cfg, 1, 128, dtype=jnp.float32)
    tokens = np.zeros((1, s0), np.int32)
    tokens[0, :len(prompt)] = prompt
    lengths = jnp.asarray([len(prompt)], jnp.int32)
    logits, cache = transformer.prefill(params, cfg, jnp.asarray(tokens),
                                        lengths, cache)
    last = logits[0, len(prompt) - 1]
    out, traj = [], [last]
    cur = jnp.argmax(last)[None]
    out.append(int(cur[0]))
    for _ in range(n_new - 1):
        logits, cache = transformer.decode_step(params, cfg, cur[:, None], cache)
        traj.append(logits[0, 0])
        cur = jnp.argmax(logits[0, 0])[None]
        out.append(int(cur[0]))
    return out, traj


def _paged_greedy(cfg, params, prompt, n_new, *, num_blocks=32, slots=4,
                  slot=1):
    """Same trajectory via paged blocks, request parked in slot `slot`."""
    paged = init_paged_cache(cfg, num_blocks, BS, dtype=jnp.float32)
    # block 0 is the dummy; the request owns blocks 1..n
    t = -(-len(prompt) // BS) * BS  # pad tail to block multiple
    n_blocks = t // BS
    my_blocks = list(range(1, 1 + n_blocks))
    max_blocks = 8
    tokens = np.zeros((1, t), np.int32)
    tokens[0, :len(prompt)] = prompt

    last, paged = transformer.paged_prefill_tail(
        params, cfg, jnp.asarray(tokens), jnp.asarray([len(prompt)], jnp.int32),
        jnp.asarray(my_blocks, jnp.int32),
        jnp.zeros((1, 1), jnp.int32), jnp.zeros((1,), jnp.int32), paged)

    block_tables = np.zeros((slots, max_blocks), np.int32)
    block_tables[slot, :n_blocks] = my_blocks
    # growth room: one extra block for decode past the prompt blocks
    extra = 1 + n_blocks + slot  # arbitrary distinct id
    block_tables[slot, n_blocks] = extra
    context_lens = np.zeros((slots,), np.int32)
    context_lens[slot] = len(prompt)

    out, traj = [], [last[0]]
    cur_tok = int(jnp.argmax(last[0]))
    out.append(cur_tok)
    toks = np.zeros((slots,), np.int32)
    for _ in range(n_new - 1):
        toks[slot] = cur_tok
        logits, paged = transformer.paged_decode_step(
            params, cfg, jnp.asarray(toks), paged,
            jnp.asarray(block_tables), jnp.asarray(context_lens))
        traj.append(logits[slot])
        cur_tok = int(jnp.argmax(logits[slot]))
        out.append(cur_tok)
        context_lens[slot] += 1
    return out, traj


@pytest.mark.parametrize("model", ["tiny-gpt2", "tiny-llama", "tiny-mixtral"])
def test_paged_equals_dense(model):
    cfg = _cfg(model)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, cfg.vocab_size, 13).tolist()   # straddles blocks
    n_new = 10
    dense_toks, dense_traj = _dense_greedy(cfg, params, prompt, n_new)
    paged_toks, paged_traj = _paged_greedy(cfg, params, prompt, n_new)
    assert dense_toks == paged_toks
    for i, (d, p) in enumerate(zip(dense_traj, paged_traj)):
        np.testing.assert_allclose(d, p, rtol=2e-4, atol=2e-4,
                                   err_msg=f"step {i}")


def test_sliding_window_paged():
    cfg = _cfg("tiny-llama").replace(sliding_window=8)
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, cfg.vocab_size, 11).tolist()
    dense_toks, _ = _dense_greedy(cfg, params, prompt, 12)
    paged_toks, _ = _paged_greedy(cfg, params, prompt, 12)
    assert dense_toks == paged_toks


@pytest.mark.parametrize("window", [None, 8])
def test_pallas_paged_decode_matches_xla(window):
    """Block-table-driven Pallas kernel ≡ gather-based XLA formulation."""
    from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
        paged_attend_decode)
    rng = np.random.default_rng(3)
    R, MB, NB, H, HKV, HD = 4, 4, 24, 8, 4, 16
    q = jnp.asarray(rng.standard_normal((R, 1, H, HD)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((NB, BS, HKV, HD)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((NB, BS, HKV, HD)), jnp.float32)
    # distinct blocks per slot; slot 0 inactive (dummy block 0, len counts 1
    # token just written)
    bt = np.zeros((R, MB), np.int32)
    ids = rng.permutation(np.arange(1, NB))[: R * MB].reshape(R, MB)
    bt[1:] = ids[1:]
    lens = np.asarray([1, 5, BS * 2, BS * 3 + 3], np.int32)
    xla_out = paged_attend_decode(q, kp, vp, jnp.asarray(bt),
                                  jnp.asarray(lens), sliding_window=window)
    pl_out = paged_attend_decode(q, kp, vp, jnp.asarray(bt),
                                 jnp.asarray(lens), sliding_window=window,
                                 backend="pallas_interpret")
    np.testing.assert_allclose(np.asarray(xla_out)[1:], np.asarray(pl_out)[1:],
                               rtol=2e-5, atol=2e-5)


def test_prefix_reuse_matches_full_prefill():
    """Tail prefill over a cached prefix ≡ full prefill of the whole prompt."""
    cfg = _cfg("tiny-llama")
    params = init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    rng = np.random.default_rng(2)
    shared = rng.integers(0, cfg.vocab_size, 2 * BS).tolist()  # 2 full blocks
    tail_a = rng.integers(0, cfg.vocab_size, 5).tolist()
    tail_b = rng.integers(0, cfg.vocab_size, 7).tolist()

    paged = init_paged_cache(cfg, 32, BS, dtype=jnp.float32)

    # Request A: no prefix cached yet — prefill the whole prompt
    prompt_a = shared + tail_a
    t_a = -(-len(prompt_a) // BS) * BS
    blocks_a = list(range(1, 1 + t_a // BS))
    toks_a = np.zeros((1, t_a), np.int32)
    toks_a[0, :len(prompt_a)] = prompt_a
    last_a, paged = transformer.paged_prefill_tail(
        params, cfg, jnp.asarray(toks_a),
        jnp.asarray([len(prompt_a)], jnp.int32),
        jnp.asarray(blocks_a, jnp.int32),
        jnp.zeros((1, 1), jnp.int32), jnp.zeros((1,), jnp.int32), paged)

    # Request B: first 2 blocks (len(shared) tokens) come from the radix
    # cache (blocks_a[:2]); only B's tail is computed.
    prompt_b = shared + tail_b
    tail_len = len(prompt_b) - len(shared)
    t_b = -(-tail_len // BS) * BS
    blocks_b = list(range(10, 10 + t_b // BS))
    toks_b = np.zeros((1, t_b), np.int32)
    toks_b[0, :tail_len] = prompt_b[len(shared):]
    last_b, paged = transformer.paged_prefill_tail(
        params, cfg, jnp.asarray(toks_b),
        jnp.asarray([tail_len], jnp.int32),
        jnp.asarray(blocks_b, jnp.int32),
        jnp.asarray([blocks_a[:2]], jnp.int32),
        jnp.asarray([len(shared)], jnp.int32), paged)

    # Oracle: full prefill of B's whole prompt, fresh blocks
    paged2 = init_paged_cache(cfg, 32, BS, dtype=jnp.float32)
    t_full = -(-len(prompt_b) // BS) * BS
    toks_full = np.zeros((1, t_full), np.int32)
    toks_full[0, :len(prompt_b)] = prompt_b
    last_full, _ = transformer.paged_prefill_tail(
        params, cfg, jnp.asarray(toks_full),
        jnp.asarray([len(prompt_b)], jnp.int32),
        jnp.asarray(list(range(1, 1 + t_full // BS)), jnp.int32),
        jnp.zeros((1, 1), jnp.int32), jnp.zeros((1,), jnp.int32), paged2)

    np.testing.assert_allclose(np.asarray(last_b[0]), np.asarray(last_full[0]),
                               rtol=2e-4, atol=2e-4)
