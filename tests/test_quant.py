"""Weight-only int8 quantization (ops/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import (
    init_params, param_bytes)
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
from distributed_llm_inferencing_tpu.ops.quant import (
    dequantize_weight, maybe_quantize, quantize_weight)
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine


def test_quantize_roundtrip_error():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    q = quantize_weight(w)
    assert q["q"].dtype == jnp.int8 and q["scale"].shape == (32,)
    err = np.abs(np.asarray(dequantize_weight(q)) - np.asarray(w))
    # per-channel symmetric int8: max error is scale/2 per channel
    assert np.all(err <= np.asarray(q["scale"]) / 2 + 1e-7)


@pytest.mark.parametrize("model", ["tiny-gpt2", "tiny-llama", "tiny-mixtral"])
def test_quantized_logits_close(model):
    cfg = get_config(model).replace(dtype="float32", attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qcfg = cfg.replace(quant="int8")
    qparams = maybe_quantize(params, qcfg)
    # big matmul weights are int8 now
    assert qparams["layers"]["q"]["q"].dtype == jnp.int8
    assert param_bytes(qparams) < 0.75 * param_bytes(params)

    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 12)),
        jnp.int32)
    lens = jnp.full((2,), 12, jnp.int32)

    def fwd(cfg_, p):
        cache = init_cache(cfg_, 2, 16, dtype=jnp.float32)
        logits, _ = transformer.prefill(p, cfg_, toks, lens, cache)
        return np.asarray(logits)

    full = fwd(cfg, params)
    quant = fwd(qcfg, qparams)
    # weight-only int8 should track full precision closely on random nets
    rel = np.abs(quant - full) / (np.abs(full).mean() + 1e-6)
    assert rel.mean() < 0.05, rel.mean()


def test_engine_generate_int8_and_sharded():
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla", quant="int8")
    params = init_params(get_config("tiny-llama").replace(dtype="float32"),
                         jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = np.random.default_rng(1).integers(0, 256, 9).tolist()

    eng = InferenceEngine(cfg, params, max_seq=64)
    r1 = eng.generate([prompt], max_new_tokens=8,
                      sampling=SamplingParams.greedy())
    assert len(r1.tokens[0]) == 8

    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    eng2 = InferenceEngine(cfg, params, mesh_spec=MeshSpec(tp=2), max_seq=64)
    r2 = eng2.generate([prompt], max_new_tokens=8,
                       sampling=SamplingParams.greedy())
    # same quantized weights; tp=2 reduction order may flip argmax ties on
    # random nets, so compare trajectories only up to first divergence
    assert r2.tokens[0][0] == r1.tokens[0][0]


def test_batcher_int8():
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla", quant="int8")
    b = ContinuousBatcher(cfg, num_blocks=32, block_size=8, slots=2,
                          max_seq=64)
    r = b.submit([1, 2, 3, 4], max_new_tokens=6,
                 sampling=SamplingParams.greedy())
    for _ in range(20):
        b.step()
        if r.done.is_set():
            break
    assert r.wait() and len(r.tokens) == 6


def test_plan_accounts_int8_bytes():
    from distributed_llm_inferencing_tpu.parallel.plan import make_plan
    full = make_plan("llama-3-8b", {"tp": 1})
    q = make_plan(get_config("llama-3-8b").replace(quant="int8"), {"tp": 1})
    # weights dominate an 8B model: int8 plan must be close to half
    assert q["param_bytes_total"] < 0.62 * full["param_bytes_total"]


def test_quantized_checkpoint_roundtrip(tmp_path):
    from distributed_llm_inferencing_tpu.models import checkpoint
    cfg = get_config("tiny-llama").replace(dtype="float32", quant="int8")
    params = init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    checkpoint.save_checkpoint(str(tmp_path / "q"), cfg, params)
    cfg2, params2 = checkpoint.load_checkpoint(str(tmp_path / "q"))
    assert cfg2.quant == "int8"
    assert params2["layers"]["up"]["q"].dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(params["layers"]["up"]["q"]),
                                  np.asarray(params2["layers"]["up"]["q"]))


def test_random_init_emits_int8_directly():
    """cfg.quant='int8' random init produces quantized leaves WITHOUT ever
    materializing the float tree (the 8B flagship would not fit one chip's
    HBM through an init-bf16-then-quantize path)."""
    cfg = get_config("tiny-llama").replace(dtype="float32", quant="int8")
    p = init_params(cfg, jax.random.PRNGKey(0))
    for leaf in ("q", "k", "v", "o", "up", "gate", "down"):
        assert "w" not in p["layers"][leaf]
        assert p["layers"][leaf]["q"].dtype == jnp.int8
        assert p["layers"][leaf]["scale"].dtype == jnp.float32
    # norms/embeddings stay float (ops/quant.py policy)
    assert p["embed"]["tokens"].dtype == jnp.float32
    # the engine runs it end to end
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    eng = InferenceEngine(cfg, p, max_seq=64)
    out = eng.generate([[3, 5, 7, 11]], max_new_tokens=6,
                       sampling=SamplingParams.greedy())
    assert len(out.tokens[0]) == 6


def test_random_init_int8_moe_experts():
    cfg = get_config("tiny-mixtral").replace(dtype="float32", quant="int8")
    p = init_params(cfg, jax.random.PRNGKey(1))
    for k in ("gate", "up", "down"):
        assert p["layers"]["experts"][k]["q"].dtype == jnp.int8
    assert "w" in p["layers"]["router"]   # router kept float: routing-critical
