"""Weight-only int8 quantization (ops/quant.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import (
    init_params, param_bytes)
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
from distributed_llm_inferencing_tpu.ops.quant import (
    dequantize_weight, maybe_quantize, quantize_weight)
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine


def test_quantize_roundtrip_error():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    q = quantize_weight(w)
    assert q["q"].dtype == jnp.int8 and q["scale"].shape == (32,)
    err = np.abs(np.asarray(dequantize_weight(q)) - np.asarray(w))
    # per-channel symmetric int8: max error is scale/2 per channel
    assert np.all(err <= np.asarray(q["scale"]) / 2 + 1e-7)


@pytest.mark.parametrize("model", ["tiny-gpt2", "tiny-llama",
                                   "tiny-mixtral", "tiny-deepseek"])
def test_quantized_logits_close(model):
    cfg = get_config(model).replace(dtype="float32", attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qcfg = cfg.replace(quant="int8")
    qparams = maybe_quantize(params, qcfg)
    # big matmul weights are int8 now (deepseek MLA: the q bottleneck)
    ql = qparams["layers"]["q_a" if cfg.mla and cfg.q_lora_rank else "q"]
    assert ql["q"].dtype == jnp.int8
    assert param_bytes(qparams) < 0.75 * param_bytes(params)

    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 12)),
        jnp.int32)
    lens = jnp.full((2,), 12, jnp.int32)

    def fwd(cfg_, p):
        cache = init_cache(cfg_, 2, 16, dtype=jnp.float32)
        logits, _ = transformer.prefill(p, cfg_, toks, lens, cache)
        return np.asarray(logits)

    full = fwd(cfg, params)
    quant = fwd(qcfg, qparams)
    # weight-only int8 should track full precision closely on random nets
    rel = np.abs(quant - full) / (np.abs(full).mean() + 1e-6)
    assert rel.mean() < 0.05, rel.mean()


def test_engine_generate_int8_and_sharded():
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla", quant="int8")
    params = init_params(get_config("tiny-llama").replace(dtype="float32"),
                         jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = np.random.default_rng(1).integers(0, 256, 9).tolist()

    eng = InferenceEngine(cfg, params, max_seq=64)
    r1 = eng.generate([prompt], max_new_tokens=8,
                      sampling=SamplingParams.greedy())
    assert len(r1.tokens[0]) == 8

    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    eng2 = InferenceEngine(cfg, params, mesh_spec=MeshSpec(tp=2), max_seq=64)
    r2 = eng2.generate([prompt], max_new_tokens=8,
                       sampling=SamplingParams.greedy())
    # same quantized weights; tp=2 reduction order may flip argmax ties on
    # random nets, so compare trajectories only up to first divergence
    assert r2.tokens[0][0] == r1.tokens[0][0]


def test_batcher_int8():
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla", quant="int8")
    b = ContinuousBatcher(cfg, num_blocks=32, block_size=8, slots=2,
                          max_seq=64)
    r = b.submit([1, 2, 3, 4], max_new_tokens=6,
                 sampling=SamplingParams.greedy())
    for _ in range(20):
        b.step()
        if r.done.is_set():
            break
    assert r.wait() and len(r.tokens) == 6


def test_plan_accounts_int8_bytes():
    from distributed_llm_inferencing_tpu.parallel.plan import make_plan
    full = make_plan("llama-3-8b", {"tp": 1})
    q = make_plan(get_config("llama-3-8b").replace(quant="int8"), {"tp": 1})
    # weights dominate an 8B model: int8 plan must be close to half
    assert q["param_bytes_total"] < 0.62 * full["param_bytes_total"]


def test_quantized_checkpoint_roundtrip(tmp_path):
    from distributed_llm_inferencing_tpu.models import checkpoint
    cfg = get_config("tiny-llama").replace(dtype="float32", quant="int8")
    params = init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    checkpoint.save_checkpoint(str(tmp_path / "q"), cfg, params)
    cfg2, params2 = checkpoint.load_checkpoint(str(tmp_path / "q"))
    assert cfg2.quant == "int8"
    assert params2["layers"]["up"]["q"].dtype == jnp.int8
    np.testing.assert_array_equal(np.asarray(params["layers"]["up"]["q"]),
                                  np.asarray(params2["layers"]["up"]["q"]))


def test_random_init_emits_int8_directly():
    """cfg.quant='int8' random init produces quantized leaves WITHOUT ever
    materializing the float tree (the 8B flagship would not fit one chip's
    HBM through an init-bf16-then-quantize path)."""
    cfg = get_config("tiny-llama").replace(dtype="float32", quant="int8")
    p = init_params(cfg, jax.random.PRNGKey(0))
    for leaf in ("q", "k", "v", "o", "up", "gate", "down"):
        assert "w" not in p["layers"][leaf]
        assert p["layers"][leaf]["q"].dtype == jnp.int8
        assert p["layers"][leaf]["scale"].dtype == jnp.float32
    # norms/embeddings stay float (ops/quant.py policy)
    assert p["embed"]["tokens"].dtype == jnp.float32
    # the engine runs it end to end
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    eng = InferenceEngine(cfg, p, max_seq=64)
    out = eng.generate([[3, 5, 7, 11]], max_new_tokens=6,
                       sampling=SamplingParams.greedy())
    assert len(out.tokens[0]) == 6


def test_random_init_int8_moe_experts():
    cfg = get_config("tiny-mixtral").replace(dtype="float32", quant="int8")
    p = init_params(cfg, jax.random.PRNGKey(1))
    for k in ("gate", "up", "down"):
        assert p["layers"]["experts"][k]["q"].dtype == jnp.int8
    assert "w" in p["layers"]["router"]   # router kept float: routing-critical


# ---------------- int4 (nibble-packed) weight-only ----------------

def test_int4_pack_roundtrip_exact():
    from distributed_llm_inferencing_tpu.ops.quant import (
        pack_int4, unpack_int4)
    # every nibble value through pack->unpack, odd leading dims included
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.integers(-8, 8, (3, 10, 7)), jnp.int8)
    np.testing.assert_array_equal(np.asarray(unpack_int4(pack_int4(q))),
                                  np.asarray(q))


def test_int4_quantize_roundtrip_error():
    from distributed_llm_inferencing_tpu.ops.quant import quantize_weight_int4
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    p = quantize_weight_int4(w)
    assert p["p4"].dtype == jnp.uint8 and p["p4"].shape == (32, 32)
    assert p["scale"].shape == (32,)
    err = np.abs(np.asarray(dequantize_weight(p)) - np.asarray(w))
    # per-channel symmetric int4: max error is scale/2 per channel
    assert np.all(err <= np.asarray(p["scale"]) / 2 + 1e-7)


@pytest.mark.parametrize("model", ["tiny-gpt2", "tiny-llama", "tiny-mixtral"])
def test_int4_forward_matches_dequantized_weights(model):
    """The packed-int4 compute path (unpack fused into the matmul,
    models/transformer.py _qw) must equal an ordinary float forward over
    the *dequantized* weights — this isolates the pack/unpack/scale
    plumbing from the (intentional) int4 rounding loss."""
    from distributed_llm_inferencing_tpu.ops.quant import is_quantized
    cfg = get_config(model).replace(dtype="float32", attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qcfg = cfg.replace(quant="int4")
    qparams = maybe_quantize(params, qcfg)
    assert qparams["layers"]["q"]["p4"].dtype == jnp.uint8
    assert param_bytes(qparams) < 0.45 * param_bytes(params)

    def deq_tree(p):
        if isinstance(p, dict):
            # NB the layers dict itself has a key named "q" (the query
            # projection), so require an array leaf before dequantizing
            if is_quantized(p) and not isinstance(p.get("q", p.get("p4")),
                                                  dict):
                out = {k: v for k, v in p.items() if k not in ("p4", "q",
                                                               "scale")}
                out["w"] = dequantize_weight(p).astype(jnp.float32)
                return out
            return {k: deq_tree(v) for k, v in p.items()}
        return p

    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 12)),
        jnp.int32)
    lens = jnp.full((2,), 12, jnp.int32)

    def fwd(cfg_, p):
        cache = init_cache(cfg_, 2, 16, dtype=jnp.float32)
        logits, _ = transformer.prefill(p, cfg_, toks, lens, cache)
        return np.asarray(logits)

    quant = fwd(qcfg, qparams)
    ref = fwd(cfg, deq_tree(qparams))
    np.testing.assert_allclose(quant, ref, rtol=2e-3, atol=2e-3)


def test_random_init_emits_int4_directly():
    cfg = get_config("tiny-llama").replace(dtype="float32", quant="int4")
    p = init_params(cfg, jax.random.PRNGKey(0))
    for leaf in ("q", "k", "v", "o", "up", "gate", "down"):
        assert "w" not in p["layers"][leaf]
        assert p["layers"][leaf]["p4"].dtype == jnp.uint8
        # packed along din: half the rows of the float weight
    assert p["layers"]["up"]["p4"].shape[-2] == cfg.hidden_size // 2
    eng = InferenceEngine(cfg, p, max_seq=64)
    out = eng.generate([[3, 5, 7, 11]], max_new_tokens=6,
                       sampling=SamplingParams.greedy())
    assert len(out.tokens[0]) == 6


def test_engine_generate_int4_sharded():
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla", quant="int4")
    params = init_params(get_config("tiny-llama").replace(dtype="float32"),
                         jax.random.PRNGKey(0), dtype=jnp.float32)
    eng = InferenceEngine(cfg, params, max_seq=64)
    prompt = np.random.default_rng(1).integers(0, 256, 9).tolist()
    r1 = eng.generate([prompt], max_new_tokens=8,
                      sampling=SamplingParams.greedy())
    assert len(r1.tokens[0]) == 8
    eng2 = InferenceEngine(cfg, params, mesh_spec=MeshSpec(tp=2), max_seq=64)
    r2 = eng2.generate([prompt], max_new_tokens=8,
                       sampling=SamplingParams.greedy())
    assert r2.tokens[0][0] == r1.tokens[0][0]


def test_batcher_int4():
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla", quant="int4")
    b = ContinuousBatcher(cfg, num_blocks=32, block_size=8, slots=2,
                          max_seq=64)
    r = b.submit([1, 2, 3, 4], max_new_tokens=6,
                 sampling=SamplingParams.greedy())
    for _ in range(20):
        b.step()
        if r.done.is_set():
            break
    assert r.wait() and len(r.tokens) == 6


def test_plan_accounts_int4_bytes():
    from distributed_llm_inferencing_tpu.parallel.plan import make_plan
    full = make_plan("llama-3-8b", {"tp": 1})
    q = make_plan(get_config("llama-3-8b").replace(quant="int4"), {"tp": 1})
    # int4 packs two weights per byte: ~0.25x + embeddings/norms float
    assert q["param_bytes_total"] < 0.45 * full["param_bytes_total"]


def test_int4_checkpoint_roundtrip(tmp_path):
    from distributed_llm_inferencing_tpu.models import checkpoint
    cfg = get_config("tiny-llama").replace(dtype="float32", quant="int4")
    params = init_params(cfg, jax.random.PRNGKey(2), dtype=jnp.float32)
    checkpoint.save_checkpoint(str(tmp_path / "q4"), cfg, params)
    cfg2, params2 = checkpoint.load_checkpoint(str(tmp_path / "q4"))
    assert cfg2.quant == "int4"
    np.testing.assert_array_equal(np.asarray(params["layers"]["up"]["p4"]),
                                  np.asarray(params2["layers"]["up"]["p4"]))


@pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
def test_q4_matmul_kernel_matches_reference(dtype):
    """The pallas int4 kernel (interpret mode here — the real thing needs
    a TPU) against the dequantized-weight reference, both nibble planes
    and the bias-correction path exercised."""
    from distributed_llm_inferencing_tpu.ops.pallas.quant_matmul import (
        q4_matmul)
    from distributed_llm_inferencing_tpu.ops.quant import (
        quantize_weight_int4)
    rng = np.random.default_rng(0)
    din, dout, b = 256, 384, 3        # b deliberately off the sublane tile
    w = jnp.asarray(rng.standard_normal((din, dout)) * 0.1, jnp.float32)
    p = quantize_weight_int4(w)
    x = jnp.asarray(rng.standard_normal((b, din)), jnp.dtype(dtype))
    ref = jnp.einsum("bd,df->bf", x.astype(jnp.float32),
                     dequantize_weight(p))
    out = q4_matmul(x, p["p4"], p["scale"], interpret=True)
    assert out.dtype == x.dtype and out.shape == (b, dout)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref),
        rtol=0.05 if dtype == "bfloat16" else 2e-3,
        atol=0.05 if dtype == "bfloat16" else 2e-3)


# ---------------- int8 embedding table (cfg.embed_quant) ----------------

def test_embed_quantize_roundtrip_error():
    from distributed_llm_inferencing_tpu.ops.quant import (
        dequantize_embed, quantize_embed)
    rng = np.random.default_rng(0)
    emb = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    p = quantize_embed(emb)
    assert p["q8"].dtype == jnp.int8 and p["rscale"].shape == (64,)
    err = np.abs(np.asarray(dequantize_embed(p)) - np.asarray(emb))
    assert np.all(err <= np.asarray(p["rscale"])[:, None] / 2 + 1e-7)


@pytest.mark.parametrize("model", ["tiny-gpt2", "tiny-llama"])
def test_embed_quant_forward_matches_dequantized_table(model):
    """int8-table forward (gather dequant + tied-head commuted scale) vs a
    float forward over the dequantized table — isolates the plumbing from
    the rounding loss. Covers a tied (gpt2) and an untied (llama) family."""
    from distributed_llm_inferencing_tpu.ops.quant import (
        dequantize_embed, maybe_quantize_embed)
    cfg = get_config(model).replace(dtype="float32", attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qcfg = cfg.replace(embed_quant="int8")
    qparams = maybe_quantize_embed(params, qcfg)
    assert qparams["layers"] is params["layers"]   # only the table changes

    ref_params = dict(qparams)
    ref_params["embed"] = dict(qparams["embed"])
    ref_params["embed"]["tokens"] = dequantize_embed(
        qparams["embed"]["tokens"]).astype(jnp.float32)

    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 12)),
        jnp.int32)
    lens = jnp.full((2,), 12, jnp.int32)

    def fwd(cfg_, p):
        cache = init_cache(cfg_, 2, 16, dtype=jnp.float32)
        logits, _ = transformer.prefill(p, cfg_, toks, lens, cache)
        return np.asarray(logits)

    np.testing.assert_allclose(fwd(qcfg, qparams), fwd(cfg, ref_params),
                               rtol=2e-3, atol=2e-3)


def test_random_init_emits_embed_int8_directly():
    cfg = get_config("tiny-gpt2").replace(dtype="float32",
                                          embed_quant="int8")
    p = init_params(cfg, jax.random.PRNGKey(0))
    assert p["embed"]["tokens"]["q8"].dtype == jnp.int8
    eng = InferenceEngine(cfg, p, max_seq=64)
    out = eng.generate([[3, 5, 7, 11]], max_new_tokens=6,
                       sampling=SamplingParams.greedy())
    assert len(out.tokens[0]) == 6


def test_embed_quant_sharded_and_stacked_with_int4():
    """embed int8 + weights int4 together, tp=2: specs cover the dict
    table leaf and the engine still decodes."""
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    cfg = get_config("tiny-gpt2").replace(
        dtype="float32", attn_backend="xla", quant="int4",
        embed_quant="int8")
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = np.random.default_rng(1).integers(0, 256, 9).tolist()
    eng = InferenceEngine(cfg, params, max_seq=64)
    r1 = eng.generate([prompt], max_new_tokens=8,
                      sampling=SamplingParams.greedy())
    eng2 = InferenceEngine(cfg, params, mesh_spec=MeshSpec(tp=2), max_seq=64)
    r2 = eng2.generate([prompt], max_new_tokens=8,
                       sampling=SamplingParams.greedy())
    assert r2.tokens[0][0] == r1.tokens[0][0]


def test_int4_pallas_multidevice_mesh_construction_allowed(monkeypatch):
    """The kernel now carries a GSPMD/shardy partitioning rule
    (ops/pallas/quant_matmul.py), so int4 on a multi-device mesh is no
    longer refused at construction — with any DLI_INT4_PALLAS mode —
    and the tp=2 engine still decodes correctly (column-parallel leaves
    per-shard, row-parallel on the XLA unpack; equivalence pinned in
    tests/test_quant_partition.py)."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    monkeypatch.setenv("DLI_INT4_PALLAS", "always")
    cfg = get_config("tiny-llama").replace(dtype="float32", quant="int4")
    eng = InferenceEngine(cfg, init_params(cfg, jax.random.PRNGKey(0)),
                          mesh_spec=MeshSpec(tp=2), max_seq=64)
    monkeypatch.delenv("DLI_INT4_PALLAS")
    ref = InferenceEngine(cfg, init_params(cfg, jax.random.PRNGKey(0)),
                          max_seq=64)
    g = SamplingParams.greedy()
    a = eng.generate([[3, 1, 4, 1]], max_new_tokens=6, sampling=g).tokens[0]
    b = ref.generate([[3, 1, 4, 1]], max_new_tokens=6, sampling=g).tokens[0]
    assert a == b


def test_embed_quant_untied_int4_full_stack():
    """The llama-family full quant story (bench llama_3_8b_int4_eq8):
    int4 matmuls INCLUDING the untied lm_head + int8 embedding table.
    Greedy decode must match the same stack with a dequantized table at
    relaxed tolerance, and the engine must serve it tp-sharded."""
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    cfg = get_config("tiny-llama").replace(
        dtype="float32", attn_backend="xla", quant="int4",
        embed_quant="int8")
    params = init_params(cfg, jax.random.PRNGKey(3))
    assert "p4" in params["lm_head"]            # untied head is int4
    assert "q8" in params["embed"]["tokens"]    # table is int8
    prompt = np.random.default_rng(2).integers(0, 256, 9).tolist()
    eng = InferenceEngine(cfg, params, max_seq=64)
    r1 = eng.generate([prompt], max_new_tokens=8,
                      sampling=SamplingParams.greedy())
    assert len(r1.tokens[0]) == 8

    # same stack, table dequantized to float: first greedy tokens agree
    # (rounding-loss tolerance: compare the first token only, the rest
    # can legitimately diverge after an argmax flip)
    from distributed_llm_inferencing_tpu.ops.quant import dequantize_embed
    ref = {k: v for k, v in params.items()}
    ref["embed"] = dict(params["embed"])
    ref["embed"]["tokens"] = dequantize_embed(
        params["embed"]["tokens"]).astype(jnp.float32)
    eng_ref = InferenceEngine(cfg.replace(embed_quant=None), ref, max_seq=64)
    r2 = eng_ref.generate([prompt], max_new_tokens=8,
                          sampling=SamplingParams.greedy())
    assert r1.tokens[0][0] == r2.tokens[0][0]

    eng_tp = InferenceEngine(cfg, params, mesh_spec=MeshSpec(tp=2),
                             max_seq=64)
    r3 = eng_tp.generate([prompt], max_new_tokens=8,
                         sampling=SamplingParams.greedy())
    assert r3.tokens[0][0] == r1.tokens[0][0]


def test_embed_quant_checkpoint_roundtrip(tmp_path):
    from distributed_llm_inferencing_tpu.models import checkpoint
    cfg = get_config("tiny-gpt2").replace(dtype="float32",
                                          embed_quant="int8")
    params = init_params(cfg, jax.random.PRNGKey(2))
    checkpoint.save_checkpoint(str(tmp_path / "eq"), cfg, params)
    cfg2, params2 = checkpoint.load_checkpoint(str(tmp_path / "eq"))
    assert cfg2.embed_quant == "int8"
    np.testing.assert_array_equal(
        np.asarray(params["embed"]["tokens"]["q8"]),
        np.asarray(params2["embed"]["tokens"]["q8"]))


def test_plan_accounts_embed_int8_bytes():
    from distributed_llm_inferencing_tpu.parallel.plan import make_plan
    full = make_plan("gpt2-xl", {"tp": 1})
    q = make_plan(get_config("gpt2-xl").replace(embed_quant="int8"),
                  {"tp": 1})
    # gpt2-xl's [50257, 1600] table is ~5% of the model in bf16; int8
    # saves half of it
    assert q["param_bytes_total"] < 0.98 * full["param_bytes_total"]


def test_cli_quant_modes_in_sync():
    """__main__ keeps a literal copy of MODES so jax-free subcommands
    never import jax to build the parser."""
    from distributed_llm_inferencing_tpu import __main__ as cli
    from distributed_llm_inferencing_tpu.ops.quant import MODES
    assert tuple(cli.quant_modes) == tuple(MODES)


def test_engine_applies_embed_quant_to_float_params():
    """Caller-supplied float params + cfg.embed_quant: the engine must
    quantize the table itself (the specs already expect the dict leaf)."""
    cfg = get_config("tiny-gpt2").replace(dtype="float32",
                                          embed_quant="int8")
    fparams = init_params(get_config("tiny-gpt2").replace(dtype="float32"),
                          jax.random.PRNGKey(0), dtype=jnp.float32)
    assert not isinstance(fparams["embed"]["tokens"], dict)
    eng = InferenceEngine(cfg, fparams, max_seq=64)
    assert isinstance(eng.params["embed"]["tokens"], dict)
    out = eng.generate([[3, 5, 7]], max_new_tokens=4,
                       sampling=SamplingParams.greedy())
    assert len(out.tokens[0]) == 4
