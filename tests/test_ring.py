"""Ring attention (sequence parallelism) tests on the 8-device CPU mesh.

Golden property: the sp-sharded ring (parallel/ring.py) must match the
dense single-device attention (ops/attention.py:attend) and the full
transformer prefill must be invariant to sp.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.attention import attend
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
from distributed_llm_inferencing_tpu.parallel import ring, sharding as shd
from distributed_llm_inferencing_tpu.parallel.mesh import (
    MeshSpec, create_mesh, validate_spec)


def _dense_ref(q, k, v, lengths, sliding_window=None):
    B, S = q.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    valid = pos < lengths[:, None]
    return np.asarray(attend(q, k, v, pos, pos, valid,
                             sliding_window=sliding_window))


@pytest.mark.parametrize("spec,window", [
    (MeshSpec(sp=4), None),
    (MeshSpec(sp=8), None),
    (MeshSpec(dp=2, sp=2, tp=2), None),
    (MeshSpec(sp=4), 7),            # sliding window crosses chunk bounds
])
def test_ring_matches_dense(spec, window):
    rng = np.random.default_rng(0)
    B, S, H, Hkv, hd = 4, 32, 4, 2, 8
    q = jnp.asarray(rng.standard_normal((B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, Hkv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, Hkv, hd)), jnp.float32)
    lengths = jnp.asarray([S, S - 5, 17, 1], jnp.int32)  # ragged
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))

    ref = _dense_ref(q, k, v, lengths, window)
    mesh = create_mesh(spec)
    with mesh:
        got = jax.jit(lambda q, k, v: ring.ring_attend_prefill(
            q, k, v, pos, lengths, mesh=mesh, sliding_window=window)
        )(q, k, v)
    # rows past a sequence's length attend nothing (ring emits zeros;
    # dense path emits an arbitrary uniform average) — compare valid rows
    mask = np.asarray(pos < lengths[:, None])[..., None, None]
    np.testing.assert_allclose(np.where(mask, np.asarray(got), 0),
                               np.where(mask, ref, 0), atol=1e-5, rtol=1e-5)


@pytest.mark.parametrize("spec", [
    MeshSpec(sp=4),
    MeshSpec(dp=2, sp=2, tp=2),
])
def test_prefill_invariant_to_sp(spec):
    """Full-model prefill logits with sp sharding == single-device logits."""
    cfg = get_config("tiny-llama").replace(dtype="float32")
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    B, S = 2, 16
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S)),
        jnp.int32)
    lengths = jnp.asarray([S, S - 3], jnp.int32)

    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    ref, _ = transformer.prefill(params, cfg, tokens, lengths, cache)
    ref = np.asarray(ref)

    mesh = create_mesh(spec)
    with mesh:
        sp_params = shd.shard_params(params, mesh, cfg, spec)
        cache = init_cache(cfg, B, S, dtype=jnp.float32)
        cache = jax.device_put(
            cache, shd.named(mesh, shd.cache_specs(cfg, spec)))
        got, _ = jax.jit(lambda p, t, l, c: transformer.prefill(
            p, cfg, t, l, c, mesh=mesh))(sp_params, tokens, lengths, cache)
    got = np.asarray(got)
    # compare logits at valid positions only (padding rows are garbage on
    # both sides but not necessarily the same garbage)
    pos = np.arange(S)[None, :]
    valid = (pos < np.asarray(lengths)[:, None])[..., None]
    np.testing.assert_allclose(np.where(valid, got, 0),
                               np.where(valid, ref, 0),
                               atol=2e-4, rtol=2e-4)


def test_ring_then_decode_end_to_end():
    """Prefill via ring (sp=4), then greedy decode steps; tokens must match
    the single-device engine exactly."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

    cfg = get_config("tiny-llama").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = np.random.default_rng(3).integers(
        1, cfg.vocab_size, 21).tolist()
    sp_eng = InferenceEngine(cfg, params, mesh_spec=MeshSpec(sp=4),
                             max_seq=64)
    ref_eng = InferenceEngine(cfg, params, max_seq=64)
    g = SamplingParams.greedy()
    got = sp_eng.generate([prompt], max_new_tokens=12, sampling=g)
    ref = ref_eng.generate([prompt], max_new_tokens=12, sampling=g)
    assert got.tokens == ref.tokens


def test_ring_rejects_kv_replication():
    mesh = create_mesh(MeshSpec(sp=2, tp=4))
    q = jnp.zeros((1, 8, 4, 8))
    k = jnp.zeros((1, 8, 1, 8))  # 1 kv head < tp=4 -> replication needed
    pos = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="kv"):
        ring.ring_attend_prefill(q, k, k, pos, jnp.ones((1,), jnp.int32),
                                 mesh=mesh)


# ---- ring decode (flash-decoding combine over sp) -----------------------


@pytest.mark.parametrize("spec,window", [
    (MeshSpec(sp=4), None),
    (MeshSpec(sp=8), None),
    (MeshSpec(dp=2, sp=2, tp=2), None),
    (MeshSpec(sp=4), 7),
])
def test_ring_decode_matches_dense(spec, window):
    """One-token attention over an sp-sharded cache == dense attention."""
    rng = np.random.default_rng(1)
    B, S, H, Hkv, hd = 4, 32, 4, 2, 8
    q = jnp.asarray(rng.standard_normal((B, 1, H, hd)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, S, Hkv, hd)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, S, Hkv, hd)), jnp.float32)
    lengths = jnp.asarray([S, S - 5, 17, 1], jnp.int32)  # ragged

    # dense reference: query sits at position length-1
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    valid = pos < lengths[:, None]
    ref = np.asarray(attend(q, k, v, (lengths - 1)[:, None], pos, valid,
                            sliding_window=window))

    mesh = create_mesh(spec)
    with mesh:
        got = jax.jit(lambda q, k, v, l: ring.ring_attend_decode(
            q, k, v, l, mesh=mesh, sliding_window=window))(q, k, v, lengths)
    np.testing.assert_allclose(np.asarray(got), ref, atol=1e-5, rtol=1e-5)


def test_sp_tp_decode_trajectory_matches_dense():
    """sp=2 x tp=2 engine: full greedy trajectory == single-device engine
    (VERDICT round-1 item 5 done-condition)."""
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

    cfg = get_config("tiny-llama").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = np.random.default_rng(7).integers(
        1, cfg.vocab_size, 19).tolist()
    sp_eng = InferenceEngine(cfg, params, mesh_spec=MeshSpec(sp=2, tp=2),
                             max_seq=64)
    ref_eng = InferenceEngine(cfg, params, max_seq=64)
    g = SamplingParams.greedy()
    got = sp_eng.generate([prompt], max_new_tokens=12, sampling=g)
    ref = ref_eng.generate([prompt], max_new_tokens=12, sampling=g)
    assert got.tokens == ref.tokens


def test_ring_decode_bench_harness_runs():
    """The perf-evidence harness (benchmarks/ring_decode_bench.py) stays
    runnable and its two formulations stay numerically aligned."""
    import json
    import os
    import subprocess
    import sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "benchmarks",
                                      "ring_decode_bench.py"), "256", "2"],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["seq_len"] == 256 and line["sp"] == 2
    assert line["max_abs_diff"] < 1e-4
    assert line["ring_collective_bytes"] > 0


def test_ring_alibi_matches_dense():
    """sp + ALiBi: the ring carries the linear bias (slopes shard over tp
    with the heads) — prefill and decode must match the dense xla path."""
    import jax
    from distributed_llm_inferencing_tpu.ops.attention import (
        alibi_slopes, attend_decode, attend_prefill)
    from distributed_llm_inferencing_tpu.parallel.mesh import (
        MeshSpec, create_mesh)
    from distributed_llm_inferencing_tpu.parallel.ring import (
        ring_attend_decode, ring_attend_prefill)

    rng = np.random.default_rng(11)
    B, S, H, Hkv, hd = 2, 32, 4, 4, 16
    q = jnp.asarray(rng.normal(size=(B, S, H, hd)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, Hkv, hd)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, Hkv, hd)), jnp.float32)
    lengths = jnp.asarray([S, S - 5], jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    sl = alibi_slopes(H)
    mesh = create_mesh(MeshSpec(sp=2, tp=2))

    # reference: the dense formulation with per-sequence validity masks
    # (what the ring sees)
    valid = pos < lengths[:, None]
    from distributed_llm_inferencing_tpu.ops.attention import attend
    ref = attend(q, k, v, pos, pos, valid, alibi=sl)
    got = ring_attend_prefill(q, k, v, pos, lengths, mesh=mesh, alibi=sl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    qd = jnp.asarray(rng.normal(size=(B, 1, H, hd)), jnp.float32)
    refd = attend_decode(qd, k, v, lengths, backend="xla", alibi=sl)
    gotd = ring_attend_decode(qd, k, v, lengths, mesh=mesh, alibi=sl)
    np.testing.assert_allclose(np.asarray(gotd), np.asarray(refd),
                               rtol=2e-5, atol=2e-5)


def test_sp_pp_engine_matches_dense():
    """sp × pp (the 70B-long-context corner): the pipelined executor
    routes per-stage attention through the ring path via a nested
    shard_map on the abstract context mesh — greedy decode must match
    the single-device engine exactly, with and without tp."""
    import jax
    from distributed_llm_inferencing_tpu.models.params import init_params
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = np.random.default_rng(0).integers(0, 256, 11).tolist()
    g = SamplingParams.greedy()
    ref = InferenceEngine(cfg, params, max_seq=64).generate(
        [prompt, prompt[:7]], max_new_tokens=6, sampling=g).tokens
    for spec in (MeshSpec(pp=2, sp=2), MeshSpec(pp=2, sp=2, tp=2)):
        got = InferenceEngine(cfg, params, mesh_spec=spec,
                              max_seq=64).generate(
            [prompt, prompt[:7]], max_new_tokens=6, sampling=g).tokens
        assert got == ref, (spec, got, ref)
