"""Clock-seam determinism suite (docs/simulator.md).

Every timer-bearing runtime subsystem reads time through
``utils/clock.py``; these tests pin the behaviors the simulator relies
on by driving them on a frozen/stepped :class:`VirtualClock`: TSDB
bucket placement, breaker open -> half-open -> closed timing, retry
backoff schedules, requeue due-time gating, and the HA lease expiry
decision. If one of these drifts back to ``time.time()`` the dlilint
``time-direct`` rule catches the source; these tests catch the
behavior (a site that reads the seam but caches a real-clock value at
import, say).
"""

import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest

from distributed_llm_inferencing_tpu.utils import clock
from distributed_llm_inferencing_tpu.utils.clock import VirtualClock


@pytest.fixture
def vclock():
    vc = VirtualClock(1_700_000_000.0, owner=True)
    prev = clock.set_clock(vc)
    try:
        yield vc
    finally:
        clock.set_clock(prev)


# ---- the clock seam itself --------------------------------------------

def test_virtual_clock_advance_and_elapsed(vclock):
    t0 = clock.now()
    assert t0 == 1_700_000_000.0
    vclock.advance(12.5)
    assert clock.now() == t0 + 12.5
    assert vclock.elapsed() == 12.5
    m0 = clock.monotonic()
    vclock.advance(0.5)
    assert clock.monotonic() == m0 + 0.5


def test_owner_sleep_advances_virtual_time(vclock):
    t0 = clock.now()
    clock.sleep(3.0)          # owner thread: no real waiting
    assert clock.now() == t0 + 3.0


def test_deadline_uses_virtual_monotonic(vclock):
    d = clock.deadline(10.0)
    assert d == clock.monotonic() + 10.0
    vclock.advance(11.0)
    assert clock.monotonic() > d


def test_set_clock_restores_system():
    vc = VirtualClock(5.0)
    prev = clock.set_clock(vc)
    assert clock.get_clock() is vc
    clock.set_clock(prev)
    assert clock.get_clock() is not vc
    # back on the system clock: now() tracks the host again
    import time
    assert abs(clock.now() - time.time()) < 5.0


# ---- TSDB bucketing ---------------------------------------------------

def test_tsdb_buckets_pinned_by_virtual_clock(vclock):
    from distributed_llm_inferencing_tpu.runtime.tsdb import TSDB
    db = TSDB(step_s=10.0, window_s=600.0)
    t0 = clock.now()
    db.record("n1", "depth", 3.0)          # t=None -> seam read
    vclock.advance(4.0)
    db.record("n1", "depth", 5.0)          # same 10s bucket
    series = db.query("depth", now=clock.now())
    assert len(series) == 1
    pts = series[0]["points"]
    # same bucket: freshest wins, bucket epoch is the step-aligned
    # virtual time — fully deterministic, no host time anywhere
    assert pts == [[t0 - (t0 % 10.0), 5.0]]
    vclock.advance(10.0)
    db.record("n1", "depth", 7.0)
    pts = db.query("depth", now=clock.now())[0]["points"]
    assert [v for _, v in pts] == [5.0, 7.0]
    assert pts[1][0] - pts[0][0] == 10.0


def test_tsdb_counter_rate_over_virtual_interval(vclock):
    from distributed_llm_inferencing_tpu.runtime.tsdb import TSDB
    db = TSDB(step_s=10.0, window_s=600.0)
    db.record("n1", "reqs", 100.0, kind="counter")
    vclock.advance(10.0)
    db.record("n1", "reqs", 150.0, kind="counter")
    pts = db.query("reqs", now=clock.now())[0]["points"]
    # 50 increments over exactly 10 virtual seconds = 5.0/s (the fine
    # bucket plus the in-progress coarse accumulator both report it)
    assert pts and {v for _, v in pts} == {5.0}


# ---- breaker state machine --------------------------------------------

def test_breaker_half_open_probe_cycle_on_virtual_clock(vclock):
    """Strikes -> OPEN stamps the virtual time; the next health sweep
    of the recovered node flips to HALF-OPEN; a probe success closes.
    Same sequence the sim's adversarial leg exercises at fleet scale,
    pinned here on one node with exact timestamps."""
    from tools.dlisim import DEFAULT_MODEL, SimMaster, SyntheticFleet
    fleet = SyntheticFleet.uniform(1, DEFAULT_MODEL)
    m = SimMaster(fleet, vclock, health_interval=15.0)
    try:
        spec = fleet.nodes[0].spec
        nid = m.store.add_node(spec.name, "sim.invalid", spec.port,
                               is_active=True)
        t_open = clock.now()
        for _ in range(3):
            m._node_failure(m.store.get_node(nid))
        row = m.store.get_node(nid)
        assert row["breaker_state"] == "open"
        assert not row["is_active"]
        assert row["breaker_opened_at"] == t_open
        vclock.advance(15.0)
        m._health_sweep()                     # node reachable again
        row = m.store.get_node(nid)
        assert row["breaker_state"] == "half_open"
        assert row["is_active"]
        m._node_success(m.store.get_node(nid))
        row = m.store.get_node(nid)
        assert row["breaker_state"] == "closed"
        assert row["consecutive_failures"] == 0
        m.store.flush()   # group commit: see our own buffered events
        counts = {e["type"] for e in m.store.query_events(limit=50)}
        assert {"breaker-open", "breaker-half-open",
                "breaker-closed"} <= counts
    finally:
        m.stop()


# ---- retry backoff ----------------------------------------------------

def test_backoff_schedule_deterministic_under_seed(vclock):
    from tools.dlisim import DEFAULT_MODEL, SimMaster, SyntheticFleet
    fleet = SyntheticFleet.uniform(1, DEFAULT_MODEL)
    m = SimMaster(fleet, vclock)
    try:
        random.seed(1234)
        a = [m._backoff(i) for i in range(4)]
        random.seed(1234)
        b = [m._backoff(i) for i in range(4)]
        assert a == b
        # exponential shape: jitter aside, attempt k+1's ceiling
        # doubles until the cap
        assert all(x > 0 for x in a)
    finally:
        m.stop()


# ---- requeue due-time gating ------------------------------------------

def test_requeue_delay_gates_claims_until_virtual_due(vclock):
    from tools.dlisim import DEFAULT_MODEL, SimMaster, SyntheticFleet
    fleet = SyntheticFleet.uniform(1, DEFAULT_MODEL)
    m = SimMaster(fleet, vclock)
    try:
        rid = m.store.submit_request("tiny-llama", "hi", 4)
        claimed = m.store.claim_next_pending_many(8)
        assert [r["id"] for r in claimed] == [rid]
        m.store.requeue(rid, delay_s=30.0)
        m.store.flush()
        assert m.store.claim_next_pending_many(8) == []
        due = m.store.next_pending_due()
        assert due == pytest.approx(clock.now() + 30.0)
        vclock.advance(29.0)
        assert m.store.claim_next_pending_many(8) == []
        vclock.advance(1.5)
        assert [r["id"] for r in
                m.store.claim_next_pending_many(8)] == [rid]
    finally:
        m.stop()


# ---- HA lease expiry --------------------------------------------------

def test_lease_expiry_decision_on_virtual_clock(vclock):
    """The standby's takeover races a heartbeat renewing the lease:
    with the deadline in the virtual future the takeover must no-op,
    one virtual millisecond past it the standby must lead. Wall time
    plays no part."""
    from distributed_llm_inferencing_tpu.runtime.master import Master
    from distributed_llm_inferencing_tpu.runtime.replication import (
        HAController)
    m = Master(":memory:")
    try:
        r = HAController(m, peers=["http://127.0.0.1:9/"],
                         leader=False, lease_ms=3000.0,
                         repl_barrier=False)
        r._lease_deadline = clock.now() + 3.0
        term0 = r.term
        r._takeover()
        assert not r.leader and r.term == term0   # lease still valid
        vclock.advance(2.9)
        r._takeover()
        assert not r.leader
        vclock.advance(0.2)                       # now past the deadline
        r._takeover()
        assert r.leader and r.term == term0 + 1
    finally:
        m.stop()
