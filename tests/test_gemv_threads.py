"""Threaded native GEMV/GEMM kernels (native/src/qgemv.cc RowPool).

The threading contract is PARTITION-ONLY determinism: every output row is
computed start-to-finish by exactly one thread running the identical
scalar loop, so any ``DLI_NATIVE_THREADS`` setting must produce bitwise-
identical results — asserted here across 1/2/4 threads for all three
weight dtypes, at decode-shaped and GEMM-shaped M and odd K/N (no
vector-width alignment to hide an off-by-one in the row partition).

The batcher smoke test pins the tentpole's point: batch must amortize
weight streaming, i.e. batched decode throughput clearly beats
single-stream on the same host (every slot shares each weight pass, and
the per-chunk dispatch cost is paid once for all slots).
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.native import configured_threads
from distributed_llm_inferencing_tpu.ops import cpu_gemv
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher

RNG = np.random.default_rng(7)
THREADS = (1, 2, 4)


@pytest.fixture
def restore_threads():
    yield
    if cpu_gemv.available():
        cpu_gemv.set_threads(0)   # back to the env/core-count default


needs_native = pytest.mark.skipif(
    not cpu_gemv.available(),
    reason="native qgemv not built (no g++ / ffi headers)")


def test_configured_threads_parses_env(monkeypatch):
    monkeypatch.setenv("DLI_NATIVE_THREADS", "3")
    assert configured_threads() == 3
    monkeypatch.setenv("DLI_NATIVE_THREADS", "junk")
    assert configured_threads() >= 1   # falls back to core count
    monkeypatch.delenv("DLI_NATIVE_THREADS")
    assert configured_threads() >= 1


@needs_native
def test_set_threads_roundtrip(restore_threads):
    for t in THREADS:
        assert cpu_gemv.set_threads(t) == t
    assert cpu_gemv.get_threads() == THREADS[-1]
    assert cpu_gemv.set_threads(0) >= 1   # default restored


@needs_native
@pytest.mark.parametrize("m", [1, 2, 4, 8])
def test_int8_parity_and_thread_invariance(m, restore_threads):
    k, n = 193, 515   # odd K/N
    x = jnp.asarray(RNG.standard_normal((m, k)), jnp.float32)
    wt = jnp.asarray(RNG.integers(-127, 128, (n, k)), jnp.int8)
    s = jnp.asarray(RNG.random(n) * 0.02 + 1e-3, jnp.float32)
    outs = []
    for t in THREADS:
        assert cpu_gemv.set_threads(t) == t
        outs.append(np.asarray(cpu_gemv.qgemv_i8(x, wt, s)))
    for o in outs[1:]:   # bitwise: the partition decides WHO, never WHAT
        assert np.array_equal(outs[0], o)
    want = np.asarray(x) @ (np.asarray(wt, np.float32).T
                            * np.asarray(s)[None, :])
    np.testing.assert_allclose(outs[0], want, rtol=2e-5, atol=2e-5)


@needs_native
@pytest.mark.parametrize("m", [1, 2, 4, 8])
@pytest.mark.parametrize("wdtype", ["float32", "bfloat16"])
def test_float_parity_and_thread_invariance(m, wdtype, restore_threads):
    k, n = 97, 131   # odd K/N
    x = jnp.asarray(RNG.standard_normal((m, k)), jnp.float32)
    w = jnp.asarray(RNG.standard_normal((n, k)), jnp.float32)
    if wdtype == "bfloat16":
        w = w.astype(jnp.bfloat16)
    outs = []
    for t in THREADS:
        assert cpu_gemv.set_threads(t) == t
        outs.append(np.asarray(cpu_gemv.gemv_w(x, w)))
    for o in outs[1:]:
        assert np.array_equal(outs[0], o)
    want = np.asarray(x) @ np.asarray(w.astype(jnp.float32)).T
    # -ffast-math reassociates the reduction: tolerance, not bit-equality,
    # vs the jnp reference (bit-equality is asserted across THREADS above)
    tol = dict(rtol=5e-2, atol=5e-3) if wdtype == "bfloat16" \
        else dict(rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(outs[0], want, **tol)


@needs_native
def test_threaded_inside_jit(restore_threads):
    """The pool must be reentrant-safe under XLA's own threading: drive
    the custom call from inside jit at every thread count."""
    k, n = 64, 96
    wt = jnp.asarray(RNG.integers(-127, 128, (n, k)), jnp.int8)
    s = jnp.ones((n,), jnp.float32)
    x = jnp.asarray(RNG.standard_normal((2, k)), jnp.float32)
    f = jax.jit(lambda a: cpu_gemv.qgemv_i8(a, wt, s))
    outs = []
    for t in THREADS:
        cpu_gemv.set_threads(t)
        outs.append(np.asarray(f(x)))
    assert all(np.array_equal(outs[0], o) for o in outs[1:])


def test_batched_throughput_amortizes_weight_streaming():
    """Continuous batching must actually amortize: 8 concurrent requests
    through the batcher beat one request by >= 1.5x tokens/s on the same
    host (every active slot shares each weight-streaming pass and the
    per-chunk dispatch). Also pins the amortization counters the /metrics
    gauge and bench.py report."""
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    b = ContinuousBatcher(cfg, params, num_blocks=256, block_size=8,
                          slots=8, max_seq=128)
    sp = SamplingParams.greedy()
    new_tokens = 48

    def run(n_req, seed):
        rng = np.random.default_rng(seed)   # fresh prompts: no radix hits
        prompts = [rng.integers(0, cfg.vocab_size, 16).tolist()
                   for _ in range(n_req)]
        t0 = time.perf_counter()
        reqs = [b.submit(p, max_new_tokens=new_tokens, sampling=sp)
                for p in prompts]
        guard = 0
        while not all(r.done.is_set() for r in reqs):
            b.step()
            guard += 1
            assert guard < 2000
        dt = time.perf_counter() - t0
        for r in reqs:
            assert r.error is None, r.error
        return sum(len(r.tokens) for r in reqs) / dt

    run(8, 0)   # warmup: compiles the admission + chunk programs
    run(1, 1)
    single = max(run(1, 2), run(1, 3))
    batched = max(run(8, 4), run(8, 5))
    if batched < 1.5 * single:   # one retry: absorb a CI scheduler stall
        single = min(single, max(run(1, 6), run(1, 7)))
        batched = max(batched, run(8, 8), run(8, 9))
    assert batched >= 1.5 * single, (batched, single)
    # the amortization counters saw the batch: > 1.5 tokens per weight
    # pass over the batched run is what the wall-clock win is made of
    c = b.metrics.snapshot()["counters"]
    assert c["batcher_tokens_emitted"] >= 1.5 * c["batcher_weight_passes"]
