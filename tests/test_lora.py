"""Multi-LoRA adapter serving (models/lora.py, ops/lora.py, the
batcher's per-slot gathered application, and the master's routing).

The contract under test: the batched gathered delta is EXACT — a mixed-
adapter wave emits, per request, bitwise the tokens a dedicated
single-adapter batcher emits, and an adapter's output equals the dense
model with that adapter merged into its weights; the host store is a
bounded LRU tier that never evicts pinned adapters; an adapter problem
FAILS the request loudly (never silently serves base weights); the
master's adapter-affinity pick honors the convoy guard; and a
live-migration resume record carries the adapter with it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models import lora as lora_mod
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops import lora as lora_ops
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(31)

# scale ~0.8: strong enough that the rank-r delta flips greedy argmax on
# the random-init tiny model (the checkpoint-realistic 0.05 default is a
# ~0.25% relative delta greedy decoding never sees — every differential
# below would pass vacuously against base weights)
A_SRC = "synth:rank=4,seed=3,scale=0.8"
B_SRC = "synth:rank=8,seed=9,scale=0.8"


def _mk(**kw):
    kw.setdefault("num_blocks", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("slots", 4)
    kw.setdefault("max_seq", 96)
    return ContinuousBatcher(CFG, PARAMS, **kw)


def _drain(b, reqs, limit=2000):
    for _ in range(limit):
        b.step()
        if all(r.done.is_set() for r in reqs):
            return
    raise AssertionError("batcher did not drain")


def _prompt(i, n=7):
    return np.random.default_rng(100 + i).integers(0, 256, n).tolist()


# ---- ops: the gathered delta vs a plain per-row delta -----------------


def test_gathered_delta_math():
    """gathered_delta == x @ A[id] @ B[id] per row, and slot 0 (zero
    pack rows) is an exact-zero delta, not a small one."""
    rng = np.random.default_rng(5)
    S, din, rmax, dout, B, T = 3, 8, 4, 6, 4, 2
    a = rng.standard_normal((S, din, rmax)).astype(np.float32)
    b = rng.standard_normal((S, rmax, dout)).astype(np.float32)
    a[0] = 0.0
    b[0] = 0.0
    x = rng.standard_normal((B, T, din)).astype(np.float32)
    ids = np.array([0, 1, 2, 1], np.int32)
    got = np.asarray(lora_ops.gathered_delta(
        jnp.asarray(x), {"a": jnp.asarray(a), "b": jnp.asarray(b)},
        jnp.asarray(ids)))
    for r in range(B):
        want = x[r] @ a[ids[r]] @ b[ids[r]]
        np.testing.assert_allclose(got[r], want, rtol=1e-5, atol=1e-5)
    assert np.all(got[0] == 0.0)


# ---- host store: LRU by bytes, pinning, occupancy ---------------------


def test_host_store_lru_pinning_and_occupancy():
    ads = [lora_mod.synthesize(CFG, f"ad{i}", rank=2, seed=i)
           for i in range(4)]
    per = ads[0].nbytes
    store = lora_mod.LoRAHostStore(capacity_mb=2.5 * per / 2**20)
    assert store.put(ads[0]) == []
    assert store.put(ads[1]) == []
    st = store.stats()
    assert st["adapters"] == 2 and st["bytes"] == 2 * per
    # touch ad0 so ad1 becomes LRU; the third insert evicts ad1
    assert store.get("ad0") is not None
    assert store.put(ads[2]) == ["ad1"]
    assert sorted(store.names()) == ["ad0", "ad2"]
    assert store.stats()["evictions"] == 1
    # every resident adapter pinned: put must refuse AND roll back
    with pytest.raises(ValueError, match="pinned"):
        store.put(ads[3], pinned={"ad0", "ad2"})
    assert sorted(store.names()) == ["ad0", "ad2"]
    assert store.stats()["bytes"] == 2 * per
    # an adapter larger than the whole budget is refused outright
    big = lora_mod.synthesize(CFG, "big", rank=16, seed=9)
    with pytest.raises(ValueError, match="exceeds"):
        lora_mod.LoRAHostStore(capacity_mb=big.nbytes / 2**21).put(big)
    # peek must not touch recency: ad0 stays LRU and is evicted next
    store.get("ad2")
    assert store.peek("ad0") is not None
    assert store.put(ads[3]) == ["ad0"]


# ---- delta exactness: adapter serving == dense merged weights ---------


def _merged_params(ad):
    layers = dict(PARAMS["layers"])
    for t in ad.targets:
        w = np.asarray(layers[t]["w"], np.float32).copy()
        for li, lp in enumerate(ad.layers):
            a, b = lp[t]
            w[li] = lora_ops.merge_into_dense(w[li], a, b, ad.scale)
        layers[t] = dict(layers[t], w=jnp.asarray(w, jnp.float32))
    return dict(PARAMS, layers=layers)


def test_adapter_equals_merged_dense_greedy():
    """Greedy tokens through the gathered per-slot delta match the
    dense model with the adapter merged into its weights (token-level:
    the two formulations differ in fp summation order)."""
    ad = lora_mod.resolve(CFG, "diff", "synth:rank=4,seed=5,scale=0.9")
    prompts = [_prompt(i) for i in range(3)]

    b = _mk()
    b.load_adapter("diff", "synth:rank=4,seed=5,scale=0.9")
    reqs = [b.submit(p, max_new_tokens=8, sampling=SamplingParams.greedy(),
                     seed=50 + i, adapter="diff")
            for i, p in enumerate(prompts)]
    _drain(b, reqs)
    base_reqs = [b.submit(p, max_new_tokens=8,
                          sampling=SamplingParams.greedy(), seed=50)
                 for p in prompts]
    _drain(b, base_reqs)

    merged = ContinuousBatcher(CFG, _merged_params(ad), num_blocks=128,
                               block_size=8, slots=4, max_seq=96)
    mreqs = [merged.submit(p, max_new_tokens=8,
                           sampling=SamplingParams.greedy(), seed=50 + i)
             for i, p in enumerate(prompts)]
    _drain(merged, mreqs)
    for r, mr, br in zip(reqs, mreqs, base_reqs):
        assert r.tokens == mr.tokens
    # the adapter actually changed SOMETHING vs base — otherwise the
    # equality above proves nothing
    assert any(r.tokens != br.tokens for r, br in zip(reqs, base_reqs))


# ---- mixed-adapter waves: bitwise vs dedicated batchers ---------------


def test_mixed_wave_bitwise_vs_dedicated():
    """One wave mixing base + two adapters (greedy AND sampled rows)
    emits, per request, bitwise the tokens dedicated single-adapter
    batchers emit for the same (prompt, sampling, seed)."""
    sampled = SamplingParams(do_sample=True, temperature=0.9)
    specs = []   # (adapter, prompt, sampling, seed)
    for i in range(6):
        ad = (None, "a1", "a2")[i % 3]
        sp = SamplingParams.greedy() if i < 3 else sampled
        specs.append((ad, _prompt(i, 5 + i % 4), sp, 900 + i))

    mixed = _mk()
    mixed.load_adapter("a1", A_SRC)
    mixed.load_adapter("a2", B_SRC)
    reqs = [mixed.submit(p, max_new_tokens=8, sampling=sp, seed=seed,
                         adapter=ad)
            for ad, p, sp, seed in specs]
    _drain(mixed, reqs)
    got = {seed: r.tokens for (_, _, _, seed), r in zip(specs, reqs)}

    for name in (None, "a1", "a2"):
        ded = _mk()
        if name:
            ded.load_adapter(name, A_SRC if name == "a1" else B_SRC)
        sub = [s for s in specs if s[0] == name]
        dreqs = [ded.submit(p, max_new_tokens=8, sampling=sp, seed=seed,
                            adapter=ad)
                 for ad, p, sp, seed in sub]
        _drain(ded, dreqs)
        for (_, _, _, seed), r in zip(sub, dreqs):
            assert r.tokens == got[seed], \
                f"adapter {name!r} seed {seed} diverged in the mix"


# ---- failure semantics: loud rejection, never silent base -------------


def test_unknown_adapter_rejected_at_submit():
    b = _mk()
    with pytest.raises(ValueError, match="unknown adapter"):
        b.submit(_prompt(0), max_new_tokens=4, adapter="ghost")
    assert not b.queue
    # the batcher still serves base traffic afterwards
    r = b.submit(_prompt(1), max_new_tokens=4,
                 sampling=SamplingParams.greedy(), seed=1)
    _drain(b, [r])
    assert r.error is None and len(r.tokens) == 4


def test_load_failure_is_loud_never_base():
    b = _mk()
    # rank above DLI_LORA_MAX_RANK: refused at load...
    with pytest.raises(ValueError, match="rank"):
        b.load_adapter("fat", "synth:rank=99,seed=1")
    with pytest.raises(ValueError, match="synth param"):
        b.load_adapter("typo", "synth:rnak=4")
    assert b.metrics.snapshot()["counters"]["lora_load_failures"] >= 2
    # ...so a request naming it can never exist, let alone serve base
    with pytest.raises(ValueError, match="unknown adapter"):
        b.submit(_prompt(0), max_new_tokens=4, adapter="fat")
    # unload with live requests refuses; after release it drops
    b.load_adapter("ok", A_SRC)
    r = b.submit(_prompt(2), max_new_tokens=4,
                 sampling=SamplingParams.greedy(), adapter="ok")
    with pytest.raises(ValueError, match="live requests"):
        b.unload_adapter("ok")
    _drain(b, [r])
    assert b.unload_adapter("ok") is True
    assert "ok" not in b.lora_stats()["resident"]


def test_slot_exhaustion_fails_admission():
    """More DISTINCT live adapters than device slots: the overflow
    request fails with the slots error, siblings complete."""
    b = _mk(slots=4)
    b._lora_slot_names = [None, None]   # 1 device slot
    b.load_adapter("s1", A_SRC)
    b.load_adapter("s2", B_SRC)
    r1 = b.submit(_prompt(0), max_new_tokens=8,
                  sampling=SamplingParams.greedy(), adapter="s1")
    r2 = b.submit(_prompt(1), max_new_tokens=8,
                  sampling=SamplingParams.greedy(), adapter="s2")
    _drain(b, [r1, r2])
    assert r1.error is None
    assert r2.error is not None and "slots" in r2.error
    assert r2.tokens == []   # failed loudly, served nothing


# ---- migration: the resume record carries the adapter -----------------


def test_migration_resume_carries_adapter():
    src = _mk()
    src.load_adapter("mig", A_SRC)
    req = src.submit(_prompt(3), max_new_tokens=12,
                     sampling=SamplingParams.greedy(), seed=7,
                     adapter="mig", chunk_cap=2)
    for _ in range(200):
        src.step()
        if len(req.tokens) >= 4:
            break
    assert 4 <= len(req.tokens) < 12 and not req.done.is_set()
    req._migrate_requested = True
    for _ in range(50):
        src.step()
        if req.done.is_set():
            break
    rec = req.resume_record
    assert rec is not None and rec["adapter"] == "mig"

    dst = _mk()
    dst.load_adapter("mig", A_SRC)
    cont = dst.submit(rec["prompt_tokens"],
                      max_new_tokens=rec["max_new_tokens"],
                      sampling=SamplingParams.greedy(), resume=rec)
    assert cont.adapter == "mig"
    _drain(dst, [cont])

    whole = _mk()
    whole.load_adapter("mig", A_SRC)
    ref = whole.submit(_prompt(3), max_new_tokens=12,
                       sampling=SamplingParams.greedy(), seed=7,
                       adapter="mig")
    _drain(whole, [ref])
    # cont.tokens holds carried + newly decoded: the whole stream must
    # be bitwise the unmigrated run's
    assert cont.tokens[:len(rec["tokens"])] == rec["tokens"]
    assert cont.tokens == ref.tokens


# ---- master: registry validation + adapter-affinity convoy guard ------


def _master():
    from distributed_llm_inferencing_tpu.runtime.master import Master
    return Master(":memory:")


def test_registry_validation_and_submit_gate():
    m = _master()
    try:
        bad = m.api_register_adapter({"adapter": "x y", "source": "synth"})
        assert bad[0] == 400
        bad = m.api_register_adapter({"adapter": "ok"})
        assert bad[0] == 400 and "source" in bad[1]["message"]
        r = m.api_register_adapter({"adapter": "ten-a", "source": A_SRC,
                                    "model_name": "tiny-llama"})
        assert r["status"] == "success"
        assert m.adapter_registry()["ten-a"]["model"] == "tiny-llama"
        # unregistered adapter: structured 400 at the front door
        code, body = m.api_submit({"model_name": "tiny-llama",
                                   "prompt": "hi", "adapter": "ghost"})
        assert code == 400 and "not registered" in body["message"]
        # registered for ANOTHER model: also a 400, naming the mismatch
        code, body = m.api_submit({"model_name": "tiny-gpt2",
                                   "prompt": "hi", "adapter": "ten-a"})
        assert code == 400 and "tiny-llama" in body["message"]
    finally:
        m.stop()


def test_adapter_affinity_convoy_guard():
    from distributed_llm_inferencing_tpu.utils import clock
    m = _master()
    try:
        cands = [{"id": 1, "name": "n1"}, {"id": 2, "name": "n2"}]

        def snap(queue, resident):
            return {"at": clock.now(), "queue": queue, "models": {},
                    "adapters": {"tiny-llama": {"resident": resident,
                                                "bytes": 0}}}

        def pick(q1, q2, res1, res2, slo=None):
            m._node_runtime = {1: snap(q1, res1), 2: snap(q2, res2)}
            return m._score_pick(cands, model="tiny-llama",
                                 slo_class=slo, adapter="ad")

        # resident + within slack: affinity wins
        n, reason = pick(0, 0, ["ad"], [])
        assert (n["id"], reason) == (1, "adapter_affinity")
        # resident node overloaded beyond the slack: the convoy guard
        # sends the request to the cold node instead
        n, reason = pick(50, 0, ["ad"], [])
        assert n["id"] == 2 and reason != "adapter_affinity"
        # latency class zeroes the slack: one queued request is enough
        # to lose the affinity
        n, reason = pick(1, 0, ["ad"], [], slo="latency")
        assert n["id"] == 2 and reason != "adapter_affinity"
        # affinity must SEPARATE candidates: all-resident (and equally
        # loaded) means nothing to win, load policy decides
        n, reason = pick(0, 0, ["ad"], ["ad"])
        assert reason != "adapter_affinity"
    finally:
        m.stop()
