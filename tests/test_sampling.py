"""sample_batch: per-row parameterized sampling (two-tier prefix/full).

The batcher's sampler runs inside every decode-chunk program; these tests
pin (a) masking semantics (top-k, nucleus, greedy), (b) branch purity — a
row's draw never depends on its chunk-mates' configs, the property the
scheduler's reproducibility contract rests on, and (c) that the prefix
fast path samples the same *distribution* the full-vocab path does.
"""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_llm_inferencing_tpu.ops.sampling import (
    PREFIX_K, sample_batch)

RNG = np.random.default_rng(0)


_jit_sample = jax.jit(sample_batch)


def _draw(logits, seeds, steps, temps, tks, tps, ds):
    return np.asarray(_jit_sample(
        jnp.asarray(logits, jnp.float32),
        jnp.asarray(seeds, jnp.int32), jnp.asarray(steps, jnp.int32),
        jnp.asarray(temps, jnp.float32), jnp.asarray(tks, jnp.int32),
        jnp.asarray(tps, jnp.float32), jnp.asarray(ds, bool)))


def _draw_many(logits, seed, steps, temp, tk, tp):
    """Vectorized multi-step draws for distribution tests (one compile)."""
    logits = jnp.asarray(logits, jnp.float32)

    @jax.jit
    def go(steps):
        def one(step):
            return sample_batch(
                logits, jnp.asarray([seed], jnp.int32),
                jnp.asarray([step], jnp.int32),
                jnp.asarray([temp], jnp.float32),
                jnp.asarray([tk], jnp.int32),
                jnp.asarray([tp], jnp.float32), jnp.asarray([True]))[0]
        return jax.vmap(one)(steps)

    return np.asarray(go(jnp.arange(steps, dtype=jnp.int32)))


def test_greedy_rows_are_argmax():
    logits = RNG.normal(size=(4, 300))
    out = _draw(logits, [1] * 4, [0] * 4, [0.8] * 4, [50] * 4, [0.95] * 4,
                [False] * 4)
    np.testing.assert_array_equal(out, logits.argmax(-1))


def test_sampled_tokens_respect_top_k():
    logits = RNG.normal(size=(8, 500))
    for step in range(20):
        out = _draw(logits, list(range(8)), [step] * 8, [1.0] * 8, [5] * 8,
                    [1.0] * 8, [True] * 8)
        for r in range(8):
            top5 = set(np.argsort(logits[r])[-5:])
            assert out[r] in top5


def test_sampled_tokens_respect_top_p():
    # one dominant logit -> nucleus at p=0.5 is exactly that token
    logits = np.zeros((2, 100), np.float32)
    logits[:, 7] = 50.0
    out = _draw(logits, [3, 4], [0, 0], [1.0] * 2, [0] * 2, [0.5] * 2,
                [True] * 2)
    np.testing.assert_array_equal(out, [7, 7])


def test_row_draw_independent_of_chunk_mates():
    """A covered row (k <= PREFIX_K) must sample the SAME token whether its
    chunk-mates are covered (fast branch) or force the full-vocab branch —
    the scheduler's (params, prompt, seed) purity contract."""
    v = PREFIX_K * 4
    logits = RNG.normal(size=(2, v))
    for step in range(10):
        fast = _draw(logits, [11, 12], [step] * 2, [0.9] * 2, [50, 50],
                     [0.95] * 2, [True] * 2)
        # mate switches to k > PREFIX_K -> slow branch for the batch
        slow = _draw(logits, [11, 12], [step] * 2, [0.9] * 2,
                     [50, PREFIX_K + 7], [0.95] * 2, [True] * 2)
        assert fast[0] == slow[0], (step, fast, slow)


def test_uncovered_row_uses_full_vocab():
    """k > PREFIX_K must actually reach beyond the prefix: with uniform
    logits and k = V, draws cover tokens outside the top PREFIX_K."""
    v = PREFIX_K * 8
    logits = np.zeros((1, v), np.float32)
    out = _draw_many(logits, seed=5, steps=64, temp=1.0, tk=0, tp=1.0)
    # ties: top_k picks the first PREFIX_K indices; anything beyond
    # proves the full path sampled the whole support
    assert (out >= PREFIX_K).any()


def test_prefix_path_matches_full_distribution():
    """Empirical frequencies from the prefix fast path match the exact
    k-masked softmax (chi-square-ish loose bound, fixed seeds)."""
    v, k, n = 64, 4, 4000   # v < PREFIX_K -> prefix covers everything
    logits = np.zeros((1, v), np.float32)
    logits[0, :k] = [2.0, 1.5, 1.0, 0.5]
    out = _draw_many(logits, seed=9, steps=n, temp=1.0, tk=k, tp=1.0)
    counts = np.bincount(out, minlength=v)
    assert counts[k:].sum() == 0          # top-k mask held
    p = np.exp(logits[0, :k]) / np.exp(logits[0, :k]).sum()
    np.testing.assert_allclose(counts[:k] / n, p, atol=0.04)
