"""Overload front-door suite (docs/robustness.md "Overload control").

Covers the acceptance-critical invariants:
- token-bucket refill math on the virtual clock: burst drains, tokens
  refill at the configured rate, and the advertised wait is exactly
  the time until the next token exists,
- every refusal is an honest 429: Retry-After on the wire, a reasoned
  body, an ``admission-rejected`` journal event — never a silent drop
  (shed, queue-full, and tenant-bucket gates alike),
- unknown ``slo_class`` / malformed ``X-DLI-Tenant`` are structured
  400s naming the accepted set,
- priority claim ordering: latency before throughput before batch,
  the rung-4 ``max_priority`` filter, and deadline-style aging that
  bounds how long an old batch row can be overtaken,
- the degradation ladder escalates/de-escalates one rung per dwell
  with hysteresis, each transition journaled WITH the gauge values
  that justified it, and rung-3 brownout injects the decode-chunk cap
  into latency-class dispatch payloads only,
- the HTTP ingress itself refuses past ``max_inflight`` with
  503 + Retry-After instead of queueing without bound.
"""

import json
import math
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest
import requests as rq

from distributed_llm_inferencing_tpu.runtime import state
from distributed_llm_inferencing_tpu.runtime.httpd import JsonHTTPService
from distributed_llm_inferencing_tpu.runtime.master import Master
from distributed_llm_inferencing_tpu.runtime.state import Store
from distributed_llm_inferencing_tpu.utils import clock
from distributed_llm_inferencing_tpu.utils.clock import VirtualClock


@pytest.fixture
def vclock():
    vc = VirtualClock(1_700_000_000.0, owner=True)
    prev = clock.set_clock(vc)
    try:
        yield vc
    finally:
        clock.set_clock(prev)


def _submit_body(slo_class="throughput", tenant=None, **kw):
    b = {"model_name": "m", "prompt": "p", "max_new_tokens": 4,
         "slo_class": slo_class}
    if tenant is not None:
        b["tenant"] = tenant
    b.update(kw)
    return b


# ---- token bucket -----------------------------------------------------

def test_bucket_burst_refill_and_wait_math(vclock):
    m = Master(":memory:", admit_rate=1.0, admit_burst=2.0)
    try:
        assert m._bucket_take("t1") == (True, 0.0)
        assert m._bucket_take("t1") == (True, 0.0)
        ok, wait = m._bucket_take("t1")
        assert not ok and wait == pytest.approx(1.0)
        # refill is linear in elapsed time: half a token after 0.5s
        vclock.advance(0.5)
        ok, wait = m._bucket_take("t1")
        assert not ok and wait == pytest.approx(0.5)
        vclock.advance(0.5)
        assert m._bucket_take("t1") == (True, 0.0)
        # tenants are isolated: t1 empty says nothing about t2
        assert m._bucket_take("t2") == (True, 0.0)
        # refill caps at burst, not beyond
        vclock.advance(60.0)
        for _ in range(2):
            assert m._bucket_take("t1") == (True, 0.0)
        assert not m._bucket_take("t1")[0]
    finally:
        m.stop()


def test_bucket_refusal_is_honest_429(vclock):
    m = Master(":memory:", admit_rate=0.5, admit_burst=1.0)
    try:
        r = m.api_submit(_submit_body(tenant="acme"))
        assert r["status"] == "success"
        refused = m.api_submit(_submit_body(tenant="acme"))
        assert isinstance(refused, tuple) and refused[0] == 429
        body, headers = refused[1], refused[2]
        assert body["reason"] == "tenant-bucket"
        # 1 token at rate 0.5/s is 2s away; Retry-After must say so
        assert headers["Retry-After"] == str(body["retry_after_s"]) \
            == "2"
        # no row was created for the refused submit
        assert m.store.counts().get("pending", 0) == 1
        m.store.flush()
        evs = m.store.query_events(etype="admission-rejected")
        assert len(evs) == 1
        d = evs[0]["data"]
        assert d["tenant"] == "acme" and d["reason"] == "tenant-bucket"
        assert d["retry_after_s"] == 2 and d["slo_class"] == "throughput"
    finally:
        m.stop()


# ---- queue-depth cap --------------------------------------------------

def test_queue_cap_refuses_with_drain_rate_hint(vclock):
    m = Master(":memory:", admit_max_pending=2)
    try:
        assert m.api_submit(_submit_body())["status"] == "success"
        assert m.api_submit(_submit_body())["status"] == "success"
        refused = m.api_submit(_submit_body())
        assert isinstance(refused, tuple) and refused[0] == 429
        assert refused[1]["reason"] == "queue-full"
        # no measured drain yet -> the 0.5/s floor prices the overage
        assert 1 <= int(refused[2]["Retry-After"]) <= 60
        assert m.store.counts()["pending"] == 2
    finally:
        m.stop()


# ---- structured 400s --------------------------------------------------

def test_unknown_slo_class_and_bad_tenant_are_structured_400s():
    m = Master(":memory:")
    try:
        r = m.api_submit(_submit_body(slo_class="gold"))
        assert isinstance(r, tuple) and r[0] == 400
        assert r[1]["accepted"] == ["latency", "throughput", "batch"]
        r = m.api_submit(_submit_body(tenant="no spaces allowed"))
        assert isinstance(r, tuple) and r[0] == 400
        assert "X-DLI-Tenant" in r[1]["message"]
    finally:
        m.stop()


def test_http_front_door_headers_and_400s():
    """The wire-level contract: X-DLI-Tenant header feeds the bucket,
    refusals carry the Retry-After HEADER, and validation failures are
    structured 400s — all through the real HTTP stack."""
    m = Master(":memory:", admit_rate=0.2, admit_burst=1.0)
    srv = m.service.serve("127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        ok = rq.post(f"{base}/api/inference/submit",
                     json=_submit_body(slo_class="latency"),
                     headers={"X-DLI-Tenant": "acme"})
        assert ok.status_code == 200 and ok.json()["status"] == "success"
        refused = rq.post(f"{base}/api/inference/submit",
                          json=_submit_body(),
                          headers={"X-DLI-Tenant": "acme"})
        assert refused.status_code == 429
        assert int(refused.headers["Retry-After"]) >= 1
        assert refused.json()["reason"] == "tenant-bucket"
        # another tenant's bucket is untouched
        other = rq.post(f"{base}/api/inference/submit",
                        json=_submit_body(),
                        headers={"X-DLI-Tenant": "globex"})
        assert other.status_code == 200
        bad = rq.post(f"{base}/api/inference/submit",
                      json=_submit_body(),
                      headers={"X-DLI-Tenant": "a b"})
        assert bad.status_code == 400
        bad = rq.post(f"{base}/api/inference/submit",
                      json=_submit_body(slo_class="gold"))
        assert bad.status_code == 400
        assert bad.json()["accepted"] == ["latency", "throughput",
                                          "batch"]
    finally:
        m.stop()


# ---- priority claim + aging ------------------------------------------

def _seed(store, slo_class):
    return store.submit_request("m", "p", 4, None, slo_class=slo_class)


def test_claim_orders_by_class_priority():
    s = Store(":memory:")
    try:
        rb = _seed(s, "batch")
        rt = _seed(s, "throughput")
        rl = _seed(s, "latency")
        claimed = [r["id"] for r in s.claim_next_pending_many(3)]
        assert claimed == [rl, rt, rb]
    finally:
        s.close()


def test_claim_max_priority_filters_declared_class():
    s = Store(":memory:")
    try:
        _seed(s, "batch")
        _seed(s, "throughput")
        rl = _seed(s, "latency")
        only = s.claim_next_pending_many(10, max_priority=0)
        assert [r["id"] for r in only] == [rl]
        # the filtered rows are untouched and claimable later
        rest = s.claim_next_pending_many(10)
        assert len(rest) == 2
    finally:
        s.close()


def test_aging_bounds_starvation(vclock, monkeypatch):
    """An old batch row outranks a fresh latency row once it has aged
    one full priority step per CLAIM_AGING_S window — the anti-
    starvation bound the dliverify priority_aging scenario model-checks
    and the dlisim sweep measures in claim waves."""
    monkeypatch.setattr(state, "CLAIM_AGING_S", 10.0)
    s = Store(":memory:")
    try:
        old_batch = _seed(s, "batch")
        vclock.advance(25.0)   # 2.5 aging windows: priority 2 -> -0.5
        fresh_latency = _seed(s, "latency")
        claimed = [r["id"] for r in s.claim_next_pending_many(2)]
        assert claimed == [old_batch, fresh_latency]
    finally:
        s.close()


def test_fresh_batch_does_not_outrank_latency(vclock, monkeypatch):
    monkeypatch.setattr(state, "CLAIM_AGING_S", 10.0)
    s = Store(":memory:")
    try:
        batch = _seed(s, "batch")
        vclock.advance(5.0)    # half a window: not enough to overtake
        latency = _seed(s, "latency")
        claimed = [r["id"] for r in s.claim_next_pending_many(2)]
        assert claimed == [latency, batch]
    finally:
        s.close()


# ---- degradation ladder ----------------------------------------------

def _ladder_master(**kw):
    kw.setdefault("overload_burn", 0.0)      # queue-only: deterministic
    kw.setdefault("overload_queue", 10.0)
    kw.setdefault("overload_hold_s", 5.0)
    return Master(":memory:", **kw)


def test_ladder_escalates_and_deescalates_with_hysteresis(vclock):
    m = _ladder_master()
    try:
        queue = [100.0]
        m._overload_signals = lambda: (None, queue[0])
        m._overload_sweep()
        assert m._overload_level == 1
        # dwell gate: a second sweep inside the hold must NOT step
        m._overload_sweep()
        assert m._overload_level == 1
        for want in (2, 3, 4):
            vclock.advance(5.0)
            m._overload_sweep()
            assert m._overload_level == want
            m._overload_sweep()
            assert m._overload_level == want
        vclock.advance(5.0)
        m._overload_sweep()
        assert m._overload_level == 4, "rung 4 is the ladder's top"
        # hysteresis: queue under the threshold but NOT under half of
        # it holds the rung
        queue[0] = 7.0
        vclock.advance(5.0)
        m._overload_sweep()
        assert m._overload_level == 4
        queue[0] = 2.0
        for want in (3, 2, 1, 0):
            vclock.advance(5.0)
            m._overload_sweep()
            assert m._overload_level == want
        m.store.flush()
        evs = m.store.query_events(etype="overload-level")
        walk = [(e["data"]["prev_level"], e["data"]["level"]) for e in evs]
        assert walk == [(0, 1), (1, 2), (2, 3), (3, 4),
                        (4, 3), (3, 2), (2, 1), (1, 0)]
        for e in evs:
            # every transition journals the gauge values behind it
            assert e["data"]["queue_depth"] in (100.0, 2.0)
            assert e["data"]["direction"] in ("up", "down")
    finally:
        m.stop()


def test_ladder_sheds_classes_in_order(vclock):
    m = _ladder_master()
    try:
        m._overload_level = 1
        r = m.api_submit(_submit_body(slo_class="batch"))
        assert isinstance(r, tuple) and r[0] == 429
        assert r[1]["reason"] == "shed-batch"
        assert int(r[2]["Retry-After"]) == math.ceil(m._overload_hold)
        assert m.api_submit(_submit_body("throughput"))["status"] == \
            "success"
        m._overload_level = 2
        r = m.api_submit(_submit_body("throughput"))
        assert isinstance(r, tuple) and r[0] == 429
        assert r[1]["reason"] == "shed-throughput"
        assert m.api_submit(_submit_body("latency"))["status"] == \
            "success"
        snap = m.metrics.snapshot()["counters"]
        assert snap["shed_batch"] == 1 and snap["shed_throughput"] == 1
        assert snap["admit_rejected"] == 2
        m.store.flush()
        evs = m.store.query_events(etype="admission-rejected")
        assert [e["data"]["level"] for e in evs] == [1, 2]
    finally:
        m.stop()


def test_rung3_injects_chunk_cap_for_latency_only(vclock):
    m = _ladder_master(overload_chunk_cap=4)
    try:
        latency = {"model_name": "m", "prompt": "p", "max_new_tokens": 4,
                   "sampling": None, "slo_class": "latency", "id": 1,
                   "max_length": None}
        batch = dict(latency, slo_class="batch", id=2)
        assert "decode_chunk_cap" not in m._infer_body(latency)
        m._overload_level = 3
        assert m._infer_body(latency)["decode_chunk_cap"] == 4
        assert "decode_chunk_cap" not in m._infer_body(batch)
    finally:
        m.stop()


def test_rung4_claim_filter(vclock):
    m = _ladder_master()
    try:
        assert m._claim_max_priority() is None
        m._overload_level = 4
        assert m._claim_max_priority() == 0
    finally:
        m.stop()


# ---- HTTP ingress saturation -----------------------------------------

def test_httpd_max_inflight_503():
    svc = JsonHTTPService("test", max_inflight=1)
    release = threading.Event()
    entered = threading.Event()

    def slow(body):
        entered.set()
        release.wait(10.0)
        return {"status": "success"}

    svc.add("GET", "/slow", slow)
    srv = svc.serve("127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        first = {}
        t = threading.Thread(
            target=lambda: first.update(r=rq.get(f"{base}/slow")))
        t.start()
        assert entered.wait(5.0)
        refused = rq.get(f"{base}/slow", timeout=5)
        assert refused.status_code == 503
        assert refused.headers["Retry-After"] == "1"
        release.set()
        t.join(timeout=10)
        assert first["r"].status_code == 200
        # the slot freed: the next request is served again
        assert rq.get(f"{base}/slow", timeout=5).status_code == 200
    finally:
        release.set()
        svc.shutdown()


def test_httpd_inflight_cap_off_by_default():
    svc = JsonHTTPService("test")
    assert svc.max_inflight == 0
