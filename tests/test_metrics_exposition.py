"""Prometheus exposition-format correctness (strict regex checker, no new
deps) and live batcher gauges/histograms moving during a batched run.
"""

import math
import re

import numpy as np
import requests

from distributed_llm_inferencing_tpu.utils.metrics import (
    HIST_BUCKETS, Metrics, hist_quantile, parse_prometheus, sanitize_name)

NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
SAMPLE_RE = re.compile(
    rf"^({NAME})"
    rf'(\{{[a-zA-Z_][a-zA-Z0-9_]*="[^"\\\n]*"'
    rf'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"\\\n]*")*\}})?'
    r" [-+]?(?:[0-9]+(?:\.[0-9]+)?(?:[eE][-+]?[0-9]+)?|\+?Inf|NaN)$")
COMMENT_RE = re.compile(rf"^# (HELP|TYPE) ({NAME}) .+$")


def check_exposition(text: str):
    """Strict text-format checker: every line is a valid sample or
    HELP/TYPE comment; TYPE precedes its family's samples; histograms
    have cumulative le= buckets ending at +Inf with matching _count."""
    types = {}
    samples = []
    for line in text.splitlines():
        if not line:
            continue
        mc = COMMENT_RE.match(line)
        if mc:
            if mc.group(1) == "TYPE":
                types[mc.group(2)] = line.split()[-1]
            continue
        ms = SAMPLE_RE.match(line)
        assert ms, f"invalid exposition line: {line!r}"
        samples.append(line)
        name = ms.group(1)
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert base in types or name in types, \
            f"sample {name!r} has no preceding # TYPE"
    # histogram structure
    hists = {}
    for name, labels, value in parse_prometheus(text):
        if name.endswith("_bucket"):
            hists.setdefault(name[:-7], []).append(
                (float(labels["le"]), value))
    for base, buckets in hists.items():
        assert types.get(base) == "histogram"
        les = [le for le, _ in buckets]
        counts = [c for _, c in buckets]
        assert les == sorted(les) and les[-1] == math.inf, \
            f"{base}: buckets not cumulative-ordered with +Inf"
        assert counts == sorted(counts), f"{base}: non-monotone buckets"
    flat = {(n, tuple(sorted(l.items()))): v
            for n, l, v in parse_prometheus(text)}
    for base, buckets in hists.items():
        inf_count = dict(buckets)[math.inf]
        assert flat[(base + "_count", ())] == inf_count
        assert (base + "_sum", ()) in flat
    return samples


def test_prometheus_strict_format_and_collisions():
    m = Metrics()
    # dots/dashes in names must sanitize; counter vs gauge sharing a name
    # must NOT collide into one exposition line
    m.inc("requests.completed-ok", 3)
    m.gauge("requests.completed-ok", 7)
    m.inc("tokens_generated", 120)
    m.gauge("queue depth", 4)   # space needs sanitizing too
    for v in (0.002, 0.004, 0.03, 0.3, 2.0, 80.0):
        m.observe("load model", v)
    text = m.prometheus()
    check_exposition(text)
    flat = {n: v for n, l, v in parse_prometheus(text) if not l}
    assert flat["dli_requests_completed_ok_total"] == 3
    assert flat["dli_requests_completed_ok"] == 7
    assert flat["dli_queue_depth"] == 4
    assert flat["dli_load_model_seconds_count"] == 6
    assert abs(flat["dli_load_model_seconds_sum"] - 82.336) < 1e-6
    # real cumulative buckets, not two quantile samples
    b = {l["le"]: v for n, l, v in parse_prometheus(text)
         if n == "dli_load_model_seconds_bucket"}
    assert b["+Inf"] == 6
    assert b["0.005"] == 2 and b["0.05"] == 3
    assert b["60"] == 5 and b["120"] == 6   # 80s lands between


def test_sanitize_name():
    assert sanitize_name("a.b-c d") == "a_b_c_d"
    assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", sanitize_name("9lives"))


def test_hist_quantile_interpolation():
    # 10 observations uniform in (0, 1]: p50 lands mid-scale
    buckets = [(0.1, 1), (0.5, 5), (1.0, 10), (math.inf, 10)]
    p50 = hist_quantile(buckets, 0.5)
    assert 0.4 <= p50 <= 0.5
    p95 = hist_quantile(buckets, 0.95)
    assert 0.5 < p95 <= 1.0
    assert hist_quantile([], 0.5) is None
    assert hist_quantile([(math.inf, 0)], 0.5) is None


def test_snapshot_has_p95():
    m = Metrics()
    for i in range(100):
        m.observe("t", i / 100)
    snap = m.snapshot()["timings"]["t"]
    assert snap["p50"] <= snap["p95"] <= snap["p99"]
    assert snap["count"] == 100


def test_worker_metrics_endpoint_parses_strict():
    from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
    agent = WorkerAgent()
    srv = agent.serve(host="127.0.0.1", port=0, background=True)
    port = srv.server_address[1]
    try:
        agent.metrics.inc("requests_completed")
        agent.metrics.observe("inference", 0.123)
        r = requests.get(f"http://127.0.0.1:{port}/metrics")
        assert r.status_code == 200
        assert r.headers["Content-Type"].startswith("text/plain")
        check_exposition(r.text)
        names = {n for n, _, _ in parse_prometheus(r.text)}
        assert "dli_requests_completed_total" in names
        assert "dli_inference_seconds_bucket" in names
    finally:
        agent.service.shutdown()


def test_master_cluster_metrics_aggregation():
    """The master scrapes each worker's /metrics exposition and serves one
    parsed cluster snapshot (counters summed, histogram p50/p95 derived
    from the cumulative buckets)."""
    from distributed_llm_inferencing_tpu.runtime.master import Master
    from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
    agent = WorkerAgent()
    wsrv = agent.serve(host="127.0.0.1", port=0, background=True)
    wport = wsrv.server_address[1]
    m = Master(":memory:", dispatcher_threads=1, health_interval=30)
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    mport = msrv.server_address[1]
    try:
        agent.metrics.inc("tokens_generated", 42)
        for v in (0.01, 0.02, 0.04, 0.08):
            agent.metrics.observe("batcher_ttft", v)
        r = requests.post(f"http://127.0.0.1:{mport}/api/nodes/add",
                          json={"name": "mw", "host": "127.0.0.1",
                                "port": wport})
        assert r.status_code == 200, r.text
        cm = requests.get(
            f"http://127.0.0.1:{mport}/api/cluster_metrics").json()
        assert cm["status"] == "success"
        (node,) = cm["nodes"]
        assert node["scraped"], node
        assert node["counters"]["tokens_generated"] == 42
        h = node["histograms"]["batcher_ttft_seconds"]
        assert h["count"] == 4 and 0.01 <= h["p50"] <= 0.08
        assert cm["cluster"]["counters"]["tokens_generated"] == 42
        assert cm["cluster"]["workers_scraped"] == 1
        assert "counters" in cm["master"]
    finally:
        m.stop()
        agent.service.shutdown()


def test_batcher_gauges_and_histograms_move():
    """Queue-depth/active-slot/free-block gauges and TTFT / inter-token
    histograms must move during a real batched run."""
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)

    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    met = Metrics()
    b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                          max_seq=64, seed=0, metrics=met)
    rng = np.random.default_rng(0)
    reqs = [b.submit(rng.integers(0, cfg.vocab_size, 5 + i).tolist(),
                     max_new_tokens=8, sampling=SamplingParams.greedy())
            for i in range(4)]
    # 4 submissions into 2 slots: the queue-depth gauge saw the backlog
    assert met.snapshot()["gauges"]["batcher_queue_depth"] >= 2
    for _ in range(200):
        b.step()
        if all(r.done.is_set() for r in reqs):
            break
    assert all(r.done.is_set() for r in reqs)
    assert not any(r.error for r in reqs)

    snap = met.snapshot()
    g = snap["gauges"]
    assert g["batcher_queue_depth"] == 0          # drained
    assert g["batcher_active_slots"] == 0
    assert g["batcher_free_kv_blocks"] == b.pool.free_count() > 0
    c = snap["counters"]
    assert c["batcher_requests_submitted"] == 4
    assert c["batcher_requests_completed"] == 4
    t = snap["timings"]
    assert t["batcher_ttft"]["count"] == 4
    assert t["batcher_e2e_latency"]["count"] == 4
    # per-GAP histogram: one observation per token after each request's
    # first -> 4 requests x 7 gaps
    assert t["batcher_inter_token"]["count"] == 4 * 7
    assert t["batcher_ttft"]["p50"] > 0
    assert t["batcher_decode_chunk"]["count"] >= 1
    assert t["batcher_admit_wave"]["count"] >= 1
    # and the whole thing round-trips through strict exposition
    check_exposition(met.prometheus())
