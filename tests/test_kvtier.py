"""Cluster prefix-cache tier (runtime/kvtier.py + batcher/master wiring).

Covers the acceptance-critical invariants:
- radix evict -> host offload -> restore round trip is BITWISE identical
  to a cold prefill (greedy and sampled),
- the host arena respects its LRU byte bound under pressure,
- same-wave duplicate-prefix admission reuses the earlier member's radix
  insert,
- prefix-digest advertisement + the master's affinity pick, including
  the load threshold (no convoys) and the staleness drop-out,
- the radix/prefix counters reach the Prometheus exposition,
- the persisted node row strips the ephemeral digest advertisement.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime import kvtier
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(7)


def run_until_done(b, reqs, max_steps=400):
    for _ in range(max_steps):
        b.step()
        if all(r.done.is_set() for r in reqs):
            return
    raise AssertionError("not done")


def run_one(b, prompt, n=8, sampling=None, seed=3):
    r = b.submit(prompt, max_new_tokens=n,
                 sampling=sampling or SamplingParams.greedy(), seed=seed)
    run_until_done(b, [r])
    return r.wait()


def make_batcher(kv_host_mb, num_blocks=24):
    # small pool: eviction pressure is the point
    return ContinuousBatcher(CFG, PARAMS, num_blocks=num_blocks,
                             block_size=8, slots=2, max_seq=128,
                             kv_host_mb=kv_host_mb)


# ---- digests / arena units ---------------------------------------------

def test_chain_digests_share_prefix():
    a = kvtier.token_chain_digests(list(range(32)), 8)
    b = kvtier.token_chain_digests(list(range(24)) + [99] * 8, 8)
    assert len(a) == 4 and a[:3] == b[:3] and a[3] != b[3]
    t1 = kvtier.text_chain_digests("x" * 48 + "A" * 16, 16)
    t2 = kvtier.text_chain_digests("x" * 48 + "B" * 16, 16)
    assert t1[:3] == t2[:3] and t1[3] != t2[3]


def test_arena_lru_bound_under_pressure():
    page = np.zeros((4, 8), np.float32)   # 128 B
    arena = kvtier.HostKVArena(capacity_bytes=4 * page.nbytes)
    for i in range(10):
        assert arena.put(f"d{i}", [page])
    st = arena.stats()
    assert st["blocks"] == 4 and st["bytes"] <= arena.capacity_bytes
    assert st["dropped"] == 6
    # LRU order: oldest gone, newest present; get() touches
    assert arena.get("d0") is None and arena.get("d9") is not None
    assert arena.get("d6") is not None
    arena.put("d10", [page])              # drops d7, not the touched d6
    assert arena.get("d6") is not None and arena.get("d7") is None
    # a block bigger than the whole budget is refused, never stored
    assert not arena.put("huge", [np.zeros((1024,), np.float64)])


def test_arena_int8_lru_counts_stored_bytes():
    """An int8 arena's LRU bound and occupancy run on STORED
    (quantized) bytes, so the same budget holds ~4x the blocks of a
    native arena — and the occupancy the arena-full routing guard
    (DLI_SCHED_ARENA_FULL) sees is the honest quantized budget, while
    logical_bytes still carries the full-precision equivalent."""
    page = RNG.standard_normal((2, 8, 2, 4)).astype(np.float32)  # 512 B
    native = kvtier.HostKVArena(capacity_bytes=4 * page.nbytes)
    int8 = kvtier.HostKVArena(capacity_bytes=4 * page.nbytes,
                              dtype="int8")
    for i in range(16):
        assert native.put(f"d{i}", [page])
        assert int8.put(f"d{i}", [page])
    sn, sq = native.stats(), int8.stats()
    assert sn["blocks"] == 4 and sn["dropped"] == 12
    assert sq["blocks"] > sn["blocks"] * 3      # the density claim
    assert sq["dropped"] == 16 - sq["blocks"]
    for st in (sn, sq):
        assert st["bytes"] <= st["capacity_bytes"]
        assert st["occupancy"] == st["bytes"] / st["capacity_bytes"]
    # honest accounting: int8 stores fewer bytes than it represents
    assert sq["bytes"] < sq["logical_bytes"] / 3.5
    assert sn["bytes"] == sn["logical_bytes"]
    # restore path dequantizes to the logical page, bounded error
    got = int8.get("d15")
    assert got is not None and got[0].shape == page.shape
    assert got[0].dtype == np.float32
    assert float(np.max(np.abs(got[0] - page))) < 0.05


def test_estimate_survives_malformed_advertisement():
    """The advertisement crossed the wire from a worker: malformed
    shapes must score 0, never raise — estimate_cached_tokens runs on
    the master's dispatcher threads, which have no exception net."""
    prompt = "x" * 64
    for bad in ({"chunk": 16, "top": [["ab", "NaN-ish"]]},
                {"chunk": 16, "top": [["ab", None]]},
                {"chunk": 16, "top": [["ab"]]},          # short pair
                {"chunk": 16, "top": ["abc"]},           # not pairs
                {"chunk": 16, "top": 7},
                {"chunk": "x", "top": [["ab", 4]]},
                {"chunk": 0, "top": [["ab", 4]]},
                {"top": [["ab", 4]]}, "nope", None, 42):
        assert kvtier.estimate_cached_tokens(prompt, bad) == 0


def test_advertise_honors_top_k_chains_for_deep_prompts():
    """top_k bounds CHAINS, not raw digest entries: top_k deep (64-chunk)
    prompt families must ALL stay advertised, each downsampled to
    geometric depths, and a prompt sharing a partial depth still gets a
    positive (conservative) estimate."""
    idx = kvtier.PrefixDigestIndex(chunk=4, top_k=8)
    sys_prompts = [f"<{g}>" + ("s%d" % g) * 140 for g in range(8)]
    for p in sys_prompts:
        idx.note(p, 256)     # 64+ full 4-byte chunks each
    adv = idx.advertise()
    assert len(adv["top"]) <= 8 * 8    # ~7 depths per chain
    for p in sys_prompts:              # every family still routable
        assert kvtier.estimate_cached_tokens(p + "tail", adv) > 0
        # a prompt sharing only the first ~32 chunks matches a
        # shallower advertised depth with a smaller estimate
        part = kvtier.estimate_cached_tokens(p[:130] + "Z" * 64, adv)
        assert 0 < part < kvtier.estimate_cached_tokens(p + "t", adv)
    # a shorter chain that is a prefix of a longer one merges (one
    # family = one chain, not one per prompt length)
    idx2 = kvtier.PrefixDigestIndex(chunk=4, top_k=8)
    idx2.note("AAAA" * 8, 32)
    idx2.note("AAAA" * 16, 64)
    assert len(idx2._chains) == 1


def test_digest_index_advertises_bounded_top_k():
    idx = kvtier.PrefixDigestIndex(chunk=8, top_k=4)
    for g in range(50):
        idx.note(f"<{g:03d}>" + "s" * 28, 32)
    adv = idx.advertise()
    assert adv["chunk"] == 8
    assert 0 < len(adv["top"]) <= idx.top_k * 4
    # estimate: deepest matching digest wins, token estimate positive
    est = kvtier.estimate_cached_tokens("<049>" + "s" * 28 + "tail", adv)
    assert est > 0
    assert kvtier.estimate_cached_tokens("<999>" + "z" * 40, adv) == 0


# ---- evict -> offload -> restore round trip ----------------------------

@pytest.fixture(scope="module")
def tier_batcher():
    return make_batcher(kv_host_mb=64)


@pytest.fixture(scope="module")
def cold_batcher():
    return make_batcher(kv_host_mb=0)


def _evict_everything(b, n_prompts=6):
    """Flood the small pool with distinct prompts so earlier radix
    prefixes evict (offloading to the arena when the tier is on)."""
    for _ in range(n_prompts):
        run_one(b, RNG.integers(0, 256, 40).tolist(), n=4)


def test_restore_bitwise_identical_greedy(tier_batcher, cold_batcher):
    prompt = RNG.integers(0, 256, 40).tolist()
    cold = run_one(cold_batcher, prompt)
    assert run_one(tier_batcher, prompt) == cold
    _evict_everything(tier_batcher)
    base = tier_batcher.metrics.snapshot()["counters"].get(
        "kvtier_restored_blocks", 0)
    again = run_one(tier_batcher, prompt)
    counters = tier_batcher.metrics.snapshot()["counters"]
    assert counters.get("kvtier_restored_blocks", 0) > base, \
        "prompt KV was not restored from the host arena"
    assert again == cold
    assert counters.get("kvtier_offloaded_blocks", 0) > 0


def test_restore_bitwise_identical_sampled(tier_batcher, cold_batcher):
    prompt = RNG.integers(0, 256, 40).tolist()
    sp = SamplingParams(temperature=0.9, top_k=7, top_p=0.95,
                        do_sample=True)
    cold = run_one(cold_batcher, prompt, sampling=sp, seed=11)
    assert run_one(tier_batcher, prompt, sampling=sp, seed=11) == cold
    _evict_everything(tier_batcher)
    again = run_one(tier_batcher, prompt, sampling=sp, seed=11)
    assert again == cold


def test_restore_after_pool_rebuild_cold_radix(cold_batcher):
    """The arena outlives radix content entirely: a FRESH tier batcher
    that offloaded everything restores into an empty radix match."""
    b = make_batcher(kv_host_mb=64, num_blocks=16)
    prompt = RNG.integers(0, 256, 40).tolist()
    cold = run_one(cold_batcher, prompt)
    first = run_one(b, prompt)
    _evict_everything(b, n_prompts=4)
    blocks, n = b.pool.match_prefix(prompt[:39])
    b.pool.release(blocks)
    assert n == 0, "radix should have evicted the prompt under pressure"
    assert run_one(b, prompt) == cold == first


# ---- same-wave duplicate prefix ----------------------------------------

def test_same_wave_duplicate_prefix_hits_earlier_insert():
    b = make_batcher(kv_host_mb=0, num_blocks=48)
    shared = RNG.integers(0, 256, 32).tolist()
    r1 = b.submit(shared + [1, 2, 3], max_new_tokens=4,
                  sampling=SamplingParams.greedy())
    r2 = b.submit(shared + [7, 8, 9], max_new_tokens=4,
                  sampling=SamplingParams.greedy())
    run_until_done(b, [r1, r2])
    c = b.metrics.snapshot()["counters"]
    # the second member deferred one wave and re-matched the first
    # member's freshly inserted prefix blocks: 4 shared blocks cached
    assert c.get("prefill_cached_tokens", 0) >= 32
    assert b.pool.stats()["prefix_hits"] >= 1
    # and both outputs match their independently-generated twins
    b2 = make_batcher(kv_host_mb=0, num_blocks=48)
    assert r1.tokens == run_one(b2, shared + [1, 2, 3], n=4)
    assert r2.tokens == run_one(b2, shared + [7, 8, 9], n=4)


def test_cold_chunked_prefill_counts_zero_cached_tokens():
    """A single cold request whose prefill chunks across several passes
    re-matches its OWN earlier blocks on each resumption — that must not
    count as cached prefill (it would inflate the A/B's cached-fraction
    acceptance metric for traffic with no sharing at all)."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=24, block_size=8,
                          slots=2, max_seq=128, kv_host_mb=0,
                          prefill_chunk=4)    # 32-token chunks
    run_one(b, RNG.integers(0, 256, 100).tolist(), n=4)
    c = b.metrics.snapshot()["counters"]
    assert c.get("prefill_uncached_tokens", 0) >= 100   # >= 3 passes ran
    assert c.get("prefill_cached_tokens", 0) == 0


# ---- metrics exposition ------------------------------------------------

def test_radix_and_kvtier_counters_reach_exposition(tier_batcher):
    tier_batcher.step()    # epilogue syncs pool counters into metrics
    text = tier_batcher.metrics.prometheus()
    for name in ("dli_radix_prefix_hits_total",
                 "dli_radix_prefix_misses_total",
                 "dli_radix_evictions_total",
                 "dli_kvtier_offloaded_blocks_total",
                 "dli_kvtier_host_bytes",
                 "dli_kvtier_occupancy",
                 "dli_prefill_cached_tokens_total",
                 "dli_prefill_uncached_tokens_total"):
        assert name in text, f"missing {name} in exposition"
    st = tier_batcher.stats()
    assert st["kvtier"]["offloaded"] > 0
    assert st["prefix_digests"] is None or "top" in st["prefix_digests"]


# ---- master affinity routing -------------------------------------------

def _master_with_two_nodes():
    from distributed_llm_inferencing_tpu.runtime.master import Master
    m = Master(":memory:")
    n1 = m.store.add_node("a", "127.0.0.1", 9001, is_active=True)
    n2 = m.store.add_node("b", "127.0.0.1", 9002, is_active=True)
    return m, n1, n2


def _advert(sys_prompt, chunk=16):
    digs = kvtier.text_chain_digests(sys_prompt, chunk)
    return {"chunk": chunk,
            "top": [[d, (i + 1) * chunk] for i, d in enumerate(digs)]}


def _rt(digests=None, queue=0, at=None):
    entry = {"queue": queue, "free": 10}
    if digests is not None:
        entry["digests"] = digests
    return {"queue": queue, "free_blocks": 10,
            "at": time.time() if at is None else at,
            "models": {"tiny-llama": entry}}


def test_affinity_pick_convoy_guard_and_staleness():
    m, n1, n2 = _master_with_two_nodes()
    try:
        sys_prompt = "S" * 64
        m._node_runtime[n1] = _rt(_advert(sys_prompt))
        m._node_runtime[n2] = _rt()
        nodes = m.store.list_nodes(active_only=True)

        pick = m._pick_node("tiny-llama", nodes=nodes,
                            prompt=sys_prompt + "tail-1")
        assert pick["id"] == n1
        c = m.metrics.snapshot()["counters"]
        assert c.get("scheduler_pick_prefix_affinity") == 1

        # FlowKV load-aware rule: the prefix holder is hot -> affinity
        # must NOT convoy; the request goes to the idle node
        m._inflight[n1] = 5
        pick = m._pick_node("tiny-llama", nodes=nodes,
                            prompt=sys_prompt + "tail-2")
        assert pick["id"] == n2
        # a stale advertisement (node silent past SCHED_STALE_S) drops
        # out of affinity scoring entirely
        m._inflight[n1] = 0
        m._node_runtime[n1] = _rt(_advert(sys_prompt),
                                  at=time.time() - 10_000)
        m._pick_node("tiny-llama", nodes=nodes, prompt=sys_prompt + "t3")
        c = m.metrics.snapshot()["counters"]
        assert c.get("scheduler_pick_prefix_affinity") == 1   # unchanged
    finally:
        m.stop()


def test_affinity_disabled_by_zero_weight():
    from distributed_llm_inferencing_tpu.runtime.master import Master
    m = Master(":memory:", prefix_weight=0.0)
    try:
        n1 = m.store.add_node("a", "127.0.0.1", 9001, is_active=True)
        n2 = m.store.add_node("b", "127.0.0.1", 9002, is_active=True)
        sys_prompt = "S" * 64
        m._node_runtime[n1] = _rt(_advert(sys_prompt))
        m._node_runtime[n2] = _rt()
        m._pick_node("tiny-llama",
                     nodes=m.store.list_nodes(active_only=True),
                     prompt=sys_prompt + "tail")
        c = m.metrics.snapshot()["counters"]
        assert "scheduler_pick_prefix_affinity" not in c
    finally:
        m.stop()


def test_persisted_node_row_strips_digest_advertisement():
    m, n1, _ = _master_with_two_nodes()
    try:
        info = {"status": "online", "loaded_models": [{
            "name": "tiny-llama",
            "scheduler": {"queued": 0, "blocks_free": 5,
                          "prefix_digests": {"chunk": 16,
                                             "top": [["aa", 16]]},
                          "pool": {"prefix_hits": 3, "prefix_misses": 1}},
        }]}
        m.store.update_node(n1, info=info)
        import json
        stored = json.loads(m.store.get_node(n1)["info"])
        sch = stored["loaded_models"][0]["scheduler"]
        assert "prefix_digests" not in sch
        assert sch["pool"]["prefix_hits"] == 3   # everything else kept
        # the caller's dict is NOT mutated (the in-memory runtime
        # snapshot still sees the advertisement)
        assert "prefix_digests" in info["loaded_models"][0]["scheduler"]
    finally:
        m.stop()
