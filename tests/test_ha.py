"""Replicated control plane suite (runtime/replication.py,
docs/robustness.md "Replicated control plane").

Covers the acceptance-critical invariants below; the kill-the-leader
chaos gate itself lives in ``bench.py --scenario ha --smoke`` (a real
SIGKILLed leader subprocess under load):

- op-log units: sequencing, retention -> snapshot demand, standby
  mirroring, reset;
- Store replication surface: full-table dump/load keeps rows AND
  autoincrement counters byte-identical (the op stream replays onto
  the same rowids), the TSDB ring snapshot stays out, committed writes
  reach the op hook in commit order (sync and group-commit), and a
  replica replaying captured ops reconstructs an identical store;
- WHERE-guarded applies: a replayed/stale frame can never resurrect a
  terminal row on the replica;
- dispatch-node persistence: the claim's replicated state names the
  node holding the in-flight generation (the takeover re-dispatch pin)
  and never touches a terminal row;
- submit idempotency: a retried ``client_tag`` submit returns the
  existing row instead of a duplicate that would generate twice;
- worker-side lease validation: newest-(term, nonce) fencing, the
  equal-term split-brain rule, 409 + X-DLI-Stale-Term on the wire, and
  the master stepping down (writing nothing) when fenced;
- the durability-barrier satellite fix: a wedged standby ack degrades
  to leader-only durability within two lease intervals — journaled,
  circuit-broken, re-armed on catch-up — and never hangs a dispatcher;
- /replicate frame validation: bad terms, stale terms (the 409 carries
  the winner's term), sequence gaps demanding resync, and at-least-once
  redelivery applying each op exactly once;
- live pair e2e: a real leader subprocess + in-proc standby — writes
  replicate, either master is a valid entry point (/api/leader + 307),
  and a SIGKILL mid-run promotes the standby within the lease budget
  with the takeover reconstructable from its journal.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import types
from pathlib import Path

import pytest
import requests as rq

from distributed_llm_inferencing_tpu.runtime import events as events_mod
from distributed_llm_inferencing_tpu.runtime import replication
from distributed_llm_inferencing_tpu.runtime.master import (
    Master, _StaleTermError)
from distributed_llm_inferencing_tpu.runtime.state import Store
from distributed_llm_inferencing_tpu.utils.platform import \
    free_port as _free_port
from distributed_llm_inferencing_tpu.runtime.worker import (
    MASTER_NONCE_HEADER, MASTER_TERM_HEADER, STALE_TERM_HEADER,
    WorkerAgent)
from distributed_llm_inferencing_tpu.utils.metrics import Metrics

REPO = Path(__file__).resolve().parents[1]


def _store():
    return Store(":memory:", group_commit=False)


def _controller(store=None, *, leader=False, lease_ms=150.0,
                barrier=True, peers=("http://127.0.0.1:1",)):
    """HAController on a minimal master-shaped namespace (no HTTP, no
    dispatch loops) — the unit under test is the controller itself."""
    store = store or _store()
    ns = types.SimpleNamespace(
        store=store, metrics=Metrics(),
        on_promote=lambda: None, on_demote=lambda: None,
        max_attempts=lambda: 5)
    hac = replication.HAController(
        ns, peers=list(peers), lease_ms=lease_ms, repl_barrier=barrier,
        leader=leader, self_url="http://127.0.0.1:2")
    return hac, ns


# ---- op-log units -------------------------------------------------------

def test_oplog_sequencing_and_since():
    ol = replication.OpLog()
    assert ol.seq() == 0
    assert ol.append_new([("a", [1]), ("b", [2])]) == 2
    assert ol.append_new([("c", [])]) == 3
    assert [s for s, _, _ in ol.since(0)] == [1, 2, 3]
    assert [s for s, _, _ in ol.since(2)] == [3]
    assert ol.since(3) == []
    assert ol.since(1, limit=1) == [(2, "b", [2])]


def test_oplog_retention_demands_snapshot():
    ol = replication.OpLog(retain=4)
    ol.append_new([("op", [i]) for i in range(10)])
    # entries 1..6 fell out of retention: a peer at cursor 2 cannot be
    # served incrementally any more
    assert ol.since(2) is None
    assert [s for s, _, _ in ol.since(6)] == [7, 8, 9, 10]
    assert ol.since(-1) is None


def test_oplog_standby_mirror_and_reset():
    ol = replication.OpLog()
    ol.append_at([(5, "a", []), (6, "b", [])])
    assert ol.seq() == 6
    # re-delivery below the high-water mark is dropped; only the
    # NUMBERING is mirrored (a promotion resyncs peers via snapshot,
    # so stored standby ops would never be served)
    ol.append_at([(6, "b", []), (7, "c", [])])
    assert ol.seq() == 7
    ol.reset_to(40)
    assert ol.seq() == 40 and ol.since(40) == []


# ---- store replication surface -----------------------------------------

def test_dump_load_roundtrip_rows_and_rowids():
    a = _store()
    a.add_node("w0", "127.0.0.1", 8100)
    r1 = a.submit_request("m", "p1")
    a.submit_request("m", "p2", client_tag="ct-1")
    a.claim_next_pending()
    a.mark_completed(r1, "out", 1, 0.5, 10.0)
    a.set_meta("tag_nonce", "abc123")
    a.set_meta("tsdb_snapshot", "x" * 1000, replicate=False)

    snap = a.dump_tables()
    # the leader-private TSDB ring dump never rides a snapshot
    meta_keys = {r[snap["meta"]["cols"].index("key")]
                 for r in snap["meta"]["rows"]}
    assert "tag_nonce" in meta_keys and "tsdb_snapshot" not in meta_keys

    b = _store()
    b.load_tables(snap)
    for table in ("nodes", "requests"):
        ra = a._all(f"SELECT * FROM {table} ORDER BY id")
        rb = b._all(f"SELECT * FROM {table} ORDER BY id")
        assert rb == ra, table
    assert b.get_meta("tag_nonce") == "abc123"
    assert b.get_meta("tsdb_snapshot") is None
    # AUTOINCREMENT continues where the leader's counter was: the op
    # stream that follows replays onto identical rowids
    assert b.submit_request("m", "p3") == a.submit_request("m", "p3")


def test_load_tables_clears_stale_autoincrement_counters():
    # a standby on a REUSED file has AUTOINCREMENT counters of its own;
    # a fresh leader's snapshot carries none — the load must still
    # clear them or every replicated INSERT lands on a diverged rowid
    # (and the UPDATEs that follow silently no-op on the replica)
    b = _store()
    for i in range(5):
        b.submit_request("m", f"old {i}")
    a = _store()                     # fresh leader: empty counters
    b.load_tables(a.dump_tables())
    assert b.submit_request("m", "p") == a.submit_request("m", "p")


def test_apply_ops_cannot_resurrect_terminal_row():
    b = _store()
    rid = b.submit_request("m", "p")
    b.claim_next_pending()
    b.mark_completed(rid, "done", 1, 0.1, 1.0)
    # a stale recovery/requeue frame replayed after the terminal write:
    # the leader's own WHERE guards make it a no-op on the replica
    b.apply_ops([
        ("UPDATE requests SET status='pending', attempts=attempts+1, "
         "next_attempt_at=0 WHERE status='processing'", []),
        ("UPDATE requests SET status='failed', completed_at=? "
         "WHERE id=? AND status NOT IN ('completed','failed')",
         [time.time(), rid]),
    ])
    row = b.get_request(rid)
    assert row["status"] == "completed" and row["result"] == "done"
    assert row["attempts"] == 0


def test_op_hook_commit_order_replays_to_identical_store():
    captured = []
    a = _store()
    a.set_op_hook(lambda ops: captured.extend(ops))
    rid = a.submit_request("m", "p", client_tag="ct-9")
    a.claim_next_pending()
    a.note_dispatch_node(rid, 7)
    a.mark_completed(rid, "out", 7, 0.2, 5.0)
    assert len(captured) >= 4

    b = _store()
    b.apply_ops(captured)
    assert (b._all("SELECT * FROM requests")
            == a._all("SELECT * FROM requests"))
    row = b.get_request(rid)
    assert row["status"] == "completed" and row["node_id"] == 7


def test_group_commit_hook_receives_flushed_batch_in_order():
    captured = []
    s = Store(":memory:", group_commit=True)
    try:
        s.set_op_hook(lambda ops: captured.append(list(ops)))
        rid = s.submit_request("m", "p")   # sync write: its own frame
        s.claim_next_pending()
        s.requeue(rid, delay_s=0.0)        # buffered; barrier waits flush
        flat = [sql for batch in captured for sql, _ in batch]
        assert any("INSERT INTO requests" in q for q in flat)
        assert any(q.startswith("UPDATE requests SET status='pending'")
                   for q in flat)
        # commit order: the insert precedes the claim precedes the requeue
        ins = next(i for i, q in enumerate(flat) if "INSERT INTO" in q)
        req_i = next(i for i, q in enumerate(flat)
                     if q.startswith("UPDATE requests SET status='pending'"))
        assert ins < req_i
    finally:
        s.close()


def test_note_dispatch_node_sets_and_never_touches_terminal():
    s = _store()
    rid = s.submit_request("m", "p")
    s.claim_next_pending()
    s.note_dispatch_node(rid, 3)
    assert s.get_request(rid)["node_id"] == 3
    s.mark_completed(rid, "out", 3, 0.1, 1.0)
    s.note_dispatch_node(rid, 9)   # late write off a slow path: no-op
    assert s.get_request(rid)["node_id"] == 3


def test_submit_client_tag_dedupes():
    s = _store()
    r1 = s.submit_request("m", "p", client_tag="ct-a")
    assert s.submit_request("m", "p", client_tag="ct-a") == r1
    assert s.find_client_tag("ct-a") == r1
    assert s.find_client_tag("ghost") is None
    r2 = s.submit_request("m", "p")          # untagged never dedupes
    r3 = s.submit_request("m", "p")
    assert len({r1, r2, r3}) == 3


def test_api_submit_client_tag_dedup_flag():
    m = Master(":memory:")           # solo: permanently leading
    try:
        a = m.api_submit({"model_name": "m", "prompt": "p",
                          "client_tag": "ct-x"})
        b = m.api_submit({"model_name": "m", "prompt": "p",
                          "client_tag": "ct-x"})
        assert a["request_id"] == b["request_id"]
        assert b.get("deduped") is True and "deduped" not in a
        snap = m.metrics.snapshot()["counters"]
        assert snap["requests_submit_deduped"] == 1
    finally:
        m.stop()


# ---- worker-side lease validation --------------------------------------

def test_note_master_term_fence_semantics():
    w = WorkerAgent(auth_key=None)
    assert w.note_master_term("A", 1) is True
    assert w.master_term() == 1
    assert w.note_master_term("A", 1) is True          # same holder ok
    assert w.note_master_term("B", 1) is False         # equal-term rival
    assert w.note_master_term("B", 2) is True          # higher term wins
    assert w.note_master_term("A", 1) is False         # stale term
    assert w.master_term() == 2
    snap = w.metrics.snapshot()["counters"]
    assert snap["stale_term_rejections"] == 2


def test_worker_fences_stale_term_on_the_wire():
    w = WorkerAgent(auth_key=None)
    srv = w.serve("127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        h2 = {MASTER_TERM_HEADER: "2", MASTER_NONCE_HEADER: "new"}
        h1 = {MASTER_TERM_HEADER: "1", MASTER_NONCE_HEADER: "old"}
        assert rq.post(f"{base}/drain", json={"timeout": 0},
                       headers=h2, timeout=10).status_code == 200
        r = rq.post(f"{base}/undrain", json={}, headers=h1, timeout=10)
        assert r.status_code == 409
        assert r.headers[STALE_TERM_HEADER] == "2"
        assert r.json()["stale_term"] is True
        # /role and /cancel are fenced the same way
        assert rq.post(f"{base}/role", json={"role": "decode"},
                       headers=h1, timeout=10).status_code == 409
        assert rq.post(f"{base}/cancel", json={"request_tag": "t"},
                       headers=h1, timeout=10).status_code == 409
        # un-fenced callers (solo masters, direct clients) never 409
        assert rq.post(f"{base}/undrain", json={},
                       timeout=10).status_code == 200
        assert w.role == "decode" or True   # role flip above may apply
    finally:
        w.service.shutdown()


def test_master_steps_down_and_writes_nothing_when_fenced():
    m = Master(":memory:", ha_peers=["http://127.0.0.1:9"],
               ha_lease_ms=60000.0, ha_leader=True)
    try:
        assert m.ha.is_leader()
        fake = types.SimpleNamespace(
            status_code=409, headers={STALE_TERM_HEADER: "7"})
        with pytest.raises(_StaleTermError):
            m._check_fence(fake, {"id": 1})
        assert not m.ha.is_leader()
        assert m.ha.term == 7
        snap = m.metrics.snapshot()["counters"]
        assert snap["repl_stale_term_rejections"] == 1
        assert snap["ha_lease_lost"] == 1
        # the dispatch tail writes NOTHING for a fenced request
        rid = m.store.submit_request("m", "p")
        req = m.store.claim_next_pending()
        m._fail_sub(req, {"id": 1, "name": "w"},
                    _StaleTermError("fenced"))
        row = m.store.get_request(rid)
        assert row["status"] == "processing"     # untouched: not ours
        assert row["attempts"] == 0
        assert m.metrics.snapshot()["counters"]["requests_fenced"] == 1
    finally:
        m.stop()


def test_ship_ignores_409_from_stale_term_peer():
    # a peer 409ing at a LOWER term is not a lease conflict (HA
    # unconfigured on it, or a stale persisted term): the leader must
    # NOT depose itself on its word — that would flap leadership
    # forever, bumping in-flight attempts every takeover
    hac, _ = _controller(leader=True)
    assert hac.term == 1

    def fake_post(peer, body, _codes=iter([0, 2])):
        term = next(_codes)
        return types.SimpleNamespace(
            status_code=409, json=lambda: {"status": "stale",
                                           "term": term, "applied": 0})
    hac._post = fake_post
    hac._ship_all()
    assert hac.is_leader()           # term-0 409 ignored
    peer = next(iter(hac._peers.values()))
    assert "stale term 0" in peer.last_error
    hac._ship_all()
    assert not hac.is_leader()       # term-2 409 deposes as before
    assert hac.term == 2


# ---- durability barrier degradation (the satellite fix) ----------------

def test_repl_barrier_times_out_degrades_and_rearms():
    prev_journal = events_mod.get_journal()
    j = events_mod.EventJournal(ring=64)
    events_mod.set_journal(j)
    hac, ns = _controller(leader=True, lease_ms=150.0)
    try:
        hac.on_ops([("SELECT 1", [])])        # op-log head moves to 1
        t0 = time.time()
        assert hac.repl_barrier() is False    # nobody ever acks
        waited = time.time() - t0
        assert 0.2 <= waited < 2.0            # ~2 lease intervals
        assert ns.metrics.snapshot()["counters"][
            "repl_barrier_timeouts"] == 1
        lag = [e for e in j.tail(10) if e["type"] == "replication-lag"]
        assert lag and lag[-1]["data"]["barrier_timeout"] is True
        # circuit: while degraded, writes do not pay the wait again
        t0 = time.time()
        assert hac.repl_barrier() is False
        assert time.time() - t0 < 0.1
        # a peer ack catching up to the head re-arms the barrier
        peer = next(iter(hac._peers.values()))
        with hac._ack_cv:
            peer.acked = hac.oplog.seq()
            peer.last_ack_at = time.time()
        hac._barrier_down_until = 0.0
        t0 = time.time()
        assert hac.repl_barrier() is True
        assert time.time() - t0 < 0.1
    finally:
        events_mod.set_journal(prev_journal)


def test_repl_barrier_fails_when_deposed_mid_window():
    """Deposed between a commit and its barrier: the write lives only
    in a diverged store the next leader overwrites — the barrier must
    report failure (api_submit turns it into a retryable 503), never
    ack silent loss."""
    hac, _ = _controller(leader=True, lease_ms=150.0)
    hac.on_ops([("SELECT 1", [])])
    hac.step_down(5, reason="test")
    t0 = time.time()
    assert hac.repl_barrier() is False
    assert time.time() - t0 < 0.1          # no pointless wait either


def test_ship_all_heartbeats_peers_concurrently():
    """One dead peer's connect timeout must not starve the other
    peers' lease renewals (N>=3: a sequential sweep stretched the live
    standby's heartbeat period past its lease and promoted it)."""
    hac, _ = _controller(leader=True, peers=(
        "http://127.0.0.1:1", "http://127.0.0.1:2"))
    t0 = time.time()
    sent = {}

    def fake_post(peer, body):
        sent[peer.url] = time.time() - t0
        if peer.url.endswith(":1"):
            time.sleep(0.5)            # the black-holed peer
        raise ConnectionError("down")
    hac._post = fake_post
    hac._ship_all()
    assert len(sent) == 2
    # both frames left within the same instant, not serialized behind
    # the dead peer's stall
    assert all(dt < 0.3 for dt in sent.values()), sent


def test_handle_replicate_refreshes_lease_after_slow_apply():
    """A snapshot apply can legitimately outlast the lease (its read
    timeout is deliberately generous) and the leader's shipper thread
    is blocked on that very POST the whole time — the standby must
    re-stamp its lease deadline AFTER the apply, or it promotes the
    instant the apply commits and deposes a healthy leader."""
    hac, ns = _controller(leader=False, lease_ms=100.0)
    real_load = ns.store.load_tables

    def slow_load(snap):
        time.sleep(0.3)                # 3x the lease
        return real_load(snap)
    ns.store.load_tables = slow_load
    ack = hac.handle_replicate({
        "term": 1, "holder": "L", "lease_ms": 100.0,
        "snapshot": _store().dump_tables(), "seq_start": 1, "ops": []})
    assert ack["status"] == "success"
    assert hac._lease_deadline > time.time()   # refreshed post-apply


def test_repl_barrier_unblocks_on_step_down():
    """Deposed WHILE waiting: the ack will never come from the new
    regime — every blocked dispatch thread must observe the demotion
    at once, not sleep out its full two-lease window (and must not arm
    the degrade circuit for a lag that isn't one)."""
    hac, _ = _controller(leader=True, lease_ms=60000.0)
    hac.on_ops([("SELECT 1", [])])
    t = threading.Timer(0.15, lambda: hac.step_down(9, reason="test"))
    t.start()
    try:
        t0 = time.time()
        assert hac.repl_barrier() is False
        assert time.time() - t0 < 5.0      # nowhere near 2x60s
        assert hac._barrier_down_until == 0.0
    finally:
        t.cancel()


def test_terms_persist_and_restart_asserts_above():
    """A bootstrap leader persists its asserted term, and a deposed
    master persists the term that deposed it — so a restart (even with
    --ha-leader) always comes back ABOVE any term it held or observed
    and can never re-contest a lease at an equal term."""
    s = _store()
    hac1, _ = _controller(s, leader=True)
    assert hac1.term == 1 and s.get_meta("ha_term") == "1"
    hac1.step_down(7, reason="test")
    assert s.get_meta("ha_term") == "7"
    hac2, _ = _controller(s, leader=True)   # the supervisor's restart
    assert hac2.term == 8
    assert s.get_meta("ha_term") == "8"


# ---- /replicate frame validation ---------------------------------------

def test_handle_replicate_validates_and_applies_exactly_once():
    hac, ns = _controller(leader=False, lease_ms=60000.0)
    assert hac.handle_replicate({"term": "bogus"})[0] == 400
    # a standby boots DIVERGED (_applied=-1): an op frame before any
    # snapshot demands resync — a restarted standby holds none of the
    # pre-op-log state, so a replay from seq 1 would silently diverge
    ack = hac.handle_replicate({
        "term": 1, "holder": "L", "lease_ms": 60000.0, "seq_start": 1,
        "ops": [["SELECT 1", []]]})
    assert ack["status"] == "resync" and ack["applied"] == -1
    # ... and applied=-1 is exactly the shipper's snapshot-me signal:
    # first contact is a snapshot frame (here: an empty fresh store)
    ack = hac.handle_replicate({
        "term": 1, "holder": "L", "lease_ms": 60000.0,
        "snapshot": _store().dump_tables(), "seq_start": 1, "ops": []})
    assert ack["status"] == "success" and ack["applied"] == 0
    frame = {"term": 1, "holder": "L", "lease_ms": 60000.0,
             "seq_start": 1,
             "ops": [["INSERT INTO requests (model_name, prompt, "
                      "sampling, created_at) VALUES (?,?,?,?)",
                      ["m", "p", "{}", 0.0]],
                     ["UPDATE requests SET attempts=attempts+1 "
                      "WHERE id=1", []]]}
    ack = hac.handle_replicate(frame)
    assert ack["status"] == "success" and ack["applied"] == 2
    assert ns.store.get_request(1)["attempts"] == 1
    # at-least-once redelivery: the already-applied prefix is skipped,
    # the attempts bump applies exactly once
    ack = hac.handle_replicate(frame)
    assert ack["applied"] == 2
    assert ns.store.get_request(1)["attempts"] == 1
    # a sequence gap demands resync instead of applying out of order
    gap = dict(frame, seq_start=9,
               ops=[["UPDATE requests SET attempts=attempts+1 "
                     "WHERE id=1", []]])
    ack = hac.handle_replicate(gap)
    assert ack["status"] == "resync" and ack["applied"] == 2
    # a higher term displaces the holder; the old term then 409s with
    # the winning term so the stale leader steps down
    assert hac.handle_replicate({"term": 3, "holder": "M",
                                 "seq_start": 3, "ops": []}
                                )["status"] == "success"
    st, payload = hac.handle_replicate({"term": 1, "holder": "L",
                                        "seq_start": 3, "ops": []})
    assert st == 409 and payload["term"] == 3
    # equal-term split-brain guard: first holder seen wins
    st, payload = hac.handle_replicate({"term": 3, "holder": "IMPOSTOR",
                                        "seq_start": 3, "ops": []})
    assert st == 409


def test_handle_replicate_snapshot_then_stream():
    src = _store()
    src.add_node("w0", "127.0.0.1", 8100)
    rid = src.submit_request("m", "p", client_tag="ct-s")
    hac, ns = _controller(leader=False, lease_ms=60000.0)
    ack = hac.handle_replicate({
        "term": 1, "holder": "L", "lease_ms": 60000.0,
        "snapshot": src.dump_tables(), "seq_start": 1, "ops": []})
    assert ack["status"] == "success" and ack["applied"] == 0
    assert ns.store.get_request(rid)["prompt"] == "p"
    assert ns.store.find_client_tag("ct-s") == rid
    # the stream that follows replays onto the snapshot's rowids
    ack = hac.handle_replicate({
        "term": 1, "holder": "L", "seq_start": 1,
        "ops": [["INSERT INTO requests (model_name, prompt, sampling, "
                 "created_at) VALUES (?,?,?,?)", ["m", "p2", "{}", 0.0]]]})
    assert ack["applied"] == 1
    assert ns.store.get_request(rid + 1)["prompt"] == "p2"


# ---- live pair e2e ------------------------------------------------------


def test_live_pair_replication_redirect_takeover():
    """A real leader subprocess + in-proc standby: writes replicate,
    either master is a valid entry point, and SIGKILL promotes the
    standby within the lease budget with the takeover reconstructable
    from its journal. (The loaded-fleet version with in-flight
    exactly-once accounting is ``bench.py --scenario ha --smoke``.)"""
    lease_ms = 500.0
    lport = _free_port()
    leader_base = f"http://127.0.0.1:{lport}"
    standby = Master(":memory:", ha_peers=[leader_base],
                     ha_lease_ms=lease_ms, ha_repl_barrier=True,
                     health_interval=0.5, rebalance=False,
                     dispatcher_threads=1, tsdb_step_s=0.5)
    # serve HTTP only: the takeover monitor (start_background) must not
    # arm until the leader subprocess is up and heartbeating, or the
    # standby takes the lease during the leader's slow boot
    ssrv = standby.service.serve("127.0.0.1", 0, background=True)
    standby_base = f"http://127.0.0.1:{ssrv.server_address[1]}"
    worker = WorkerAgent(auth_key=None)
    wsrv = worker.serve("127.0.0.1", 0, background=True)
    env = dict(os.environ, DLI_HA_PEERS=standby_base,
               DLI_HA_LEASE_MS=str(lease_ms), DLI_HA_REPL_BARRIER="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.Popen(
        [sys.executable, "-m",
         "distributed_llm_inferencing_tpu.runtime.master",
         "--host", "127.0.0.1", "--port", str(lport),
         "--db", ":memory:", "--ha-leader"],
        env=env, cwd=str(REPO),
        stdout=open("/tmp/dli_test_ha_leader.log", "w"),
        stderr=subprocess.STDOUT)
    try:
        deadline = time.time() + 120
        while time.time() < deadline:
            try:
                if rq.get(f"{leader_base}/health",
                          timeout=2).status_code == 200:
                    break
            except Exception:
                time.sleep(0.2)
        else:
            pytest.fail("leader subprocess never came up "
                        "(/tmp/dli_test_ha_leader.log)")
        # the leader's first heartbeat refreshes the standby's lease
        # deadline through /replicate before the monitor arms
        deadline = time.time() + 30
        while time.time() < deadline:
            if rq.get(f"{standby_base}/api/ha",
                      timeout=5).json().get("holder"):
                break
            time.sleep(0.05)
        standby.start_background()

        ha = rq.get(f"{leader_base}/api/ha", timeout=5).json()
        assert ha["enabled"] and ha["is_leader"] and ha["term"] >= 1

        # leader discovery makes either master a valid entry point
        ld = rq.get(f"{standby_base}/api/leader", timeout=5).json()
        assert ld["is_leader"] is False
        sub = rq.post(f"{standby_base}/api/inference/submit",
                      json={"model_name": "m", "prompt": "p"},
                      allow_redirects=False, timeout=5)
        assert sub.status_code == 307
        assert sub.headers["Location"].startswith(leader_base)

        # leader-era writes replicate: a node row + a submitted request
        r = rq.post(f"{leader_base}/api/nodes/add",
                    json={"name": "w0", "host": "127.0.0.1",
                          "port": wsrv.server_address[1]},
                    timeout=30).json()
        assert r["status"] == "success"
        rid = rq.post(f"{leader_base}/api/inference/submit",
                      json={"model_name": "ghost-model", "prompt": "hi",
                            "client_tag": "live-1"},
                      timeout=30).json()["request_id"]
        # client_tag dedup survives the wire
        again = rq.post(f"{leader_base}/api/inference/submit",
                        json={"model_name": "ghost-model", "prompt": "hi",
                              "client_tag": "live-1"}, timeout=30).json()
        assert again["request_id"] == rid and again["deduped"] is True

        deadline = time.time() + 30
        while time.time() < deadline:
            st = rq.get(f"{standby_base}/api/inference/status/{rid}",
                        timeout=5).json()
            nodes = rq.get(f"{standby_base}/api/nodes/status",
                           timeout=5).json()["nodes"]
            if st.get("request") and any(n["name"] == "w0"
                                         for n in nodes):
                break
            time.sleep(0.1)
        else:
            pytest.fail("leader writes never reached the standby")
        assert rq.get(f"{leader_base}/api/ha", timeout=5).json()[
            "peers"][0]["acked_seq"] > 0

        # SIGKILL the leader: standby must hold the lease within the
        # takeover budget (boot-grace + 2 lease intervals of slack)
        os.kill(proc.pid, signal.SIGKILL)
        t0 = time.time()
        deadline = t0 + 60
        while time.time() < deadline:
            try:
                if rq.get(f"{standby_base}/api/ha",
                          timeout=2).json()["is_leader"]:
                    break
            except Exception:
                pass
            time.sleep(0.05)
        else:
            pytest.fail("standby never took the lease")
        ha = rq.get(f"{standby_base}/api/ha", timeout=5).json()
        assert ha["term"] >= 2

        def ev(etype):
            return rq.get(f"{standby_base}/api/events",
                          params={"type": etype},
                          timeout=5).json()["events"]

        assert len(ev("lease-acquired")) >= 1
        assert len(ev("takeover-recovery")) >= 1
        # the leader-era trail survived into the survivor's journal
        assert len(ev("node-added")) >= 1
        # and the replicated state is live on the survivor
        st = rq.get(f"{standby_base}/api/inference/status/{rid}",
                    timeout=5).json()
        assert st["request"]["id"] == rid
        assert any(n["name"] == "w0" for n in
                   rq.get(f"{standby_base}/api/nodes/status",
                          timeout=5).json()["nodes"])
    finally:
        try:
            proc.kill()
        except Exception:
            pass
        standby.stop()
        worker.service.shutdown()
