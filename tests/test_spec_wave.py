"""Wave-level batched speculation (runtime/batcher.py _step_spec_wave).

The contract: ONE fused draft+verify pass serves the whole active wave
with per-slot draft widths as data, each request arbitrated by its OWN
AdaptiveSpecController — a draft-hostile request converges to width 0
and rides the wave's verify pass as plain decode (no wave-wide fallback
cliff), greedy token content is bitwise invariant to the width
assignment, and the lockstep broadcast carries everything a follower
needs to replay the identical programs.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(17)


def _drain(b, reqs, limit=600):
    for _ in range(limit):
        b.step()
        if all(r.done.is_set() for r in reqs):
            for r in reqs:
                assert r.error is None, r.error
            return
    raise AssertionError("batcher did not drain")


def _mk(spec_wave, speculative="ngram", slots=4, spec_gamma=3,
        spec_adaptive=None, small_chunks=True):
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=256, block_size=8,
                          slots=slots, max_seq=160,
                          speculative=speculative, spec_gamma=spec_gamma,
                          spec_adaptive=spec_adaptive,
                          spec_wave=spec_wave)
    if small_chunks:
        b.DECODE_CHUNKS = (4, 2, 1)   # many chunks -> many decisions
    return b


def _repetitive(n=24):
    base = RNG.integers(0, CFG.vocab_size, 4).tolist()
    return (base * (n // 4 + 2))[:n]


def _run(b, prompts, n=24, sampling=None, seed0=900):
    reqs = [b.submit(p, max_new_tokens=n,
                     sampling=sampling or SamplingParams.greedy(),
                     seed=seed0 + i) for i, p in enumerate(prompts)]
    _drain(b, reqs)
    return [r.tokens for r in reqs], reqs


# ---- bitwise greedy parity (the acceptance bar) -----------------------


def test_greedy_bitwise_wave_on_off_and_plain():
    """Greedy outputs identical across: plain batcher, wave-off
    speculation, wave-on speculation — mixed repetitive/random prompts
    so both accepted-heavy and miss-heavy slots are exercised."""
    prompts = [_repetitive(), RNG.integers(0, 256, 11).tolist(),
               _repetitive(20), RNG.integers(0, 256, 7).tolist()]
    plain, _ = _run(ContinuousBatcher(CFG, PARAMS, num_blocks=256,
                                      block_size=8, slots=4, max_seq=160),
                    prompts)
    off, _ = _run(_mk(spec_wave=False), prompts)
    on, _ = _run(_mk(spec_wave=True), prompts)
    assert on == plain
    assert off == plain


def test_wave_drafts_actually_accept():
    """On a repetitive workload the wave path must land accepted drafts
    (tokens-per-weight-pass > 1) and count them in the wave metrics."""
    b = _mk(spec_wave=True)
    prompts = [_repetitive() for _ in range(4)]
    _run(b, prompts, n=32)
    snap = b.metrics.snapshot()["counters"]
    assert snap.get("spec_wave_dispatches", 0) > 0
    assert snap.get("spec_wave_accepted_tokens", 0) > 0
    assert snap["spec_wave_accepted_tokens"] \
        <= snap["spec_wave_drafted_tokens"]
    assert b.stats()["spec_accepted_tokens"] > 0
    # amortization: accepted drafts mean strictly more tokens than
    # weight passes over the run
    assert snap["batcher_tokens_emitted"] > snap["batcher_weight_passes"]


# ---- per-slot heterogeneity: no wave-wide cliff -----------------------


def test_hostile_slot_rides_wave_while_friendly_keeps_drafting():
    """One draft-hostile request (top_k=0 full-vocab sampling: acceptance
    is zero BY DESIGN, ops/speculative.py) shares the wave with three
    repetitive greedy requests. Pre-wave behavior was a global fallback
    cliff; wave mode must keep the friendly slots drafting (accepted
    tokens keep growing) while the hostile request's own controller
    falls back — and its tokens stay bit-identical to the plain batcher
    (uncovered rows draw the plain chunk's exact sample)."""
    sp_hostile = SamplingParams(temperature=1.0, top_k=0, top_p=1.0)
    b = _mk(spec_wave=True)
    friendly = [b.submit(_repetitive(), max_new_tokens=48,
                         sampling=SamplingParams.greedy(), seed=10 + i)
                for i in range(3)]
    hostile_prompt = RNG.integers(0, CFG.vocab_size, 24).tolist()
    hostile = b.submit(hostile_prompt, max_new_tokens=48,
                       sampling=sp_hostile, seed=77)
    _drain(b, friendly + [hostile])

    # the hostile request's own controller gave up drafting...
    assert hostile._spec_ctl is not None
    assert hostile._spec_ctl.mode == "plain", hostile._spec_ctl.stats()
    # ...while the friendly ones kept it on (no wave-wide cliff)
    for r in friendly:
        assert r._spec_ctl.mode == "spec", r._spec_ctl.stats()
        assert r._spec_acc > 0
    # hostile slot rode shared verify passes as plain decode
    snap = b.metrics.snapshot()["counters"]
    assert snap.get("spec_wave_plain_rides", 0) > 0

    # bit-identical to the plain batcher for the hostile request
    pb = ContinuousBatcher(CFG, PARAMS, num_blocks=256, block_size=8,
                           slots=4, max_seq=160)
    pr = pb.submit(hostile_prompt, max_new_tokens=48, sampling=sp_hostile,
                   seed=77)
    _drain(pb, [pr])
    assert hostile.tokens == pr.tokens


@pytest.mark.slow   # covered in check.sh's dedicated step; the per-slot
                    # heterogeneity invariant stays in bare tier-1 via
                    # test_hostile_slot_rides_wave_while_friendly_keeps_drafting
def test_all_hostile_wave_falls_back_to_true_plain_chunks():
    """When EVERY request converges to width 0 the step runs real plain
    programs (not degenerate all-zero verify passes) — visible as plain
    controller modes and bit-identical output."""
    sp = SamplingParams(temperature=1.0, top_k=0, top_p=1.0)
    prompts = [RNG.integers(0, CFG.vocab_size, 20).tolist()
               for _ in range(4)]
    b = _mk(spec_wave=True)
    toks, reqs = _run(b, prompts, n=40, sampling=sp, seed0=300)
    for r in reqs:
        assert r._spec_ctl.mode == "plain", r._spec_ctl.stats()
    plain, _ = _run(ContinuousBatcher(CFG, PARAMS, num_blocks=256,
                                      block_size=8, slots=4, max_seq=160),
                    prompts, n=40, sampling=sp, seed0=300)
    assert toks == plain


def test_zero_gamma_wave_runs_plain_without_controllers():
    """spec_gamma=0 under wave mode: an explicit zero-draft request —
    no per-request controllers, plain chunks, plain-identical output."""
    b = _mk(spec_wave=True, spec_gamma=0, small_chunks=False)
    prompts = [[1, 2, 3, 4, 5, 6, 7, 8]]
    toks, reqs = _run(b, prompts, n=8)
    assert reqs[0]._spec_ctl is None
    assert b.stats()["spec_accepted_tokens"] == 0
    plain, _ = _run(ContinuousBatcher(CFG, PARAMS, num_blocks=256,
                                      block_size=8, slots=4, max_seq=160),
                    prompts, n=8)
    assert toks == plain


def test_fixed_width_wave_without_adaptivity():
    """spec_adaptive=False pins every slot at the full static width —
    wave dispatches happen, no controllers exist, greedy parity holds."""
    b = _mk(spec_wave=True, spec_adaptive=False)
    prompts = [_repetitive(), _repetitive(20)]
    toks, reqs = _run(b, prompts, n=16)
    for r in reqs:
        assert r._spec_ctl is None
    assert b.metrics.snapshot()["counters"]["spec_wave_dispatches"] > 0
    plain, _ = _run(ContinuousBatcher(CFG, PARAMS, num_blocks=256,
                                      block_size=8, slots=4, max_seq=160),
                    prompts, n=16)
    assert toks == plain


# ---- ledger + stats ----------------------------------------------------


def test_cost_ledger_attributes_draft_and_verify_tokens():
    b = _mk(spec_wave=True)
    prompts = [_repetitive() for _ in range(4)]
    _, reqs = _run(b, prompts, n=32)
    for r in reqs:
        cost = r.cost
        assert cost is not None
        assert cost["spec_drafted_tokens"] > 0
        assert cost["spec_accepted_tokens"] + cost["spec_rejected_tokens"] \
            == cost["spec_drafted_tokens"]
        assert cost["weight_passes"] > 0 and cost["decode_tokens"] > 0
    # speculation's whole point: the wave accepted drafts somewhere,
    # and the ledger's accounting reconciles with the wave counters
    snap = b.metrics.snapshot()["counters"]
    assert sum(r.cost["spec_accepted_tokens"] for r in reqs) \
        == snap["spec_wave_accepted_tokens"] > 0
    assert sum(r.cost["spec_drafted_tokens"] for r in reqs) \
        == snap["spec_wave_drafted_tokens"]


def test_spec_wave_stats_surface():
    b = _mk(spec_wave=True)
    reqs = [b.submit(_repetitive(), max_new_tokens=24,
                     sampling=SamplingParams.greedy(), seed=5)]
    for _ in range(3):
        b.step()
    st = b.stats()["spec_wave"]
    assert st is not None
    assert st["dispatches"] >= 1
    assert st["active_controllers"] >= 1
    _drain(b, reqs)
    assert _mk(spec_wave=False).stats()["spec_wave"] is None


def test_wave_metrics_reach_tsdb_catalog():
    """The telemetry plane must retain the amortization metrics: a scrape
    of the batcher's exposition ingested into the TSDB lands
    ``decode_tokens_per_weight_pass`` (gauge) and the ``spec_wave_*``
    counters (as rates) in the catalog — including BEFORE any decode ran
    (the batcher pre-registers them at 0, so 'no samples yet' can never
    read as 'metric not exported')."""
    from distributed_llm_inferencing_tpu.runtime.tsdb import TSDB
    from distributed_llm_inferencing_tpu.utils.metrics import (
        parse_prometheus)
    b = _mk(spec_wave=True)
    exposition = b.metrics.prometheus()       # pre-decode scrape
    ts = TSDB(window_s=60, step_s=1)
    ts.ingest_prometheus("w0", parse_prometheus(exposition), t=100.0)
    cat = ts.catalog()["w0"]
    assert "decode_tokens_per_weight_pass" in cat
    assert "spec_wave_dispatches" in cat
    assert "spec_wave_accepted_tokens" in cat
    assert "spec_wave_drafted_tokens" in cat
    # after a run the gauge carries the amortization signal
    _run(b, [_repetitive() for _ in range(2)], n=16)
    ts.ingest_prometheus("w0", parse_prometheus(b.metrics.prometheus()),
                         t=101.0)
    pts = ts.query("decode_tokens_per_weight_pass", node="w0", now=102.0)
    assert pts and pts[0]["points"]


def test_profiler_tags_spec_phases():
    """/api/profile attribution: wave chunks must land their wall time
    in the spec_draft / spec_verify phases, not plain dispatch."""
    from distributed_llm_inferencing_tpu.utils.profiler import PhaseProfiler
    b = _mk(spec_wave=True)
    b.profiler = PhaseProfiler(enabled=True, sample_every=1)
    _run(b, [_repetitive() for _ in range(2)], n=16)
    phases = b.profiler.summary()["phases"]
    assert "spec_verify" in phases, phases
    assert "spec_draft" in phases, phases
    assert phases["spec_verify"]["s"] > 0


# ---- lockstep replay ---------------------------------------------------


def test_wave_lockstep_broadcast_carries_widths_not_history():
    """The lockstep invariant under wave speculation: spec_decode
    broadcasts ship per-slot widths + history DELTAS (never the full
    history), and a follower replaying the JSON'd programs reconstructs
    the leader's drafting history and emits identical programs."""
    mk = lambda: ContinuousBatcher(  # noqa: E731
        CFG, PARAMS, num_blocks=64, block_size=8, slots=2, max_seq=96,
        seed=0, speculative="ngram", spec_gamma=3, spec_wave=True)
    leader, follower = mk(), mk()
    spec_payloads = []

    def hook(kind, args, run):
        wire = json.loads(json.dumps(args))   # prove JSON-safety
        if kind == "spec_decode":
            assert "hist" not in wire, "full history must not broadcast"
            assert "gammas" in wire and len(wire["gammas"]) == 2
            spec_payloads.append(wire)
        follower.replay(kind, wire)
        return run()

    leader.program_hook = hook
    prompts = [_repetitive(20), RNG.integers(0, 256, 7).tolist()]
    reqs = [leader.submit(p, max_new_tokens=12,
                          sampling=SamplingParams.greedy(), seed=9 + i)
            for i, p in enumerate(prompts)]
    for _ in range(60):
        leader.step()
        if all(r.done.is_set() for r in reqs):
            break
    outs = [r.wait() for r in reqs]
    assert all(len(o) == 12 for o in outs)
    assert spec_payloads, "wave speculative chunks must have dispatched"
    # delta amortization: only the first chunk after admission syncs rows
    assert spec_payloads[0]["hist_delta"], spec_payloads[0]
    for p in spec_payloads[1:]:
        assert p["hist_delta"] == [], p["hist_delta"]
    np.testing.assert_array_equal(follower._hist, leader._hist)


# ---- eos / streaming under wave widths --------------------------------


def test_wave_eos_and_stream_order():
    plain = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                              slots=2, max_seq=128, seed=0)
    prompt = _repetitive(18)
    r0 = plain.submit(prompt, max_new_tokens=10,
                      sampling=SamplingParams.greedy())
    _drain(plain, [r0])
    full = r0.tokens
    # first position whose token does not appear earlier: cutting there
    # is unambiguous even on a degenerate repetition loop
    cut = next((i for i in range(1, len(full))
                if full[i] not in full[:i]), None)
    if cut is None:
        pytest.skip("fully degenerate repetition: no usable eos")
    eos = full[cut]

    b = _mk(spec_wave=True, slots=2)
    seen = []
    r = b.submit(prompt, max_new_tokens=10,
                 sampling=SamplingParams.greedy(), eos_token_id=eos,
                 stream_cb=seen.append)
    _drain(b, [r])
    assert r.tokens == full[:cut]
    assert seen == r.tokens


@pytest.mark.slow   # ~10s of sampling; the dedicated check.sh step runs
                    # it (no -m filter there), bare tier-1 skips
def test_wave_sampled_distribution_against_noise_floor():
    """Sampled mode under wave widths: empirical distribution of the
    speculative-verified positions must sit within the plain-vs-plain
    sampling noise floor (same calibration as the pre-wave suite)."""
    prompt = (RNG.integers(0, 256, 4).tolist() * 5)[:18]
    sp = SamplingParams(temperature=1.2, top_k=8, top_p=0.95)
    n = 100

    def collect(wave, seed0):
        b = ContinuousBatcher(CFG, PARAMS, num_blocks=256, block_size=8,
                              slots=8, max_seq=64, seed=0,
                              speculative="ngram" if wave else None,
                              spec_gamma=2, spec_wave=True)
        reqs = [b.submit(prompt, max_new_tokens=3, sampling=sp,
                         seed=seed0 + s) for s in range(n)]
        _drain(b, reqs)
        counts = {}
        for r in reqs:
            for pos in (1, 2):
                key = (pos, r.tokens[pos])
                counts[key] = counts.get(key, 0) + 1
        return counts

    def tv(a, b):
        support = set(a) | set(b)
        return sum(abs(a.get(t, 0) - b.get(t, 0))
                   for t in support) / (2 * 2 * n)

    plain_a = collect(False, 0)
    plain_b = collect(False, 5000)
    wave_a = collect(True, 0)
    tv_null = tv(plain_a, plain_b)
    tv_wave = tv(wave_a, plain_a)
    assert tv_wave < 1.5 * tv_null + 0.08, (tv_wave, tv_null)
