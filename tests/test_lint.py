"""dlilint suite: each checker catches its seeded-violation fixture AND
runs clean on the real tree.

The fixtures are tiny synthetic repos built in tmp_path and handed to
the checkers through a hand-assembled ``Ctx`` — the same entry points
``python -m tools.dlilint`` drives, minus the repo-root discovery. The
clean-tree assertions are the actual CI gate duplicated in-process, so
a regression that sneaks past scripts/check.sh still fails the tier-1
suite.
"""

import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.dlilint import CHECKERS, run_all
from tools.dlilint.core import Ctx, SourceFile, load_lifecycle, repo_root
from tools.dlilint import check_events, check_jit, check_knobs, \
    check_lifecycle, check_metrics, check_rpc, check_threads, check_time


def _sf(tmp_path, rel, source):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return SourceFile.load(str(p), str(tmp_path))


def _ctx(tmp_path, **kw):
    kw.setdefault("package_files", [])
    kw.setdefault("runtime_files", [])
    kw.setdefault("gate_files", [])
    kw.setdefault("doc_paths", [])
    return Ctx(root=str(tmp_path), **kw)


def _rules(violations):
    return sorted(v.rule for v in violations)


# ---- knobs checker -----------------------------------------------------

def test_knobs_unregistered_read_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        X = os.environ.get("DLI_FAKE_KNOB", "1")
        Y = os.getenv("DLI_OTHER_KNOB")
        Z = os.environ["DLI_SUBSCRIPT_KNOB"]
        """)
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={}))
    assert _rules(out) == ["knob-unregistered"] * 3
    names = {v.msg.split()[2] for v in out}
    assert names == {"DLI_FAKE_KNOB", "DLI_OTHER_KNOB",
                     "DLI_SUBSCRIPT_KNOB"}


def test_knobs_name_through_module_constant_resolved(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        KNOB = "DLI_INDIRECT_KNOB"
        V = os.environ.get(KNOB, "0")
        """)
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={}))
    assert len(out) == 1 and "DLI_INDIRECT_KNOB" in out[0].msg


def test_knobs_dead_registry_row_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", "x = 1\n")
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={"DLI_GHOST": object()}))
    assert _rules(out) == ["knob-dead"]


def test_knobs_doc_dead_token_caught(tmp_path):
    doc = tmp_path / "docs" / "serving.md"
    doc.parent.mkdir()
    doc.write_text("Set `DLI_NO_SUCH_KNOB=1` to win.\n")
    out = check_knobs.check(_ctx(tmp_path, doc_paths=[str(doc)],
                                 knob_registry={}))
    assert _rules(out) == ["knob-doc-dead"]
    assert "DLI_NO_SUCH_KNOB" in out[0].msg


def test_knobs_pragma_suppresses(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        # dlilint: disable=knob-unregistered
        X = os.environ.get("DLI_WAIVED_KNOB")
        """)
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={}))
    assert out == []


def test_knobs_shell_read_counts_as_code_read(tmp_path):
    sh = tmp_path / "scripts" / "check.sh"
    sh.parent.mkdir()
    sh.write_text('if [[ "${DLI_SHELL_ONLY:-}" == "1" ]]; then :; fi\n'
                  'DLI_ARMED_FOR_CHILD=1 python x.py\n')
    # the expansion is a read; the assignment form is not
    reads = {n for _, _, n in check_knobs.collect_shell_reads([str(sh)])}
    assert reads == {"DLI_SHELL_ONLY"}
    out = check_knobs.check(_ctx(
        tmp_path, shell_paths=[str(sh)],
        knob_registry={"DLI_SHELL_ONLY": object()}))
    assert out == []   # registered shell-only knob is not knob-dead


def test_knobs_internal_underscore_names_exempt(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        X = os.environ.get("_DLI_PRIVATE_HANDSHAKE")
        """)
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={}))
    assert out == []


# ---- metrics checker ---------------------------------------------------

_REGISTERING_MOD = """\
    class M:
        def __init__(self, metrics):
            self.metrics = metrics
            self.metrics.inc("good_counter", 0)
            self.metrics.gauge("good_gauge", 0.0)
            self.metrics.inc("unseeded_counter")   # registered, not at 0

        def step(self):
            self.metrics.observe("good_latency", 0.1)
            for key, mname in (("a", "looped_counter"),):
                self.metrics.inc(mname, 0)
    """


def test_metrics_dashboard_unregistered_series_caught(tmp_path):
    pkg = _sf(tmp_path, "pkg/mod.py", _REGISTERING_MOD)
    dash = _sf(tmp_path, "pkg/dashboard_html.py", """\
        PAGE = '''
        const TS_METRICS = [
          ['good_counter', 'fine'],
          ['ghost_series', 'boom'],
        ];
        '''
        """)
    out = check_metrics.check(_ctx(tmp_path, package_files=[pkg],
                                   dashboard_file=dash))
    assert [v.rule for v in out] == ["metric-unregistered"]
    assert "ghost_series" in out[0].msg


def test_metrics_not_preregistered_caught(tmp_path):
    pkg = _sf(tmp_path, "pkg/mod.py", _REGISTERING_MOD)
    dash = _sf(tmp_path, "pkg/dashboard_html.py", """\
        PAGE = '''
        const TS_METRICS = [
          ['unseeded_counter', 'exists but invisible until first inc'],
          ['looped_counter', 'pre-registered through the loop idiom'],
        ];
        '''
        """)
    out = check_metrics.check(_ctx(tmp_path, package_files=[pkg],
                                   dashboard_file=dash))
    assert _rules(out) == ["metric-not-preregistered"]
    assert "unseeded_counter" in out[0].msg


def test_metrics_doc_counter_without_total_caught(tmp_path):
    pkg = _sf(tmp_path, "pkg/mod.py", _REGISTERING_MOD)
    doc = tmp_path / "docs" / "observability.md"
    doc.parent.mkdir()
    doc.write_text("Watch `dli_good_counter` (sic) and "
                   "`dli_good_counter_total` and `dli_good_gauge` and "
                   "`dli_good_latency_seconds` and `dli_nonexistent_total`.\n")
    out = check_metrics.check(_ctx(tmp_path, package_files=[pkg],
                                   doc_paths=[str(doc)]))
    assert _rules(out) == ["metric-counter-no-total", "metric-unregistered"]


def test_metrics_gate_series_and_fstring_patterns(tmp_path):
    pkg = _sf(tmp_path, "pkg/mod.py", """\
        class M:
            def pick(self, reason, metrics):
                metrics.inc(f"scheduler_pick_{reason}")
        """)
    gate = _sf(tmp_path, "bench.py", """\
        def report(mc):
            ok = mc.get("scheduler_pick_queue_depth", 0)
            bad = mc.get("totally_unknown_series", 0)
        """)
    out = check_metrics.check(_ctx(tmp_path, package_files=[pkg],
                                   gate_files=[gate]))
    assert [v.rule for v in out] == ["metric-unregistered"]
    assert "totally_unknown_series" in out[0].msg


# ---- jit purity checker ------------------------------------------------

def test_jit_impure_time_and_env_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        import time
        import jax

        def step(x):
            t0 = time.perf_counter()
            flag = os.environ.get("DLI_SPEC_WAVE")
            return x * t0

        fn = jax.jit(step)
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["jit-impure", "jit-impure"]


def test_jit_impure_through_callee_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import jax
        import numpy as np

        def noise(shape):
            return np.random.randn(*shape)

        def step(x):
            return x + noise(x.shape)

        fn = jax.jit(step)
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["jit-impure"]
    assert "np.random" in out[0].msg


def test_jit_logging_and_lock_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import jax
        import logging

        log = logging.getLogger("x")

        class Engine:
            def _block(self, x):
                log.info("tracing now")
                with self._lock:
                    y = x + 1
                return y

            def compile(self):
                return jax.jit(self._block)
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["jit-impure", "jit-impure"]


def test_jit_in_loop_caught_and_cached_ok(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import jax

        def bad(fs, xs):
            out = []
            for x in xs:
                fn = jax.jit(lambda v: v + 1)
                out.append(fn(x))
            return out

        def good(cache, key, f):
            if key not in cache:
                cache[key] = jax.jit(f)
            return cache[key]
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["jit-in-loop"]


def test_jit_pure_function_clean(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import jax
        import jax.numpy as jnp

        def step(x, w):
            return jnp.dot(x, w)

        fn = jax.jit(step, donate_argnums=(0,))
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert out == []


# ---- thread hygiene checker --------------------------------------------

def test_threads_silent_except_caught_and_pragma(tmp_path):
    sf = _sf(tmp_path, "pkg/runtime/mod.py", """\
        def flusher():
            try:
                flush()
            except Exception:
                pass

        def teardown():
            try:
                close()
            # dlilint: disable=silent-except
            except Exception:
                pass
        """)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf],
                                   runtime_files=[sf]))
    assert _rules(out) == ["silent-except"]
    assert out[0].line == 4


def test_threads_logged_except_clean(tmp_path):
    sf = _sf(tmp_path, "pkg/runtime/mod.py", """\
        import logging
        log = logging.getLogger("x")

        def flusher():
            try:
                flush()
            except Exception as e:
                log.warning("flush failed: %r", e)
        """)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf],
                                   runtime_files=[sf]))
    assert out == []


_CYCLING_CLASS = """\
    import threading

    class Biter:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one_way(self):
            with self._a:
                with self._b:
                    return 1

        def other_way(self):
            with self._b:
                with self._a:
                    return 2
    """


def test_threads_lock_cycle_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", _CYCLING_CLASS)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["lock-order-cycle"]
    assert "Biter._a" in out[0].msg and "Biter._b" in out[0].msg


def test_threads_cycle_through_method_call_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import threading

        class Sneaky:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def helper(self):
                with self._a:
                    return 1

            def outer(self):
                with self._b:
                    return self.helper()

            def direct(self):
                with self._a:
                    with self._b:
                        return 2
        """)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["lock-order-cycle"]


def test_threads_consistent_order_clean(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import threading

        class Fine:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def m1(self):
                with self._a:
                    with self._b:
                        return 1

            def m2(self):
                with self._a:
                    with self._b:
                        return 2
        """)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf]))
    assert out == []


def test_threads_locks_factory_recognized(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", _CYCLING_CLASS.replace(
        "threading.Lock()", 'locks.lock("x")').replace(
        "import threading", "from pkg.utils import locks"))
    out = check_threads.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["lock-order-cycle"]


# ---- rpc contract checker ----------------------------------------------

_RPC_WORKER_MOD = """\
    class W:
        def __init__(self, s):
            s.add("GET", "/health", self.health)
            s.add("POST", "/work", self.work)
            s.add("POST", "/work/<job_id>/retry", self.retry)
            s.add("POST", "/never_called", self.nope)

        def health(self, body):
            return {}

        def work(self, body):
            used = body.get("used")
            phantom = body.get("phantom_key_nobody_sends")
            return {"used": used, "phantom": phantom}

        def retry(self, body, job_id):
            return {}

        def nope(self, body):
            return {}
    """

_RPC_MASTER_MOD = """\
    class M:
        def _worker_get(self, node, path, timeout):
            pass

        def _worker_post(self, node, path, body, timeout):
            pass

        def go(self, node, jid):
            self._worker_get(node, "/health", 5)
            self._worker_post(node, "/work",
                              {"used": 1, "ghost": 2}, 5)
            self._worker_post(node, f"/work/{jid}/retry", {}, 5)
            self._worker_post(node, "/missing", {}, 5)
            self._worker_get(node, "/work", 5)
    """


def _rpc_ctx(tmp_path, worker_src=_RPC_WORKER_MOD,
             master_src=_RPC_MASTER_MOD, **kw):
    worker = _sf(tmp_path, "pkg/runtime/workerish.py", worker_src)
    master = _sf(tmp_path, "pkg/runtime/masterish.py", master_src)
    return _ctx(tmp_path, package_files=[worker, master], **kw), \
        worker, master


def test_rpc_unknown_path_and_method_mismatch_caught(tmp_path):
    ctx, _w, master = _rpc_ctx(tmp_path)
    out = check_rpc.check(ctx)
    rules = _rules(out)
    assert "rpc-unknown-path" in rules       # POST /missing
    assert "rpc-method-mismatch" in rules    # GET /work (POST-only)
    unknown = [v for v in out if v.rule == "rpc-unknown-path"]
    assert unknown[0].path == master.rel
    assert "/missing" in unknown[0].msg


def test_rpc_param_segments_match(tmp_path):
    """f-string path holes match <param> route segments — no false
    unknown-path on /work/<job_id>/retry."""
    ctx, *_ = _rpc_ctx(tmp_path)
    out = check_rpc.check(ctx)
    assert not any("retry" in v.msg for v in out
                   if v.rule == "rpc-unknown-path")


def test_rpc_dead_route_caught_and_doc_reference_clears(tmp_path):
    ctx, *_ = _rpc_ctx(tmp_path)
    out = check_rpc.check(ctx)
    dead = [v for v in out if v.rule == "rpc-dead-route"]
    assert len(dead) == 1 and "/never_called" in dead[0].msg
    # a doc mention is a reference: operator-facing routes live in docs
    doc = tmp_path / "docs" / "ops.md"
    doc.parent.mkdir(exist_ok=True)
    doc.write_text("Operators may `POST /never_called` to win.\n")
    ctx2, *_ = _rpc_ctx(tmp_path, doc_paths=[str(doc)])
    out2 = check_rpc.check(ctx2)
    assert not [v for v in out2 if v.rule == "rpc-dead-route"]


def test_rpc_quiet_set_typo_caught(tmp_path):
    quiet = _sf(tmp_path, "pkg/runtime/httpdish.py", """\
        QUIET_TRACE_PATHS = frozenset({"/health", "/helth_typo"})
        """)
    ctx, *_ = _rpc_ctx(tmp_path)
    ctx.package_files.append(quiet)
    out = check_rpc.check(ctx)
    quiets = [v for v in out if v.rule == "rpc-quiet-unknown"]
    assert len(quiets) == 1 and "/helth_typo" in quiets[0].msg


def test_rpc_fault_point_without_intercept_caught(tmp_path):
    tests = _sf(tmp_path, "tests/test_x.py", """\
        GOOD = {"point": "/work", "mode": "error"}
        ALSO_GOOD = {"point": "rpc:/work", "mode": "timeout"}
        GLOB = {"point": "/wor*", "mode": "reset"}
        BAD = {"point": "/work_typo", "mode": "error"}
        """)
    ctx, *_ = _rpc_ctx(tmp_path, test_files=[tests])
    out = check_rpc.check(ctx)
    faults = [v for v in out if v.rule == "rpc-fault-unknown"]
    assert len(faults) == 1 and "/work_typo" in faults[0].msg


def test_rpc_body_unread_and_unsent_caught(tmp_path):
    ctx, worker, master = _rpc_ctx(tmp_path)
    out = check_rpc.check(ctx)
    unread = [v for v in out if v.rule == "rpc-body-unread"]
    assert len(unread) == 1
    assert "'ghost'" in unread[0].msg and unread[0].path == master.rel
    unsent = [v for v in out if v.rule == "rpc-body-unsent"]
    assert len(unsent) == 1
    assert "phantom_key_nobody_sends" in unsent[0].msg
    assert unsent[0].path == worker.rel


def test_rpc_body_reads_follow_helpers(tmp_path):
    """Keys read by a helper the handler hands the body to count as
    read — no false unread on builder/validator splits."""
    worker_src = """\
        class W:
            def __init__(self, s):
                s.add("POST", "/work", self.work)

            def work(self, body):
                return self._inner(dict(body))

            def _inner(self, body):
                return body.get("used")
        """
    ctx, *_ = _rpc_ctx(tmp_path, worker_src=worker_src)
    out = check_rpc.check(ctx)
    # 'used' is read through dict(body) -> self._inner; 'ghost' (which
    # nothing reads) still fires
    unread = [v for v in out if v.rule == "rpc-body-unread"]
    assert not any("'used'" in v.msg for v in unread)
    assert any("'ghost'" in v.msg for v in unread)


def test_rpc_pragma_suppresses(tmp_path):
    master_src = _RPC_MASTER_MOD.replace(
        'self._worker_post(node, "/missing", {}, 5)',
        'self._worker_post(node, "/missing", {}, 5)  '
        '# dlilint: disable=rpc-unknown-path')
    ctx, *_ = _rpc_ctx(tmp_path, master_src=master_src)
    out = check_rpc.check(ctx)
    assert not any("/missing" in v.msg for v in out
                   if v.rule == "rpc-unknown-path")


# ---- lifecycle checker -------------------------------------------------

_LIFECYCLE = load_lifecycle(repo_root())


def _t(name, source, target, fn, guard, durability, counts_attempt):
    return _LIFECYCLE.Transition(name, source, target, fn, guard,
                                 durability, counts_attempt, "")


_LIFE_STATE_MOD = """\
    class Store:
        def mark_completed(self, rid):
            self._submit_write(
                "UPDATE requests SET status='completed' WHERE id=? "
                "AND status NOT IN ('completed','failed')", (rid,),
                barrier=True)

        def mark_failed(self, rid):
            self._exec(
                "UPDATE requests SET status='failed' WHERE id=?",
                (rid,))

        def vanish(self, rid):
            self._exec(
                "UPDATE requests SET status='vanished' WHERE id=?",
                (rid,))

        def requeue(self, rid):
            self._submit_write(
                "UPDATE requests SET status='pending' WHERE id=?",
                (rid,), barrier=True)
    """

_LIFE_TABLE = (
    _t("complete", ("processing",), "completed", "mark_completed",
       "not-terminal", "barrier", False),
    # declared barrier + where-guard, but the site uses _exec with no
    # WHERE status constraint -> lifecycle-barrier AND lifecycle-guard
    _t("fail", ("processing",), "failed", "mark_failed", "where",
       "barrier", False),
    # declared attempt accounting the SQL lacks -> lifecycle-attempts
    _t("requeue", ("processing",), "pending", "requeue", "none",
       "barrier", True),
    # declared transition with no site -> lifecycle-unused
    _t("ghost", ("pending",), "failed", "cancel_pending", "where",
       "sync-txn", False),
)


def test_lifecycle_fixture_catches_each_rule(tmp_path):
    sf = _sf(tmp_path, "pkg/runtime/state.py", _LIFE_STATE_MOD)
    out = check_lifecycle.check_sites(sf, _LIFE_TABLE)
    rules = _rules(out)
    assert "lifecycle-undeclared" in rules    # status='vanished'
    assert "lifecycle-barrier" in rules       # fail via _exec
    assert "lifecycle-guard" in rules         # fail without WHERE guard
    assert "lifecycle-attempts" in rules      # requeue w/o attempts+1
    assert "lifecycle-unused" in rules        # ghost
    # the correct site is NOT flagged
    assert not any("mark_completed" in v.msg or v.line == 2
                   for v in out if v.rule != "lifecycle-unused")


def test_lifecycle_clean_fixture_passes(tmp_path):
    sf = _sf(tmp_path, "pkg/runtime/state.py", """\
        class Store:
            def mark_completed(self, rid):
                self._submit_write(
                    "UPDATE requests SET status='completed' "
                    "WHERE id=? AND status NOT IN "
                    "('completed','failed')", (rid,), barrier=True)
        """)
    table = (_t("complete", ("processing",), "completed",
                "mark_completed", "not-terminal", "barrier", False),)
    assert check_lifecycle.check_sites(sf, table) == []


def test_lifecycle_locked_select_guard(tmp_path):
    src = """\
        class Store:
            def claim(self):
                with self._lock:
                    rows = self._all(
                        "SELECT * FROM requests WHERE "
                        "status='pending' LIMIT 1")
                    with self._db:
                        self._db.executemany(
                            "UPDATE requests SET status='processing' "
                            "WHERE id=?", [(1,)])
        """
    sf = _sf(tmp_path, "pkg/runtime/state.py", src)
    table = (_t("claim", ("pending",), "processing", "claim",
                "locked-select", "sync-txn", False),)
    assert check_lifecycle.check_sites(sf, table) == []
    # drop the lock: the locked-select guard must fail
    sf2 = _sf(tmp_path, "pkg/runtime/state2.py", src.replace(
        "with self._lock:", "if True:"))
    out = check_lifecycle.check_sites(sf2, table)
    # losing the lock breaks BOTH the locked-select guard and the
    # sync-txn durability claim
    assert _rules(out) == ["lifecycle-barrier", "lifecycle-guard"]


def test_lifecycle_diagram_byte_checked(tmp_path):
    doc = tmp_path / "robustness.md"
    doc.write_text("# Robustness\n\nno diagram yet\n")
    out = check_lifecycle.check_diagram(str(doc), _LIFECYCLE)
    assert _rules(out) == ["lifecycle-diagram-stale"]
    assert check_lifecycle.write_lifecycle_diagram(str(doc), _LIFECYCLE)
    assert check_lifecycle.check_diagram(str(doc), _LIFECYCLE) == []
    # drift by one byte -> stale again
    doc.write_text(doc.read_text().replace("pending", "pending ", 1))
    out = check_lifecycle.check_diagram(str(doc), _LIFECYCLE)
    assert _rules(out) == ["lifecycle-diagram-stale"]
    # idempotent regenerate restores byte equality
    assert check_lifecycle.write_lifecycle_diagram(str(doc), _LIFECYCLE)
    assert not check_lifecycle.write_lifecycle_diagram(str(doc),
                                                       _LIFECYCLE)


def test_lifecycle_declared_machine_is_sane():
    """The committed table covers the four states, reaches both
    terminals, and every terminal transition declares a durability
    mechanism."""
    ts = _LIFECYCLE.TRANSITIONS
    assert {t.target for t in ts} == set(_LIFECYCLE.STATES)
    for t in ts:
        if t.target in _LIFECYCLE.TERMINAL:
            assert t.durability in ("barrier", "sync-txn")
    assert any(t.counts_attempt for t in ts)


# ---- events checker ----------------------------------------------------

class _EvDecl:
    def __init__(self, doc="documented"):
        self.doc = doc
        self.fields = ()


def test_events_undeclared_emit_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        from distributed_llm_inferencing_tpu.runtime import events
        events.emit("ghost-event", node_id=1)
        events.emit("real-event")
        """)
    out = check_events.check(_ctx(
        tmp_path, package_files=[sf],
        event_registry={"real-event": _EvDecl()}))
    assert _rules(out) == ["event-undeclared"]
    assert "ghost-event" in out[0].msg


def test_events_self_attribute_emit_resolved(tmp_path):
    """The master's ``self.events.emit(...)`` form counts as an emit
    site too (the dotted callee ends in events.emit)."""
    sf = _sf(tmp_path, "pkg/mod.py", """\
        class M:
            def go(self):
                self.events.emit("real-event", node_id=1)
        """)
    out = check_events.check(_ctx(
        tmp_path, package_files=[sf],
        event_registry={"real-event": _EvDecl()}))
    assert out == []


def test_events_unemitted_declared_type_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", "x = 1\n")
    out = check_events.check(_ctx(
        tmp_path, package_files=[sf],
        event_registry={"never-fired": _EvDecl()}))
    assert _rules(out) == ["event-unemitted"]
    assert "never-fired" in out[0].msg


def test_events_undoc_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        from distributed_llm_inferencing_tpu.runtime import events
        events.emit("bare-event")
        """)
    out = check_events.check(_ctx(
        tmp_path, package_files=[sf],
        event_registry={"bare-event": _EvDecl(doc="  ")}))
    assert _rules(out) == ["event-undoc"]


def test_events_pragma_suppresses(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        from distributed_llm_inferencing_tpu.runtime import events
        # dlilint: disable=event-undeclared
        events.emit("waived-event")
        """)
    out = check_events.check(_ctx(tmp_path, package_files=[sf],
                                  event_registry={}))
    assert out == []


def test_events_table_stale_caught(tmp_path):
    """A drifted (or missing) generated block in observability.md fails;
    write_event_table repairs it to a fixed point."""
    from tools.dlilint.core import load_events
    events_mod = load_events(repo_root())
    doc = tmp_path / "docs" / "observability.md"
    doc.parent.mkdir()
    doc.write_text("# Observability\n")
    sf = _sf(tmp_path, "pkg/mod.py", "\n".join(
        f'events.emit("{name}")' for name in events_mod.registry()) + "\n")
    ctx = _ctx(tmp_path, package_files=[sf],
               event_registry=events_mod.registry(),
               events_mod=events_mod,
               observability_md=str(doc))
    out = check_events.check(ctx)
    assert _rules(out) == ["event-table-stale"]
    assert check_events.write_event_table(str(doc), events_mod)
    assert check_events.check(ctx) == []
    # idempotent: a second write is a no-op
    assert not check_events.write_event_table(str(doc), events_mod)
    # hand edits to the block fail again
    doc.write_text(doc.read_text().replace("| `breaker-open` |",
                                           "| `breaker-open!!` |"))
    out = check_events.check(ctx)
    assert _rules(out) == ["event-table-stale"]


def test_events_real_registry_fully_emitted():
    """Acceptance: three-way parity on the committed tree — every
    declared type has a live emit site and the docs appendix is the
    registry's exact rendering (the byte check runs via
    test_real_tree_clean; this pins the emit-site leg explicitly)."""
    ctx = Ctx.for_repo()
    emitted = {name for _, _, name in
               check_events.collect_emit_sites(
                   ctx.package_files + ctx.gate_files)}
    declared = set(ctx.event_registry)
    assert declared <= emitted, (
        f"declared-but-never-emitted: {sorted(declared - emitted)}")
    assert emitted <= declared, (
        f"emitted-but-undeclared: {sorted(emitted - declared)}")


# ---- the real tree is the fixture for "runs clean" ---------------------

# ---- time checker ------------------------------------------------------

def test_time_direct_call_and_bare_ref_caught(tmp_path):
    """Calls AND bare references: a ``default_factory=time.time``
    stamps rows just as directly as a call does."""
    sf = _sf(tmp_path, "pkg/runtime/mod.py", """\
        import time
        t0 = time.time()
        m = time.monotonic
        def nap():
            time.sleep(1.0)
        """)
    out = check_time.check(_ctx(tmp_path, runtime_files=[sf]))
    assert _rules(out) == ["time-direct"] * 3


def test_time_from_import_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/runtime/mod.py", """\
        from time import sleep, perf_counter
        """)
    out = check_time.check(_ctx(tmp_path, runtime_files=[sf]))
    # sleep is seamed; perf_counter measures the host and stays legal
    assert _rules(out) == ["time-direct"]


def test_time_host_measurement_exempt(tmp_path):
    """perf_counter/time_ns measure the host, not the cluster
    timeline — the virtual clock must never warp them."""
    sf = _sf(tmp_path, "pkg/runtime/mod.py", """\
        import time
        a = time.perf_counter()
        b = time.time_ns()
        c = time.strftime("%F")
        """)
    assert check_time.check(_ctx(tmp_path, runtime_files=[sf])) == []


def test_time_outside_runtime_not_scanned(tmp_path):
    """The seam covers runtime/ only: bench harness, tools and tests
    legitimately measure wall time."""
    sf = _sf(tmp_path, "pkg/other/mod.py", """\
        import time
        t0 = time.time()
        """)
    assert check_time.check(_ctx(tmp_path, runtime_files=[],
                                 package_files=[sf])) == []


def test_time_pragma_suppression(tmp_path):
    sf = _sf(tmp_path, "pkg/runtime/mod.py", """\
        import time
        t0 = time.time()   # dlilint: disable=time-direct

        t1 = time.time()
        """)
    out = check_time.check(_ctx(tmp_path, runtime_files=[sf]))
    assert len(out) == 1 and out[0].line == 4


@pytest.fixture(scope="module")
def repo_results():
    return run_all()


@pytest.mark.parametrize("checker", sorted(CHECKERS))
def test_real_tree_clean(repo_results, checker):
    assert repo_results[checker] == [], (
        f"dlilint {checker} found violations on the committed tree — "
        f"run `python -m tools.dlilint` (docs/static_analysis.md)")


def test_knob_registry_three_way_parity():
    """Acceptance: code knobs == registry == docs, exactly. "Code"
    includes shell scripts: a check.sh-only knob (DLI_TSAN_FAST) is a
    knob like any other."""
    from distributed_llm_inferencing_tpu.utils import knobs
    ctx = Ctx.for_repo()
    reads = {name for _, _, name in
             check_knobs.collect_env_reads(
                 ctx.package_files + ctx.gate_files)}
    reads |= {name for _, _, name in
              check_knobs.collect_shell_reads(ctx.shell_paths)}
    assert reads == set(knobs.registry()), (
        "registry drifted from code reads")
    with open(ctx.serving_md, encoding="utf-8") as f:
        serving = f.read()
    missing = [n for n in knobs.registry() if n not in serving]
    assert not missing, f"knobs missing from docs/serving.md: {missing}"


def test_cli_exits_zero_on_clean_tree():
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-m", "tools.dlilint"],
                       cwd=root, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "— clean" in r.stdout
