"""dlilint suite: each checker catches its seeded-violation fixture AND
runs clean on the real tree.

The fixtures are tiny synthetic repos built in tmp_path and handed to
the checkers through a hand-assembled ``Ctx`` — the same entry points
``python -m tools.dlilint`` drives, minus the repo-root discovery. The
clean-tree assertions are the actual CI gate duplicated in-process, so
a regression that sneaks past scripts/check.sh still fails the tier-1
suite.
"""

import os
import sys
import textwrap

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tools.dlilint import CHECKERS, run_all
from tools.dlilint.core import Ctx, SourceFile
from tools.dlilint import check_jit, check_knobs, check_metrics, \
    check_threads


def _sf(tmp_path, rel, source):
    p = tmp_path / rel
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(source))
    return SourceFile.load(str(p), str(tmp_path))


def _ctx(tmp_path, **kw):
    kw.setdefault("package_files", [])
    kw.setdefault("runtime_files", [])
    kw.setdefault("gate_files", [])
    kw.setdefault("doc_paths", [])
    return Ctx(root=str(tmp_path), **kw)


def _rules(violations):
    return sorted(v.rule for v in violations)


# ---- knobs checker -----------------------------------------------------

def test_knobs_unregistered_read_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        X = os.environ.get("DLI_FAKE_KNOB", "1")
        Y = os.getenv("DLI_OTHER_KNOB")
        Z = os.environ["DLI_SUBSCRIPT_KNOB"]
        """)
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={}))
    assert _rules(out) == ["knob-unregistered"] * 3
    names = {v.msg.split()[2] for v in out}
    assert names == {"DLI_FAKE_KNOB", "DLI_OTHER_KNOB",
                     "DLI_SUBSCRIPT_KNOB"}


def test_knobs_name_through_module_constant_resolved(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        KNOB = "DLI_INDIRECT_KNOB"
        V = os.environ.get(KNOB, "0")
        """)
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={}))
    assert len(out) == 1 and "DLI_INDIRECT_KNOB" in out[0].msg


def test_knobs_dead_registry_row_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", "x = 1\n")
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={"DLI_GHOST": object()}))
    assert _rules(out) == ["knob-dead"]


def test_knobs_doc_dead_token_caught(tmp_path):
    doc = tmp_path / "docs" / "serving.md"
    doc.parent.mkdir()
    doc.write_text("Set `DLI_NO_SUCH_KNOB=1` to win.\n")
    out = check_knobs.check(_ctx(tmp_path, doc_paths=[str(doc)],
                                 knob_registry={}))
    assert _rules(out) == ["knob-doc-dead"]
    assert "DLI_NO_SUCH_KNOB" in out[0].msg


def test_knobs_pragma_suppresses(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        # dlilint: disable=knob-unregistered
        X = os.environ.get("DLI_WAIVED_KNOB")
        """)
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={}))
    assert out == []


def test_knobs_shell_read_counts_as_code_read(tmp_path):
    sh = tmp_path / "scripts" / "check.sh"
    sh.parent.mkdir()
    sh.write_text('if [[ "${DLI_SHELL_ONLY:-}" == "1" ]]; then :; fi\n'
                  'DLI_ARMED_FOR_CHILD=1 python x.py\n')
    # the expansion is a read; the assignment form is not
    reads = {n for _, _, n in check_knobs.collect_shell_reads([str(sh)])}
    assert reads == {"DLI_SHELL_ONLY"}
    out = check_knobs.check(_ctx(
        tmp_path, shell_paths=[str(sh)],
        knob_registry={"DLI_SHELL_ONLY": object()}))
    assert out == []   # registered shell-only knob is not knob-dead


def test_knobs_internal_underscore_names_exempt(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        X = os.environ.get("_DLI_PRIVATE_HANDSHAKE")
        """)
    out = check_knobs.check(_ctx(tmp_path, package_files=[sf],
                                 knob_registry={}))
    assert out == []


# ---- metrics checker ---------------------------------------------------

_REGISTERING_MOD = """\
    class M:
        def __init__(self, metrics):
            self.metrics = metrics
            self.metrics.inc("good_counter", 0)
            self.metrics.gauge("good_gauge", 0.0)
            self.metrics.inc("unseeded_counter")   # registered, not at 0

        def step(self):
            self.metrics.observe("good_latency", 0.1)
            for key, mname in (("a", "looped_counter"),):
                self.metrics.inc(mname, 0)
    """


def test_metrics_dashboard_unregistered_series_caught(tmp_path):
    pkg = _sf(tmp_path, "pkg/mod.py", _REGISTERING_MOD)
    dash = _sf(tmp_path, "pkg/dashboard_html.py", """\
        PAGE = '''
        const TS_METRICS = [
          ['good_counter', 'fine'],
          ['ghost_series', 'boom'],
        ];
        '''
        """)
    out = check_metrics.check(_ctx(tmp_path, package_files=[pkg],
                                   dashboard_file=dash))
    assert [v.rule for v in out] == ["metric-unregistered"]
    assert "ghost_series" in out[0].msg


def test_metrics_not_preregistered_caught(tmp_path):
    pkg = _sf(tmp_path, "pkg/mod.py", _REGISTERING_MOD)
    dash = _sf(tmp_path, "pkg/dashboard_html.py", """\
        PAGE = '''
        const TS_METRICS = [
          ['unseeded_counter', 'exists but invisible until first inc'],
          ['looped_counter', 'pre-registered through the loop idiom'],
        ];
        '''
        """)
    out = check_metrics.check(_ctx(tmp_path, package_files=[pkg],
                                   dashboard_file=dash))
    assert _rules(out) == ["metric-not-preregistered"]
    assert "unseeded_counter" in out[0].msg


def test_metrics_doc_counter_without_total_caught(tmp_path):
    pkg = _sf(tmp_path, "pkg/mod.py", _REGISTERING_MOD)
    doc = tmp_path / "docs" / "observability.md"
    doc.parent.mkdir()
    doc.write_text("Watch `dli_good_counter` (sic) and "
                   "`dli_good_counter_total` and `dli_good_gauge` and "
                   "`dli_good_latency_seconds` and `dli_nonexistent_total`.\n")
    out = check_metrics.check(_ctx(tmp_path, package_files=[pkg],
                                   doc_paths=[str(doc)]))
    assert _rules(out) == ["metric-counter-no-total", "metric-unregistered"]


def test_metrics_gate_series_and_fstring_patterns(tmp_path):
    pkg = _sf(tmp_path, "pkg/mod.py", """\
        class M:
            def pick(self, reason, metrics):
                metrics.inc(f"scheduler_pick_{reason}")
        """)
    gate = _sf(tmp_path, "bench.py", """\
        def report(mc):
            ok = mc.get("scheduler_pick_queue_depth", 0)
            bad = mc.get("totally_unknown_series", 0)
        """)
    out = check_metrics.check(_ctx(tmp_path, package_files=[pkg],
                                   gate_files=[gate]))
    assert [v.rule for v in out] == ["metric-unregistered"]
    assert "totally_unknown_series" in out[0].msg


# ---- jit purity checker ------------------------------------------------

def test_jit_impure_time_and_env_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import os
        import time
        import jax

        def step(x):
            t0 = time.perf_counter()
            flag = os.environ.get("DLI_SPEC_WAVE")
            return x * t0

        fn = jax.jit(step)
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["jit-impure", "jit-impure"]


def test_jit_impure_through_callee_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import jax
        import numpy as np

        def noise(shape):
            return np.random.randn(*shape)

        def step(x):
            return x + noise(x.shape)

        fn = jax.jit(step)
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["jit-impure"]
    assert "np.random" in out[0].msg


def test_jit_logging_and_lock_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import jax
        import logging

        log = logging.getLogger("x")

        class Engine:
            def _block(self, x):
                log.info("tracing now")
                with self._lock:
                    y = x + 1
                return y

            def compile(self):
                return jax.jit(self._block)
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["jit-impure", "jit-impure"]


def test_jit_in_loop_caught_and_cached_ok(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import jax

        def bad(fs, xs):
            out = []
            for x in xs:
                fn = jax.jit(lambda v: v + 1)
                out.append(fn(x))
            return out

        def good(cache, key, f):
            if key not in cache:
                cache[key] = jax.jit(f)
            return cache[key]
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["jit-in-loop"]


def test_jit_pure_function_clean(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import jax
        import jax.numpy as jnp

        def step(x, w):
            return jnp.dot(x, w)

        fn = jax.jit(step, donate_argnums=(0,))
        """)
    out = check_jit.check(_ctx(tmp_path, package_files=[sf]))
    assert out == []


# ---- thread hygiene checker --------------------------------------------

def test_threads_silent_except_caught_and_pragma(tmp_path):
    sf = _sf(tmp_path, "pkg/runtime/mod.py", """\
        def flusher():
            try:
                flush()
            except Exception:
                pass

        def teardown():
            try:
                close()
            # dlilint: disable=silent-except
            except Exception:
                pass
        """)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf],
                                   runtime_files=[sf]))
    assert _rules(out) == ["silent-except"]
    assert out[0].line == 4


def test_threads_logged_except_clean(tmp_path):
    sf = _sf(tmp_path, "pkg/runtime/mod.py", """\
        import logging
        log = logging.getLogger("x")

        def flusher():
            try:
                flush()
            except Exception as e:
                log.warning("flush failed: %r", e)
        """)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf],
                                   runtime_files=[sf]))
    assert out == []


_CYCLING_CLASS = """\
    import threading

    class Biter:
        def __init__(self):
            self._a = threading.Lock()
            self._b = threading.Lock()

        def one_way(self):
            with self._a:
                with self._b:
                    return 1

        def other_way(self):
            with self._b:
                with self._a:
                    return 2
    """


def test_threads_lock_cycle_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", _CYCLING_CLASS)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["lock-order-cycle"]
    assert "Biter._a" in out[0].msg and "Biter._b" in out[0].msg


def test_threads_cycle_through_method_call_caught(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import threading

        class Sneaky:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def helper(self):
                with self._a:
                    return 1

            def outer(self):
                with self._b:
                    return self.helper()

            def direct(self):
                with self._a:
                    with self._b:
                        return 2
        """)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["lock-order-cycle"]


def test_threads_consistent_order_clean(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", """\
        import threading

        class Fine:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def m1(self):
                with self._a:
                    with self._b:
                        return 1

            def m2(self):
                with self._a:
                    with self._b:
                        return 2
        """)
    out = check_threads.check(_ctx(tmp_path, package_files=[sf]))
    assert out == []


def test_threads_locks_factory_recognized(tmp_path):
    sf = _sf(tmp_path, "pkg/mod.py", _CYCLING_CLASS.replace(
        "threading.Lock()", 'locks.lock("x")').replace(
        "import threading", "from pkg.utils import locks"))
    out = check_threads.check(_ctx(tmp_path, package_files=[sf]))
    assert _rules(out) == ["lock-order-cycle"]


# ---- the real tree is the fixture for "runs clean" ---------------------

@pytest.fixture(scope="module")
def repo_results():
    return run_all()


@pytest.mark.parametrize("checker", sorted(CHECKERS))
def test_real_tree_clean(repo_results, checker):
    assert repo_results[checker] == [], (
        f"dlilint {checker} found violations on the committed tree — "
        f"run `python -m tools.dlilint` (docs/static_analysis.md)")


def test_knob_registry_three_way_parity():
    """Acceptance: code knobs == registry == docs, exactly. "Code"
    includes shell scripts: a check.sh-only knob (DLI_TSAN_FAST) is a
    knob like any other."""
    from distributed_llm_inferencing_tpu.utils import knobs
    ctx = Ctx.for_repo()
    reads = {name for _, _, name in
             check_knobs.collect_env_reads(
                 ctx.package_files + ctx.gate_files)}
    reads |= {name for _, _, name in
              check_knobs.collect_shell_reads(ctx.shell_paths)}
    assert reads == set(knobs.registry()), (
        "registry drifted from code reads")
    with open(ctx.serving_md, encoding="utf-8") as f:
        serving = f.read()
    missing = [n for n in knobs.registry() if n not in serving]
    assert not missing, f"knobs missing from docs/serving.md: {missing}"


def test_cli_exits_zero_on_clean_tree():
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-m", "tools.dlilint"],
                       cwd=root, capture_output=True, text=True,
                       timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "— clean" in r.stdout
