"""Pallas interpret-mode parity suite: every hand-written kernel diffed
against its XLA reference formulation on CPU.

The kernels (ops/pallas/) are the TPU-compiled fast path; the XLA
formulations are the always-available oracle. This suite pins them
together in tier-1 so a kernel edit can't silently diverge: odd shapes,
batch > 1, masked tails (context lengths mid-block), every quantized
weight form, and the end-to-end batcher greedy parity for the fused
decode step behind ``DLI_FUSED_DECODE``.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.attention import attend_prefill
from distributed_llm_inferencing_tpu.ops.pallas import flash_attention
from distributed_llm_inferencing_tpu.ops.pallas.fused_decode import (
    fused_decode_step, rope_cos_sin, supported)
from distributed_llm_inferencing_tpu.ops.pallas.paged_attention import (
    paged_flash_decode)
from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
    paged_attend_decode)
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams


def _rand(rng, *shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


# ---- quant_matmul: int4 dequant-GEMV kernel vs the XLA unpack ---------

def _q4_ref(x, p4, scale, chunks=1):
    from distributed_llm_inferencing_tpu.ops.quant import unpack_int4
    w = unpack_int4(np.asarray(p4), chunks).astype(np.float32)
    return np.asarray(x, np.float32) @ w * np.asarray(scale, np.float32)


@pytest.mark.parametrize("rows,din,dout", [
    (1, 64, 128),      # decode GEMV
    (4, 64, 192),      # batch > 1, dout off the 128 tile
    (8, 128, 384),     # tile boundary + ragged final block
    (3, 96, 160),      # odd-ish everything (din still even)
])
def test_q4_matmul_matches_xla_unpack(rows, din, dout):
    from distributed_llm_inferencing_tpu.ops.pallas.quant_matmul import (
        q4_matmul)
    from distributed_llm_inferencing_tpu.ops.quant import (
        quantize_weight_int4)
    rng = np.random.default_rng(rows * din)
    w = rng.normal(size=(din, dout)).astype(np.float32)
    leaf = quantize_weight_int4(jnp.asarray(w))
    x = rng.normal(size=(rows, din)).astype(np.float32)
    ref = _q4_ref(x, leaf["p4"], leaf["scale"])
    out = q4_matmul(jnp.asarray(x), leaf["p4"], leaf["scale"],
                    interpret=True)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_q4_matmul_row_chunked_matches_xla_unpack():
    """The row-parallel (chunk-local packed) variant: single-device body
    must honor the chunked layout, matching the unpack reference."""
    from distributed_llm_inferencing_tpu.ops.pallas.quant_matmul import (
        q4_matmul_row)
    from distributed_llm_inferencing_tpu.ops.quant import (
        quantize_weight_int4, repack_int4_rows)
    rng = np.random.default_rng(7)
    din, dout, chunks = 128, 256, 2
    w = rng.normal(size=(din, dout)).astype(np.float32)
    leaf = repack_int4_rows(quantize_weight_int4(jnp.asarray(w)), chunks)
    x = rng.normal(size=(2, din)).astype(np.float32)
    ref = _q4_ref(x, leaf["p4"], leaf["scale"], chunks=chunks)
    out = q4_matmul_row(jnp.asarray(x), leaf["p4"], leaf["scale"],
                        interpret=True, chunks=chunks)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


# ---- flash_attention: odd shapes beyond test_pallas_attention's -------

@pytest.mark.parametrize("B,S,H,Hkv,hd", [
    (3, 40, 6, 2, 16),     # odd batch, S with no pow2 block fit
    (1, 24, 2, 1, 8),      # tiny head_dim (tiny-llama shape), MQA
])
def test_flash_prefill_odd_shapes(B, S, H, Hkv, hd):
    rng = np.random.default_rng(B * S)
    q, k, v = (_rand(rng, B, S, H, hd), _rand(rng, B, S, Hkv, hd),
               _rand(rng, B, S, Hkv, hd))
    ref = attend_prefill(q, k, v, backend="xla")
    out = flash_attention(q, k, v, block_q=16, block_kv=16, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---- paged_flash_decode: block-table kernel vs the gather formulation --

def _paged_pool(rng, nb, bs, hkv, hd):
    return (_rand(rng, nb, bs, hkv, hd), _rand(rng, nb, bs, hkv, hd))


@pytest.mark.parametrize("lens", [
    [5, 17, 32, 1],        # masked tails mid-block + a full block + 1
    [9, 9, 9, 9],          # uniform
    [31, 2, 16, 7],        # block-boundary -1 / cross-block mix
])
def test_paged_flash_decode_matches_gather(lens):
    rng = np.random.default_rng(sum(lens))
    r, nb, bs, mb, h, hkv, hd = len(lens), 32, 8, 4, 4, 2, 16
    k_pool, v_pool = _paged_pool(rng, nb, bs, hkv, hd)
    bt = np.zeros((r, mb), np.int32)
    used = set([0])
    for i in range(r):
        for j in range(mb):
            b = int(rng.integers(1, nb))
            while b in used:
                b = int(rng.integers(1, nb))
            used.add(b)
            bt[i, j] = b
    q = _rand(rng, r, 1, h, hd)
    lens_a = jnp.asarray(lens, jnp.int32)
    ref = paged_attend_decode(q, k_pool, v_pool, jnp.asarray(bt), lens_a,
                              backend="xla")
    out = paged_flash_decode(q, k_pool, v_pool, jnp.asarray(bt), lens_a,
                             interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_paged_flash_decode_sliding_window():
    rng = np.random.default_rng(11)
    r, nb, bs, mb, h, hkv, hd = 2, 16, 8, 3, 4, 4, 16
    k_pool, v_pool = _paged_pool(rng, nb, bs, hkv, hd)
    bt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    lens = jnp.asarray([20, 13], jnp.int32)
    q = _rand(rng, r, 1, h, hd)
    ref = paged_attend_decode(q, k_pool, v_pool, jnp.asarray(bt), lens,
                              sliding_window=6, backend="xla")
    out = paged_flash_decode(q, k_pool, v_pool, jnp.asarray(bt), lens,
                             sliding_window=6, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


# ---- fused_decode_step: dequant-GEMV -> RoPE -> paged attention -------

def _fused_ref(cfg, x, q_leaf, k_pool, v_pool, bt, lens, positions,
               sliding_window=None):
    """The unfused oracle: XLA q projection + apply_rope + gather
    attention — exactly the ops the kernel chains."""
    from distributed_llm_inferencing_tpu.models.transformer import _linear
    from distributed_llm_inferencing_tpu.ops.rope import apply_rope
    r, d = x.shape
    hd = k_pool.shape[-1]
    q = _linear(x[:, None], q_leaf)
    h = q.shape[-1] // hd
    q = q.reshape(r, 1, h, hd)
    if positions is not None:
        q = apply_rope(q, positions[:, None], cfg.rope_theta,
                       cfg.rope_pct, cfg.rope_interleaved,
                       inv_freq=cfg.rope_inv_freq,
                       attn_factor=cfg.rope_attn_factor)
    return paged_attend_decode(q, k_pool, v_pool, bt, lens,
                               sliding_window=sliding_window,
                               backend="xla")[:, 0]


def _quant_leaf(w, form):
    if form == "float":
        return {"w": jnp.asarray(w)}
    if form == "int8":
        from distributed_llm_inferencing_tpu.ops.quant import quantize_weight
        return quantize_weight(jnp.asarray(w))
    from distributed_llm_inferencing_tpu.ops.quant import (
        quantize_weight_int4)
    return quantize_weight_int4(jnp.asarray(w))


@pytest.mark.parametrize("form", ["float", "int8", "int4"])
@pytest.mark.parametrize("gqa", ["gqa", "mqa", "mha"])
def test_fused_decode_step_matches_unfused(form, gqa):
    cfg = get_config("tiny-llama").replace(dtype="float32")
    rng = np.random.default_rng(hash((form, gqa)) % 2**31)
    hkv = {"gqa": 2, "mqa": 1, "mha": 4}[gqa]
    r, nb, bs, mb, h, hd, d = 3, 16, 8, 3, 4, 16, 32
    k_pool, v_pool = _paged_pool(rng, nb, bs, hkv, hd)
    bt = np.asarray([[1, 2, 3], [4, 5, 6], [7, 8, 9]], np.int32)
    lens = jnp.asarray([7, 21, 12], jnp.int32)
    positions = lens - 1
    x = rng.normal(size=(r, d)).astype(np.float32)
    w = rng.normal(size=(d, h * hd)).astype(np.float32) / np.sqrt(d)
    leaf = _quant_leaf(w, form)
    cos, sin = rope_cos_sin(cfg, positions, hd)
    ref = _fused_ref(cfg, jnp.asarray(x), leaf, k_pool, v_pool,
                     jnp.asarray(bt), lens, positions)
    out = fused_decode_step(jnp.asarray(x), leaf, k_pool, v_pool,
                            jnp.asarray(bt), lens, rope_cos=cos,
                            rope_sin=sin, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)


def test_fused_decode_step_no_rope_and_window():
    """Positional-free q (learned/none embeddings) and a sliding window."""
    cfg = get_config("tiny-llama").replace(dtype="float32")
    rng = np.random.default_rng(23)
    r, nb, bs, mb, hkv, h, hd, d = 2, 16, 8, 3, 2, 4, 16, 32
    k_pool, v_pool = _paged_pool(rng, nb, bs, hkv, hd)
    bt = np.asarray([[1, 2, 3], [4, 5, 6]], np.int32)
    lens = jnp.asarray([19, 8], jnp.int32)
    x = rng.normal(size=(r, d)).astype(np.float32)
    leaf = {"w": jnp.asarray(
        rng.normal(size=(d, h * hd)).astype(np.float32))}
    ref = _fused_ref(cfg, jnp.asarray(x), leaf, k_pool, v_pool,
                     jnp.asarray(bt), lens, None, sliding_window=5)
    out = fused_decode_step(jnp.asarray(x), leaf, k_pool, v_pool,
                            jnp.asarray(bt), lens, sliding_window=5,
                            interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)


def test_fused_supported_gate():
    cfg = get_config("tiny-llama")
    assert supported(cfg)
    assert not supported(cfg.replace(qk_norm="rms_head"))
    assert not supported(cfg.replace(rope_interleaved=True))
    assert not supported(cfg.replace(attn_softcap=30.0))
    assert not supported(cfg.replace(kv_quant="int8"))
    assert not supported(cfg, {"w": None, "b": None})   # biased q leaf


# ---- end-to-end: batcher greedy parity with DLI_FUSED_DECODE ----------

def _batch_tokens(monkeypatch, fused: bool, quant=None, spec=False):
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    monkeypatch.setenv("DLI_FUSED_DECODE", "1" if fused else "0")
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    if quant:
        cfg = cfg.replace(quant=quant)
    b = ContinuousBatcher(
        cfg, params, num_blocks=128, block_size=8, slots=4, max_seq=96,
        seed=0, speculative="ngram" if spec else None, spec_gamma=3)
    rng = np.random.default_rng(5)
    base = rng.integers(0, 256, 4).tolist()
    prompts = [(base * 6)[:20], rng.integers(0, 256, 9).tolist(),
               rng.integers(0, 256, 13).tolist()]
    reqs = [b.submit(p, max_new_tokens=12, sampling=SamplingParams.greedy(),
                     seed=50 + i) for i, p in enumerate(prompts)]
    for _ in range(200):
        b.step()
        if all(r.done.is_set() for r in reqs):
            break
    return [r.wait() for r in reqs]


@pytest.mark.slow   # two full batcher runs per form — the suite's most
                    # exhaustive parametrization; check.sh's dedicated
                    # pallas step runs it (no -m filter), and the
                    # kernel-level fused parity grid above stays in the
                    # bare tier-1 command's budget
@pytest.mark.parametrize("quant", [None, "int8"])
def test_batcher_greedy_bitwise_fused_on_off(monkeypatch, quant):
    """The acceptance bar: greedy decode through the continuous batcher
    is bitwise identical with DLI_FUSED_DECODE on and off."""
    off = _batch_tokens(monkeypatch, fused=False, quant=quant)
    on = _batch_tokens(monkeypatch, fused=True, quant=quant)
    assert on == off


@pytest.mark.slow   # three full batcher runs; check.sh's dedicated step
                    # runs it (no -m filter), bare tier-1 keeps the
                    # two-run fused on/off parity below
def test_batcher_greedy_bitwise_fused_with_spec_wave(monkeypatch):
    """Fused decode composes with wave speculation: spec chunks keep the
    side-buffer program, plain rides (and all-plain fallback chunks) go
    through the fused stepwise path — tokens identical either way."""
    off = _batch_tokens(monkeypatch, fused=False, spec=True)
    on = _batch_tokens(monkeypatch, fused=True, spec=True)
    plain = _batch_tokens(monkeypatch, fused=False, spec=False)
    assert on == off == plain
