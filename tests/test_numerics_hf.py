"""Golden-parity tests: our JAX forward vs HF transformers (torch, CPU).

This is the property the reference conspicuously never verified (SURVEY.md
§4): that the framework's compute matches the source checkpoints. We build
tiny random HF models from configs (fully offline) and require logits to
agree to float32 tolerance.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models import convert, transformer
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache


def _logits_ours(cfg, params, tokens):
    B, S = tokens.shape
    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    lengths = jnp.full((B,), S, jnp.int32)
    logits, _ = transformer.prefill(params, cfg, jnp.asarray(tokens), lengths, cache)
    return np.asarray(logits)


def _check_model(hf_model, tokens, atol=2e-3):
    import torch
    cfg, params = convert.load_hf_model(hf_model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")
    with torch.no_grad():
        ref = hf_model(torch.tensor(tokens)).logits.float().numpy()
    ours = _logits_ours(cfg, params, tokens)
    np.testing.assert_allclose(ours, ref, atol=atol, rtol=1e-3)


def test_gpt2_matches_hf():
    import transformers
    torch_cfg = transformers.GPT2Config(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=3, n_head=4)
    import torch
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(torch_cfg).eval()
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 128, size=(2, 12), dtype=np.int64)
    _check_model(model, tokens)


def test_llama_gqa_matches_hf():
    import transformers
    torch_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        tie_word_embeddings=False, attention_bias=False)
    import torch
    torch.manual_seed(1)
    model = transformers.LlamaForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_mistral_sliding_window_matches_hf():
    import transformers
    torch_cfg = transformers.MistralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=4,
        tie_word_embeddings=False)
    import torch
    torch.manual_seed(2)
    model = transformers.MistralForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(2)
    tokens = rng.integers(0, 128, size=(1, 16), dtype=np.int64)
    _check_model(model, tokens)


def test_opt_matches_hf():
    import transformers
    torch_cfg = transformers.OPTConfig(
        vocab_size=128, hidden_size=32, ffn_dim=64, num_hidden_layers=3,
        num_attention_heads=4, max_position_embeddings=64,
        word_embed_proj_dim=32, do_layer_norm_before=True)
    import torch
    torch.manual_seed(3)
    model = transformers.OPTForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(3)
    tokens = rng.integers(0, 128, size=(2, 8), dtype=np.int64)
    _check_model(model, tokens)


def test_opt_350m_arch_matches_hf():
    """The opt-350m shape: word_embed_proj_dim < hidden (project_in/out)
    plus post-LN blocks and no final norm (reference supported this arch
    via shard_model.py:46-50; the TPU build must serve the real
    checkpoint)."""
    import transformers
    torch_cfg = transformers.OPTConfig(
        vocab_size=128, hidden_size=32, ffn_dim=64, num_hidden_layers=3,
        num_attention_heads=4, max_position_embeddings=64,
        word_embed_proj_dim=16, do_layer_norm_before=False)
    import torch
    torch.manual_seed(6)
    model = transformers.OPTForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.embed_proj_dim == 16 and cfg.post_norm
    rng = np.random.default_rng(6)
    tokens = rng.integers(0, 128, size=(2, 8), dtype=np.int64)
    _check_model(model, tokens)


def test_opt_350m_decode_matches_hf_generate():
    """Greedy decode through the dense cache ≡ HF generate for the
    post-LN + projected-embedding arch (exercises decode_step, not just
    prefill)."""
    import torch
    import transformers
    torch_cfg = transformers.OPTConfig(
        vocab_size=128, hidden_size=32, ffn_dim=64, num_hidden_layers=2,
        num_attention_heads=4, max_position_embeddings=64,
        word_embed_proj_dim=16, do_layer_norm_before=False)
    torch.manual_seed(7)
    model = transformers.OPTForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    rng = np.random.default_rng(7)
    prompt = rng.integers(4, 128, size=(1, 6), dtype=np.int64)
    with torch.no_grad():
        want = model.generate(
            torch.tensor(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0)[0, 6:].tolist()

    cache = init_cache(cfg, 1, 32, dtype=jnp.float32)
    logits, cache = transformer.prefill(
        params, cfg, jnp.asarray(prompt.astype(np.int32)),
        jnp.asarray([6], jnp.int32), cache)
    cur = int(np.argmax(np.asarray(logits)[0, 5]))
    got = [cur]
    for _ in range(7):
        logits, cache = transformer.decode_step(
            params, cfg, jnp.asarray([[cur]], jnp.int32), cache)
        cur = int(np.argmax(np.asarray(logits)[0, 0]))
        got.append(cur)
    assert got == want


def test_mixtral_matches_hf():
    import transformers
    torch_cfg = transformers.MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, num_local_experts=4,
        num_experts_per_tok=2, tie_word_embeddings=False,
        sliding_window=None)
    import torch
    torch.manual_seed(4)
    model = transformers.MixtralForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(4)
    tokens = rng.integers(0, 128, size=(1, 8), dtype=np.int64)
    _check_model(model, tokens)


def test_ragged_prefill_matches_unpadded():
    """Right-padded batched prefill must give the same logits (at valid
    positions) as running each sequence alone."""
    import transformers, torch
    torch_cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=32, n_embd=16, n_layer=2, n_head=2)
    torch.manual_seed(5)
    model = transformers.GPT2LMHeadModel(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    rng = np.random.default_rng(5)
    a = rng.integers(0, 64, size=(1, 9), dtype=np.int64)
    b = rng.integers(0, 64, size=(1, 5), dtype=np.int64)
    padded = np.zeros((2, 9), dtype=np.int64)
    padded[0] = a[0]
    padded[1, :5] = b[0]

    cache = init_cache(cfg, 2, 16, dtype=jnp.float32)
    logits, _ = transformer.prefill(
        params, cfg, jnp.asarray(padded), jnp.asarray([9, 5], jnp.int32), cache)
    sole_a = _logits_ours(cfg, params, a)
    sole_b = _logits_ours(cfg, params, b)
    np.testing.assert_allclose(np.asarray(logits)[0, :9], sole_a[0], atol=1e-4, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(logits)[1, :5], sole_b[0], atol=1e-4, rtol=1e-4)


def test_qwen2_matches_hf():
    """Qwen2: llama layout + bias on q/k/v only (o_proj bias-free)."""
    import transformers
    torch_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, rope_theta=10000.0,
        tie_word_embeddings=False, use_sliding_window=False)
    import torch
    torch.manual_seed(8)
    model = transformers.Qwen2ForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.attn_bias and cfg.o_bias is False
    assert cfg.sliding_window is None   # declared but not applied by HF
    rng = np.random.default_rng(8)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_gemma_matches_hf():
    """Gemma: (1+w) rmsnorm (absorbed at conversion), sqrt(D) embedding
    normalizer, tanh-gelu gated MLP, head_dim > hidden/heads, tied head."""
    import transformers
    torch_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, max_position_embeddings=64, rope_theta=10000.0,
        hidden_activation="gelu_pytorch_tanh")
    import torch
    torch.manual_seed(9)
    model = transformers.GemmaForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.tie_word_embeddings and cfg.norm_offset
    assert cfg.head_dim == 16 and cfg.embed_scale == 32 ** 0.5
    rng = np.random.default_rng(9)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_gemma_decode_matches_hf_generate():
    """Greedy decode parity for the gemma deltas (embed scale must apply
    on the decode path too, and the MQA cache must round-trip)."""
    import torch
    import transformers
    torch_cfg = transformers.GemmaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16, max_position_embeddings=64,
        hidden_activation="gelu_pytorch_tanh")
    torch.manual_seed(10)
    model = transformers.GemmaForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    rng = np.random.default_rng(10)
    prompt = rng.integers(4, 128, size=(1, 6), dtype=np.int64)
    with torch.no_grad():
        want = model.generate(
            torch.tensor(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0)[0, 6:].tolist()

    cache = init_cache(cfg, 1, 32, dtype=jnp.float32)
    logits, cache = transformer.prefill(
        params, cfg, jnp.asarray(prompt.astype(np.int32)),
        jnp.asarray([6], jnp.int32), cache)
    cur = int(np.argmax(np.asarray(logits)[0, 5]))
    got = [cur]
    for _ in range(7):
        logits, cache = transformer.decode_step(
            params, cfg, jnp.asarray([[cur]], jnp.int32), cache)
        cur = int(np.argmax(np.asarray(logits)[0, 0]))
        got.append(cur)
    assert got == want


def test_gpt_neox_matches_hf():
    """GPT-NeoX/Pythia: parallel-residual blocks, per-head-interleaved
    fused QKV, partial rotary (rotary_pct), exact (erf) gelu, untied
    embed_out head."""
    import transformers
    torch_cfg = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.25,
        use_parallel_residual=True, tie_word_embeddings=False)
    import torch
    torch.manual_seed(11)
    model = transformers.GPTNeoXForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.parallel_residual and cfg.rope_pct == 0.25
    assert cfg.activation == "gelu_exact"
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_gpt_neox_sequential_residual_matches_hf():
    """use_parallel_residual=False NeoX variants run the sequential
    two-residual block — the conversion must carry the flag through."""
    import transformers
    torch_cfg = transformers.GPTNeoXConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, rotary_pct=0.5,
        use_parallel_residual=False, tie_word_embeddings=False)
    import torch
    torch.manual_seed(12)
    model = transformers.GPTNeoXForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert not cfg.parallel_residual
    rng = np.random.default_rng(12)
    tokens = rng.integers(0, 128, size=(1, 9), dtype=np.int64)
    _check_model(model, tokens)


def test_phi_matches_hf():
    """Phi: parallel residual with ONE shared layernorm per block,
    partial rotary, biases everywhere including the untied lm_head."""
    import transformers
    torch_cfg = transformers.PhiConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        tie_word_embeddings=False)
    import torch
    torch.manual_seed(13)
    model = transformers.PhiForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.parallel_residual and cfg.shared_attn_mlp_norm
    assert cfg.lm_head_bias and "b" in params["lm_head"]
    assert "mlp_norm" not in params["layers"]
    rng = np.random.default_rng(13)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_falcon_mqa_matches_hf():
    """Falcon-7B layout: multi-query fused QKV (H query heads + 1 k +
    1 v), parallel residual, single shared norm, no biases, tied head."""
    import transformers
    torch_cfg = transformers.FalconConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=3,
        num_attention_heads=4, multi_query=True,
        new_decoder_architecture=False, parallel_attn=True, bias=False,
        alibi=False, max_position_embeddings=64)
    import torch
    torch.manual_seed(14)
    model = transformers.FalconForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.num_kv_heads == 1 and cfg.parallel_residual
    assert cfg.shared_attn_mlp_norm
    rng = np.random.default_rng(14)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_falcon_new_arch_matches_hf():
    """Falcon new decoder architecture (40B/180B layout): grouped-KV
    fused QKV with ln_attn + ln_mlp parallel norms."""
    import transformers
    torch_cfg = transformers.FalconConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, num_kv_heads=2,
        new_decoder_architecture=True, parallel_attn=True, bias=False,
        alibi=False, max_position_embeddings=64)
    import torch
    torch.manual_seed(15)
    model = transformers.FalconForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.num_kv_heads == 2 and not cfg.shared_attn_mlp_norm
    assert "mlp_norm" in params["layers"]
    rng = np.random.default_rng(15)
    tokens = rng.integers(0, 128, size=(1, 8), dtype=np.int64)
    _check_model(model, tokens)


def test_mpt_matches_hf():
    """MPT: ALiBi, straight-concat bias-free fused QKV, zero-bias
    layernorms, exact gelu, tied head."""
    import transformers
    torch_cfg = transformers.MptConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=3, max_seq_len=64)
    import torch
    torch.manual_seed(24)
    model = transformers.MptForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.position_embedding == "alibi" and not cfg.attn_bias
    assert cfg.tie_word_embeddings
    rng = np.random.default_rng(24)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_mpt_unsupported_attn_options_rejected():
    import transformers
    torch_cfg = transformers.MptConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2,
        attn_config=dict(qk_ln=True))
    with pytest.raises(NotImplementedError, match="qk_ln"):
        convert.config_from_hf(torch_cfg)
    torch_cfg = transformers.MptConfig(
        vocab_size=128, d_model=36, n_heads=6, n_layers=2)
    with pytest.raises(NotImplementedError, match="power-of-two"):
        convert.config_from_hf(torch_cfg)


def test_unsupported_model_type_names_supported_families():
    """The unsupported-architecture error must enumerate what converts."""
    class FakeCfg:
        model_type = "mamba"
    with pytest.raises(NotImplementedError, match="gpt_neox"):
        convert.config_from_hf(FakeCfg())


def test_phi_decode_matches_hf_generate():
    """Greedy decode parity for the phi deltas (shared-norm parallel
    block + partial rotary on the decode path, biased head)."""
    import torch
    import transformers
    torch_cfg = transformers.PhiConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        tie_word_embeddings=False)
    torch.manual_seed(16)
    model = transformers.PhiForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    rng = np.random.default_rng(16)
    prompt = rng.integers(4, 128, size=(1, 6), dtype=np.int64)
    with torch.no_grad():
        want = model.generate(
            torch.tensor(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0)[0, 6:].tolist()

    cache = init_cache(cfg, 1, 32, dtype=jnp.float32)
    logits, cache = transformer.prefill(
        params, cfg, jnp.asarray(prompt.astype(np.int32)),
        jnp.asarray([6], jnp.int32), cache)
    cur = int(np.argmax(np.asarray(logits)[0, 5]))
    got = [cur]
    for _ in range(7):
        logits, cache = transformer.decode_step(
            params, cfg, jnp.asarray([[cur]], jnp.int32), cache)
        cur = int(np.argmax(np.asarray(logits)[0, 0]))
        got.append(cur)
    assert got == want


def test_bloom_matches_hf():
    """BLOOM: ALiBi position bias, layernormed embedding output, per-head
    interleaved fused QKV, tied head."""
    import transformers
    torch_cfg = transformers.BloomConfig(
        vocab_size=128, hidden_size=32, n_layer=3, n_head=4,
        layer_norm_epsilon=1e-5)
    import torch
    torch.manual_seed(17)
    model = transformers.BloomForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.position_embedding == "alibi" and cfg.embed_norm
    assert "norm" in params["embed"]
    rng = np.random.default_rng(17)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_bloom_nonpow2_heads_matches_hf():
    """ALiBi slope interpolation for non-power-of-two head counts must
    match HF's build_alibi_tensor exactly."""
    import transformers
    torch_cfg = transformers.BloomConfig(
        vocab_size=128, hidden_size=36, n_layer=2, n_head=6)
    import torch
    torch.manual_seed(18)
    model = transformers.BloomForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(18)
    tokens = rng.integers(0, 128, size=(1, 9), dtype=np.int64)
    _check_model(model, tokens)


def test_bloom_decode_matches_hf_generate():
    """Greedy decode parity for ALiBi: the bias must track the query's
    absolute position on the cached decode path too."""
    import torch
    import transformers
    torch_cfg = transformers.BloomConfig(
        vocab_size=128, hidden_size=32, n_layer=2, n_head=4)
    torch.manual_seed(19)
    model = transformers.BloomForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    rng = np.random.default_rng(19)
    prompt = rng.integers(4, 128, size=(1, 6), dtype=np.int64)
    with torch.no_grad():
        want = model.generate(
            torch.tensor(prompt), max_new_tokens=8, do_sample=False,
            pad_token_id=0)[0, 6:].tolist()

    cache = init_cache(cfg, 1, 32, dtype=jnp.float32)
    logits, cache = transformer.prefill(
        params, cfg, jnp.asarray(prompt.astype(np.int32)),
        jnp.asarray([6], jnp.int32), cache)
    cur = int(np.argmax(np.asarray(logits)[0, 5]))
    got = [cur]
    for _ in range(7):
        logits, cache = transformer.decode_step(
            params, cfg, jnp.asarray([[cur]], jnp.int32), cache)
        cur = int(np.argmax(np.asarray(logits)[0, 0]))
        got.append(cur)
    assert got == want


def test_gptj_matches_hf():
    """GPT-J: interleaved (rotate_every_two) partial rotary, parallel
    residual with one shared norm, biased MLP + untied biased head."""
    import transformers
    torch_cfg = transformers.GPTJConfig(
        vocab_size=128, n_embd=32, n_layer=3, n_head=4, rotary_dim=4,
        n_positions=64, tie_word_embeddings=False)
    import torch
    torch.manual_seed(20)
    model = transformers.GPTJForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.rope_interleaved and cfg.rope_pct == 0.5  # 4 of 8 dims
    assert cfg.parallel_residual and cfg.shared_attn_mlp_norm
    assert "b" in params["lm_head"]
    rng = np.random.default_rng(20)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_falcon_rw_alibi_matches_hf():
    """The Falcon-RW layout: per-head fused QKV, SEQUENTIAL residual
    (parallel_attn=False), ALiBi positions."""
    import transformers
    torch_cfg = transformers.FalconConfig(
        vocab_size=128, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, multi_query=False,
        new_decoder_architecture=False, parallel_attn=False, bias=True,
        alibi=True, max_position_embeddings=64)
    import torch
    torch.manual_seed(22)
    model = transformers.FalconForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.position_embedding == "alibi"
    assert not cfg.parallel_residual and "mlp_norm" in params["layers"]
    rng = np.random.default_rng(22)
    tokens = rng.integers(0, 128, size=(1, 9), dtype=np.int64)
    _check_model(model, tokens)


def test_alibi_paged_serving_matches_engine():
    """ALiBi through the SERVING path: the continuous batcher's paged
    prefill + chunked decode must reproduce the engine's greedy tokens
    (the bias rides q/kv positions, so block-table indirection must not
    disturb it)."""
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)
    torch_cfg = transformers.BloomConfig(
        vocab_size=128, hidden_size=32, n_layer=2, n_head=4)
    torch.manual_seed(23)
    model = transformers.BloomForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32", attn_backend="xla")
    rng = np.random.default_rng(23)
    prompt = rng.integers(0, 128, size=11).tolist()

    eng = InferenceEngine(cfg, params, max_seq=64)
    want = eng.generate([prompt], max_new_tokens=10,
                        sampling=SamplingParams.greedy()).tokens[0]

    b = ContinuousBatcher(cfg, params, num_blocks=32, block_size=8,
                          slots=2, max_seq=64, seed=0)
    r = b.submit(prompt, max_new_tokens=10,
                 sampling=SamplingParams.greedy())
    for _ in range(40):
        b.step()
        if r.done.is_set():
            break
    assert r.wait() == want, (r.tokens, want)


def test_qwen2_mixed_window_rejected():
    """Qwen2's layer-indexed sliding window (full attention below
    max_window_layers) is not representable by the global
    cfg.sliding_window — conversion must refuse, not silently window
    every layer."""
    import transformers
    torch_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        use_sliding_window=True, sliding_window=8, max_window_layers=2)
    with pytest.raises(NotImplementedError, match="max_window_layers"):
        convert.config_from_hf(torch_cfg)
    # ...but the two exactly-representable shapes convert
    all_win = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        use_sliding_window=True, sliding_window=8, max_window_layers=0)
    assert convert.config_from_hf(all_win).sliding_window == 8
    none_win = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        use_sliding_window=True, sliding_window=8, max_window_layers=4)
    assert convert.config_from_hf(none_win).sliding_window is None


def test_gpt_bigcode_mqa_matches_hf():
    """StarCoder layout: MQA (1 kv head) + learned positions + fused
    nn.Linear c_attn — paths the other 14 families don't combine."""
    import torch
    import transformers
    torch_cfg = transformers.GPTBigCodeConfig(
        vocab_size=128, n_positions=64, n_embd=32, n_layer=3, n_head=4,
        multi_query=True, activation_function="gelu_pytorch_tanh")
    torch.manual_seed(11)
    model = transformers.GPTBigCodeForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(11)
    tokens = rng.integers(0, 128, size=(2, 12), dtype=np.int64)
    _check_model(model, tokens)


def test_gpt_bigcode_mha_matches_hf():
    import torch
    import transformers
    torch_cfg = transformers.GPTBigCodeConfig(
        vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        multi_query=False)
    torch.manual_seed(12)
    model = transformers.GPTBigCodeForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(12)
    tokens = rng.integers(0, 96, size=(1, 9), dtype=np.int64)
    _check_model(model, tokens)


def test_stablelm_matches_hf():
    """StableLM: llama layout with biased layernorms + partial rotary +
    qkv-only bias."""
    import torch
    import transformers
    torch_cfg = transformers.StableLmConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, partial_rotary_factor=0.5,
        use_qkv_bias=True, tie_word_embeddings=False)
    torch.manual_seed(13)
    model = transformers.StableLmForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(13)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_stablelm_unsupported_options_rejected():
    import transformers
    import pytest as _pytest
    cfg = transformers.StableLmConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=4,
        use_parallel_residual=True)
    with _pytest.raises(NotImplementedError, match="parallel_residual"):
        convert.config_from_hf(cfg)
    cfg2 = transformers.StableLmConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=4, qk_layernorm=True)
    with _pytest.raises(NotImplementedError, match="qk_layernorm"):
        convert.config_from_hf(cfg2)


def test_codegen_matches_hf():
    """CodeGen: GPT-J topology via a DIFFERENT fused-QKV layout (mp_num=4
    TP blocks, q|v|k order within each block) + partial interleaved
    rotary."""
    import torch
    import transformers
    torch_cfg = transformers.CodeGenConfig(
        vocab_size=128, n_positions=64, n_ctx=64, n_embd=32, n_layer=3,
        n_head=4, rotary_dim=4, activation_function="gelu_new",
        tie_word_embeddings=False)
    torch.manual_seed(14)
    model = transformers.CodeGenForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(14)
    tokens = rng.integers(0, 128, size=(2, 11), dtype=np.int64)
    _check_model(model, tokens)


def test_codegen_head_divisibility_rejected():
    import transformers
    import pytest as _pytest
    cfg = transformers.CodeGenConfig(
        vocab_size=64, n_positions=64, n_embd=30, n_layer=1, n_head=6,
        rotary_dim=4)
    with _pytest.raises(NotImplementedError, match="mp_num"):
        convert.config_from_hf(cfg)


def test_starcoder2_matches_hf():
    """StarCoder2: llama layer names with biased layernorms, biased
    linears and a plain (non-gated) tanh-gelu c_fc/c_proj MLP."""
    import torch
    import transformers
    torch_cfg = transformers.Starcoder2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, use_bias=True, sliding_window=None,
        tie_word_embeddings=True)
    torch.manual_seed(15)
    model = transformers.Starcoder2ForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(15)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_olmo_matches_hf():
    """OLMo: llama layout with NON-PARAMETRIC layernorms (converted to
    unit-scale/zero-bias leaves)."""
    import torch
    import transformers
    torch_cfg = transformers.OlmoConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, clip_qkv=None,
        tie_word_embeddings=False)
    torch.manual_seed(16)
    model = transformers.OlmoForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(16)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_olmo_clip_qkv_rejected():
    import transformers
    import pytest as _pytest
    cfg = transformers.OlmoConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=1, num_attention_heads=4, clip_qkv=8.0)
    with _pytest.raises(NotImplementedError, match="clip_qkv"):
        convert.config_from_hf(cfg)


def test_phi3_matches_hf():
    """Phi-3: llama semantics with fused qkv_proj and gate_up_proj rows
    split at conversion."""
    import torch
    import transformers
    torch_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, sliding_window=None,
        pad_token_id=0, tie_word_embeddings=False)
    torch.manual_seed(17)
    model = transformers.Phi3ForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(17)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_phi3_longrope_matches_hf():
    """Phi-3.5 longrope (previously refused): the static conversion
    picks the LONG factor set + attention factor when the checkpoint
    advertises an extended window — exact HF parity for sequences past
    original_max_position_embeddings (where HF also uses the long set).
    Sequence length 24 > original 16 here."""
    import torch
    import transformers
    torch_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, original_max_position_embeddings=16,
        rope_scaling={"type": "longrope",
                      "short_factor": [1.0, 1.1, 1.2, 1.3],
                      "long_factor": [1.5, 2.0, 3.0, 4.0]},
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(56)
    model = transformers.Phi3ForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.rope_inv_freq is not None and len(cfg.rope_inv_freq) == 4
    assert cfg.rope_attn_factor > 1.0
    rng = np.random.default_rng(56)
    tokens = rng.integers(0, 128, size=(1, 24), dtype=np.int64)
    _check_model(model, tokens)


def test_gpt_neo_matches_hf():
    """GPT-Neo: UNSCALED attention (sqrt(hd) folded into q at conversion)
    + alternating global/local-window layers via the per-layer traced
    ``attn_window`` leaf. window_size=8 < seq so the local mask binds."""
    import torch
    import transformers
    torch_cfg = transformers.GPTNeoConfig(
        vocab_size=128, max_position_embeddings=64, hidden_size=32,
        num_layers=4, attention_types=[[["global", "local"], 2]],
        num_heads=4, window_size=8)
    torch.manual_seed(18)
    model = transformers.GPTNeoForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(18)
    tokens = rng.integers(0, 128, size=(2, 14), dtype=np.int64)
    _check_model(model, tokens)


def test_gpt_neo_all_global_matches_hf():
    """All-global GPT-Neo converts WITHOUT attn_windows (uniform path)."""
    import torch
    import transformers
    torch_cfg = transformers.GPTNeoConfig(
        vocab_size=96, max_position_embeddings=64, hidden_size=32,
        num_layers=2, attention_types=[[["global"], 2]], num_heads=4)
    cfg = convert.config_from_hf(torch_cfg)
    assert cfg.attn_windows is None
    torch.manual_seed(19)
    model = transformers.GPTNeoForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(19)
    tokens = rng.integers(0, 96, size=(1, 9), dtype=np.int64)
    _check_model(model, tokens)


def test_gpt_neo_decode_matches_hf_generate():
    """Greedy decode through the engine (cached attend_decode with the
    traced per-layer window) vs HF generate."""
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)
    torch_cfg = transformers.GPTNeoConfig(
        vocab_size=128, max_position_embeddings=64, hidden_size=32,
        num_layers=4, attention_types=[[["global", "local"], 2]],
        num_heads=4, window_size=8)
    torch.manual_seed(20)
    model = transformers.GPTNeoForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")
    rng = np.random.default_rng(20)
    prompt = rng.integers(0, 128, size=12).tolist()
    eng = InferenceEngine(cfg, params, max_seq=40)
    ours = eng.generate([prompt], max_new_tokens=16,
                        sampling=SamplingParams.greedy()).tokens[0]
    with torch.no_grad():
        ref = model.generate(torch.tensor([prompt]), max_new_tokens=16,
                             do_sample=False)
    assert ours == ref[0, len(prompt):].tolist()


def test_gpt_neo_paged_serving_matches_engine():
    """Per-layer windows through the SERVING path: paged prefill +
    chunked decode reproduce the engine's greedy tokens (the window mask
    rides q/kv positions, so block-table indirection must not disturb
    it — and decode must keep attending far-back pool blocks on GLOBAL
    layers while masking them on local ones)."""
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)
    torch_cfg = transformers.GPTNeoConfig(
        vocab_size=128, max_position_embeddings=64, hidden_size=32,
        num_layers=4, attention_types=[[["global", "local"], 2]],
        num_heads=4, window_size=8)
    torch.manual_seed(21)
    model = transformers.GPTNeoForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32", attn_backend="xla")
    rng = np.random.default_rng(21)
    prompt = rng.integers(0, 128, size=11).tolist()

    eng = InferenceEngine(cfg, params, max_seq=64)
    want = eng.generate([prompt], max_new_tokens=12,
                        sampling=SamplingParams.greedy()).tokens[0]

    b = ContinuousBatcher(cfg, params, num_blocks=32, block_size=8,
                          slots=2, max_seq=64, seed=0)
    r = b.submit(prompt, max_new_tokens=12,
                 sampling=SamplingParams.greedy())
    for _ in range(40):
        b.step()
        if r.done.is_set():
            break
    assert r.wait() == want, (r.tokens, want)


def test_gemma2_matches_hf():
    """Gemma-2: sandwich norms (post_block_norms), attention + final
    logit softcapping, query_pre_attn_scalar folded into q, alternating
    sliding/full layers, explicit head_dim != hidden/heads, (1+w) norm
    absorb, sqrt(D) embed scale. Window 8 < seq so the sliding mask
    binds; qpas=32 != head_dim=16 so the scale fold binds."""
    import torch
    import transformers
    torch_cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, sliding_window=8,
        query_pre_attn_scalar=32, attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0, pad_token_id=0,
        tie_word_embeddings=True)
    torch.manual_seed(24)
    model = transformers.Gemma2ForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(24)
    tokens = rng.integers(0, 128, size=(2, 14), dtype=np.int64)
    _check_model(model, tokens)


def test_gemma2_decode_matches_hf_stepwise():
    """Greedy decode through the engine: softcaps + alternating windows
    through the cached path. Compared against HF run FULL-CONTEXT each
    step (not HF generate: its HybridCache decode reorders fp ops and the
    final softcap squashes logits into +-cap, so exact-tie flips between
    HF's own cached and uncached paths are expected — observed 8e-3 logit
    gaps flipping argmax; our full-context logits match HF's to 0.0)."""
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)
    torch_cfg = transformers.Gemma2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, sliding_window=8,
        query_pre_attn_scalar=32, attn_logit_softcapping=50.0,
        final_logit_softcapping=30.0, pad_token_id=0,
        tie_word_embeddings=True)
    torch.manual_seed(25)
    model = transformers.Gemma2ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")
    rng = np.random.default_rng(25)
    prompt = rng.integers(0, 128, size=12).tolist()
    eng = InferenceEngine(cfg, params, max_seq=40)
    ours = eng.generate([prompt], max_new_tokens=14,
                        sampling=SamplingParams.greedy()).tokens[0]
    seq = list(prompt)
    for got in ours:
        with torch.no_grad():
            hl = model(torch.tensor([seq])).logits[0, -1].float().numpy()
        want = int(hl.argmax())
        # accept either side of an exact near-tie (the cached engine path
        # reorders fp like HF's cache does); anything beyond tie range is
        # a real bug
        assert got == want or hl[want] - hl[got] < 2e-2, (
            seq, got, want, hl[want] - hl[got])
        seq.append(got)


def test_cohere_matches_hf():
    """Cohere: shared bias-free layernorm parallel residual, INTERLEAVED
    rotary, tied head with constant logit scale."""
    import torch
    import transformers
    torch_cfg = transformers.CohereConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, logit_scale=0.25, pad_token_id=0,
        tie_word_embeddings=True)
    torch.manual_seed(26)
    model = transformers.CohereForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(26)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_cohere_qk_norm_matches_hf():
    """Command-R+ use_qk_norm: bias-free per-head layernorm on q/k with
    DISTINCT [H, hd] scales (qk_norm="ln_head")."""
    import torch
    import transformers
    torch_cfg = transformers.CohereConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, logit_scale=0.25, use_qk_norm=True,
        pad_token_id=0, tie_word_embeddings=True)
    torch.manual_seed(27)
    model = transformers.CohereForCausalLM(torch_cfg).eval()
    # random-init layernorm scales are all-ones — perturb them so the
    # test distinguishes per-head scales from a shared one
    with torch.no_grad():
        for lyr in model.model.layers:
            lyr.self_attn.q_norm.weight.mul_(
                torch.rand_like(lyr.self_attn.q_norm.weight) + 0.5)
            lyr.self_attn.k_norm.weight.mul_(
                torch.rand_like(lyr.self_attn.k_norm.weight) + 0.5)
    rng = np.random.default_rng(27)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_qwen3_matches_hf():
    """Qwen3: llama layout + per-head RMS q/k norms (shared [head_dim]
    scale) + head_dim decoupled from hidden//heads."""
    import torch
    import transformers
    torch_cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64,
        tie_word_embeddings=False)
    torch.manual_seed(28)
    model = transformers.Qwen3ForCausalLM(torch_cfg).eval()
    with torch.no_grad():
        for lyr in model.model.layers:
            lyr.self_attn.q_norm.weight.mul_(
                torch.rand_like(lyr.self_attn.q_norm.weight) + 0.5)
            lyr.self_attn.k_norm.weight.mul_(
                torch.rand_like(lyr.self_attn.k_norm.weight) + 0.5)
    rng = np.random.default_rng(28)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_qwen3_mixed_sliding_windows_match_hf():
    """Qwen3 with MIXED sliding/full layer_types (use_sliding_window +
    max_window_layers < num_layers): the per-layer windows must ride the
    param tree as the stacked attn_window leaf — the qwen3 config branch
    reuses the llama state-dict path, which emits no per-layer leaves of
    its own, so a missing generic emission silently ran every layer
    global (seq > window here, so that bug shifts logits by ~0.17)."""
    import torch
    import transformers
    torch_cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=64, tie_word_embeddings=False,
        use_sliding_window=True, sliding_window=4, max_window_layers=1)
    assert len(set(torch_cfg.layer_types)) == 2  # genuinely mixed
    torch.manual_seed(29)
    model = transformers.Qwen3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.attn_windows is not None and cfg.sliding_window is None
    assert "attn_window" in params["layers"]
    rng = np.random.default_rng(29)
    tokens = rng.integers(0, 128, size=(2, 12), dtype=np.int64)  # 12 > 4
    _check_model(model, tokens)


def test_qwen3_moe_matches_hf():
    """Qwen3-MoE: qwen3 attention + mixtral-convention router
    (softmax -> top-k -> renormalize; norm_topk_prob=True)."""
    import torch
    import transformers
    torch_cfg = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=48, num_experts=4, num_experts_per_tok=2,
        norm_topk_prob=True, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=64,
        mlp_only_layers=[], decoder_sparse_step=1,
        tie_word_embeddings=False)
    torch.manual_seed(29)
    model = transformers.Qwen3MoeForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(29)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens, atol=4e-3)


def test_granite_matches_hf():
    """Granite: llama layout + the four scalar multipliers (embedding,
    attention, residual, logits_scaling) absorbed into existing fields."""
    import torch
    import transformers
    torch_cfg = transformers.GraniteConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, embedding_multiplier=6.0,
        attention_multiplier=0.31, residual_multiplier=0.22,
        logits_scaling=4.0, tie_word_embeddings=False)
    torch.manual_seed(30)
    model = transformers.GraniteForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(30)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_olmo2_matches_hf():
    """OLMo-2: post-sublayer norms only (x + norm(f(x))) and full-width
    RMS q/k norms on the projections."""
    import torch
    import transformers
    torch_cfg = transformers.Olmo2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False)
    torch.manual_seed(31)
    model = transformers.Olmo2ForCausalLM(torch_cfg).eval()
    with torch.no_grad():
        for lyr in model.model.layers:
            lyr.self_attn.q_norm.weight.mul_(
                torch.rand_like(lyr.self_attn.q_norm.weight) + 0.5)
            lyr.self_attn.k_norm.weight.mul_(
                torch.rand_like(lyr.self_attn.k_norm.weight) + 0.5)
    rng = np.random.default_rng(31)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_glm_matches_hf():
    """GLM: interleaved PARTIAL rotary (gpt-j pairing over the first
    half of head_dim), fused gate_up split, qkv bias without o bias."""
    import torch
    import transformers
    torch_cfg = transformers.GlmConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, partial_rotary_factor=0.5, attention_bias=True,
        max_position_embeddings=64, pad_token_id=0,
        tie_word_embeddings=False)
    torch.manual_seed(32)
    model = transformers.GlmForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(32)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_glm4_matches_hf():
    """GLM-4: glm plus sandwich post norms (post_self_attn/post_mlp ->
    post_block_norms)."""
    import torch
    import transformers
    torch_cfg = transformers.Glm4Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, partial_rotary_factor=0.5, attention_bias=True,
        max_position_embeddings=64, pad_token_id=0,
        tie_word_embeddings=False)
    torch.manual_seed(33)
    model = transformers.Glm4ForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(33)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_nemotron_matches_hf():
    """Nemotron: LayerNorm1P ((1+w) absorbed), squared-ReLU ungated MLP,
    partial non-interleaved rotary."""
    import torch
    import transformers
    torch_cfg = transformers.NemotronConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        partial_rotary_factor=0.5, max_position_embeddings=64,
        tie_word_embeddings=False)
    torch.manual_seed(34)
    model = transformers.NemotronForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(34)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def _deepseek_cfg(**kw):
    import transformers
    base = dict(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=4,
        q_lora_rank=24, kv_lora_rank=16, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=12, head_dim=8,
        n_routed_experts=8, n_shared_experts=1, num_experts_per_tok=2,
        n_group=4, topk_group=2, routed_scaling_factor=2.5,
        norm_topk_prob=True, first_k_dense_replace=0,
        max_position_embeddings=64, rope_scaling=None,
        tie_word_embeddings=False, pad_token_id=0)
    base.update(kw)
    return transformers.DeepseekV3Config(**base)


def test_deepseek_v3_dense_mla_matches_hf():
    """DeepSeek-V3 multi-head latent attention, all-dense MLP layers
    (first_k_dense_replace >= num_layers). Exercises the low-rank q/kv
    bottlenecks with mid-stack RMSNorms, the [rope|nope] head-dim
    permutation, the shared (MQA-style) rope head, interleaved rope, and
    the v_head_dim < qk_head_dim zero-padding."""
    import torch
    import transformers
    torch_cfg = _deepseek_cfg(first_k_dense_replace=3)
    torch.manual_seed(40)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.mla and cfg.num_experts == 0
    assert cfg.head_dim == 24 and cfg.v_head_dim == 12
    rng = np.random.default_rng(40)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_deepseek_v3_no_q_lora_matches_hf():
    """q_lora_rank=None: full-rank q projection path."""
    import torch
    import transformers
    torch_cfg = _deepseek_cfg(first_k_dense_replace=3, q_lora_rank=None)
    torch.manual_seed(41)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    rng = np.random.default_rng(41)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_deepseek_v3_moe_matches_hf():
    """All-MoE layers: sigmoid scores, e_score_correction_bias-ranked
    group-limited top-k (selection bias only — weights are the unbiased
    scores), renormalized, routed_scaling_factor, plus the always-active
    shared-experts MLP."""
    import torch
    import transformers
    torch_cfg = _deepseek_cfg()
    torch.manual_seed(42)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    # non-zero correction bias so the selection-vs-weight distinction is
    # actually exercised (the buffer inits to zeros)
    with torch.no_grad():
        for lyr in model.model.layers:
            lyr.mlp.gate.e_score_correction_bias.uniform_(0.0, 0.2)
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.moe_router == "deepseek_v3" and cfg.moe_shared_experts == 1
    rng = np.random.default_rng(42)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_deepseek_v3_mixed_stack_refuses_pp():
    """The GPipe stage split assumes one uniformly-stacked layer tree;
    a mixed stack under pp is refused at plan time with a named error
    (parallel/mesh.validate_spec)."""
    import transformers
    from distributed_llm_inferencing_tpu.parallel.mesh import (
        MeshSpec, validate_spec)
    cfg = convert.config_from_hf(_deepseek_cfg(
        first_k_dense_replace=1, num_hidden_layers=4))
    with pytest.raises(NotImplementedError, match="mixed dense/MoE"):
        validate_spec(MeshSpec(pp=2), cfg)
    validate_spec(MeshSpec(tp=2, ep=2), cfg)   # tp/ep compose fine


def test_deepseek_v3_decode_and_batcher_match_hf_generate():
    """MLA through the REAL serving paths: greedy decode via the engine's
    dense cache AND via the paged continuous batcher ≡ HF generate.
    Exercises cached k (with the shared rope head materialized per head),
    the zero-padded v riding the caches, and the deepseek MoE router
    under single-token decode shapes."""
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)

    torch_cfg = _deepseek_cfg()
    torch.manual_seed(43)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    rng = np.random.default_rng(43)
    prompt = rng.integers(0, 128, 8).tolist()
    with torch.no_grad():
        want = model.generate(
            torch.tensor([prompt]), max_new_tokens=10, do_sample=False,
            pad_token_id=0)[0, 8:].tolist()

    eng = InferenceEngine(cfg, max_seq=32, seed=0, params=params)
    got = eng.generate([prompt], max_new_tokens=10,
                       sampling=SamplingParams.greedy()).tokens[0]
    assert got == want

    b = ContinuousBatcher(cfg, num_blocks=16, block_size=8, slots=2,
                          max_seq=32, seed=0, params=params)
    r = b.submit(prompt, max_new_tokens=10,
                 sampling=SamplingParams.greedy())
    while b.step():
        pass
    assert r.error is None and r.tokens == want


def test_deepseek_v3_yarn_rope_scaling_matches_hf():
    """Yarn context extension: NTK-by-part interpolated rope ladder
    (cfg.rope_inv_freq), the attention_factor on cos/sin, AND the
    separate mscale_all_dim uniform score multiplier (folded into the q
    weights via the query_pre_attn_scalar absorption). mscale !=
    mscale_all_dim so both mechanisms are exercised; seq length runs
    past original_max_position_embeddings so the extension bites."""
    import torch
    import transformers
    torch_cfg = _deepseek_cfg(
        first_k_dense_replace=3,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 16,
                      "beta_fast": 32, "beta_slow": 1,
                      "mscale": 0.8, "mscale_all_dim": 1.2})
    torch.manual_seed(44)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.rope_inv_freq is not None and len(cfg.rope_inv_freq) == 4
    assert cfg.rope_attn_factor != 1.0
    assert cfg.query_pre_attn_scalar is not None
    rng = np.random.default_rng(44)
    tokens = rng.integers(0, 128, size=(1, 24), dtype=np.int64)
    _check_model(model, tokens)


def test_deepseek_v3_mixed_dense_moe_matches_hf():
    """The SHIPPED DeepSeek layout: first_k_dense_replace dense-MLP
    layers ahead of the MoE tail. The param tree carries the prefix as
    its own stacked segment (layers_dense) and the layer scans run the
    two segments back to back (transformer.layer_segments)."""
    import torch
    import transformers
    torch_cfg = _deepseek_cfg(first_k_dense_replace=1)
    torch.manual_seed(45)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.dense_prefix_layers == 1 and cfg.num_experts == 8
    assert cfg.dense_intermediate_size == 64
    assert "layers_dense" in params
    assert params["layers_dense"]["up"]["w"].shape == (1, 32, 64)
    assert params["layers"]["experts"]["up"]["w"].shape == (2, 8, 32, 16)
    rng = np.random.default_rng(45)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_deepseek_v3_mixed_decode_and_batcher_match_hf_generate():
    """Mixed stack through the real serving paths: greedy decode via the
    engine (dense cache + CPU layer-unroll eligibility) and via the
    paged continuous batcher, both ≡ HF generate."""
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)

    torch_cfg = _deepseek_cfg(first_k_dense_replace=1)
    torch.manual_seed(46)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    rng = np.random.default_rng(46)
    prompt = rng.integers(0, 128, 8).tolist()
    with torch.no_grad():
        want = model.generate(
            torch.tensor([prompt]), max_new_tokens=10, do_sample=False,
            pad_token_id=0)[0, 8:].tolist()

    eng = InferenceEngine(cfg, max_seq=32, seed=0, params=params)
    got = eng.generate([prompt], max_new_tokens=10,
                       sampling=SamplingParams.greedy()).tokens[0]
    assert got == want

    b = ContinuousBatcher(cfg, num_blocks=16, block_size=8, slots=2,
                          max_seq=32, seed=0, params=params)
    r = b.submit(prompt, max_new_tokens=10,
                 sampling=SamplingParams.greedy())
    while b.step():
        pass
    assert r.error is None and r.tokens == want


def test_llama31_rope_scaling_matches_hf():
    """Llama 3.1+ ships rope_scaling rope_type="llama3" (NTK-by-part
    smoothing); before cfg.rope_inv_freq existed this was silently
    IGNORED, corrupting every position past the unscaled ladder's
    wavelengths. Parity at sequence lengths where the smoothing bites."""
    import torch
    import transformers
    torch_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rope_theta=10000.0,
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 16},
        tie_word_embeddings=False, attention_bias=False)
    torch.manual_seed(47)
    model = transformers.LlamaForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.rope_inv_freq is not None and len(cfg.rope_inv_freq) == 4
    rng = np.random.default_rng(47)
    tokens = rng.integers(0, 128, size=(1, 40), dtype=np.int64)
    _check_model(model, tokens)


def test_qwen2_linear_rope_scaling_matches_hf():
    """Position-interpolation ("linear") scaling: uniform /factor."""
    import torch
    import transformers
    torch_cfg = transformers.Qwen2Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128,
        rope_scaling={"rope_type": "linear", "factor": 4.0},
        tie_word_embeddings=False)
    torch.manual_seed(48)
    model = transformers.Qwen2ForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.rope_inv_freq is not None
    rng = np.random.default_rng(48)
    tokens = rng.integers(0, 128, size=(1, 24), dtype=np.int64)
    _check_model(model, tokens)


def test_unknown_rope_scaling_refused():
    import transformers
    torch_cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4,
        rope_scaling={"rope_type": "dynamic", "factor": 2.0})
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        convert.config_from_hf(torch_cfg)


def test_deepseek_v3_mixed_stack_with_yarn_matches_hf():
    """The shipped 671B combination: mixed dense-prefix/MoE-tail stack
    WITH yarn (the q-weight mscale fold must land in BOTH segments'
    q projections, and the scaled rope ladder rides every layer)."""
    import torch
    import transformers
    torch_cfg = _deepseek_cfg(
        first_k_dense_replace=1,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 16,
                      "mscale": 1.0, "mscale_all_dim": 1.0})
    torch.manual_seed(49)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.dense_prefix_layers == 1 and cfg.rope_inv_freq is not None
    assert cfg.query_pre_attn_scalar is not None  # mscale fold active
    rng = np.random.default_rng(49)
    tokens = rng.integers(0, 128, size=(1, 24), dtype=np.int64)
    _check_model(model, tokens)


def test_ernie45_matches_hf():
    """ERNIE 4.5 dense: llama layout, one use_bias switch on every
    linear, explicit head_dim decoupled from hidden/heads."""
    import torch
    import transformers
    torch_cfg = transformers.Ernie4_5Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, use_bias=True, max_position_embeddings=64,
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(50)
    model = transformers.Ernie4_5ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.attn_bias and cfg.mlp_bias and "b" in params["layers"]["o"]
    rng = np.random.default_rng(50)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_smollm3_nope_layers_match_hf():
    """SmolLM3: per-layer NoPE (no_rope_layers) — the rope_on leaf must
    disable rotation exactly on the flagged layers."""
    import torch
    import transformers
    torch_cfg = transformers.SmolLM3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        no_rope_layers=[1, 1, 1, 0], no_rope_layer_interval=4,
        max_position_embeddings=64, tie_word_embeddings=False,
        pad_token_id=0)
    torch.manual_seed(51)
    model = transformers.SmolLM3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.rope_layers == (1, 1, 1, 0)
    assert "rope_on" in params["layers"]
    rng = np.random.default_rng(51)
    tokens = rng.integers(0, 128, size=(2, 12), dtype=np.int64)
    _check_model(model, tokens)


def test_hunyuan_dense_post_rope_qk_norm_matches_hf():
    """HunYuan-Dense: shared [head_dim] q/k RMS norms applied AFTER
    RoPE (query_layernorm/key_layernorm; qwen3 norms before)."""
    import torch
    import transformers
    torch_cfg = transformers.HunYuanDenseV1Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64, tie_word_embeddings=False,
        pad_token_id=0)
    torch.manual_seed(52)
    model = transformers.HunYuanDenseV1ForCausalLM(torch_cfg).eval()
    with torch.no_grad():   # distinguish the norms from identity
        for lyr in model.model.layers:
            lyr.self_attn.query_layernorm.weight.mul_(
                torch.rand_like(lyr.self_attn.query_layernorm.weight) + 0.5)
            lyr.self_attn.key_layernorm.weight.mul_(
                torch.rand_like(lyr.self_attn.key_layernorm.weight) + 0.5)
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.qk_norm == "rms_head" and cfg.qk_norm_after_rope
    rng = np.random.default_rng(52)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_exaone4_hybrid_matches_hf():
    """EXAONE 4.0: sublayer-postnorm topology (x + norm(f(x))), shared
    [head_dim] q/k norms, hybrid attention — sliding layers rotate,
    full-attention layers are NoPE — with per-layer windows. Sequence
    longer than the window so both mechanisms bite."""
    import torch
    import transformers
    torch_cfg = transformers.Exaone4Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        sliding_window=4, sliding_window_pattern=4,
        max_position_embeddings=64, tie_word_embeddings=False,
        pad_token_id=0)
    torch.manual_seed(53)
    model = transformers.Exaone4ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.sublayer_postnorm_only and cfg.qk_norm == "rms_head"
    assert cfg.rope_layers is not None and 0 in cfg.rope_layers
    assert cfg.attn_windows is not None
    rng = np.random.default_rng(53)
    tokens = rng.integers(0, 128, size=(1, 12), dtype=np.int64)
    _check_model(model, tokens)


def test_dbrx_matches_hf():
    """DBRX: fused-Wqkv pre-LN block with the clip_qkv activation clamp,
    bias-free LayerNorms, and a fused-GLU MoE whose router renormalizes
    top-k softmax weights by L1 (p=1). top_k=2 of 4 experts here."""
    import torch
    import transformers
    torch_cfg = transformers.DbrxConfig(
        vocab_size=128, d_model=32, n_heads=4, n_layers=3, max_seq_len=64,
        attn_config={"kv_n_heads": 2, "clip_qkv": 0.5,
                     "rope_theta": 10000.0},
        ffn_config={"ffn_hidden_size": 16, "moe_num_experts": 4,
                    "moe_top_k": 2, "moe_normalize_expert_weights": 1.0},
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(54)
    model = transformers.DbrxForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.qkv_clip == 0.5 and cfg.num_experts == 4
    assert cfg.moe_norm_topk
    rng = np.random.default_rng(54)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_qwen3_moe_no_renorm_matches_hf():
    """qwen3_moe with norm_topk_prob=False (previously refused): the
    top-k softmax weights apply UNnormalized (cfg.moe_norm_topk)."""
    import torch
    import transformers
    torch_cfg = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, num_experts=4, num_experts_per_tok=2,
        norm_topk_prob=False, num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=64,
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(55)
    model = transformers.Qwen3MoeForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert not cfg.moe_norm_topk
    rng = np.random.default_rng(55)
    tokens = rng.integers(0, 128, size=(2, 8), dtype=np.int64)
    _check_model(model, tokens)


def test_phi3_partial_rotary_longrope_matches_hf():
    """Phi-4-mini shape: partial_rotary_factor < 1 WITH longrope — the
    scaled ladder sizes to the partial dim and rope_pct keeps the
    rotated slice to the same width (full-width rotation would
    shape-mismatch the 6-entry ladder against 8-dim halves)."""
    import torch
    import transformers
    torch_cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        partial_rotary_factor=0.75,
        max_position_embeddings=64, original_max_position_embeddings=16,
        rope_scaling={"type": "longrope",
                      "short_factor": [1.0] * 6,
                      "long_factor": [1.5, 2.0, 2.5, 3.0, 3.5, 4.0]},
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(57)
    model = transformers.Phi3ForCausalLM(torch_cfg).eval()
    cfg, _ = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.rope_pct == 0.75 and len(cfg.rope_inv_freq) == 6
    rng = np.random.default_rng(57)
    tokens = rng.integers(0, 128, size=(1, 24), dtype=np.int64)
    _check_model(model, tokens)


def test_longrope_without_original_attr_uses_short_and_rs_factor():
    """HF reads original_max_position_embeddings from the CONFIG
    attribute only; without it the short factors apply and the
    attention factor derives from rope_scaling['factor'] (mirrors
    modeling_rope_utils._compute_longrope_parameters)."""
    import math
    from types import SimpleNamespace
    hf = SimpleNamespace(
        rope_theta=10000.0, max_position_embeddings=64,
        rope_scaling={"type": "longrope", "factor": 4.0,
                      "short_factor": [1.0, 1.1, 1.2, 1.3],
                      "long_factor": [9.0] * 4})
    inv, attn, _ = convert._rope_scaling_params(hf, 8, "test")
    base = 10000.0 ** (np.arange(0, 8, 2) / 8)
    np.testing.assert_allclose(
        inv, 1.0 / (np.array([1.0, 1.1, 1.2, 1.3]) * base), rtol=1e-12)
    assert attn == pytest.approx(
        math.sqrt(1 + math.log(4.0) / math.log(64)))


def test_glm45_moe_matches_hf():
    """GLM-4.5 (glm4_moe): llama block + per-head q/k norms + partial
    half-split rotary + DeepSeek-V3's exact sigmoid group-limited
    routing with shared experts over a first_k_dense_replace mixed
    stack — every mechanism shared with existing families, composed."""
    from conftest import tiny_glm45_moe_model
    model = tiny_glm45_moe_model(seed=58)
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.moe_router == "deepseek_v3" and cfg.dense_prefix_layers == 1
    assert cfg.qk_norm == "rms_head" and cfg.rope_pct == 0.5
    assert "layers_dense" in params
    assert "bias" in params["layers"]["router"]
    rng = np.random.default_rng(58)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_ernie45_moe_matches_hf():
    """ERNIE 4.5 MoE: softmax routing with bias-corrected SELECTION
    (moe_statics.e_score_correction_bias — weights stay unbiased),
    shared experts, and a dense prefix (moe_layer_start_index) through
    the mixed-stack machinery."""
    import torch
    import transformers
    torch_cfg = transformers.Ernie4_5_MoeConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, moe_num_experts=4, moe_k=2,
        moe_num_shared_experts=1, moe_layer_start_index=1,
        moe_layer_interval=1, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=64, tie_word_embeddings=False,
        use_bias=True, pad_token_id=0)   # biases on EVERY linear incl.
    # the per-expert and shared-expert MLPs
    torch.manual_seed(59)
    model = transformers.Ernie4_5_MoeForCausalLM(torch_cfg).eval()
    with torch.no_grad():   # non-zero selection bias
        for lyr in model.model.layers:
            if hasattr(lyr.mlp, "moe_statics"):
                lyr.mlp.moe_statics.e_score_correction_bias.uniform_(
                    0.0, 0.3)
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.moe_router == "ernie" and cfg.dense_prefix_layers == 1
    assert cfg.moe_shared_experts == 1
    assert "bias" in params["layers"]["router"]
    rng = np.random.default_rng(59)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)


def test_gpt_oss_matches_hf():
    """gpt-oss: learned per-head attention sinks (virtual softmax
    column), clamped-swish expert GLU with per-expert biases,
    top-k-then-softmax routing, alternating sliding/full layers, and
    yarn rope with truncate=false. Sequence longer than the window and
    past the original rope window so everything bites."""
    import torch
    import transformers
    torch_cfg = transformers.GptOssConfig(
        vocab_size=128, hidden_size=32, intermediate_size=16,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, num_local_experts=4, num_experts_per_tok=2,
        sliding_window=4, layer_types=["sliding_attention",
                                       "full_attention"],
        max_position_embeddings=64,
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "beta_fast": 32.0, "beta_slow": 1.0,
                      "truncate": False,
                      "original_max_position_embeddings": 16},
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(60)
    model = transformers.GptOssForCausalLM(torch_cfg).eval()
    with torch.no_grad():   # non-trivial sinks (init may be empty/zeros)
        for lyr in model.model.layers:
            lyr.self_attn.sinks.normal_(0.0, 1.0)
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.attn_sinks and cfg.moe_router == "topk_softmax"
    assert cfg.moe_swiglu_limit == 7.0
    assert "sinks" in params["layers"]
    assert "b" in params["layers"]["experts"]["gate"]
    rng = np.random.default_rng(60)
    tokens = rng.integers(0, 128, size=(2, 20), dtype=np.int64)
    _check_model(model, tokens)


def test_gpt_oss_decode_and_batcher_match_hf_generate():
    """gpt-oss through the REAL serving paths: the sinks column must
    ride cached decode (dense engine) and the paged batcher's chunk and
    prefix formulations identically — greedy ≡ HF generate."""
    import torch
    from conftest import tiny_gpt_oss_model
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)
    model = tiny_gpt_oss_model(seed=61)
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    prompt = np.random.default_rng(61).integers(0, 128, 9).tolist()
    with torch.no_grad():
        want = model.generate(
            torch.tensor([prompt]), max_new_tokens=10, do_sample=False,
            pad_token_id=0)[0, 9:].tolist()

    eng = InferenceEngine(cfg, max_seq=32, seed=0, params=params)
    got = eng.generate([prompt], max_new_tokens=10,
                       sampling=SamplingParams.greedy()).tokens[0]
    assert got == want

    b = ContinuousBatcher(cfg, num_blocks=16, block_size=8, slots=2,
                          max_seq=32, seed=0, params=params)
    r = b.submit(prompt, max_new_tokens=10,
                 sampling=SamplingParams.greedy())
    while b.step():
        pass
    assert r.error is None and r.tokens == want


def test_hunyuan_moe_matches_hf():
    """HunYuan-MoE: post-RoPE q/k norms + mixtral-convention routing +
    an always-active shared MLP of the same intermediate width (router
    named mlp.gate.wg, shared weights under mlp.shared_mlp)."""
    import torch
    import transformers
    torch_cfg = transformers.HunYuanMoEV1Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_experts=4, moe_topk=2, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=64,
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(62)
    model = transformers.HunYuanMoEV1ForCausalLM(torch_cfg).eval()
    with torch.no_grad():
        for lyr in model.model.layers:
            lyr.self_attn.query_layernorm.weight.mul_(
                torch.rand_like(lyr.self_attn.query_layernorm.weight) + 0.5)
            lyr.self_attn.key_layernorm.weight.mul_(
                torch.rand_like(lyr.self_attn.key_layernorm.weight) + 0.5)
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    assert cfg.num_experts == 4 and cfg.moe_norm_topk
    assert cfg.moe_shared_experts == 1 and cfg.qk_norm_after_rope
    assert "shared_gate" in params["layers"]
    rng = np.random.default_rng(62)
    tokens = rng.integers(0, 128, size=(2, 10), dtype=np.int64)
    _check_model(model, tokens)
