"""GSPMD partitioning for the int4 pallas kernel (ops/pallas/quant_matmul).

The kernel's value is the llama-8B-tp / 70B-pp+tp regimes, so it must run
INSIDE multi-device GSPMD programs — these tests pin the partitioning
rule on a CPU mesh (pallas interpret mode): column-parallel (dout over
tp) runs per-shard and matches the XLA unpack bit-for-bit at f32 tile
sizes, row-parallel leaves keep the XLA path (supported() hint), and an
int4 model on a tp=2 engine matches its tp=1 twin.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_llm_inferencing_tpu.ops.pallas import quant_matmul as qm
from distributed_llm_inferencing_tpu.ops.quant import (
    quantize_weight_int4, unpack_int4)


def _leaf(din, dout, seed=0):
    rng = np.random.default_rng(seed)
    w = jnp.asarray(rng.standard_normal((din, dout)), jnp.float32)
    return quantize_weight_int4(w)


def _ref(x, leaf):
    return x @ (unpack_int4(leaf["p4"]).astype(jnp.float32)
                * leaf["scale"][None, :])


def test_q4_matmul_partitions_column_parallel():
    leaf = _leaf(64, 256)
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
    p4 = jax.device_put(leaf["p4"], NamedSharding(mesh, P(None, "tp")))
    sc = jax.device_put(leaf["scale"], NamedSharding(mesh, P("tp")))
    xr = jax.device_put(x, NamedSharding(mesh, P(None, None)))
    out = jax.jit(lambda a, p, s: qm.q4_matmul(a, p, s, interpret=True))(
        xr, p4, sc)
    # the rule shards the OUTPUT channel axis — no resharding collective
    # on the weight, result lands tp-sharded
    assert out.sharding.spec == P(None, "tp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, leaf)),
                               rtol=1e-4, atol=1e-4)


def test_q4_matmul_batch_sharded_rows():
    leaf = _leaf(64, 128)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((8, 64)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("dp",))
    xr = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
    out = jax.jit(lambda a: qm.q4_matmul(a, leaf["p4"], leaf["scale"],
                                         interpret=True))(xr)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, leaf)),
                               rtol=1e-4, atol=1e-4)


def test_supported_gates(monkeypatch):
    monkeypatch.setenv("DLI_INT4_PALLAS", "interpret")
    assert qm.supported(1, 64, 128)
    # row-sharded leaves keep XLA regardless of platform/mode
    assert not qm.supported(1, 64, 128, row_sharded=True)
    monkeypatch.setenv("DLI_INT4_PALLAS", "never")
    assert not qm.supported(1, 64, 128)
    monkeypatch.setenv("DLI_INT4_PALLAS", "auto")
    # CPU backend without interpret: XLA fallback
    assert not qm.supported(1, 64, 128)


def test_int4_engine_tp2_matches_tp1(monkeypatch):
    """Whole-model check: an int4 engine on a tp=2 mesh (kernel engaged
    via interpret mode, column-parallel per-shard; row-parallel leaves on
    XLA) greedy-decodes identically to the single-device engine."""
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.models import convert
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

    monkeypatch.setenv("DLI_INT4_PALLAS", "interpret")
    monkeypatch.setenv("DLI_UNROLL_LAYERS", "0")  # exercise the scan path
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=96, n_positions=64, n_embd=128, n_layer=2,
        n_head=4)).eval()

    def mk(spec):
        cfg, params = convert.load_hf_model(hf, dtype=jnp.float32)
        cfg = cfg.replace(dtype="float32", name="tiny-int4", quant="int4")
        return InferenceEngine(cfg, params, mesh_spec=spec, max_seq=64)

    prompt = [3, 17, 52, 9]
    g = SamplingParams.greedy()
    a = mk(None).generate([prompt], max_new_tokens=8, sampling=g).tokens[0]
    b = mk(MeshSpec(tp=2)).generate([prompt], max_new_tokens=8,
                                    sampling=g).tokens[0]
    assert a == b


def test_q4_row_parallel_matches_reference():
    """Row-parallel (din-sharded) leaves: after the chunk-local repack
    (ops/quant.py repack_int4_rows) each shard's slice is self-contained,
    the kernel runs locally and one psum combines partials."""
    from distributed_llm_inferencing_tpu.ops.quant import repack_int4_rows
    leaf = _leaf(64, 256, seed=3)
    ch = repack_int4_rows(leaf, 2)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("tp",))
    p4 = jax.device_put(ch["p4"], NamedSharding(mesh, P("tp", None)))
    sc = jax.device_put(ch["scale"], NamedSharding(mesh, P(None)))
    xs = jax.device_put(x, NamedSharding(mesh, P(None, "tp")))
    out = jax.jit(lambda a, p, s: qm.q4_matmul_row(
        a, p, s, interpret=True, chunks=2))(xs, p4, sc)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(x, leaf)),
                               rtol=1e-4, atol=1e-4)


def test_chunked_repack_preserves_values():
    from distributed_llm_inferencing_tpu.ops.quant import (
        dequantize_weight, repack_int4_rows, unpack_int4)
    leaf = _leaf(96, 160, seed=4)
    for chunks in (2, 4):
        ch = repack_int4_rows(leaf, chunks)
        np.testing.assert_array_equal(
            np.asarray(unpack_int4(ch["p4"], chunks)),
            np.asarray(unpack_int4(leaf["p4"])))
        np.testing.assert_array_equal(np.asarray(dequantize_weight(ch)),
                                      np.asarray(dequantize_weight(leaf)))


def test_int4_engine_tp2_row_and_col_kernels(monkeypatch):
    """Whole model on tp=2 with BOTH kernel modes engaged — q/k/v/up
    column-partitioned, o/down row-partitioned via the shard-time repack
    (parallel/sharding.py shard_params) — matches the tp=1 engine."""
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.models import convert
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

    monkeypatch.setenv("DLI_INT4_PALLAS", "interpret")
    monkeypatch.setenv("DLI_UNROLL_LAYERS", "0")
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=96, n_positions=64, n_embd=128, n_layer=2,
        n_head=4)).eval()

    def mk(spec):
        cfg, params = convert.load_hf_model(hf, dtype=jnp.float32)
        cfg = cfg.replace(dtype="float32", name="tiny-int4rc", quant="int4")
        return InferenceEngine(cfg, params, mesh_spec=spec, max_seq=64)

    tp2 = mk(MeshSpec(tp=2))
    # the shard-time repack actually engaged on the row-parallel leaves
    assert "chunked" in tp2.params["layers"]["o"]
    assert "chunked" in tp2.params["layers"]["down"]
    assert "chunked" not in tp2.params["layers"]["up"]
    g = SamplingParams.greedy()
    a = mk(None).generate([[3, 17, 52, 9]], max_new_tokens=8,
                          sampling=g).tokens[0]
    b = tp2.generate([[3, 17, 52, 9]], max_new_tokens=8,
                     sampling=g).tokens[0]
    assert a == b
