"""Multiplexed batched dispatch + pooled keep-alive RPC (ISSUE 4).

Covers the control-plane pipeline end to end: the single-transaction
multi-claim, FIFO order through batcher.submit_many and across master
dispatch batches, per-sub-request failure isolation (a poisoned
sub-request requeues alone while its batch siblings complete),
idempotent replay of a timed-out batch member, and connection reuse
through the per-node keep-alive sessions.

Reproduce any failure locally:

    JAX_PLATFORMS=cpu python -m pytest tests/test_dispatch_batch.py -q
"""

import os
import threading
import time

import pytest
import requests

os.environ.setdefault("DLI_FAULTS_ENABLE", "1")

import jax
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher
from distributed_llm_inferencing_tpu.runtime.master import Master
from distributed_llm_inferencing_tpu.runtime.state import Store
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent


def _url(port, path):
    return f"http://127.0.0.1:{port}{path}"


# ---- store: single-transaction multi-claim ---------------------------

def test_claim_many_order_limit_and_due_filter():
    s = Store(":memory:")
    ids = [s.submit_request("m", f"p{i}") for i in range(5)]
    # park one behind backoff: invisible to the claim until due
    s.claim_next_pending()                      # ids[0] -> processing
    s.requeue(ids[0], delay_s=60.0)             # parked
    got = s.claim_next_pending_many(3)
    assert [r["id"] for r in got] == ids[1:4]   # FIFO, limit respected
    assert all(r["status"] == "pending" for r in got)  # snapshot pre-flip
    for r in got:
        assert s.get_request(r["id"])["status"] == "processing"
        assert r["started_at"] is not None
    rest = s.claim_next_pending_many(10)
    assert [r["id"] for r in rest] == ids[4:]   # parked id stays invisible
    assert s.claim_next_pending_many(10) == []


def test_group_commit_store_reads_its_own_writes(tmp_path):
    """Barriered group commit: a requeue/terminal write is visible (and
    on disk) the moment the call returns, even with the write-behind
    flusher in between."""
    db = str(tmp_path / "gc.sqlite3")
    s = Store(db, group_commit=True)
    rid = s.submit_request("m", "p")
    assert s.claim_next_pending()["id"] == rid
    s.requeue(rid, excluded_node_id=3, delay_s=0.0)
    assert s.claim_next_pending()["id"] == rid  # read-your-writes
    s.mark_completed(rid, "out", 1, 0.1, 2.0)
    # durability barrier: a fresh connection (separate Store) sees the
    # terminal status immediately — it was committed before return
    assert Store(db).get_request(rid)["status"] == "completed"
    s.close()


# ---- batcher: multi-submit entry -------------------------------------

def test_submit_many_preserves_order_and_validates_all_first():
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    b = ContinuousBatcher(cfg, params, num_blocks=64, block_size=8,
                          slots=4, max_seq=64)
    specs = [{"prompt": [1 + i, 2, 3], "max_new_tokens": 4,
              "sampling": SamplingParams.greedy()} for i in range(5)]
    reqs = b.submit_many(specs)
    assert [r.prompt[0] for r in reqs] == [1, 2, 3, 4, 5]
    assert [q.prompt[0] for q in b.queue] == [1, 2, 3, 4, 5]  # FIFO queue
    # all-or-nothing: one invalid spec enqueues nothing new
    bad = specs[:2] + [{"prompt": [1], "max_new_tokens": 999,
                        "sampling": SamplingParams.greedy()}]
    with pytest.raises(ValueError):
        b.submit_many(bad)
    assert len(b.queue) == 5


# ---- end-to-end: master + worker over /inference_batch ---------------

@pytest.fixture(scope="module")
def batched_worker():
    """Standing worker serving tiny-llama through the continuous
    batcher with ONE slot, so completion order proves admission order."""
    agent = WorkerAgent()
    srv = agent.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    r = requests.post(_url(port, "/load_model"), json={
        "model_name": "tiny-llama", "allow_random_init": True,
        "dtype": "float32", "serving": "batched", "slots": 1,
        "kv_blocks": 64, "kv_block_size": 8, "max_seq": 64}, timeout=300)
    assert r.status_code == 200, r.text
    # jit-warm one generation so timed tests don't pay compilation
    r = requests.post(_url(port, "/inference"), json={
        "model_name": "tiny-llama", "prompt": "hi", "max_new_tokens": 2,
        "sampling": {"do_sample": False}}, timeout=300)
    assert r.status_code == 200, r.text
    yield agent, port
    agent.service.shutdown()


def _mk_master(**kw):
    kw.setdefault("dispatcher_threads", 1)
    kw.setdefault("health_interval", 0.3)
    kw.setdefault("retry_backoff_base", 0.05)
    m = Master(":memory:", **kw)
    srv = m.service.serve("127.0.0.1", 0, background=True)
    return m, srv.server_address[1]


def _add_node(mport, wport, name="w1"):
    r = requests.post(_url(mport, "/api/nodes/add"), json={
        "name": name, "host": "127.0.0.1", "port": wport}).json()
    assert r["status"] == "success", r
    return r["node_id"]


def _submit(mport, prompt="hi", **kw):
    body = {"model_name": "tiny-llama", "prompt": prompt,
            "max_new_tokens": 3,
            "sampling": {"do_sample": False, "allow_random_init": True}}
    body.update(kw)
    r = requests.post(_url(mport, "/api/inference/submit"), json=body).json()
    assert r["status"] == "success", r
    return r["request_id"]


def _wait_terminal(mport, rid, timeout=90):
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = requests.get(
            _url(mport, f"/api/inference/status/{rid}")).json()["request"]
        if r["status"] in ("completed", "failed"):
            return r
        time.sleep(0.1)
    raise TimeoutError(f"request {rid} never reached a terminal state")


def test_fifo_order_within_and_across_batches(batched_worker):
    """6 requests, dispatch batch 3, single dispatcher, single batcher
    slot: completions must land in submission order — within one
    multiplexed batch (submit_many preserves wire order) and across
    consecutive batches (claim_next_pending_many is id-ordered)."""
    _, wport = batched_worker
    m, mport = _mk_master(dispatch_batch=3)
    try:
        _add_node(mport, wport)
        # submit before the dispatcher starts so batches form
        rids = [_submit(mport, prompt=f"request number {i}")
                for i in range(6)]
        m.start_background()
        finals = [_wait_terminal(mport, rid) for rid in rids]
        assert all(f["status"] == "completed" for f in finals), finals
        completed_at = [f["completed_at"] for f in finals]
        assert completed_at == sorted(completed_at), completed_at
        assert all(f["attempts"] == 0 for f in finals)
        # the multiplexed path actually ran: fewer RPC batches than reqs
        snap = m.metrics.snapshot()
        assert snap["timings"]["master_dispatch_batch_size"]["count"] >= 1
    finally:
        m.stop()


def test_poisoned_subrequest_requeues_alone(batched_worker):
    """One sub-request of a batch joins a wedged execution (its tag is
    registered in-flight on the worker) and times out into a per-sub
    408; the master requeues JUST that request — its two batch siblings
    complete on the first attempt. Releasing the wedge lets the retry
    take ownership and complete."""
    agent, wport = batched_worker
    # infer_timeout=8 -> worker join budget 3s: the poisoned sub answers
    # its 408 line well inside the master's read timeout
    m, mport = _mk_master(dispatch_batch=3, infer_timeout=8)
    try:
        _add_node(mport, wport)
        # the fixture-warmed prompt shape: no fresh prefill-bucket
        # compile may eat the 3s worker budget the 408 path relies on
        rids = [_submit(mport, prompt="hi") for _ in range(3)]
        poison = rids[1]
        tag = m._tag(poison)
        wedge = threading.Event()
        with agent._idem_lock:
            agent._inflight_tags[tag] = wedge   # simulate a stuck owner
        m.start_background()
        sib_finals = [_wait_terminal(mport, rid)
                      for rid in rids if rid != poison]
        assert all(f["status"] == "completed" and f["attempts"] == 0
                   for f in sib_finals), sib_finals
        # the poisoned member burned (at least) one attempt alone
        deadline = time.time() + 30
        while time.time() < deadline:
            st = requests.get(_url(
                mport, f"/api/inference/status/{poison}")).json()["request"]
            if st["attempts"] >= 1:
                break
            time.sleep(0.1)
        assert st["attempts"] >= 1, st
        assert st["status"] != "completed"
        # release the wedge exactly like _idem_release on a failed owner:
        # drop the in-flight registration, then wake joiners — the retry
        # re-claims ownership and runs the generation
        with agent._idem_lock:
            agent._inflight_tags.pop(tag, None)
            wedge.set()
        done = _wait_terminal(mport, poison)
        assert done["status"] == "completed", done
        assert done["attempts"] >= 1
    finally:
        m.stop()


def test_idempotent_replay_of_timed_out_batch_member(batched_worker):
    """The whole batch stalls past the master's timeout (latency fault
    on /inference_batch); every member requeues sticky, the worker
    finishes the generations anyway, and the retries replay from the
    idempotency cache — each prompt generated exactly once."""
    agent, wport = batched_worker
    m, mport = _mk_master(dispatch_batch=3, infer_timeout=7.5)
    try:
        _add_node(mport, wport)
        before = agent.metrics.snapshot()["timings"].get(
            "inference", {}).get("count", 0)
        r = requests.post(_url(wport, "/api/faults"), json={"faults": [
            {"point": "/inference_batch", "mode": "latency",
             "delay_s": 4.0, "times": 1}]}).json()
        assert r["status"] == "success", r
        # warmed prompt shape (see the poison test): the 2.5s worker
        # budget must cover generation, not a fresh bucket compile
        rids = [_submit(mport, prompt="hi") for _ in range(3)]
        m.start_background()
        finals = [_wait_terminal(mport, rid) for rid in rids]
        assert all(f["status"] == "completed" for f in finals), finals
        deadline = time.time() + 10     # late replays may still be landing
        while time.time() < deadline:
            after = agent.metrics.snapshot()["timings"]["inference"]["count"]
            if after - before == len(rids):
                break
            time.sleep(0.2)
        assert after - before == len(rids), \
            "a batch member was generated more than once"
    finally:
        agent.service.faults.clear()
        m.stop()


def test_connection_reuse_counter_climbs_under_sustained_load(
        batched_worker):
    """Pooled keep-alive sessions: sustained dispatch + health sweeps
    ride a handful of connections; the reuse counter climbs while the
    created counter stays near the pool's floor."""
    _, wport = batched_worker
    m, mport = _mk_master(dispatch_batch=4)
    try:
        _add_node(mport, wport)
        m.start_background()
        for i in range(12):
            _wait_terminal(mport, _submit(mport, prompt=f"reuse {i}"))
        c = m.metrics.snapshot()["counters"]
        created = c.get("master_rpc_conns_created", 0)
        reused = c.get("master_rpc_conns_reused", 0)
        assert reused >= 12, c
        assert reused / max(1.0, created + reused) > 0.6, c
    finally:
        m.stop()
