"""Disaggregated prefill/decode pools + cross-node KV transfer.

Covers the acceptance-critical invariants:
- the KV wire's frame codec round-trips and rejects every corruption
  class (bad magic, truncated stream, over-cap lengths, spec drift),
- ``POST /kv_fetch`` streams exactly the arena blocks asked for,
  reports missing digests, honors the size cap, and stays auth-gated,
- a decode continued from transferred KV is BITWISE identical to a cold
  prefill (greedy and sampled),
- chaos on the transfer wire (mid-stream disconnect, corrupt frames,
  injected 500, dead peer) degrades to recompute with identical output
  and never fails or corrupts the request — and costs at most one
  breaker strike,
- role-aware routing: strict pools, the mixed default's full backward
  compatibility, the sticky-retry pin surviving the role filter, and
  the >90%-full arena prefill avoidance,
- worker-side peer sessions reuse keep-alive sockets (created/reused
  accounting) and tear down on connection faults.
"""

import json
import time

import numpy as np
import pytest
import requests as rq

from distributed_llm_inferencing_tpu.runtime import kvwire
from distributed_llm_inferencing_tpu.runtime.master import Master
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent

# ~100 byte-tokens: long enough for many full 8-token blocks, short
# enough that "<mode> "-prefixed variants + 8 new tokens fit max_seq 128
LONG_PROMPT = "The quick brown fox jumps over the lazy dog. " * 2 + "Go."
SHORT_PROMPT = "hi there"


# ---- frame codec units --------------------------------------------------

def _pages():
    return [np.arange(24, dtype=np.float32).reshape(2, 3, 4),
            np.arange(6, dtype=np.int8).reshape(6),
            np.arange(4, dtype=np.float16).reshape(2, 2)]


def test_frame_roundtrip():
    frames = (kvwire.encode_frame("d1", _pages())
              + kvwire.encode_frame("d2", [np.ones((3,), np.int32)])
              + kvwire.encode_end(2, ["gone"], truncated=1,
                                  served_bytes=84))
    # feed in awkward chunk sizes: the reader must reassemble across
    # chunk boundaries
    chunks = [frames[i:i + 7] for i in range(0, len(frames), 7)]
    blocks, end = kvwire.decode_frames(chunks)
    assert set(blocks) == {"d1", "d2"}
    for got, want in zip(blocks["d1"], _pages()):
        assert got.dtype == want.dtype and got.shape == want.shape
        np.testing.assert_array_equal(got, want)
    assert end == {"end": True, "served": 2, "served_bytes": 84,
                   "missing": ["gone"], "missing_count": 1,
                   "truncated": 1}
    # the missing LIST is capped so the end-frame header can never blow
    # the decoder's MAX_HDR_BYTES; the count stays exact
    big = kvwire.encode_end(0, [f"{i:016x}" for i in range(4096)])
    _, end = kvwire.decode_frames([big])
    assert end["missing_count"] == 4096 and len(end["missing"]) == 256


@pytest.mark.parametrize("mangle", ["magic", "truncate", "hdr_cap",
                                    "payload_cap", "spec_short", "garbage"])
def test_frame_corruption_raises(mangle):
    import struct
    good = kvwire.encode_frame("d", _pages()) + kvwire.encode_end(1, [])
    if mangle == "magic":
        bad = b"XXXX" + good[4:]
    elif mangle == "truncate":
        bad = good[:len(good) // 2]     # stream ends before the end frame
    elif mangle == "hdr_cap":
        bad = kvwire.MAGIC + struct.pack(">II", 1 << 20, 0)
    elif mangle == "payload_cap":
        bad = kvwire.MAGIC + struct.pack(">II", 2, 1 << 30) + b"{}"
    elif mangle == "spec_short":
        # header promises more page bytes than the payload carries
        hdr = json.dumps({"digest": "d", "pages": [
            {"dtype": "<f4", "shape": [64]}]}).encode()
        bad = kvwire.MAGIC + struct.pack(">II", len(hdr), 8) + hdr + b"\0" * 8
    else:
        bad = b"#!<<injected corrupt body; not JSON>>"
    with pytest.raises(kvwire.WireError):
        kvwire.decode_frames([bad])


def test_decode_frames_byte_cap():
    frames = kvwire.encode_frame("d", [np.zeros((1024,), np.float32)])
    with pytest.raises(kvwire.WireError):
        kvwire.decode_frames([frames], max_total_bytes=64)


# ---- quantized (kvq8) frames --------------------------------------------

def _q8_record():
    from distributed_llm_inferencing_tpu.ops import kvblock_quant as kvq
    rng = np.random.default_rng(9)
    return kvq.quantize_block(
        [rng.standard_normal((2, 8, 2, 4)).astype(np.float32),
         rng.integers(0, 7, (5,)).astype(np.int8)])


def test_kvq8_frame_roundtrip():
    from distributed_llm_inferencing_tpu.ops import kvblock_quant as kvq
    rec = _q8_record()
    frames = (kvwire.encode_stored("q1", rec)
              + kvwire.encode_end(1, [], served_bytes=len(rec)))
    chunks = [frames[i:i + 7] for i in range(0, len(frames), 7)]
    blocks, _ = kvwire.decode_frames(chunks)
    got = blocks["q1"]
    assert kvq.is_quantized_block(got)
    for a, b in zip(kvq.dequantize_block(got), kvq.dequantize_block(rec)):
        np.testing.assert_array_equal(a, b)
    # stored/logical accounting dispatches on the representation
    assert kvwire.stored_nbytes(rec) == kvq.stored_nbytes(rec)
    assert kvwire.logical_nbytes(rec) > kvwire.stored_nbytes(rec)
    pages = _pages()
    assert kvwire.stored_nbytes(pages) == sum(p.nbytes for p in pages)


def _reframe(frame, mutate_hdr=None, mutate_payload=None):
    """Unpack one encoded frame, apply mutations, re-pack with
    consistent lengths — corruption the length prefixes can't catch,
    so the VALIDATION layer has to."""
    import struct
    hl, pl = struct.unpack(">II", frame[4:12])
    hdr = json.loads(frame[12:12 + hl])
    payload = frame[12 + hl:12 + hl + pl]
    if mutate_hdr:
        hdr = mutate_hdr(hdr)
    if mutate_payload:
        payload = mutate_payload(payload, hdr)
    h = json.dumps(hdr).encode()
    return (kvwire.MAGIC + struct.pack(">II", len(h), len(payload))
            + h + payload)


@pytest.mark.parametrize("mangle", [
    "quant_scheme", "meta_missing", "meta_count", "bad_dtype",
    "scale_truncated", "nonfinite_scale"])
def test_kvq8_frame_corruption_raises(mangle):
    """Quantized-frame corruption classes — bad scale lengths, dtype
    drift, truncated scale payloads, NaN scales — all raise WireError
    (-> recompute on the fetching side), never crash or yield a record
    that would silently poison a dequant."""
    rec = _q8_record()
    frame = kvwire.encode_stored("q", rec)
    q_nbytes = rec["pages"][0]["q"].nbytes

    def hdr_mut(hdr):
        if mangle == "quant_scheme":
            hdr["quant"] = "kvq9"
        elif mangle == "meta_missing":
            del hdr["meta"]
        elif mangle == "meta_count":
            hdr["meta"] = hdr["meta"] + [{"kind": "raw"}]
        elif mangle == "bad_dtype":
            hdr["meta"][0]["dtype"] = "int64"
        elif mangle == "scale_truncated":
            # scale page shorter than the q page's (layers, heads)
            hdr["pages"][1]["shape"] = [1, 2]
        return hdr

    def payload_mut(payload, hdr):
        if mangle == "scale_truncated":
            return payload[:q_nbytes + 8]   # 1x2 float32 scales
        if mangle == "nonfinite_scale":
            import struct
            return (payload[:q_nbytes] + struct.pack("<f", float("nan"))
                    + payload[q_nbytes + 4:])
        return payload

    bad = _reframe(frame, hdr_mut, payload_mut) + kvwire.encode_end(1, [])
    with pytest.raises(kvwire.WireError):
        kvwire.decode_frames([bad])


# ---- live workers -------------------------------------------------------

def _mk_worker(role="mixed", **load_kw):
    agent = WorkerAgent(role=role)
    srv = agent.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    body = {"model_name": "tiny-llama", "allow_random_init": True,
            "dtype": "float32", "serving": "batched", "slots": 4,
            "kv_blocks": 64, "kv_block_size": 8, "max_seq": 128}
    body.update(load_kw)
    r = rq.post(f"http://127.0.0.1:{port}/load_model", json=body,
                timeout=600)
    assert r.status_code == 200, r.text
    return agent, port


def _infer(port, prompt, max_new=6, seed=11, do_sample=False, **extra):
    body = {"model_name": "tiny-llama", "prompt": prompt,
            "max_new_tokens": max_new, "seed": seed,
            "sampling": {"do_sample": do_sample, "temperature": 0.8,
                         "top_k": 20}}
    body.update(extra)
    r = rq.post(f"http://127.0.0.1:{port}/inference", json=body,
                timeout=600)
    assert r.status_code == 200, r.text
    return r.json()


def _counters(agent):
    return agent.metrics.snapshot()["counters"]


@pytest.fixture(scope="module")
def prefill_worker():
    agent, port = _mk_worker(role="prefill")
    yield agent, port
    agent.service.shutdown()


def test_health_reports_role_and_occupancy(prefill_worker):
    agent, port = prefill_worker
    h = rq.get(f"http://127.0.0.1:{port}/health").json()
    assert h["role"] == "prefill"
    assert "arena_occupancy" in h
    _infer(port, LONG_PROMPT, kv_export=True)
    h = rq.get(f"http://127.0.0.1:{port}/health").json()
    assert h["arena_occupancy"] is not None and h["arena_occupancy"] > 0
    # the scheduler stats carry the occupancy fraction per model too
    kv = h["loaded_models"][0]["scheduler"]["kvtier"]
    assert 0 < kv["occupancy"] <= 1


def test_bad_role_rejected():
    with pytest.raises(ValueError):
        WorkerAgent(role="gpu")


def test_kv_fetch_endpoint_serves_exported_blocks(prefill_worker):
    agent, port = prefill_worker
    res = _infer(port, LONG_PROMPT, kv_export=True)
    m = agent.models["tiny-llama"]
    bs = m.batcher.block_size
    prompt_toks = m.tokenizer.encode(LONG_PROMPT)
    digs = m.batcher.kvtier.block_digests(
        prompt_toks[:len(prompt_toks) // bs * bs])
    assert digs and all(m.batcher.kvtier.arena.peek(d) for d in digs)
    r = rq.post(f"http://127.0.0.1:{port}/kv_fetch",
                json={"model_name": "tiny-llama",
                      "digests": digs + ["feedfacefeedface"]},
                stream=True, timeout=30)
    assert r.status_code == 200
    assert "octet-stream" in r.headers["Content-Type"]
    blocks, end = kvwire.decode_frames(r.iter_content(chunk_size=4096))
    assert set(blocks) == set(digs)
    assert end["served"] == len(digs) and end["truncated"] == 0
    assert end["served_bytes"] > 0      # honest partial-fetch sizing
    assert end["missing"] == ["feedfacefeedface"]
    # frames carry the exact arena bytes
    for d in digs:
        arena_pages = m.batcher.kvtier.arena.peek_pages(d)
        for got, want in zip(blocks[d], arena_pages):
            np.testing.assert_array_equal(got, np.asarray(want))
    assert res["tokens"]   # the export pass still answered normally


def test_kv_fetch_validation(prefill_worker):
    _, port = prefill_worker
    url = f"http://127.0.0.1:{port}/kv_fetch"
    assert rq.post(url, json={"model_name": "nope",
                              "digests": ["d"]}).status_code == 404
    assert rq.post(url, json={"model_name": "tiny-llama",
                              "digests": []}).status_code == 400
    assert rq.post(url, json={"model_name": "tiny-llama",
                              "digests": [1, 2]}).status_code == 400
    assert rq.post(url, json={
        "model_name": "tiny-llama",
        "digests": ["d"] * (kvwire.MAX_DIGESTS + 1)}).status_code == 400


def test_kv_fetch_size_cap(prefill_worker, monkeypatch):
    from distributed_llm_inferencing_tpu.runtime import worker as worker_mod
    agent, port = prefill_worker
    _infer(port, LONG_PROMPT, kv_export=True)
    m = agent.models["tiny-llama"]
    toks = m.tokenizer.encode(LONG_PROMPT)
    bs = m.batcher.block_size
    digs = m.batcher.kvtier.block_digests(toks[:len(toks) // bs * bs])
    # cap below one frame: everything truncates, nothing served
    monkeypatch.setattr(worker_mod, "KV_FETCH_MAX_MB", 1e-6)
    r = rq.post(f"http://127.0.0.1:{port}/kv_fetch",
                json={"model_name": "tiny-llama", "digests": digs},
                stream=True, timeout=30)
    blocks, end = kvwire.decode_frames(r.iter_content(chunk_size=4096))
    assert not blocks and end["truncated"] == len(digs)
    assert end["served"] == 0 and end["served_bytes"] == 0
    # cap fitting exactly one frame: the terminal frame reports the
    # blocks AND bytes actually served, so the peer can size its
    # recompute fallback to the true shortfall
    one = len(kvwire.encode_stored(
        digs[0], m.batcher.kvtier.arena.peek_stored(digs[0])))
    monkeypatch.setattr(worker_mod, "KV_FETCH_MAX_MB", one / (1 << 20))
    r = rq.post(f"http://127.0.0.1:{port}/kv_fetch",
                json={"model_name": "tiny-llama", "digests": digs},
                stream=True, timeout=30)
    blocks, end = kvwire.decode_frames(r.iter_content(chunk_size=4096))
    assert len(blocks) == 1 and end["served"] == 1
    assert end["served_bytes"] == one
    assert end["truncated"] == len(digs) - 1


@pytest.fixture(scope="module")
def trio():
    """(src prefill, dst decode, cold mixed) worker trio shared by the
    bitwise and chaos tests — each test uses a distinct prompt family so
    one test's radix/arena state can't mask another's transfer."""
    src = _mk_worker(role="prefill")
    dst = _mk_worker(role="decode")
    cold = _mk_worker(role="mixed")
    yield src, dst, cold
    for a, _ in (src, dst, cold):
        a.service.shutdown()


def test_transferred_decode_bitwise_identical(trio):
    """The headline guarantee: decode continued from fetched KV emits
    the exact tokens a cold single-node run emits — greedy AND sampled."""
    (src, src_port), (dst, dst_port), (cold, cold_port) = trio
    for do_sample, seed in ((False, 11), (True, 12)):
        # cold reference on a worker that never saw the prompt
        ref = _infer(cold_port, LONG_PROMPT, max_new=8, seed=seed,
                     do_sample=do_sample)
        # disaggregated: prefill+export on src, decode on dst with a
        # kv_source hint back at src
        _infer(src_port, LONG_PROMPT, max_new=1, seed=seed,
               do_sample=do_sample, kv_export=True)
        before = _counters(dst).get("kv_transfer_blocks", 0)
        got = _infer(dst_port, LONG_PROMPT, max_new=8, seed=seed,
                     do_sample=do_sample,
                     kv_source={"url": f"http://127.0.0.1:{src_port}",
                                "model": "tiny-llama"})
        assert got["tokens"] == ref["tokens"], (do_sample, seed)
        assert got["result"] == ref["result"]
        transferred = _counters(dst)["kv_transfer_blocks"] - before
        if do_sample:
            # second pass, same prompt: the first already parked the
            # blocks locally, so no new transfer is required
            assert got["cost"]["prefill_cached_tokens"] > 0
        else:
            assert transferred > 0      # the KV really crossed nodes
            assert got["cost"]["kv_transfer_bytes"] > 0


def test_peer_session_reuse_and_teardown():
    """PR 4 treatment on the worker-side peer sessions: the second fetch
    rides the pooled keep-alive socket (reused climbs, created doesn't),
    and a dead peer purges the session so the next dial is fresh."""
    src, src_port = _mk_worker(role="prefill")
    dst, _dst_port = _mk_worker(role="decode")
    try:
        _infer(src_port, LONG_PROMPT, kv_export=True)
        m = src.models["tiny-llama"]
        toks = m.tokenizer.encode(LONG_PROMPT)
        bs = m.batcher.block_size
        digs = m.batcher.kvtier.block_digests(toks[:len(toks) // bs * bs])
        client = dst.peer_client()
        url = f"http://127.0.0.1:{src_port}"
        got = client.fetch(url, "tiny-llama", digs)
        assert set(got) == set(digs)
        c = _counters(dst)
        assert c["worker_peer_conns_created"] == 1
        client.fetch(url, "tiny-llama", digs[:1])
        c = _counters(dst)
        assert c["worker_peer_conns_created"] == 1
        assert c["worker_peer_conns_reused"] >= 1
        # dead peer: the fetch fails loudly and the session is purged
        src.service.shutdown()
        with pytest.raises(Exception):
            client.fetch(url, "tiny-llama", digs[:1])
        assert url not in client._sessions
    finally:
        dst.service.shutdown()
        src.service.shutdown()


def test_restore_from_peer_rejects_mismatched_pages():
    """A peer serving a different cache layout must degrade to
    recompute, not crash the scheduler thread in the restore scatter."""
    import jax
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models.params import init_params
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)

    class BadFetcher:
        calls = 0

        def fetch(self, url, model, digests):
            self.calls += 1
            return {d: [np.zeros((3, 5), np.float64)] for d in digests}

    fetcher = BadFetcher()
    b = ContinuousBatcher(cfg, params, num_blocks=32, block_size=8,
                          slots=2, max_seq=128, kv_fetcher=fetcher)
    prompt = list(range(40))
    ref = b.submit(list(prompt), max_new_tokens=6,
                   sampling=SamplingParams.greedy(), seed=5)
    for _ in range(200):
        b.step()
        if ref.done.is_set():
            break
    cold = ref.wait()
    b2 = ContinuousBatcher(cfg, params, num_blocks=32, block_size=8,
                           slots=2, max_seq=128, kv_fetcher=fetcher)
    r2 = b2.submit(list(prompt), max_new_tokens=6,
                   sampling=SamplingParams.greedy(), seed=5,
                   kv_source={"url": "http://peer", "model": "tiny-llama"})
    for _ in range(200):
        b2.step()
        if r2.done.is_set():
            break
    assert r2.wait() == cold            # recompute, identical output
    assert fetcher.calls == 1           # one peer RPC per request
    c = b2.metrics.snapshot()["counters"]
    assert c["kv_transfer_failures"] >= 1
    assert c["kv_transfer_blocks"] == 0


# ---- chaos on the transfer wire ----------------------------------------

@pytest.mark.parametrize("mode", ["disconnect", "corrupt", "error",
                                  "timeout"])
def test_chaos_kv_fetch_degrades_to_recompute(trio, mode):
    """Killing/corrupting the KV source mid-fetch never fails or
    corrupts the decode request: output stays bitwise identical to a
    cold prefill (no duplicated or lost tokens) and the failure is
    surfaced in kv_transfer_failures. ``timeout`` arms the CLIENT-side
    ``rpc:/kv_fetch`` point (the decode node's own fault injector);
    the rest are server-side on the source."""
    (src, src_port), (dst, dst_port), (cold, cold_port) = trio
    prompt = f"<{mode}> {LONG_PROMPT}"    # per-mode prompt family: an
    # earlier mode's recompute left ITS prompt radix-cached on dst
    try:
        ref = _infer(cold_port, prompt, max_new=8, seed=21)
        _infer(src_port, prompt, max_new=1, seed=21, kv_export=True)
        if mode == "timeout":
            dst.service.faults.arm([{"point": "rpc:/kv_fetch",
                                     "mode": "timeout", "times": 1}],
                                   seed=0)
        else:
            src.service.faults.arm([{"point": "/kv_fetch", "mode": mode,
                                     "times": 1}], seed=0)
        fails0 = _counters(dst).get("kv_transfer_failures", 0)
        blocks0 = _counters(dst).get("kv_transfer_blocks", 0)
        got = _infer(dst_port, prompt, max_new=8, seed=21,
                     kv_source={"url": f"http://127.0.0.1:{src_port}",
                                "model": "tiny-llama"})
        assert got["tokens"] == ref["tokens"]
        c = _counters(dst)
        assert c["kv_transfer_failures"] - fails0 >= 1
        assert c["kv_transfer_blocks"] - blocks0 == 0
    finally:
        src.service.faults.clear()
        dst.service.faults.clear()


def test_chaos_disagg_source_death_no_breaker_storm():
    """Full master-driven flow with the prefill node crashing before
    the fetch: the decode request completes by recompute, and the chaos
    costs AT MOST one breaker strike (the transfer failure itself is a
    worker-to-worker affair the master's breaker never sees)."""
    src, src_port = _mk_worker(role="prefill")
    dst, dst_port = _mk_worker(role="decode")
    m = Master(":memory:", health_interval=30.0, disagg_min_prompt=64)
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{msrv.server_address[1]}"
    try:
        for i, p in enumerate((src_port, dst_port)):
            r = rq.post(f"{base}/api/nodes/add",
                        json={"name": f"w{i}", "host": "127.0.0.1",
                              "port": p}).json()
            assert r["status"] == "success", r
        m.start_background()
        # the decode-side fetch will hit a dead listener: sever the
        # source right after its prefill pass via a crash fault
        src.service.faults.arm([{"point": "/kv_fetch", "mode": "crash",
                                 "times": 1}], seed=0)
        rid = rq.post(f"{base}/api/inference/submit", json={
            "model_name": "tiny-llama", "prompt": LONG_PROMPT,
            "max_new_tokens": 6,
            "sampling": {"do_sample": False,
                         "allow_random_init": True}}).json()["request_id"]
        deadline = time.time() + 240
        while time.time() < deadline:
            st = rq.get(f"{base}/api/inference/status/{rid}"
                        ).json()["request"]
            if st["status"] in ("completed", "failed"):
                break
            time.sleep(0.2)
        assert st["status"] == "completed", st
        assert _counters(dst)["kv_transfer_failures"] >= 1
        strikes = [n["consecutive_failures"]
                   for n in m.store.list_nodes()]
        assert max(strikes) <= 1
        mc = m.metrics.snapshot()["counters"]
        assert mc["scheduler_disagg_transfer"] >= 1
    finally:
        m.stop()
        src.service.shutdown()
        dst.service.shutdown()


# ---- int8 wire tier + single-flight prefetch ----------------------------

def test_int8_worker_transfer_greedy_match_and_compression(
        trio, monkeypatch):
    """End-to-end int8 transfer between live workers: the decode
    continued from quantized fetched KV emits the exact greedy tokens
    of a cold native run, the wire ships >=3.5x fewer bytes than the
    logical pages, and the arena advertises honest stored bytes."""
    monkeypatch.setenv("DLI_KV_HOST_DTYPE", "int8")
    src, src_port = _mk_worker(role="prefill")
    dst, dst_port = _mk_worker(role="decode")
    (_, _), (_, _), (cold, cold_port) = trio   # native cold reference
    prompt = f"<q8> {LONG_PROMPT}"
    try:
        ref = _infer(cold_port, prompt, max_new=8, seed=31)
        _infer(src_port, prompt, max_new=1, seed=31, kv_export=True)
        got = _infer(dst_port, prompt, max_new=8, seed=31,
                     kv_source={"url": f"http://127.0.0.1:{src_port}",
                                "model": "tiny-llama"})
        assert got["tokens"] == ref["tokens"]
        sc, dc = _counters(src), _counters(dst)
        assert sc["kv_wire_sent_bytes"] > 0
        assert sc["kv_wire_sent_bytes"] < sc["kv_wire_raw_bytes"] / 3.5
        assert dc["kv_transfer_failures"] == 0
        assert dc["kv_transfer_blocks"] > 0
        # transfer accounting counts STORED (compressed) wire bytes
        assert dc["kv_transfer_bytes"] == sc["kv_wire_sent_bytes"]
        st = src.models["tiny-llama"].batcher.kvtier.stats()
        assert st["dtype"] == "int8"
        assert st["logical_bytes"] > st["bytes"] * 3.5
    finally:
        src.service.shutdown()
        dst.service.shutdown()


def test_single_flight_prefetch_coalesces():
    """Seeded concurrent prefetches of the same digest set coalesce
    onto ONE wire transfer: the first caller leads, the rest register
    as waiters (kv_prefetch_coalesced), and every caller finds the
    blocks arena-resident afterward."""
    import threading
    import jax
    import jax.numpy as jnp
    from distributed_llm_inferencing_tpu.models.params import init_params
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = list(range(40))
    # source batcher: run + export so its arena holds the blocks
    b1 = ContinuousBatcher(cfg, params, num_blocks=32, block_size=8,
                           slots=2, max_seq=128)
    r = b1.submit(list(prompt), max_new_tokens=2,
                  sampling=SamplingParams.greedy(), seed=5,
                  kv_export=True)
    for _ in range(200):
        b1.step()
        if r.done.is_set():
            break
    r.wait()
    bs = b1.block_size
    digs = b1.kvtier.block_digests(prompt[:len(prompt) // bs * bs])
    served = {d: tuple(np.asarray(p)
                       for p in b1.kvtier.arena.peek_pages(d))
              for d in digs}

    class Peer:
        calls = 0

        def fetch(self, url, model, digests):
            self.calls += 1
            return {d: served[d] for d in digests if d in served}

    fetcher = Peer()
    b2 = ContinuousBatcher(cfg, params, num_blocks=32, block_size=8,
                           slots=2, max_seq=128, kv_fetcher=fetcher)
    b2._wire_overlap = False
    started, release = threading.Event(), threading.Event()
    wire_calls = []
    orig = b2._wire_fetch

    def gated(url, model, want, progress=None):
        wire_calls.append(list(want))
        started.set()
        release.wait(30)    # hold the leader in flight so the waiters
        return orig(url, model, want, progress=progress)   # must queue

    b2._wire_fetch = gated
    src = {"url": "http://peer", "model": "tiny-llama"}
    results = []

    def prefetch():
        results.append(b2.prefetch_kv(list(prompt), src))

    leader = threading.Thread(target=prefetch)
    leader.start()
    assert started.wait(10)
    waiters = [threading.Thread(target=prefetch) for _ in range(4)]
    for t in waiters:
        t.start()
    # every waiter must have REGISTERED (seen the in-flight entry and
    # counted itself) before the leader is released — that is the race
    # the registry exists for
    deadline = time.time() + 10
    while time.time() < deadline:
        c = b2.metrics.snapshot()["counters"]
        if c.get("kv_prefetch_coalesced", 0) >= 4:
            break
        time.sleep(0.01)
    release.set()
    leader.join(timeout=30)
    for t in waiters:
        t.join(timeout=30)
    c = b2.metrics.snapshot()["counters"]
    assert c["kv_prefetch_coalesced"] == 4
    assert fetcher.calls == 1           # exactly one wire transfer
    assert len(wire_calls) == 1
    want = digs[:(len(prompt) - 1) // bs]
    assert wire_calls[0] == want        # the deduped union, in order
    assert all(b2.kvtier.arena.peek(d) for d in want)
    assert sorted(results, reverse=True)[0] > 0     # leader got bytes
    assert sorted(results)[:4] == [0, 0, 0, 0]      # waiters shared


# ---- role-aware routing -------------------------------------------------

def _role_master(roles, runtime=None):
    """Master with synthetic nodes declaring ``roles`` (no live workers
    — routing units only)."""
    m = Master(":memory:", dispatcher_threads=0)
    for i, role in enumerate(roles):
        nid = m.store.add_node(f"n{i}", "127.0.0.1", 9000 + i,
                               is_active=True)
        m.store.update_node(nid, info={
            "role": role, "arena_occupancy": 0.1,
            "loaded_models": [{"name": "mod", "scheduler": {
                "queued": 0, "blocks_free": 10,
                "kvtier": {"occupancy": 0.1}}}]})
        m._note_runtime(nid, json.loads(
            m.store.get_node(nid)["info"]))
        if runtime and i in runtime:
            m._node_runtime[nid].update(runtime[i])
    return m


def test_pick_node_role_pools():
    m = _role_master(["prefill", "decode", "mixed"])
    try:
        ids = {n["name"]: n["id"]
               for n in m.store.list_nodes()}
        picked = {m._pick_node("mod", role="decode")["id"]
                  for _ in range(12)}
        assert ids["n0"] not in picked          # strict prefill excluded
        picked = {m._pick_node("mod", role="prefill")["id"]
                  for _ in range(12)}
        assert ids["n1"] not in picked          # strict decode excluded
        # no compatible node at all -> fall back to everyone
        m2 = _role_master(["prefill", "prefill"])
        assert m2._pick_node("mod", role="decode") is not None
        m2.stop()
        # mixed fleet: role filter is a no-op, counters untouched
        m3 = _role_master(["mixed", "mixed"])
        m3._pick_node("mod", role="decode")
        assert m3.metrics.snapshot()["counters"][
            "scheduler_pick_role_decode"] == 0
        m3.stop()
    finally:
        m.stop()


def test_pick_node_role_keeps_sticky_pin():
    """A timeout retry pinned to the node that holds its in-flight
    generation must reach it even when the role filter would drop it."""
    m = _role_master(["prefill", "decode"])
    try:
        pid = m.store.list_nodes()[0]["id"]
        n = m._pick_node("mod", role="decode", prefer=pid)
        assert n["id"] == pid
    finally:
        m.stop()


def test_pick_node_avoids_full_arena_for_prefill():
    m = _role_master(["prefill", "prefill"],
                     runtime={0: {"arena_occ": 0.97},
                              1: {"arena_occ": 0.2}})
    try:
        nodes = m.store.list_nodes()
        for _ in range(6):
            assert m._pick_node("mod", role="prefill")["id"] \
                == nodes[1]["id"]
        c = m.metrics.snapshot()["counters"]
        assert c["scheduler_pick_arena_full_avoided"] >= 1
        # both full: better a full arena than no prefill at all
        m._node_runtime[nodes[1]["id"]]["arena_occ"] = 0.99
        assert m._pick_node("mod", role="prefill") is not None
    finally:
        m.stop()


def test_plan_disagg_decisions():
    m = _role_master(["prefill", "decode"])
    try:
        snapshot = m.store.list_nodes(active_only=True)

        def req(prompt, attempts=0, excluded=None):
            return {"id": 1, "model_name": "mod", "prompt": prompt,
                    "attempts": attempts,
                    "excluded_nodes": excluded or [],
                    "sampling": {}}
        m._disagg_min_prompt = 64
        plan = m._plan_disagg(req("x" * 100), snapshot)
        assert plan is not None
        (pn, dn) = plan
        assert m._node_role(pn) == "prefill" and m._node_role(dn) == "decode"
        # reservations were taken — release for the next checks
        with m._inflight_lock:
            m._inflight.clear()
        # short prompt / retries / disabled policy never disaggregate
        assert m._plan_disagg(req("x" * 10), snapshot) is None
        assert m._plan_disagg(req("x" * 100, attempts=1), snapshot) is None
        assert m._plan_disagg(req("x" * 100, excluded=[1]), snapshot) is None
        m._disagg = False
        assert m._plan_disagg(req("x" * 100), snapshot) is None
        m._disagg = True
        # a prefill node WITHOUT a host arena (engine-serving or
        # kv_host_mb=0) cannot export: the plan must refuse instead of
        # silently double-prefilling every long prompt
        for n in snapshot:
            n.pop("_can_export", None)
        pid = snapshot[0]["id"]
        saved = m._node_runtime[pid]
        m._node_runtime[pid] = {"queue": 0, "free_blocks": 10,
                                "arena_occ": None, "at": time.time(),
                                "models": {}}
        m.store.update_node(pid, info={"role": "prefill",
                                       "arena_occupancy": None,
                                       "loaded_models": []})
        snap2 = m.store.list_nodes(active_only=True)
        assert m._plan_disagg(req("x" * 100), snap2) is None
        with m._inflight_lock:
            m._inflight.clear()
        m._node_runtime[pid] = saved
        # a warm decode node tips the decision to recompute-by-affinity
        from distributed_llm_inferencing_tpu.runtime.kvtier import (
            PrefixDigestIndex)
        idx = PrefixDigestIndex(chunk=16)
        idx.note("x" * 100, 25)
        dn_id = snapshot[1]["id"]
        m._node_runtime[dn_id]["models"]["mod"]["digests"] = \
            idx.advertise()
        before = m.metrics.snapshot()["counters"][
            "scheduler_disagg_recompute"]
        assert m._plan_disagg(req("x" * 100), snapshot) is None
        after = m.metrics.snapshot()["counters"][
            "scheduler_disagg_recompute"]
        assert after == before + 1
    finally:
        m.stop()


def test_mixed_fleet_never_disaggregates():
    m = _role_master(["mixed", "mixed"])
    try:
        snapshot = m.store.list_nodes(active_only=True)
        req = {"id": 1, "model_name": "mod", "prompt": "x" * 4096,
               "attempts": 0, "excluded_nodes": [], "sampling": {}}
        assert m._plan_disagg(req, snapshot) is None
        c = m.metrics.snapshot()["counters"]
        assert c["scheduler_disagg_transfer"] == 0
        assert c["scheduler_disagg_recompute"] == 0
    finally:
        m.stop()


def test_node_status_reports_role_and_arena():
    m = _role_master(["prefill", "decode"],
                     runtime={0: {"arena_occ": 0.5}})
    try:
        nodes = m.api_node_status({})["nodes"]
        assert [n["role"] for n in nodes] == ["prefill", "decode"]
        assert nodes[0]["arena_occupancy"] == 0.5
    finally:
        m.stop()
