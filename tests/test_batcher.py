"""Continuous batcher: scheduling, prefix reuse, preemption, streaming.

Oracle for token content is the dense-cache engine in greedy mode (dense ≡
paged is pinned separately in tests/test_paged.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(0)


def run_until_done(b, reqs, max_steps=400):
    for _ in range(max_steps):
        b.step()
        if all(r.done.is_set() for r in reqs):
            return
    raise AssertionError(
        f"not done after {max_steps} steps: "
        f"{[(r.done.is_set(), r.error, len(r.tokens)) for r in reqs]}")


def dense_greedy(prompt, n):
    eng = InferenceEngine(CFG, PARAMS, max_seq=128)
    return eng.generate([prompt], max_new_tokens=n,
                        sampling=SamplingParams.greedy()).tokens[0]


def test_single_request_matches_engine():
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=4, max_seq=128)
    prompt = RNG.integers(0, CFG.vocab_size, 13).tolist()
    r = b.submit(prompt, max_new_tokens=20, sampling=SamplingParams.greedy())
    run_until_done(b, [r])
    assert r.wait() == dense_greedy(prompt, 20)
    assert r.ttft_ms is not None and r.finished_at is not None


def test_concurrent_mixed_sampling():
    """Slots advance together; per-slot sampling params are independent."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=128, block_size=8,
                          slots=4, max_seq=128)
    greedy_prompt = RNG.integers(0, CFG.vocab_size, 9).tolist()
    reqs = [b.submit(greedy_prompt, max_new_tokens=15,
                     sampling=SamplingParams.greedy())]
    for i in range(5):   # more requests than slots -> queueing
        p = RNG.integers(0, CFG.vocab_size, 5 + i).tolist()
        reqs.append(b.submit(p, max_new_tokens=10 + i,
                             sampling=SamplingParams(temperature=0.7)))
    run_until_done(b, reqs)
    for i, r in enumerate(reqs):
        assert r.error is None, r.error
        want = 15 if i == 0 else 10 + (i - 1)
        assert len(r.tokens) == want
    # the greedy request must be bit-identical to the engine even though it
    # shared decode steps with sampling requests
    assert reqs[0].tokens == dense_greedy(greedy_prompt, 15)


def test_prefix_cache_reuse_across_requests():
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=2, max_seq=128)
    sys_prompt = RNG.integers(0, CFG.vocab_size, 24).tolist()  # 3 full blocks
    p1 = sys_prompt + RNG.integers(0, CFG.vocab_size, 4).tolist()
    r1 = b.submit(p1, max_new_tokens=5, sampling=SamplingParams.greedy())
    run_until_done(b, [r1])
    misses_before = b.pool.stats()["prefix_misses"]

    p2 = sys_prompt + RNG.integers(0, CFG.vocab_size, 6).tolist()
    r2 = b.submit(p2, max_new_tokens=5, sampling=SamplingParams.greedy())
    run_until_done(b, [r2])
    st = b.pool.stats()
    assert st["prefix_hits"] >= 1, st      # shared blocks were reused
    assert st["prefix_misses"] == misses_before
    assert r2.wait() == dense_greedy(p2, 5)   # reuse didn't change tokens


def test_identical_prompt_full_hit():
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=2, max_seq=128)
    prompt = RNG.integers(0, CFG.vocab_size, 17).tolist()
    r1 = b.submit(prompt, max_new_tokens=6, sampling=SamplingParams.greedy())
    run_until_done(b, [r1])
    r2 = b.submit(prompt, max_new_tokens=6, sampling=SamplingParams.greedy())
    run_until_done(b, [r2])
    assert r1.wait() == r2.wait()


def test_preemption_under_memory_pressure():
    """A pool too small for all requests still completes every request
    correctly via preempt-and-resume."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=10, block_size=8,
                          slots=3, max_seq=80)
    prompts = [RNG.integers(0, CFG.vocab_size, 12).tolist() for _ in range(3)]
    reqs = [b.submit(p, max_new_tokens=12, sampling=SamplingParams.greedy())
            for p in prompts]
    run_until_done(b, reqs)
    for p, r in zip(prompts, reqs):
        assert r.error is None, r.error
        assert r.wait() == dense_greedy(p, 12)


def test_pool_exhausted_is_reported():
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=2, block_size=8,
                          slots=2, max_seq=64)
    r = b.submit(RNG.integers(0, CFG.vocab_size, 30).tolist(),
                 max_new_tokens=4)
    for _ in range(20):
        b.step()
        if r.done.is_set():
            break
    assert r.error and "exhausted" in r.error
    with pytest.raises(RuntimeError):
        r.wait()


def test_streaming_and_eos():
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=2, max_seq=128)
    prompt = RNG.integers(0, CFG.vocab_size, 11).tolist()
    full = dense_greedy(prompt, 10)
    # use the 4th generated token as "eos": generation must stop before it
    eos = full[3]
    want = full[:3] if eos not in full[:3] else None
    seen = []
    r = b.submit(prompt, max_new_tokens=10, sampling=SamplingParams.greedy(),
                 eos_token_id=eos, stream_cb=seen.append)
    run_until_done(b, [r])
    got = r.wait()
    if want is not None:
        assert got == want
    assert seen == got          # streamed exactly the kept tokens, in order
    assert eos not in got


def test_seeded_sampling_reproducible_across_interleavings():
    """A request's sampled output depends only on (params, prompt, seed) —
    not on what else shares its decode steps."""
    prompt = RNG.integers(0, CFG.vocab_size, 10).tolist()
    sp = SamplingParams(temperature=0.9, top_k=40, top_p=0.9)

    b1 = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                           slots=4, max_seq=128)
    alone = b1.submit(prompt, max_new_tokens=12, sampling=sp, seed=1234)
    run_until_done(b1, [alone])

    b2 = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                           slots=4, max_seq=128)
    noise = [b2.submit(RNG.integers(0, CFG.vocab_size, 6 + i).tolist(),
                       max_new_tokens=20, sampling=sp, seed=i)
             for i in range(3)]
    crowded = b2.submit(prompt, max_new_tokens=12, sampling=sp, seed=1234)
    run_until_done(b2, noise + [crowded])
    assert crowded.wait() == alone.wait()


def test_cancel_frees_slot():
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=2, max_seq=128)
    r = b.submit(RNG.integers(0, CFG.vocab_size, 8).tolist(),
                 max_new_tokens=100, sampling=SamplingParams.greedy())
    b.step()
    assert not r.done.is_set()
    r.cancel()
    b.step()
    assert r.done.is_set() and r.error == "cancelled"
    assert b.stats()["active"] == 0
    # its blocks came back
    assert b.pool.free_count() > 0


def test_stop_drains_inflight_requests():
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=1, max_seq=128)
    active = b.submit(RNG.integers(0, CFG.vocab_size, 8).tolist(),
                      max_new_tokens=100)
    queued = b.submit(RNG.integers(0, CFG.vocab_size, 8).tolist(),
                      max_new_tokens=100)
    b.step()
    b.stop()   # no thread started; must still fail both requests
    assert active.done.is_set() and queued.done.is_set()
    with pytest.raises(RuntimeError, match="stopped"):
        queued.wait(timeout=1)


def test_background_thread_serving():
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=4, max_seq=128)
    b.start()
    try:
        prompt = RNG.integers(0, CFG.vocab_size, 8).tolist()
        reqs = [b.submit(prompt, max_new_tokens=8,
                         sampling=SamplingParams.greedy())
                for _ in range(6)]
        outs = [r.wait(timeout=300) for r in reqs]
        assert all(o == outs[0] for o in outs)
    finally:
        b.stop()
    st = b.stats()
    assert st["active"] == 0 and st["tokens_out"] >= 48

class OpCounter:
    """program_hook stand-in that counts dispatched programs by kind."""

    def __init__(self):
        self.ops = []

    def __call__(self, kind, args, run):
        self.ops.append((kind, args))
        return run()

    def count(self, kind):
        return sum(1 for k, _ in self.ops if k == kind)


def test_chunked_decode_amortizes_dispatches():
    """K-token on-device chunks: a 40-token generation costs a handful of
    dispatched programs, not one per token (the round-2 batcher's 6.6x
    regression vs the engine)."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=128, block_size=8,
                          slots=4, max_seq=128)
    counter = OpCounter()
    b.program_hook = counter
    prompts = [RNG.integers(0, CFG.vocab_size, 12).tolist() for _ in range(4)]
    reqs = [b.submit(p, max_new_tokens=40, sampling=SamplingParams.greedy())
            for p in prompts]
    run_until_done(b, reqs)
    for p, r in zip(prompts, reqs):
        assert r.wait() == dense_greedy(p, 40)
    # burst of 4 same-bucket prompts = ONE admission program; 39 post-first
    # tokens = chunk 32 then round-up chunk 8 (overshoot masked by budgets)
    # = 2 decode programs
    assert counter.count("admit") == 1, counter.ops
    assert counter.count("decode") <= 3, counter.ops
    assert len(counter.ops) <= 4


def test_wave_admission_one_dispatch_for_burst():
    """A burst of same-bucket requests admits in one batched program with
    first-token sampling fused in (no separate sample dispatch)."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=128, block_size=8,
                          slots=8, max_seq=128)
    counter = OpCounter()
    b.program_hook = counter
    prompts = [RNG.integers(0, CFG.vocab_size, 9).tolist() for _ in range(6)]
    reqs = [b.submit(p, max_new_tokens=1, sampling=SamplingParams.greedy())
            for p in prompts]
    run_until_done(b, reqs)
    assert counter.count("admit") == 1
    assert counter.count("decode") == 0
    for p, r in zip(prompts, reqs):
        assert r.wait() == dense_greedy(p, 1)


def test_eos_mid_chunk_stops_on_device():
    """Per-slot eos masks inside the chunk: tokens after the eos step are
    never emitted even though the program ran past it."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=2, max_seq=128)
    prompt = RNG.integers(0, CFG.vocab_size, 11).tolist()
    full = dense_greedy(prompt, 30)
    eos = full[10]   # eos lands mid-chunk (after the 32-chunk starts)
    first = full.index(eos)
    r = b.submit(prompt, max_new_tokens=30, sampling=SamplingParams.greedy(),
                 eos_token_id=eos)
    run_until_done(b, [r])
    assert r.wait() == full[:first]
    assert b.stats()["active"] == 0 and b.pool.free_count() > 0


def test_mixed_budgets_mid_chunk():
    """Slots with different max_new_tokens share chunks; budget masks stop
    each at its own limit."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=128, block_size=8,
                          slots=4, max_seq=128)
    prompts = [RNG.integers(0, CFG.vocab_size, 7 + i).tolist()
               for i in range(4)]
    wants = [3, 17, 33, 50]
    reqs = [b.submit(p, max_new_tokens=w, sampling=SamplingParams.greedy())
            for p, w in zip(prompts, wants)]
    run_until_done(b, reqs)
    for p, w, r in zip(prompts, wants, reqs):
        assert len(r.wait()) == w
        assert r.wait() == dense_greedy(p, w)


# ---- mesh-sharded batching (tensor/expert parallel) ---------------------
# The batcher's single program partitions over a tp/ep mesh via GSPMD
# (runtime/batcher.py mesh_spec) — the round-2 lift of the old
# single-device-only restriction.

from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec  # noqa: E402


def test_tp_sharded_batcher_matches_dense_engine():
    spec = MeshSpec(tp=2)
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=4, max_seq=128, mesh_spec=spec)
    assert b.stats()["mesh"]["tp"] == 2
    prompt = RNG.integers(0, CFG.vocab_size, 13).tolist()
    r = b.submit(prompt, max_new_tokens=16, sampling=SamplingParams.greedy())
    run_until_done(b, [r])
    eng = InferenceEngine(CFG, PARAMS, mesh_spec=spec, max_seq=128)
    want = eng.generate([prompt], max_new_tokens=16,
                        sampling=SamplingParams.greedy()).tokens[0]
    assert r.wait() == want


def test_tp_sharded_batcher_concurrent_and_prefix_reuse():
    spec = MeshSpec(tp=4)
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=4, max_seq=128, mesh_spec=spec)
    sys_prompt = RNG.integers(0, CFG.vocab_size, 16).tolist()  # 2 full blocks
    prompts = [sys_prompt + RNG.integers(0, CFG.vocab_size, 3 + i).tolist()
               for i in range(4)]
    reqs = [b.submit(p, max_new_tokens=8, sampling=SamplingParams.greedy())
            for p in prompts]
    run_until_done(b, reqs)
    assert b.pool.stats()["prefix_hits"] >= 1
    for p, r in zip(prompts, reqs):
        assert r.wait() == dense_greedy(p, 8)


def test_ep_sharded_batcher_moe():
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.models.params import init_params
    import jax
    cfg = get_config("tiny-mixtral").replace(dtype="float32",
                                             attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    spec = MeshSpec(ep=2, tp=2)
    b = ContinuousBatcher(cfg, params, num_blocks=64, block_size=8,
                          slots=2, max_seq=128, mesh_spec=spec)
    prompt = RNG.integers(0, cfg.vocab_size, 11).tolist()
    r = b.submit(prompt, max_new_tokens=8, sampling=SamplingParams.greedy())
    run_until_done(b, [r])
    eng = InferenceEngine(cfg, params, max_seq=128)
    want = eng.generate([prompt], max_new_tokens=8,
                        sampling=SamplingParams.greedy()).tokens[0]
    assert r.wait() == want


def test_batcher_rejects_non_tensor_axes():
    # dp/sp stay rejected (the slot scheduler owns the batch dim; decode
    # chunks never span one sequence); pp>1 is now a supported serving
    # mode (tests/test_paged_pipeline.py)
    for spec in (MeshSpec(dp=2), MeshSpec(sp=2)):
        with pytest.raises(ValueError, match="tp/ep"):
            ContinuousBatcher(CFG, PARAMS, num_blocks=16, block_size=8,
                              slots=2, max_seq=64, mesh_spec=spec)


# ---------------- chunked prefill ----------------

def test_chunked_prefill_matches_monolithic():
    """A prompt admitted in chunks (via radix re-entry) must produce the
    exact token trajectory of a monolithic admission, and the chunked
    batcher must actually have taken >1 admission pass."""
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, 256, 50).tolist()   # 7 blocks @ bs 8

    def run(chunk):
        b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                              max_seq=128, seed=0, prefill_chunk=chunk)
        r = b.submit(prompt, max_new_tokens=8,
                     sampling=SamplingParams.greedy())
        for _ in range(60):
            b.step()
            if r.done.is_set():
                break
        assert r.wait(), r.error
        return r.tokens, b.stats()

    mono, s0 = run(None)
    chunked, s1 = run(2)   # 2-block (16-token) chunks -> 3 partial passes
    assert s0["chunked_admissions"] == 0
    assert s1["chunked_admissions"] >= 3
    assert chunked == mono


def test_chunked_prefill_decode_interleaves():
    """While a long prompt admits chunk by chunk, an already-active
    request must keep generating between the chunks (the whole point:
    bounded decode stalls)."""
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(1)
    b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                          max_seq=128, seed=0, prefill_chunk=1)
    short = b.submit([1, 2, 3], max_new_tokens=100,
                     sampling=SamplingParams.greedy())
    b.step()                      # admit short; it starts decoding
    long_prompt = rng.integers(0, 256, 60).tolist()
    longr = b.submit(long_prompt, max_new_tokens=4,
                     sampling=SamplingParams.greedy())
    progress = [(len(short.tokens), len(longr.tokens))]
    for _ in range(80):
        b.step()
        progress.append((len(short.tokens), len(longr.tokens)))
        if short.done.is_set() and longr.done.is_set():
            break
    assert short.wait() and longr.wait()
    assert len(longr.tokens) == 4
    # decode interleaved with the long prompt's chunked admission: the
    # short stream grew in >= 2 steps BEFORE the long stream's first
    # token (i.e. during its multi-step admission)
    grew_during_admission = sum(
        1 for (s0, l0), (s1, l1) in zip(progress, progress[1:])
        if l1 == 0 and s1 > s0)
    assert grew_during_admission >= 2, progress
    assert b.stats()["chunked_admissions"] >= 7


def test_chunked_prefill_cancel_mid_admission():
    """Cancelling between chunks must finish the request without binding
    a slot and leak no blocks."""
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(2)
    b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                          max_seq=128, seed=0, prefill_chunk=1)
    free0 = b.pool.free_count()
    r = b.submit(rng.integers(0, 256, 40).tolist(), max_new_tokens=4,
                 sampling=SamplingParams.greedy())
    b.step()                      # first chunk admitted, request requeued
    r.cancel()
    for _ in range(10):
        b.step()
        if r.done.is_set():
            break
    assert r.done.is_set() and not r.tokens
    # all non-radix references returned; radix-held blocks are evictable
    # (free_count counts refcount-0 radix leaves as reclaimable or not —
    # either way active references must be zero)
    assert b.stats()["active"] == 0
    assert b.pool.free_count() + 40 // 8 + 1 >= free0 - 1


def test_chunked_prefill_progresses_with_all_slots_busy():
    """Partial admissions need no decode slot: a long prompt's chunks
    must land while every slot is occupied by active decodes."""
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    rng = np.random.default_rng(3)
    b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=1,
                          max_seq=128, seed=0, prefill_chunk=1)
    hog = b.submit([1, 2, 3], max_new_tokens=120,
                   sampling=SamplingParams.greedy())
    b.step()                      # the only slot is now decoding
    assert b.stats()["active"] == 1
    longr = b.submit(rng.integers(0, 256, 40).tolist(), max_new_tokens=2,
                     sampling=SamplingParams.greedy())
    for _ in range(3):
        b.step()
    # the long prompt chunk-admitted while the slot stayed busy
    assert b.stats()["chunked_admissions"] >= 2
    assert not longr.done.is_set() or not longr.error
    for _ in range(60):
        b.step()
        if hog.done.is_set() and longr.done.is_set():
            break
    assert hog.wait() and longr.wait()


def test_overlapped_decode_matches_sequential():
    """Double-buffered decode dispatch (decode_overlap): tokens must be
    bit-identical to the sequential step — the overlap only changes WHEN
    the host syncs, never what the programs compute — for both greedy
    and sampled requests, and the overlapped run must actually engage."""
    prompts = [RNG.integers(0, CFG.vocab_size, 9).tolist()
               for _ in range(3)]

    def run(overlap, sp, seed0):
        b = ContinuousBatcher(CFG, PARAMS, num_blocks=128, block_size=8,
                              slots=4, max_seq=128,
                              decode_overlap=overlap)
        b.DECODE_CHUNKS = (8, 4, 2, 1)   # small chunks: budget spans many
        reqs = [b.submit(p, max_new_tokens=40, sampling=sp,
                         seed=seed0 + i) for i, p in enumerate(prompts)]
        run_until_done(b, reqs)
        for r in reqs:
            assert r.error is None, r.error
        return [r.tokens for r in reqs], b.stats()["overlapped_dispatches"]

    for sp in (SamplingParams.greedy(),
               SamplingParams(temperature=0.8, top_k=20, top_p=0.9)):
        seq, n_off = run(False, sp, 7)
        ovl, n_on = run(True, sp, 7)
        assert seq == ovl
        assert n_off == 0 and n_on > 0


def test_overlap_defers_to_eos_and_queue():
    """Stop-condition checks win: requests with an eos must never take
    the overlapped path (the host needs every chunk's tokens to decide),
    and the output contract is unchanged."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=128, block_size=8,
                          slots=2, max_seq=128, decode_overlap=True)
    b.DECODE_CHUNKS = (8, 4, 2, 1)
    probe = b.submit([1, 2, 3], max_new_tokens=3,
                     sampling=SamplingParams.greedy())
    run_until_done(b, [probe])
    eos = probe.tokens[1]
    r = b.submit([1, 2, 3], max_new_tokens=40,
                 sampling=SamplingParams.greedy(), eos_token_id=eos)
    run_until_done(b, [r])
    assert eos not in r.tokens and len(r.tokens) < 40
    assert b.stats()["overlapped_dispatches"] == 0

    # queue deferral: a waiting admission must disable the pair (it
    # would otherwise wait two chunks instead of one), re-enabling the
    # moment the queue drains
    from distributed_llm_inferencing_tpu.runtime.batcher import BatchRequest
    r2 = b.submit([5, 6, 7], max_new_tokens=40,
                  sampling=SamplingParams.greedy())
    b.step()   # admit + first chunk: no eos, no stream, budget >= 2k
    active = [i for i, a in enumerate(b.active) if a is not None]
    assert b._overlap_eligible(active, 4)
    b.queue.append(BatchRequest(prompt=[1], max_new_tokens=4,
                                sampling=SamplingParams.greedy()))
    assert not b._overlap_eligible(active, 4)
    b.queue.pop()
    assert b._overlap_eligible(active, 4)
    run_until_done(b, [r2])
