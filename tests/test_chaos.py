"""Chaos suite: master + worker in-process under injected faults.

Drives the fault-injection harness (utils/faults.py, armed over
``POST /api/faults``) against the self-healing dispatch path and asserts
the robustness invariants the reference system violated (SURVEY.md §3.4,
§5.3 — one strike deactivated a node forever; a timed-out generation
kept running for nobody; a requeue could double-generate a prompt):

- every submitted request reaches exactly one terminal state
- no prompt is ever generated twice (idempotency cache hit observable
  in metrics)
- a node whose fault clears is rescheduled via the breaker's half-open
  probe without operator action
- drain finishes in-flight work, 503s new work, and costs no strike

Reproduce any failure locally with the same schedule:

    DLI_FAULTS_SEED=<seed> JAX_PLATFORMS=cpu \
        python -m pytest tests/test_chaos.py -q
"""

import os
import threading
import time

import pytest
import requests

# The fault-admin surface only registers when injection is explicitly
# enabled at service construction (it includes a remote kill switch);
# must be set before any fixture builds a worker/master.
os.environ.setdefault("DLI_FAULTS_ENABLE", "1")

from distributed_llm_inferencing_tpu.runtime.master import (
    FAILURE_STRIKES, MAX_ATTEMPTS, Master)
from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent
from distributed_llm_inferencing_tpu.utils.faults import FaultInjector


def _url(port, path):
    return f"http://127.0.0.1:{port}{path}"


def _load_tiny(port, name="tiny-gpt2", **kw):
    body = {"model_name": name, "allow_random_init": True,
            "dtype": "float32", "max_seq": 64, **kw}
    r = requests.post(_url(port, "/load_model"), json=body, timeout=300)
    assert r.status_code == 200, r.text


def _warm(port, name="tiny-gpt2"):
    r = requests.post(_url(port, "/inference"), json={
        "model_name": name, "prompt": "hi", "max_new_tokens": 4,
        "sampling": {"do_sample": False}}, timeout=300)
    assert r.status_code == 200, r.text


@pytest.fixture(scope="module")
def worker():
    """Standing worker with a preloaded + jit-warmed tiny engine."""
    agent = WorkerAgent()
    srv = agent.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    _load_tiny(port)
    _warm(port)
    yield agent, port
    agent.service.shutdown()


@pytest.fixture()
def clean_worker(worker):
    """Per-test guard: faults cleared and drain lifted on teardown."""
    agent, port = worker
    yield agent, port
    agent.service.faults.clear()
    agent._draining = False


@pytest.fixture()
def master():
    m = Master(":memory:", dispatcher_threads=2, health_interval=0.3,
               infer_timeout=15, retry_backoff_base=0.05)
    m.start_background()
    srv = m.service.serve("127.0.0.1", 0, background=True)
    port = srv.server_address[1]
    yield m, port
    m.stop()


def _add_node(mport, wport, name="w1"):
    r = requests.post(_url(mport, "/api/nodes/add"), json={
        "name": name, "host": "127.0.0.1", "port": wport}).json()
    assert r["status"] == "success", r
    return r["node_id"]


def _submit(mport, **kw):
    body = {"model_name": "tiny-gpt2", "prompt": "hi", "max_new_tokens": 4,
            "sampling": {"do_sample": False, "allow_random_init": True}}
    body.update(kw)
    r = requests.post(_url(mport, "/api/inference/submit"), json=body).json()
    assert r["status"] == "success", r
    return r["request_id"]


def _wait_terminal(mport, rid, timeout=60):
    deadline = time.time() + timeout
    while time.time() < deadline:
        r = requests.get(
            _url(mport, f"/api/inference/status/{rid}")).json()["request"]
        if r["status"] in ("completed", "failed"):
            return r
        time.sleep(0.1)
    raise TimeoutError(f"request {rid} never reached a terminal state")


def _node(mport, node_id):
    ns = requests.get(_url(mport, "/api/nodes/status")).json()["nodes"]
    return next(n for n in ns if n["id"] == node_id)


def _wait_breaker(mport, node_id, states, timeout=15):
    deadline = time.time() + timeout
    while time.time() < deadline:
        n = _node(mport, node_id)
        if n["breaker"] in states:
            return n
        time.sleep(0.1)
    raise TimeoutError(f"breaker never reached {states}: {n}")


def _arm(port, faults, seed=None):
    body = {"faults": faults}
    if seed is not None:
        body["seed"] = seed
    r = requests.post(_url(port, "/api/faults"), json=body).json()
    assert r["status"] == "success", r


# ---- injector unit behavior ------------------------------------------

def test_fault_injector_deterministic_and_bounded():
    mk = lambda: FaultInjector("w", seed=7)
    a, b = mk(), mk()
    # synthetic point: this test drives inj.intercept("/x") directly
    # dlilint: disable=rpc-fault-unknown
    spec = [{"point": "/x", "mode": "error", "p": 0.5, "after": 2,
             "times": 4}]
    a.arm(spec)
    b.arm(spec)
    fa = [a.intercept("/x") is not None for _ in range(40)]
    fb = [b.intercept("/x") is not None for _ in range(40)]
    assert fa == fb                      # seeded: replayable schedule
    assert not any(fa[:2])               # 'after' skips the first hits
    assert sum(fa) == 4                  # 'times' bounds total firings
    assert a.intercept("/y") is None     # point is matched
    st = a.state()["faults"][0]
    assert st["fired"] == 4 and st["hits"] == 40


def test_fault_injector_env_arming(monkeypatch):
    monkeypatch.setenv(
        "DLI_FAULTS",
        '[{"point": "/inference", "mode": "latency", "delay_s": 0.5}]')
    monkeypatch.setenv("DLI_FAULTS_SEED", "9")
    inj = FaultInjector.from_env("worker")
    assert inj.state()["seed"] == 9
    f = inj.intercept("/inference")
    assert f is not None and f.mode == "latency" and f.delay_s == 0.5
    with pytest.raises(ValueError):
        # dlilint: disable=rpc-fault-unknown
        inj.arm([{"point": "/x", "mode": "no-such-mode"}])


def test_fault_admin_api(clean_worker):
    _, port = clean_worker
    # deliberately-unmatched points: the admin API must round-trip them
    # dlilint: disable=rpc-fault-unknown
    _arm(port, [{"point": "/never", "mode": "error"}], seed=3)
    st = requests.get(_url(port, "/api/faults")).json()
    assert st["seed"] == 3 and len(st["faults"]) == 1
    r = requests.post(_url(port, "/api/faults"),
                      # dlilint: disable=rpc-fault-unknown
                      json={"faults": [{"point": "/x"}]})
    assert r.status_code == 400          # mode missing -> rejected
    requests.post(_url(port, "/api/faults/clear"), json={})
    assert requests.get(_url(port, "/api/faults")).json()["faults"] == []


# ---- retry / failover under response faults --------------------------

def test_corrupt_response_is_retried_to_completion(clean_worker, master):
    _, wport = clean_worker
    m, mport = master
    nid = _add_node(mport, wport)
    _arm(wport, [{"point": "/inference", "mode": "corrupt", "times": 1}])
    done = _wait_terminal(mport, _submit(mport))
    assert done["status"] == "completed", done
    assert done["attempts"] >= 1         # the corrupt attempt was retried
    assert _node(mport, nid)["is_active"]  # one strike != deactivation


def test_mid_response_disconnect_is_retried(clean_worker, master):
    _, wport = clean_worker
    m, mport = master
    _add_node(mport, wport)
    _arm(wport, [{"point": "/inference", "mode": "disconnect", "times": 1}])
    done = _wait_terminal(mport, _submit(mport))
    assert done["status"] == "completed", done
    assert done["attempts"] >= 1


def test_injected_500_is_retried(clean_worker, master):
    _, wport = clean_worker
    m, mport = master
    _add_node(mport, wport)
    _arm(wport, [{"point": "/inference", "mode": "error", "times": 1}])
    done = _wait_terminal(mport, _submit(mport))
    assert done["status"] == "completed", done
    assert done["attempts"] >= 1


# ---- idempotent dispatch: exactly-once execution ---------------------

def test_duplicate_dispatch_replays_cached_result(clean_worker):
    agent, wport = clean_worker
    body = {"model_name": "tiny-gpt2", "prompt_tokens": [5, 6, 7],
            "max_new_tokens": 4, "sampling": {"do_sample": False},
            "request_tag": "chaos-dup-1"}
    before = agent.metrics.snapshot()["timings"].get(
        "inference", {}).get("count", 0)
    r1 = requests.post(_url(wport, "/inference"), json=body).json()
    r2 = requests.post(_url(wport, "/inference"), json=body).json()
    assert r1["status"] == r2["status"] == "success"
    assert r2["tokens"] == r1["tokens"]
    assert r2.get("idempotent") is True and not r1.get("idempotent")
    after = agent.metrics.snapshot()["timings"]["inference"]["count"]
    assert after - before == 1           # the generation ran exactly once
    assert agent.metrics.snapshot()["counters"]["idempotent_hits"] >= 1


def test_concurrent_same_tag_joins_single_execution(clean_worker):
    agent, wport = clean_worker
    body = {"model_name": "tiny-gpt2", "prompt_tokens": [9, 8, 7, 6],
            "max_new_tokens": 4, "sampling": {"do_sample": False},
            "request_tag": "chaos-join-1"}
    before = agent.metrics.snapshot()["timings"].get(
        "inference", {}).get("count", 0)
    results = []

    def post():
        results.append(
            requests.post(_url(wport, "/inference"), json=body).json())

    threads = [threading.Thread(target=post) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(r["status"] == "success" for r in results)
    assert len({tuple(r["tokens"]) for r in results}) == 1
    after = agent.metrics.snapshot()["timings"]["inference"]["count"]
    assert after - before == 1           # 3 dispatches, one execution


def test_timeout_retry_does_not_regenerate(clean_worker):
    """Master-side timeout + retry loop against a slow worker: the
    prompt is generated exactly once; the master's eventual success is
    an idempotency-cache replay, visible in both sides' metrics."""
    agent, wport = clean_worker
    m = Master(":memory:", dispatcher_threads=2, health_interval=0.5,
               infer_timeout=2.5, retry_backoff_base=0.05)
    m.start_background()
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    mport = msrv.server_address[1]
    try:
        _add_node(mport, wport)
        before = agent.metrics.snapshot()["timings"].get(
            "inference", {}).get("count", 0)
        # first two dispatches stall 4s in the HTTP layer — the master
        # (2.5s timeout) gives up on both; the generation itself runs
        # (once) and lands in the worker's completed-result cache
        _arm(wport, [{"point": "/inference", "mode": "latency",
                      "delay_s": 4.0, "times": 2}])
        done = _wait_terminal(mport, _submit(mport), timeout=40)
        assert done["status"] == "completed", done
        assert done["attempts"] >= 1
        deadline = time.time() + 10      # late replays may still be landing
        while time.time() < deadline:
            after = agent.metrics.snapshot()["timings"]["inference"]["count"]
            hits = agent.metrics.snapshot()["counters"].get(
                "idempotent_hits", 0)
            if after - before == 1 and hits >= 1:
                break
            time.sleep(0.2)
        assert after - before == 1, "prompt was generated more than once"
        assert hits >= 1
        assert m.metrics.snapshot()["counters"].get(
            "requests_idempotent_replayed", 0) >= 1
    finally:
        m.stop()


# ---- circuit breaker: partition opens, recovery closes ---------------

def test_partition_opens_breaker_then_recovers(clean_worker, master):
    m, mport = master
    _, wport = clean_worker
    nid = _add_node(mport, wport)
    # partition: every master->worker RPC fails at the client side
    m.service.faults.arm([{"point": "rpc:*", "mode": "reset"}])
    rid = _submit(mport)
    done = _wait_terminal(mport, rid, timeout=30)
    assert done["status"] == "failed"    # exactly one terminal state
    n = _wait_breaker(mport, nid, ("open",))
    assert not n["is_active"]
    # fault clears -> health probe flips the breaker half-open with no
    # operator involvement, and real traffic closes it
    m.service.faults.clear()
    n = _wait_breaker(mport, nid, ("half_open", "closed"))
    assert n["is_active"]
    done = _wait_terminal(mport, _submit(mport))
    assert done["status"] == "completed", done
    assert _wait_breaker(mport, nid, ("closed",))["strikes"] == 0


def test_worker_crash_fails_over_to_peer(worker, master):
    """Crash-on-Nth-request: the struck node's breaker opens, the
    request fails over to the surviving peer, and still reaches exactly
    one terminal state."""
    m, mport = master
    _, bport = worker                    # surviving peer (standing worker)
    agent_a = WorkerAgent()
    asrv = agent_a.serve("127.0.0.1", 0, background=True)
    aport = asrv.server_address[1]
    try:
        _load_tiny(aport)
        aid = _add_node(mport, aport, name="doomed")
        bid = _add_node(mport, bport, name="survivor")
        _arm(aport, [{"point": "/inference", "mode": "crash", "times": 1}])
        done = _wait_terminal(mport, _submit(mport), timeout=60)
        assert done["status"] == "completed", done
        assert done["node_id"] == bid    # failover excluded the crasher
        n = _wait_breaker(mport, aid, ("open",))
        assert not n["is_active"]
        assert _node(mport, bid)["is_active"]
    finally:
        agent_a.service.shutdown()


# ---- graceful drain ---------------------------------------------------

def test_drain_finishes_inflight_and_rejects_new():
    # dedicated master: the long batched generation needs the full
    # production inference budget, not this module's fast-retry fixture
    m = Master(":memory:", dispatcher_threads=2, health_interval=0.3,
               retry_backoff_base=0.05)
    m.start_background()
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    mport = msrv.server_address[1]
    agent = WorkerAgent()
    srv = agent.serve("127.0.0.1", 0, background=True)
    wport = srv.server_address[1]
    try:
        _load_tiny(wport, name="tiny-llama", serving="batched",
                   kv_blocks=64, kv_block_size=8, slots=2, max_seq=128)
        nid = _add_node(mport, wport)
        rid = _submit(mport, model_name="tiny-llama", prompt="hello world",
                      max_new_tokens=110)
        # wait until the request is actually running in the batcher
        deadline = time.time() + 60
        while time.time() < deadline:
            st = requests.get(_url(wport, "/health")).json()[
                "loaded_models"][0]["scheduler"]
            if st["active"] > 0:
                break
            time.sleep(0.05)
        assert st["active"] > 0, "request never started"
        d = requests.post(_url(wport, "/drain"), json={"timeout": 120},
                          timeout=300).json()
        assert d["drained"] is True and d["in_flight"] == 0, d
        # zero in-flight loss: the admitted request finished normally
        done = _wait_terminal(mport, rid, timeout=30)
        assert done["status"] == "completed", done
        assert len(done["result"]) > 0
        # new work is refused with Retry-After
        r = requests.post(_url(wport, "/inference"), json={
            "model_name": "tiny-llama", "prompt": "x"})
        assert r.status_code == 503 and r.headers.get("Retry-After")
        assert r.json().get("draining") is True
        # the master sees draining: unschedulable, but NOT struck
        deadline = time.time() + 10
        while time.time() < deadline:
            n = _node(mport, nid)
            if n["draining"]:
                break
            time.sleep(0.1)
        assert n["draining"] and n["strikes"] == 0 and \
            n["breaker"] == "closed", n
        assert m._pick_node("tiny-llama") is None
        # undrain -> schedulable again, still no strikes
        requests.post(_url(wport, "/undrain"), json={})
        done = _wait_terminal(mport, _submit(
            mport, model_name="tiny-llama", max_new_tokens=4), timeout=60)
        assert done["status"] == "completed", done
        assert _node(mport, nid)["strikes"] == 0
    finally:
        m.stop()
        agent.service.shutdown()


# ---- relayed worker responses (satellite: structured 502) ------------

def test_corrupt_load_relay_returns_structured_502(clean_worker, master):
    _, wport = clean_worker
    m, mport = master
    nid = _add_node(mport, wport)
    _arm(wport, [{"point": "/load_model", "mode": "corrupt", "times": 1}])
    r = requests.post(_url(mport, "/api/models/load"), json={
        "model_name": "tiny-gpt2", "node_id": nid,
        "allow_random_init": True})
    assert r.status_code == 502
    body = r.json()
    assert body["status"] == "error" and "unparseable" in body["message"]


def test_corrupt_deploy_relay_returns_structured_502(clean_worker, master):
    _, wport = clean_worker
    m, mport = master
    _add_node(mport, wport)
    p = requests.post(_url(mport, "/api/plans/create"), json={
        "model_name": "tiny-gpt2", "mesh": {"tp": 1},
        "max_seq": 64}).json()
    _arm(wport, [{"point": "/load_shard", "mode": "corrupt", "times": 1}])
    r = requests.post(_url(mport, f"/api/plans/deploy/{p['plan_id']}"),
                      json={"allow_random_init": True})
    assert r.status_code == 502
    assert "unparseable" in r.json()["message"]


# ---- multiplexed batch dispatch under faults -------------------------

def test_mid_batch_disconnect_recovers_each_subrequest_exactly_once(
        clean_worker):
    """A batch RPC dies mid-stream (disconnect fault on
    /inference_batch): the master requeues every unanswered sub-request
    individually, strikes the node at most once for the shared socket
    fault, and the retries land each prompt exactly once — no
    double-generation, no lost request."""
    agent, wport = clean_worker
    m = Master(":memory:", dispatcher_threads=1, health_interval=0.3,
               infer_timeout=15, retry_backoff_base=0.05,
               dispatch_batch=4)
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    mport = msrv.server_address[1]
    try:
        nid = _add_node(mport, wport)
        before = agent.metrics.snapshot()["timings"].get(
            "inference", {}).get("count", 0)
        _arm(wport, [{"point": "/inference_batch", "mode": "disconnect",
                      "times": 1}])
        # submit before the dispatcher starts so one claim batches all 4
        rids = [_submit(mport) for _ in range(4)]
        m.start_background()
        finals = {rid: _wait_terminal(mport, rid, timeout=90)
                  for rid in rids}
        assert all(r["status"] == "completed" for r in finals.values()), \
            finals
        # each sub-request burned the failed batch attempt, exactly once
        assert all(r["attempts"] >= 1 for r in finals.values())
        deadline = time.time() + 10
        while time.time() < deadline:
            after = agent.metrics.snapshot()["timings"]["inference"]["count"]
            if after - before == len(rids):
                break
            time.sleep(0.2)
        assert after - before == len(rids), \
            "a sub-request was generated more than once (or lost)"
        # one socket fault = one strike, not four: breaker still closed
        n = _node(mport, nid)
        assert n["breaker"] == "closed" and n["strikes"] <= 1, n
    finally:
        m.stop()


# ---- barrage: every request ends in exactly one terminal state -------

def test_mixed_fault_barrage_all_requests_terminal(clean_worker, master):
    _, wport = clean_worker
    m, mport = master
    _add_node(mport, wport)
    _arm(wport, [
        {"point": "/inference", "mode": "corrupt", "p": 0.5, "times": 3},
        {"point": "/inference", "mode": "disconnect", "p": 0.3, "times": 2},
        {"point": "/inference", "mode": "latency", "delay_s": 0.1,
         "p": 0.5},
    ], seed=1234)
    rids = [_submit(mport) for _ in range(6)]
    finals = {rid: _wait_terminal(mport, rid, timeout=90) for rid in rids}
    states = {rid: r["status"] for rid, r in finals.items()}
    assert all(s in ("completed", "failed") for s in states.values())
    assert sum(s == "completed" for s in states.values()) >= 1
    # terminal means terminal: statuses never change afterwards
    time.sleep(0.5)
    for rid in rids:
        r = requests.get(
            _url(mport, f"/api/inference/status/{rid}")).json()["request"]
        assert r["status"] == states[rid]
    counts = requests.get(_url(mport, "/api/inference/recent")).json()[
        "counts"]
    assert counts.get("pending", 0) == 0 and counts.get("processing", 0) == 0
