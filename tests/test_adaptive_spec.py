"""Adaptive speculation (ops/speculative.py AdaptiveSpecController).

The contract: ``speculative="ngram"`` must never end up slower than plain
decode. Drafting stays on while it pays (high-repetition workloads),
gamma shrinks as acceptance drops, and a draft-hostile workload converges
to plain decode — with periodic probes bounding the cost of being wrong
in either direction. Token CONTENT is invariant throughout: greedy
speculative output is bit-identical to plain decode whichever mode each
individual chunk ran in, so every integration test also asserts output
equality against the plain batcher/engine.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.ops.speculative import (
    AdaptiveSpecController)
from distributed_llm_inferencing_tpu.runtime.batcher import ContinuousBatcher
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine

CFG = get_config("tiny-llama").replace(dtype="float32", attn_backend="xla")
PARAMS = init_params(CFG, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(3)


# ---- controller policy (pure, no jax) ---------------------------------

def test_high_acceptance_keeps_drafting_and_grows_gamma():
    c = AdaptiveSpecController(8, warmup=2)
    c.gamma = 2
    for _ in range(12):
        g = c.choose()
        assert g > 0
        c.record("spec", emitted=5 * g, elapsed_s=0.01,
                 drafted=5 * g, accepted=4 * g)
    assert c.mode == "spec"
    assert c.gamma == 8          # grew back to the configured max
    assert c.fallbacks == 0


def test_low_acceptance_shrinks_gamma_then_falls_back():
    c = AdaptiveSpecController(8, warmup=2)
    gammas = []
    for _ in range(40):
        g = c.choose()
        if g == 0:
            break
        gammas.append(g)
        c.record("spec", emitted=5, elapsed_s=0.01, drafted=5 * g,
                 accepted=0)
    assert c.mode == "plain"
    assert c.fallbacks == 1
    assert min(gammas) == 2      # tried shorter drafts before giving up
    # steady state: plain with at most one probe per probe_every chunks
    probes = sum(1 for _ in range(c.probe_every) if c.choose() > 0)
    assert probes == 1


def test_measured_losing_tps_falls_back_despite_acceptance():
    """Full acceptance does not save drafting when the measured clock
    says plain is faster (the BENCH_r05 failure mode: dispatch-dominated
    host where even perfect drafts lose to big plain chunks)."""
    c = AdaptiveSpecController(4, warmup=2)
    c.record("plain", emitted=32, elapsed_s=0.01)   # plain: 3200 tok/s
    for _ in range(10):
        if c.choose() == 0:
            break
        c.record("spec", emitted=5, elapsed_s=0.01,  # spec: 500 tok/s
                 drafted=4, accepted=4)
    assert c.mode == "plain"
    assert c.fallbacks == 1


def test_probe_recovers_when_workload_turns_repetitive():
    c = AdaptiveSpecController(4, warmup=2, probe_every=4)
    for _ in range(20):          # drive into plain
        g = c.choose()
        if g == 0:
            continue
        c.record("spec", emitted=1, elapsed_s=0.01, drafted=g, accepted=0)
        if c.mode == "plain":
            break
    assert c.mode == "plain"
    # workload turns draft-friendly: probes now measure high acceptance
    for _ in range(4 * c.probe_every):
        g = c.choose()
        if g == 0:
            c.record("plain", emitted=4, elapsed_s=0.01)
        else:
            c.record("spec", emitted=5 * g, elapsed_s=0.001,
                     drafted=5 * g, accepted=4 * g)
        if c.mode == "spec":
            break
    assert c.mode == "spec"
    assert c.reactivations == 1


def test_spec_mode_plain_probe_arms_tps_fallback():
    """High acceptance alone must not pin a losing spec arm forever: a
    periodic PLAIN probe in spec mode measures the other arm, after
    which the tok/s clause can fall back (the BENCH_r05 shape —
    dispatch-dominated host where drafting loses at full acceptance)."""
    c = AdaptiveSpecController(4, warmup=2, probe_every=4)
    saw_plain_probe = False
    for _ in range(40):
        g = c.choose()
        if g == 0:
            if c.mode == "spec":
                saw_plain_probe = True
            c.record("plain", emitted=32, elapsed_s=0.01)   # 3200 tok/s
        else:
            c.record("spec", emitted=5, elapsed_s=0.01,     # 500 tok/s
                     drafted=g, accepted=g)                 # full accept
        if c.mode == "plain" and c.fallbacks:
            break
    assert saw_plain_probe
    assert c.mode == "plain" and c.fallbacks == 1


def test_zero_gamma_request_runs_plain_without_controller():
    """spec_gamma=0 is an explicit zero-draft request: the adaptive
    controller must not clamp it up to gamma=1 drafting."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=64, block_size=8,
                          slots=2, max_seq=96, speculative="ngram",
                          spec_gamma=0)
    assert b._spec_ctl is None
    r = b.submit([1, 2, 3, 4], max_new_tokens=8,
                 sampling=SamplingParams.greedy())
    _drain(b, [r])
    assert r.tokens == _plain_tokens([[1, 2, 3, 4]], 8)[0]
    sa = b.stats()
    assert sa["spec_adaptive"] is None
    assert sa["spec_accepted_tokens"] == 0   # nothing was ever drafted


def test_compiled_chunks_excluded_from_throughput():
    c = AdaptiveSpecController(4)
    c.record("spec", emitted=5, elapsed_s=10.0, drafted=4, accepted=4,
             compiled=True)      # cold compile: must not poison the EMA
    assert c.spec_tps is None
    c.record("spec", emitted=5, elapsed_s=0.01, drafted=4, accepted=4)
    assert c.spec_tps == pytest.approx(500.0)


# ---- batcher integration ----------------------------------------------

def _plain_tokens(prompts, n, sampling=None, seed0=None):
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=256, block_size=8,
                          slots=4, max_seq=160)
    reqs = [b.submit(p, max_new_tokens=n,
                     sampling=sampling or SamplingParams.greedy(),
                     seed=None if seed0 is None else seed0 + i)
            for i, p in enumerate(prompts)]
    _drain(b, reqs)
    return [r.tokens for r in reqs]


def _drain(b, reqs, limit=600):
    for _ in range(limit):
        b.step()
        if all(r.done.is_set() for r in reqs):
            for r in reqs:
                assert r.error is None, r.error
            return
    raise AssertionError("batcher did not drain")


def _spec_batcher():
    # spec_wave=False: these suites pin the pre-wave GLOBAL-controller
    # arbitration (one gamma per wave, whole-wave plain fallback), which
    # stays supported behind DLI_SPEC_WAVE=0; the wave-mode per-request
    # controllers have their own suite (tests/test_spec_wave.py)
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=256, block_size=8,
                          slots=4, max_seq=160, speculative="ngram",
                          spec_gamma=3, spec_wave=False)
    b.DECODE_CHUNKS = (4, 2, 1)   # many small chunks -> many decisions
    return b


def test_repetitive_workload_keeps_drafting():
    """Greedy decode of this model on a repeated prompt degenerates into
    a repeating loop a few tokens in — prompt-lookup's best case. The
    controller must ride out the (genuinely draft-hostile) first tokens
    without abandoning drafting (min_evidence), then keep it on."""
    base = RNG.integers(0, CFG.vocab_size, 4).tolist()
    prompts = [(base * 8)[:24] for _ in range(4)]
    b = _spec_batcher()
    reqs = [b.submit(p, max_new_tokens=64, sampling=SamplingParams.greedy())
            for p in prompts]
    _drain(b, reqs)
    sa = b.stats()["spec_adaptive"]
    assert sa["mode"] == "spec", sa
    assert sa["fallbacks"] == 0
    assert b.stats()["spec_accepted_tokens"] > 0   # drafts actually landed
    assert [r.tokens for r in reqs] == _plain_tokens(prompts, 64)


def test_adversarial_workload_converges_to_plain():
    """Draft-hostile by construction: full-vocab sampling (top_k=0) is
    outside the covered prefix tier, so acceptance is zero BY DESIGN
    (ops/speculative.py accept_rejection_batch) — deterministic, not a
    hope about the model. The controller must fall back and run the tail
    as plain chunks — the 'within tolerance of plain throughput'
    guarantee, asserted structurally (post-fallback chunks are real
    plain dispatches; wall-clock on a shared CI box is noise). Uncovered
    sampled rows draw the same token the plain chunk would, so output
    stays bit-identical to the plain batcher under matching seeds."""
    sp = SamplingParams(temperature=1.0, top_k=0, top_p=1.0)
    prompts = [RNG.integers(0, CFG.vocab_size, 24).tolist()
               for _ in range(4)]
    b = _spec_batcher()
    reqs = [b.submit(p, max_new_tokens=48, sampling=sp, seed=100 + i)
            for i, p in enumerate(prompts)]
    _drain(b, reqs)
    sa = b.stats()["spec_adaptive"]
    assert sa["mode"] == "plain", sa
    assert sa["fallbacks"] >= 1
    assert sa["plain_chunks"] > 0          # the tail really ran plain
    assert sa["spec_chunks"] <= 8, sa      # gave up fast, probes bounded
    assert [r.tokens for r in reqs] == _plain_tokens(prompts, 48,
                                                     sampling=sp,
                                                     seed0=100)


def test_fixed_gamma_mode_still_available():
    """spec_adaptive=False pins the always-draft behavior (A/B arm and
    the pre-existing parity suites)."""
    b = ContinuousBatcher(CFG, PARAMS, num_blocks=128, block_size=8,
                          slots=2, max_seq=160, speculative="ngram",
                          spec_gamma=3, spec_adaptive=False)
    assert b.stats()["spec_adaptive"] is None
    base = RNG.integers(0, CFG.vocab_size, 4).tolist()
    prompt = (base * 8)[:24]
    r = b.submit(prompt, max_new_tokens=16, sampling=SamplingParams.greedy())
    _drain(b, [r])
    assert r.tokens == _plain_tokens([prompt], 16)[0]


def test_lockstep_plain_chunks_keep_follower_history_in_sync():
    """Adaptive fallback under lockstep: plain 'decode' broadcasts must
    carry admission-time history deltas (and followers must mirror the
    per-chunk appends), or a row admitted while the controller sits in
    plain mode leaves a permanent hole in the follower's drafting
    history that the next spec probe's delta skips forever."""
    import json
    mk = lambda: ContinuousBatcher(  # noqa: E731
        CFG, PARAMS, num_blocks=64, block_size=8, slots=2, max_seq=96,
        seed=0, speculative="ngram", spec_gamma=3, spec_wave=False)
    leader, follower = mk(), mk()
    # force the fallback steady state from the start: every chunk until
    # the first probe runs PLAIN, including the one right after admission
    leader._spec_ctl.mode = "plain"
    kinds = []

    def hook(kind, args, run):
        wire = json.loads(json.dumps(args))   # JSON-safety incl. deltas
        kinds.append(kind)
        follower.replay(kind, wire)
        return run()

    leader.program_hook = hook
    prompts = [(RNG.integers(0, CFG.vocab_size, 3).tolist() * 7)[:20],
               RNG.integers(0, CFG.vocab_size, 9).tolist()]
    reqs = [leader.submit(p, max_new_tokens=10,
                          sampling=SamplingParams.greedy(), seed=31 + i)
            for i, p in enumerate(prompts)]
    for _ in range(80):
        leader.step()
        if all(r.done.is_set() for r in reqs):
            break
    assert all(len(r.wait()) == 10 for r in reqs)
    assert "decode" in kinds          # the fallback path really ran
    # histories bit-identical (the SPMD input of any later spec probe);
    # watermarks may lag on the follower — a promoted follower merely
    # re-broadcasts rows, which is harmless over-send, never a hole
    np.testing.assert_array_equal(follower._hist, leader._hist)


# ---- engine integration -----------------------------------------------

@pytest.mark.parametrize("repetitive", [True, False])
def test_engine_adaptive_spec_output_invariant(repetitive, monkeypatch):
    """The single-stream engine loop consults the same controller: output
    must equal plain greedy decode whether chunks ran drafted or plain
    (the adversarial arm exercises the mid-generation fallback path)."""
    monkeypatch.setenv("DLI_SPEC_ADAPTIVE", "1")
    eng = InferenceEngine(CFG, PARAMS, max_seq=160)
    if repetitive:
        base = RNG.integers(0, CFG.vocab_size, 4).tolist()
        prompt = (base * 8)[:24]
    else:
        prompt = RNG.integers(0, CFG.vocab_size, 24).tolist()
    g = SamplingParams.greedy()
    plain = eng.generate([prompt], max_new_tokens=40, sampling=g).tokens[0]
    spec = eng.generate([prompt], max_new_tokens=40, sampling=g,
                        speculative="ngram", spec_gamma=4).tokens[0]
    assert spec == plain
