"""MLA latent-cache serving (cfg.mla_latent_cache).

The latent formulation caches ONE shared [k_rot | c] row per token
(kv_lora_rank + qk_rope_head_dim wide) instead of materialized per-head
K/V, and decodes via the absorbed reassociation (scores q_nope·(W_uk c)
== (W_uk^T q_nope)·c; outputs W_uv (Σ w c)) — mathematically the same
attention, so these tests pin numerical equivalence against the
materialized path, HF greedy parity through the engine (which
auto-enables the latent layout on eligible meshes), and the cache-size
claim itself.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine


def _decode_logits(cfg, params, prompt, steps=6):
    """Prefill + greedy decode loop; returns stacked per-step logits."""
    B, S = prompt.shape
    cache = init_cache(cfg, B, 32, dtype=jnp.float32)
    logits, cache = transformer.prefill(
        params, cfg, jnp.asarray(prompt), jnp.full((B,), S, jnp.int32),
        cache)
    outs = [np.asarray(logits)[:, S - 1]]
    cur = jnp.argmax(logits[:, S - 1], axis=-1).astype(jnp.int32)
    for _ in range(steps):
        logits, cache = transformer.decode_step(
            params, cfg, cur[:, None], cache)
        outs.append(np.asarray(logits)[:, 0])
        cur = jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32)
    return np.stack(outs)


def test_latent_decode_matches_materialized():
    base = get_config("tiny-deepseek").replace(dtype="float32",
                                               attn_backend="xla")
    params = init_params(base, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = np.random.default_rng(0).integers(
        0, base.vocab_size, (2, 9)).astype(np.int32)
    dense = _decode_logits(base, params, prompt)
    latent = _decode_logits(base.replace(mla_latent_cache=True), params,
                            prompt)
    np.testing.assert_allclose(latent, dense, atol=2e-4, rtol=2e-4)


def test_latent_cache_is_smaller_by_the_claimed_ratio():
    cfg = get_config("deepseek-proxy").replace(dtype="float32")
    lat = cfg.replace(mla_latent_cache=True)
    dense_bytes = 2 * cfg.num_kv_heads * cfg.head_dim
    latent_bytes = (lat.cache_head_dim + lat.cache_v_head_dim)
    assert lat.cache_kv_heads == 1 and lat.cache_v_head_dim == 0
    # deepseek-proxy: 2*16*96 / (128+32) = 19.2x
    assert dense_bytes / latent_bytes == pytest.approx(19.2)
    ck = init_cache(lat, 1, 64, dtype=jnp.float32)
    cd = init_cache(cfg, 1, 64, dtype=jnp.float32)
    ratio = (cd.k.size + cd.v.size) / (ck.k.size + ck.v.size)
    assert ratio == pytest.approx(19.2)


def test_engine_auto_enables_latent_and_matches_hf_generate():
    import torch
    import transformers
    from distributed_llm_inferencing_tpu.models import convert
    torch_cfg = transformers.DeepseekV3Config(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        moe_intermediate_size=16, num_hidden_layers=3,
        num_attention_heads=4, num_key_value_heads=4, q_lora_rank=24,
        kv_lora_rank=16, qk_nope_head_dim=16, qk_rope_head_dim=8,
        v_head_dim=12, head_dim=8, n_routed_experts=8,
        n_shared_experts=1, num_experts_per_tok=2, n_group=4,
        topk_group=2, routed_scaling_factor=2.5, first_k_dense_replace=1,
        max_position_embeddings=64, rope_scaling=None,
        tie_word_embeddings=False, pad_token_id=0)
    torch.manual_seed(70)
    model = transformers.DeepseekV3ForCausalLM(torch_cfg).eval()
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")

    prompt = np.random.default_rng(70).integers(0, 128, 8).tolist()
    with torch.no_grad():
        want = model.generate(
            torch.tensor([prompt]), max_new_tokens=10, do_sample=False,
            pad_token_id=0)[0, 8:].tolist()

    eng = InferenceEngine(cfg, max_seq=32, seed=0, params=params)
    assert eng.cfg.mla_latent_cache   # auto-enabled on this mesh
    got = eng.generate([prompt], max_new_tokens=10,
                       sampling=SamplingParams.greedy()).tokens[0]
    assert got == want


def test_latent_int8_weights_compose():
    """int8 weight-only quantization with the latent cache: kv_b_k/v
    dequantize inside the absorbed einsums (_wfull)."""
    base = get_config("tiny-deepseek").replace(dtype="float32",
                                               attn_backend="xla")
    from distributed_llm_inferencing_tpu.ops.quant import maybe_quantize
    params = init_params(base, jax.random.PRNGKey(1), dtype=jnp.float32)
    qcfg = base.replace(quant="int8")
    qparams = maybe_quantize(params, qcfg)
    prompt = np.random.default_rng(1).integers(
        0, base.vocab_size, (1, 7)).astype(np.int32)
    dense = _decode_logits(qcfg, qparams, prompt, steps=4)
    latent = _decode_logits(qcfg.replace(mla_latent_cache=True), qparams,
                            prompt, steps=4)
    np.testing.assert_allclose(latent, dense, atol=2e-4, rtol=2e-4)


def test_latent_excludes_kv_quant():
    base = get_config("tiny-deepseek")
    with pytest.raises(AssertionError, match="mutually exclusive"):
        base.replace(mla_latent_cache=True, kv_quant="int8")


def test_latent_speculative_verify_matches_plain_greedy():
    """Multi-token speculative VERIFY over the latent cache: the verify
    step runs forward with s = gamma+1 fresh tokens and per-token
    q_positions — each draft must be causally masked at its own position
    (a lengths-1 default would let drafts attend their own future).
    Greedy + ngram speculation must emit exactly plain greedy's tokens."""
    base = get_config("tiny-deepseek").replace(dtype="float32",
                                               attn_backend="xla")
    params = init_params(base, jax.random.PRNGKey(2), dtype=jnp.float32)
    # repetitive prompt: the workload prompt-lookup drafting accepts on
    rng = np.random.default_rng(2)
    piece = rng.integers(0, base.vocab_size, 4).tolist()
    prompt = (piece * 5)[:18]

    eng = InferenceEngine(base, params, max_seq=64)
    assert eng.cfg.mla_latent_cache
    plain = eng.generate([prompt], max_new_tokens=12,
                         sampling=SamplingParams.greedy()).tokens[0]
    spec = eng.generate([prompt], max_new_tokens=12,
                        sampling=SamplingParams.greedy(),
                        speculative="ngram").tokens[0]
    assert spec == plain


def test_deepseek_materialized_kv8_batcher():
    """MLA through the continuous batcher with the int8-quantized paged
    pool (the batcher always uses the materialized layout): greedy
    trajectory must match the unquantized batcher's closely enough to
    emit identical tokens on a short run."""
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    base = get_config("tiny-deepseek").replace(dtype="float32",
                                               attn_backend="xla")
    params = init_params(base, jax.random.PRNGKey(3), dtype=jnp.float32)
    prompt = np.random.default_rng(3).integers(
        0, base.vocab_size, 9).tolist()

    outs = {}
    for tag, kvq in (("f32", None), ("kv8", "int8")):
        b = ContinuousBatcher(base.replace(kv_quant=kvq), num_blocks=16,
                              block_size=8, slots=2, max_seq=32, seed=0,
                              params=params)
        r = b.submit(prompt, max_new_tokens=8,
                     sampling=SamplingParams.greedy())
        while b.step():
            pass
        assert r.error is None
        outs[tag] = r.tokens
    assert outs["f32"] == outs["kv8"]


def test_deepseek_tp_ep_batcher_matches_engine():
    """MLA + deepseek MoE through the tp x ep sharded continuous batcher
    (materialized pool) must emit the same greedy tokens as the
    single-device engine (which auto-enables the latent cache) — the two
    layouts and the sharding are all numerically the same attention."""
    from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)
    base = get_config("tiny-deepseek").replace(dtype="float32",
                                               attn_backend="xla")
    params = init_params(base, jax.random.PRNGKey(4), dtype=jnp.float32)
    prompt = np.random.default_rng(4).integers(
        0, base.vocab_size, 11).tolist()

    spec = MeshSpec(tp=2, ep=2)
    b = ContinuousBatcher(base, params, num_blocks=16, block_size=8,
                          slots=2, max_seq=32, mesh_spec=spec)
    r = b.submit(prompt, max_new_tokens=8,
                 sampling=SamplingParams.greedy())
    while b.step():
        pass
    assert r.error is None

    eng = InferenceEngine(base, params, max_seq=32)
    want = eng.generate([prompt], max_new_tokens=8,
                        sampling=SamplingParams.greedy()).tokens[0]
    assert r.tokens == want
