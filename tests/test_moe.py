"""MoE dispatch strategies: dense compute-all-experts vs GShard-style
capacity dispatch (models/transformer.py _moe_dense/_moe_capacity).

Golden property: with capacity sized so no token drops, capacity dispatch
must reproduce the dense path exactly (same top-k gates, same expert
math) — sharded or not.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
from distributed_llm_inferencing_tpu.parallel import sharding as shd
from distributed_llm_inferencing_tpu.parallel.mesh import (
    MeshSpec, create_mesh, validate_spec)

BASE = get_config("tiny-mixtral").replace(dtype="float32",
                                          attn_backend="xla")
# capacity C = factor * k * N / E; factor = E/k makes C = N: zero drops
# regardless of how unbalanced the router is
NO_DROP = float(BASE.num_experts) / BASE.num_experts_per_tok
PARAMS = init_params(BASE, jax.random.PRNGKey(0), dtype=jnp.float32)
RNG = np.random.default_rng(0)


def _prefill_logits(cfg, params, tokens, mesh=None, spec=None):
    B, S = tokens.shape
    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    lengths = jnp.full((B,), S, jnp.int32)
    if mesh is None:
        logits, _ = transformer.prefill(params, cfg, tokens, lengths, cache)
        return np.asarray(logits)
    with mesh:
        p = shd.shard_params(params, mesh, cfg, spec)
        cache = jax.device_put(cache,
                               shd.named(mesh, shd.cache_specs(cfg, spec)))
        logits, _ = jax.jit(
            lambda p, t, l, c: transformer.prefill(p, cfg, t, l, c)
        )(p, tokens, lengths, cache)
    return np.asarray(logits)


def test_capacity_matches_dense_no_drops():
    tokens = jnp.asarray(
        RNG.integers(0, BASE.vocab_size, (2, 24)), jnp.int32)
    ref = _prefill_logits(BASE.replace(moe_dispatch="dense"), PARAMS, tokens)
    got = _prefill_logits(
        BASE.replace(moe_dispatch="capacity",
                     moe_capacity_factor=NO_DROP), PARAMS, tokens)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_capacity_ep_sharded_matches_unsharded():
    cfg = BASE.replace(moe_dispatch="capacity", moe_capacity_factor=NO_DROP)
    spec = MeshSpec(ep=2, tp=2)
    validate_spec(spec, cfg)
    tokens = jnp.asarray(
        RNG.integers(0, BASE.vocab_size, (2, 24)), jnp.int32)
    ref = _prefill_logits(cfg, PARAMS, tokens)
    got = _prefill_logits(cfg, PARAMS, tokens, create_mesh(spec), spec)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_capacity_overflow_drops_are_finite():
    """A deliberately tiny capacity must degrade (dropped tokens), never
    produce NaNs or crash — the load-shedding contract."""
    cfg = BASE.replace(moe_dispatch="capacity", moe_capacity_factor=0.25)
    tokens = jnp.asarray(
        RNG.integers(0, BASE.vocab_size, (1, 32)), jnp.int32)
    out = _prefill_logits(cfg, PARAMS, tokens)
    assert np.isfinite(out).all()


def test_auto_picks_dense_for_decode_and_capacity_for_prefill():
    from distributed_llm_inferencing_tpu.models.transformer import (
        _MOE_AUTO_DENSE_MAX_TOKENS)
    # decode-shaped input (N = 8) -> dense; prefill-shaped -> capacity.
    # Pin by checking auto ≡ explicit on both shapes.
    cfg_auto = BASE.replace(moe_dispatch="auto",
                            moe_capacity_factor=NO_DROP)
    small = jnp.asarray(RNG.integers(0, BASE.vocab_size, (1, 8)), jnp.int32)
    assert small.size <= _MOE_AUTO_DENSE_MAX_TOKENS
    np.testing.assert_array_equal(
        _prefill_logits(cfg_auto, PARAMS, small),
        _prefill_logits(BASE.replace(moe_dispatch="dense"), PARAMS, small))
    big = jnp.asarray(RNG.integers(0, BASE.vocab_size, (2, 48)), jnp.int32)
    assert big.size > _MOE_AUTO_DENSE_MAX_TOKENS
    np.testing.assert_array_equal(
        _prefill_logits(cfg_auto, PARAMS, big),
        _prefill_logits(BASE.replace(moe_dispatch="capacity",
                                     moe_capacity_factor=NO_DROP),
                        PARAMS, big))


def test_capacity_overflow_actually_drops_tokens():
    """Token-level drop semantics (not just finiteness): rig the router
    so EVERY token picks expert 0 with k=1 and size capacity C=2; the
    first C tokens (priority = token order) must match the dense path's
    expert output, and every later token must come out exactly zero —
    its single expert choice was shed."""
    cfg = BASE.replace(moe_dispatch="capacity", num_experts_per_tok=1,
                       moe_capacity_factor=1.0)
    E, D, I = cfg.num_experts, cfg.hidden_size, cfg.intermediate_size
    rng = np.random.default_rng(2)
    router = np.zeros((D, E), np.float32)
    router[:, 0] = 1.0   # expert 0 dominates for any positive-sum token
    lp = {
        "router": {"w": jnp.asarray(router)},
        "experts": {
            "gate": {"w": jnp.asarray(rng.standard_normal((E, D, I)) * 0.1,
                                      jnp.float32)},
            "up": {"w": jnp.asarray(rng.standard_normal((E, D, I)) * 0.1,
                                    jnp.float32)},
            "down": {"w": jnp.asarray(rng.standard_normal((E, I, D)) * 0.1,
                                      jnp.float32)},
        },
    }
    N = 8
    x = jnp.abs(jnp.asarray(rng.standard_normal((N, D)), jnp.float32)) + 0.1
    # C = factor * k * N / E = 1 * 1 * 8 / 4 = 2
    out_cap = np.asarray(transformer._moe_capacity(x, lp, cfg))
    out_dense = np.asarray(transformer._moe_dense(x, lp, cfg))
    np.testing.assert_allclose(out_cap[:2], out_dense[:2],
                               atol=1e-5, rtol=1e-5)
    kept_norm = np.abs(out_dense[2:]).max()
    assert kept_norm > 1e-3   # the dense path would have produced signal
    np.testing.assert_array_equal(out_cap[2:], np.zeros((N - 2, D)))
