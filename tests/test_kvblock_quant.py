"""Per-(layer, head) int8 KV-block quantization: the differential suite.

`DLI_KV_HOST_DTYPE=int8` trades the arena/wire tier's bit-exactness for
~3.9x density, so it is gated by its own evidence rather than riding the
bitwise pins:

- quantize -> dequantize error stays inside the per-(layer, head)
  half-step bound on every supported logical dtype,
- decode-step LOGITS computed against a quantize-roundtripped paged
  cache stay within a small max-abs-err of the native cache on registry
  models, with the greedy argmax unchanged,
- a greedy decode continued from int8-quantized transferred blocks
  emits the exact tokens of a cold native run (the end-to-end twin of
  ``test_disagg.py``'s bitwise pin),
- wire flattening round-trips, and ``block_from_wire`` rejects every
  malformed-meta class (the payload came off a socket),
- the arena's byte accounting is honest in int8 mode: ``occupancy``
  counts stored bytes, ``logical_bytes`` what they restore to.

Native mode is deliberately NOT touched here — its bitwise guarantees
stay pinned by the unmodified tests in ``test_kvtier.py`` and
``test_disagg.py``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops import kvblock_quant as kvq
from distributed_llm_inferencing_tpu.ops.paged_kvcache import (
    init_paged_cache)
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.runtime.batcher import (
    ContinuousBatcher)
from distributed_llm_inferencing_tpu.runtime.kvtier import HostKVArena

BS = 8


def _page(rng, dtype=np.float32, L=2, bs=BS, H=2, D=4, scale=1.0):
    return (rng.standard_normal((L, bs, H, D)) * scale).astype(dtype)


# ---- numeric bounds -----------------------------------------------------

@pytest.mark.parametrize("dtype", ["float32", "float16", "bfloat16"])
def test_roundtrip_bounded_error(dtype):
    import ml_dtypes
    np_dtype = (np.dtype(ml_dtypes.bfloat16) if dtype == "bfloat16"
                else np.dtype(dtype))
    rng = np.random.default_rng(0)
    page = _page(rng, np.float32).astype(np_dtype)
    e = kvq.quantize_page(page)
    assert e["kind"] == "q8" and e["q"].dtype == np.int8
    assert e["scale"].shape == (page.shape[0], page.shape[-2])
    deq = kvq.dequantize_page(e)
    assert deq.dtype == page.dtype and deq.shape == page.shape
    # per-(layer, head) half-step bound: |x - deq| <= scale/2 plus the
    # logical dtype's own rounding on the way back
    f = np.asarray(page, np.float32)
    err = np.abs(f - np.asarray(deq, np.float32))
    bound = e["scale"][:, None, :, None] * 0.55 + np.abs(f) * 1e-2
    assert np.all(err <= bound), float(err.max())


def test_scale_varies_per_layer_and_head():
    """A hot head must not inflate a quiet head's quantization step —
    the per-(layer, head) granularity is the scheme's whole point."""
    rng = np.random.default_rng(1)
    page = _page(rng)
    page[0, :, 0, :] *= 100.0           # one hot (layer, head)
    e = kvq.quantize_page(page)
    assert e["scale"][0, 0] > 50 * e["scale"][0, 1]
    deq = kvq.dequantize_page(e)
    quiet_err = np.abs(page[0, :, 1, :] - deq[0, :, 1, :]).max()
    assert quiet_err <= e["scale"][0, 1] * 0.55


def test_raw_passthrough():
    """Integer pages (kv-quantized device caches) and low-rank float
    leaves (their scale planes) must pass through bit-identically —
    re-quantizing either would be lossy-on-lossy."""
    rng = np.random.default_rng(2)
    pages = [rng.integers(-127, 127, (2, BS, 2, 4)).astype(np.int8),
             rng.standard_normal((2, BS, 2)).astype(np.float32)]  # 3D
    rec = kvq.quantize_block(pages)
    assert all(e["kind"] == "raw" for e in rec["pages"])
    for got, want in zip(kvq.dequantize_block(rec), pages):
        np.testing.assert_array_equal(got, want)


def test_accounting_and_specs():
    rng = np.random.default_rng(3)
    pages = [_page(rng), _page(rng)]
    rec = kvq.quantize_block(pages)
    logical = sum(p.nbytes for p in pages)
    assert kvq.logical_nbytes(rec) == logical
    assert kvq.stored_nbytes(rec) < logical / 3.5
    assert kvq.logical_specs(rec) == [(p.shape, p.dtype) for p in pages]
    assert kvq.is_quantized_block(rec)
    assert not kvq.is_quantized_block(tuple(pages))


# ---- wire flattening / untrusted-meta validation ------------------------

def test_wire_roundtrip():
    rng = np.random.default_rng(4)
    pages = [_page(rng), rng.integers(0, 5, (3,)).astype(np.int32)]
    rec = kvq.quantize_block(pages)
    back = kvq.block_from_wire(kvq.wire_meta(rec), kvq.wire_arrays(rec))
    for got, want in zip(kvq.dequantize_block(back),
                         kvq.dequantize_block(rec)):
        np.testing.assert_array_equal(got, want)


@pytest.mark.parametrize("mangle", [
    "kind", "dtype", "q_dtype", "scale_dtype", "scale_shape",
    "nonfinite", "short", "long", "low_rank"])
def test_block_from_wire_rejects(mangle):
    """Every malformed-meta class raises ValueError (the codec maps it
    to WireError -> recompute) — a socket payload is never trusted."""
    rng = np.random.default_rng(5)
    rec = kvq.quantize_block([_page(rng)])
    meta, arrs = kvq.wire_meta(rec), kvq.wire_arrays(rec)
    if mangle == "kind":
        meta = [{"kind": "zstd"}]
    elif mangle == "dtype":
        meta = [{"kind": "q8", "dtype": "int64"}]
    elif mangle == "q_dtype":
        arrs = [arrs[0].astype(np.int16), arrs[1]]
    elif mangle == "scale_dtype":
        arrs = [arrs[0], arrs[1].astype(np.float64)]
    elif mangle == "scale_shape":
        # truncated scale payload: fewer scales than (layers, heads)
        arrs = [arrs[0], arrs[1][:1]]
    elif mangle == "nonfinite":
        bad = arrs[1].copy()
        bad.flat[0] = np.nan
        arrs = [arrs[0], bad]
    elif mangle == "short":
        arrs = arrs[:1]
    elif mangle == "long":
        arrs = arrs + [arrs[1]]
    else:   # a q page too low-rank to carry (layer, head) axes
        arrs = [arrs[0][0], arrs[1]]
    with pytest.raises(ValueError):
        kvq.block_from_wire(meta, arrs)


# ---- arena accounting in int8 mode --------------------------------------

def test_arena_int8_density_and_honest_bytes():
    rng = np.random.default_rng(6)
    pages = tuple(_page(rng) for _ in range(2))
    logical = sum(p.nbytes for p in pages)
    native = HostKVArena(capacity_bytes=1 << 20)
    q8 = HostKVArena(capacity_bytes=1 << 20, dtype="int8")
    assert native.put("d", pages) and q8.put("d", pages)
    sn, sq = native.stats(), q8.stats()
    assert sn["bytes"] == logical == sn["logical_bytes"]
    assert sq["bytes"] < logical / 3.5      # occupancy counts STORED
    assert sq["logical_bytes"] == logical
    assert sq["dtype"] == "int8"
    # restore path: logical pages out, bounded error
    got = q8.get("d")
    assert [g.shape for g in got] == [p.shape for p in pages]
    rec = q8.peek_stored("d")
    assert kvq.is_quantized_block(rec)
    # a quantized record fetched from an int8 peer stores as-is in a
    # NATIVE arena too (cross-mode transfer)
    assert native.put("q", rec)
    assert native.stats()["bytes"] > logical  # d native + q stored
    assert [g.shape for g in native.get("q")] == [p.shape for p in pages]


def test_arena_rejects_bad_dtype():
    with pytest.raises(ValueError):
        HostKVArena(capacity_bytes=1024, dtype="fp4")


# ---- logit differential on registry models ------------------------------

@pytest.mark.parametrize("model", ["tiny-llama", "tiny-gpt2"])
def test_decode_logits_bounded_vs_native_restore(model):
    """Decode-step logits against a quantize-roundtripped paged cache
    stay within a small max-abs-err of the native cache, and the greedy
    argmax is unchanged — the numeric core of the int8 quality gate."""
    cfg = get_config(model).replace(dtype="float32", attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, cfg.vocab_size, 21).tolist()
    t = -(-len(prompt) // BS) * BS
    n_blocks = t // BS
    my_blocks = list(range(1, 1 + n_blocks))
    tokens = np.zeros((1, t), np.int32)
    tokens[0, :len(prompt)] = prompt
    paged = init_paged_cache(cfg, 16, BS, dtype=jnp.float32)
    last, paged = transformer.paged_prefill_tail(
        params, cfg, jnp.asarray(tokens),
        jnp.asarray([len(prompt)], jnp.int32),
        jnp.asarray(my_blocks, jnp.int32),
        jnp.zeros((1, 1), jnp.int32), jnp.zeros((1,), jnp.int32), paged)

    def roundtrip(leaf):
        a = np.array(leaf)              # [L, NB, bs, H, D]
        for b in my_blocks:
            a[:, b] = kvq.dequantize_page(kvq.quantize_page(a[:, b]))
        return jnp.asarray(a)

    paged_q = paged._replace(k=roundtrip(paged.k), v=roundtrip(paged.v))
    block_tables = np.zeros((1, 8), np.int32)
    block_tables[0, :n_blocks] = my_blocks
    block_tables[0, n_blocks] = 1 + n_blocks
    context_lens = np.asarray([len(prompt)], np.int32)
    toks = np.asarray([int(jnp.argmax(last[0]))], np.int32)
    ln, _ = transformer.paged_decode_step(
        params, cfg, jnp.asarray(toks), paged,
        jnp.asarray(block_tables), jnp.asarray(context_lens))
    lq, _ = transformer.paged_decode_step(
        params, cfg, jnp.asarray(toks), paged_q,
        jnp.asarray(block_tables), jnp.asarray(context_lens))
    err = float(jnp.max(jnp.abs(lq[0] - ln[0])))
    assert err < 0.25, err
    assert int(jnp.argmax(lq[0])) == int(jnp.argmax(ln[0]))


# ---- end-to-end: greedy decode from int8-transferred blocks -------------

def test_greedy_decode_from_quantized_transfer_matches_cold():
    """A greedy decode continued from int8-quantized transferred KV
    emits the exact tokens of a cold native run, at zero transfer
    failures — the end-to-end acceptance gate for int8 mode. (Wire
    overlap is irrelevant to the numerics; the blocking fetch path
    keeps the fake peer simple.)"""
    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = list(range(40))

    def run(b, **kw):
        r = b.submit(list(prompt), max_new_tokens=6,
                     sampling=SamplingParams.greedy(), seed=5, **kw)
        for _ in range(300):
            b.step()
            if r.done.is_set():
                break
        return r.wait()

    b1 = ContinuousBatcher(cfg, params, num_blocks=32, block_size=BS,
                           slots=2, max_seq=128)
    cold = run(b1)
    run(b1, kv_export=True)     # park the prompt's blocks in the arena
    digs = b1.kvtier.block_digests(prompt[:len(prompt) // BS * BS])
    assert digs and all(b1.kvtier.arena.peek(d) for d in digs)
    records = {d: kvq.quantize_block(
        [np.asarray(p) for p in b1.kvtier.arena.peek_pages(d)])
        for d in digs}

    class QuantPeer:
        calls = 0

        def fetch(self, url, model, digests):
            self.calls += 1
            return {d: records[d] for d in digests if d in records}

    fetcher = QuantPeer()
    b2 = ContinuousBatcher(cfg, params, num_blocks=32, block_size=BS,
                           slots=2, max_seq=128, kv_fetcher=fetcher)
    b2._wire_overlap = False
    got = run(b2, kv_source={"url": "http://peer", "model": "tiny-llama"})
    assert got == cold
    assert fetcher.calls == 1
    c = b2.metrics.snapshot()["counters"]
    # the restore leaves the final block to the tail prefill (its last
    # position's KV is never fetchable), so limit = (n-1)//bs blocks
    assert c["kv_transfer_blocks"] == (len(prompt) - 1) // BS
    assert c["kv_transfer_failures"] == 0
    assert c["kv_transfer_bytes"] < sum(
        kvq.logical_nbytes(r) for r in records.values()) / 3.5
