"""InferenceEngine behavior tests (CPU mesh)."""

import numpy as np
import pytest
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models import convert
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
from distributed_llm_inferencing_tpu.parallel.mesh import MeshSpec
from distributed_llm_inferencing_tpu.runtime.engine import InferenceEngine


def _tiny_hf_engine(mesh_spec=None):
    import torch, transformers
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=96, n_positions=64, n_embd=32, n_layer=2, n_head=4)).eval()
    cfg, params = convert.load_hf_model(hf, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32", name="tiny-hf-gpt2")
    eng = InferenceEngine(cfg, params, mesh_spec=mesh_spec, max_seq=64)
    return hf, eng


def test_greedy_matches_hf_generate():
    import torch
    hf, eng = _tiny_hf_engine()
    prompt = [3, 17, 52, 9]
    res = eng.generate([prompt], max_new_tokens=10,
                       sampling=SamplingParams.greedy())
    with torch.no_grad():
        ref = hf.generate(torch.tensor([prompt]), max_new_tokens=10,
                          do_sample=False)
    assert prompt + res.tokens[0] == ref[0].tolist()
    assert res.steps == 10
    assert res.prefill_ms > 0 and res.decode_ms > 0


def test_ragged_batch_greedy_matches_single():
    _, eng = _tiny_hf_engine()
    a, b = [5, 6, 7, 8, 9, 10], [11, 12]
    batched = eng.generate([a, b], max_new_tokens=6,
                           sampling=SamplingParams.greedy())
    sole_a = eng.generate([a], max_new_tokens=6, sampling=SamplingParams.greedy())
    sole_b = eng.generate([b], max_new_tokens=6, sampling=SamplingParams.greedy())
    assert batched.tokens[0] == sole_a.tokens[0]
    assert batched.tokens[1] == sole_b.tokens[0]


def test_streaming_callback_sees_every_token():
    _, eng = _tiny_hf_engine()
    seen = []
    res = eng.generate([[1, 2, 3]], max_new_tokens=5,
                       sampling=SamplingParams.greedy(),
                       stream_cb=lambda step, toks: seen.append((step, toks[0])))
    assert [t for _, t in seen] == res.tokens[0]
    assert [s for s, _ in seen] == list(range(5))


def test_streaming_pipelined_matches_plain_across_chunks():
    """Without an eos stop-check the streaming path queues every chunk's
    dispatch up front (runtime/engine.py) — tokens and stream ordering
    must be identical to the fire-and-forget path across multiple chunk
    boundaries (40 tokens spans the 32/8 chunk schedule)."""
    _, eng = _tiny_hf_engine()
    plain = eng.generate([[1, 2, 3]], max_new_tokens=40,
                         sampling=SamplingParams.greedy())
    seen = []
    res = eng.generate([[1, 2, 3]], max_new_tokens=40,
                       sampling=SamplingParams.greedy(),
                       stream_cb=lambda step, toks: seen.append(toks[0]))
    assert res.tokens[0] == plain.tokens[0]
    assert seen == res.tokens[0]


def test_eos_stops_decode():
    _, eng = _tiny_hf_engine()
    # find which token greedy emits first, use it as "eos"
    probe = eng.generate([[1, 2, 3]], max_new_tokens=3,
                         sampling=SamplingParams.greedy())
    eos = probe.tokens[0][1]
    res = eng.generate([[1, 2, 3]], max_new_tokens=20,
                       sampling=SamplingParams.greedy(), eos_token_id=eos)
    assert res.steps < 20
    assert eos not in res.tokens[0]


def test_context_window_guard():
    _, eng = _tiny_hf_engine()
    with pytest.raises(ValueError, match="exceeds engine max_seq"):
        eng.generate([[1] * 30], max_new_tokens=40)
    with pytest.raises(ValueError, match="empty prompt"):
        eng.generate([[]], max_new_tokens=4)


def test_sampling_reproducible_by_seed():
    _, eng = _tiny_hf_engine()
    sp = SamplingParams(temperature=0.8, top_k=50, top_p=0.95)
    r1 = eng.generate([[4, 5, 6]], max_new_tokens=8, sampling=sp, seed=123)
    r2 = eng.generate([[4, 5, 6]], max_new_tokens=8, sampling=sp, seed=123)
    r3 = eng.generate([[4, 5, 6]], max_new_tokens=8, sampling=sp, seed=124)
    assert r1.tokens == r2.tokens
    assert r1.tokens != r3.tokens or True  # different seed may coincide on tiny vocab


def test_engine_on_tp_dp_mesh_matches_single_device():
    _, ref_eng = _tiny_hf_engine()
    _, mesh_eng = _tiny_hf_engine(mesh_spec=MeshSpec(dp=2, tp=2))
    prompts = [[3, 1, 4, 1, 5], [9, 2, 6]]
    ref = ref_eng.generate(prompts, max_new_tokens=6, sampling=SamplingParams.greedy())
    got = mesh_eng.generate(prompts, max_new_tokens=6, sampling=SamplingParams.greedy())
    assert ref.tokens == got.tokens


def test_dp_mesh_pads_odd_batch():
    """dp=2 with a single prompt must work (batch padded internally)."""
    _, eng = _tiny_hf_engine(mesh_spec=MeshSpec(dp=2))
    _, ref = _tiny_hf_engine()
    got = eng.generate([[7, 8, 9]], max_new_tokens=4,
                       sampling=SamplingParams.greedy())
    want = ref.generate([[7, 8, 9]], max_new_tokens=4,
                        sampling=SamplingParams.greedy())
    assert got.tokens == want.tokens
    assert len(got.tokens) == 1


def test_bucket_capped_at_max_seq():
    """Non-bucket max_seq: prefill bucket must not exceed cache capacity."""
    import torch, transformers
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(
        vocab_size=96, n_positions=100, n_embd=16, n_layer=2, n_head=2)).eval()
    from distributed_llm_inferencing_tpu.models import convert as cv
    cfg, params = cv.load_hf_model(hf, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32")
    eng = InferenceEngine(cfg, params, max_seq=100)
    res = eng.generate([[1] * 70], max_new_tokens=10,
                       sampling=SamplingParams.greedy())
    assert len(res.tokens[0]) == 10


def test_engine_stats():
    _, eng = _tiny_hf_engine()
    eng.generate([[1, 2]], max_new_tokens=2, sampling=SamplingParams.greedy())
    s = eng.stats()
    assert s["model"] == "tiny-hf-gpt2"
    assert s["compiled_prefill_buckets"] == [16]
    assert s["params"] > 0
