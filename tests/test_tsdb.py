"""Telemetry plane: TSDB ring/rate/downsample semantics, SLO evaluator,
tolerant exposition parsing, decode-profiler sampling, trace
tail-retention, and the live master e2e (/api/timeseries +
/api/requests/<id>/cost) over a real batched worker."""

import math
import time

import pytest
import requests

from distributed_llm_inferencing_tpu.runtime import tsdb
from distributed_llm_inferencing_tpu.utils import trace as trace_mod
from distributed_llm_inferencing_tpu.utils.metrics import parse_prometheus
from distributed_llm_inferencing_tpu.utils.profiler import PhaseProfiler

T0 = 1_700_000_000.0


# ---- TSDB ------------------------------------------------------------

def test_series_ring_bounds_and_eviction():
    db = tsdb.TSDB(window_s=100, step_s=1)
    for i in range(5000):
        db.record("n", "g", float(i), t=T0 + i)
    [s] = db.query("g", now=T0 + 5000)
    # bounded: fine ring caps at window/step buckets; nothing older than
    # the window survives
    assert len(s["points"]) <= 100 + 2
    assert s["points"][-1][1] == 4999.0
    assert all(t >= T0 + 5000 - 100 for t, _ in s["points"])


def test_counter_rate_and_reset_monotonicity():
    db = tsdb.TSDB(window_s=600, step_s=1)
    # steady 100 tokens/s...
    for i in range(10):
        db.record("w", "tokens_generated", i * 100.0, kind="counter",
                  t=T0 + i)
    # ...then the worker restarts: the cumulative counter resets to a
    # small value. The rate series must never go negative.
    db.record("w", "tokens_generated", 40.0, kind="counter", t=T0 + 10)
    db.record("w", "tokens_generated", 140.0, kind="counter", t=T0 + 11)
    [s] = db.query("tokens_generated", now=T0 + 12)
    vals = [v for _, v in s["points"]]
    assert all(v >= 0 for v in vals), vals
    assert vals[0] == 100.0
    # post-reset sample treats the new cumulative as growth-since-restart
    assert 40.0 in vals and vals[-1] == 100.0


def test_downsampling_serves_history_past_the_fine_ring():
    # window larger than the fine ring's span: old points must come from
    # the 8x-downsampled coarse ring, in time order, without overlap
    db = tsdb.TSDB(window_s=10_000, step_s=1)   # fine capped at 512
    for i in range(5000):
        db.record("n", "g", float(i % 7), t=T0 + i)
    [s] = db.query("g", window=10_000, now=T0 + 5000)
    ts = [t for t, _ in s["points"]]
    assert ts == sorted(ts)
    assert len(ts) == len(set(ts))
    span = ts[-1] - ts[0]
    assert span > 4000, span            # history beyond the 512-pt fine ring
    assert len(ts) < 1500               # ...but downsampled, not dense


def test_staleness_is_a_gap_not_a_flatline():
    db = tsdb.TSDB(window_s=600, step_s=1)
    for i in range(5):
        db.record("n", "g", 1.0, t=T0 + i)
    # the node goes silent for 100s, then returns
    db.record("n", "g", 2.0, t=T0 + 105)
    [s] = db.query("g", now=T0 + 106)
    ts = [t for t, _ in s["points"]]
    # no synthetic samples were invented inside the silence
    assert not any(T0 + 5 < t < T0 + 105 for t in ts), ts


def test_series_cap_and_catalog_and_nonfinite():
    db = tsdb.TSDB(window_s=60, step_s=1, max_series_per_node=3)
    for i in range(10):
        db.record("n", f"m{i}", 1.0, t=T0)
    assert db.series_count() == 3        # cap: new names dropped
    db.record("n", "m0", float("nan"), t=T0 + 1)
    db.record("n", "m0", float("inf"), t=T0 + 2)
    [s] = db.query("m0", now=T0 + 3)
    assert all(math.isfinite(v) for _, v in s["points"])
    assert db.catalog() == {"n": ["m0", "m1", "m2"]}


def test_ingest_prometheus_strips_and_classifies():
    db = tsdb.TSDB(window_s=60, step_s=1)
    samples = [("dli_tokens_generated_total", {}, 100.0),
               ("dli_batcher_queue_depth", {}, 4.0),
               ("dli_x_seconds_bucket", {"le": "1"}, 3.0),   # skipped
               ("dli_x_seconds_sum", {}, 1.0),               # skipped
               ("dli_x_seconds_count", {}, 3.0)]             # skipped
    db.ingest_prometheus("w0", samples, t=T0)
    db.ingest_prometheus("w0", [("dli_tokens_generated_total", {}, 150.0),
                                ("dli_batcher_queue_depth", {}, 2.0)],
                         t=T0 + 1)
    assert db.catalog() == {"w0": ["batcher_queue_depth",
                                   "tokens_generated"]}
    [s] = db.query("tokens_generated", now=T0 + 2)
    assert s["kind"] == "counter" and s["points"][-1][1] == 50.0
    [s] = db.query("batcher_queue_depth", node="w0", now=T0 + 2)
    assert s["points"][-1][1] == 2.0


# ---- tolerant exposition parsing (satellite) -------------------------

def test_parse_prometheus_tolerates_malformed_lines():
    text = "\n".join([
        "good_total 3",
        "this is : not a sample",          # malformed — must be skipped
        'labeled{a="x",b="y"} 2',
        "exp_v 1.5e-3",
        "neg_inf -Inf",
        "nan_v NaN",
        'escaped{msg="a\\"b\\\\c\\nd"} 1',
        "{} 5",                             # malformed
        "trailing_ts 7 1700000000000",      # exposition timestamp ok
    ])
    out = parse_prometheus(text)
    names = [n for n, _, _ in out]
    assert names == ["good_total", "labeled", "exp_v", "neg_inf", "nan_v",
                     "escaped", "trailing_ts"]
    d = {n: (l, v) for n, l, v in out}
    assert d["labeled"][0] == {"a": "x", "b": "y"}
    assert d["escaped"][0]["msg"] == 'a"b\\c\nd'
    assert d["exp_v"][1] == 1.5e-3
    assert d["neg_inf"][1] == float("-inf")
    assert math.isnan(d["nan_v"][1])
    assert d["trailing_ts"][1] == 7.0
    # strict mode still raises for format checkers
    try:
        parse_prometheus("not a sample !!", strict=True)
        assert False, "strict must raise"
    except ValueError:
        pass


# ---- SLO evaluator ---------------------------------------------------

def test_slo_evaluator_windows_and_burn_rate():
    ev = tsdb.SLOEvaluator(targets={"ttft_ms": 100, "itl_p95_ms": 50,
                                    "availability": 0.9},
                           fast_window_s=10, slow_window_s=100)
    now = T0 + 1000
    for i in range(90):                      # old window: all good
        ev.record(True, t=now - 100 + i)
    for i in range(10):                      # recent: half bad
        ev.record(i % 2 == 0, t=now - 10 + i)
    assert ev.attainment(10, now=now) == 0.5
    assert ev.attainment(100, now=now) == 0.95
    # budget is 10%: burning 50% of requests = 5x budget on the fast
    # window, 0.5x on the slow — the classic page-vs-wait split
    assert abs(ev.burn_rate(10, now=now) - 5.0) < 1e-6
    assert abs(ev.burn_rate(100, now=now) - 0.5) < 1e-6
    snap = ev.snapshot(now=now)
    assert snap["requests_total"] == 100 and snap["violations_total"] == 5
    assert tsdb.SLOEvaluator().attainment(10) is None


def test_cost_within_slo():
    t = {"ttft_ms": 100.0, "itl_p95_ms": 50.0, "availability": 0.99}
    assert tsdb.cost_within_slo(
        {"queue_ms": 30, "prefill_ms": 40, "itl_p95_ms": 10}, t) is True
    assert tsdb.cost_within_slo(
        {"queue_ms": 80, "prefill_ms": 40, "itl_p95_ms": 10}, t) is False
    assert tsdb.cost_within_slo(
        {"queue_ms": 1, "prefill_ms": 1, "itl_p95_ms": 90}, t) is False
    assert tsdb.cost_within_slo(None, t) is None
    assert tsdb.cost_within_slo({"queue_ms": "garbage"}, t) is None
    # schema drift (no phase keys at all) is unevaluable, not a free pass
    assert tsdb.cost_within_slo({}, t) is None
    assert tsdb.cost_within_slo({"decode_ms": 5.0}, t) is None


# ---- decode profiler -------------------------------------------------

def test_profiler_disabled_records_nothing():
    p = PhaseProfiler(enabled=False)
    rec = p.step_begin()
    assert rec is None
    with p.phase("dispatch"):
        pass
    p.step_end(rec)
    assert p.samples() == []
    assert p.summary()["steps_sampled"] == 0


def test_profiler_phases_ring_and_sampling():
    p = PhaseProfiler(capacity=16, sample_every=2, enabled=True)
    for i in range(50):
        rec = p.step_begin()
        with p.phase("dispatch"):
            time.sleep(0.0005)
        with p.phase("emit"):
            pass
        p.step_end(rec, keep=True, active=1)
    # every other step sampled, ring bounded at its capacity
    assert len(p.samples()) == 16
    summ = p.summary()
    assert summ["steps_sampled"] == 16 and summ["steps_seen"] == 50
    assert summ["phases"]["dispatch"]["s"] > 0
    # unattributed time is conserved into "other", so fractions sum ~1
    total_frac = sum(v["frac"] for v in summ["phases"].values())
    assert 0.99 <= total_frac <= 1.01, summ
    flame = p.flame()
    assert flame["name"] == "batcher.step"
    assert {c["name"] for c in flame["children"]} >= {"dispatch", "emit"}
    ev = p.chrome_events(pid=1)
    assert ev and all(e["ph"] == "X" for e in ev)
    # runtime toggle clears and disarms
    cfg = p.configure(enabled=False, reset=True)
    assert cfg["enabled"] is False and p.samples() == []
    # keep=False discards (idle polls)
    p.configure(enabled=True)
    p.step_end(p.step_begin(), keep=False)
    assert p.samples() == []


# ---- trace tail-retention (satellite) --------------------------------

def test_trace_retention_survives_ring_eviction():
    tr = trace_mod.Tracer(service="t", capacity=64)
    bad = tr.record("req.bad", T0, T0 + 1, attrs={"error": "boom"})
    tr.retain(bad.trace_id)
    # flood the main ring far past capacity
    for i in range(500):
        tr.record(f"noise{i}", T0 + 2, T0 + 3)
    assert not any(s.trace_id == bad.trace_id for s in tr.spans())
    kept = [s for s in tr.retained_spans() if s.trace_id == bad.trace_id]
    assert kept and kept[0].name == "req.bad"
    # spans recorded AFTER the flag are captured too
    tr.record("req.bad.child", T0 + 4, T0 + 5,
              parent=trace_mod.SpanCtx(bad.trace_id, bad.span_id))
    names = {s.name for s in tr.retained_spans()
             if s.trace_id == bad.trace_id}
    assert names == {"req.bad", "req.bad.child"}
    # retained spans reach the chrome export exactly once
    events = tr.chrome_trace()["traceEvents"]
    assert sum(1 for e in events if e["name"] == "req.bad") == 1
    # retain is idempotent
    tr.retain(bad.trace_id)
    assert sum(1 for s in tr.retained_spans()
               if s.span_id == bad.span_id) == 1


# ---- batcher cost ledger: exact phase partition ----------------------

def test_batcher_cost_record_partitions_e2e_exactly():
    import numpy as np
    from distributed_llm_inferencing_tpu.models.registry import get_config
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.batcher import (
        ContinuousBatcher)

    cfg = get_config("tiny-llama").replace(dtype="float32",
                                           attn_backend="xla")
    b = ContinuousBatcher(cfg, num_blocks=64, block_size=8, slots=2,
                          max_seq=64, seed=0)
    rng = np.random.default_rng(0)
    prompt = rng.integers(1, cfg.vocab_size, 20).tolist()
    reqs = [b.submit(prompt, max_new_tokens=8,
                     sampling=SamplingParams.greedy()),
            b.submit(prompt, max_new_tokens=8,
                     sampling=SamplingParams.greedy())]
    for _ in range(200):
        b.step()
        if all(r.done.is_set() for r in reqs):
            break
    for r in reqs:
        assert not r.error
        c = r.cost
        assert c is not None
        # the three phases partition [submitted, finished) exactly
        e2e_ms = (r.finished_at - r.submitted_at) * 1e3
        phase_sum = c["queue_ms"] + c["prefill_ms"] + c["decode_ms"]
        assert abs(phase_sum - e2e_ms) < 1.0, (c, e2e_ms)
        assert c["decode_tokens"] == 8
        assert c["weight_passes"] >= 1
        assert c["kv_blocks_peak"] >= len(prompt) // 8
    # identical prompts in one wave: the second leg's prefix came from
    # the radix cache, and the ledger reconciles with the counters
    cached_total = sum(r.cost["prefill_cached_tokens"] for r in reqs)
    uncached_total = sum(r.cost["prefill_uncached_tokens"] for r in reqs)
    counters = b.metrics.snapshot()["counters"]
    assert counters.get("prefill_cached_tokens", 0) == cached_total
    assert counters["prefill_uncached_tokens"] == uncached_total
    assert cached_total >= 16   # two full 8-token blocks reused


# ---- live master e2e: /api/timeseries + cost endpoint ----------------

@pytest.mark.slow   # ~1 min (two live services + model load); always
                    # runs in check.sh's dedicated telemetry step and in
                    # scripts/telemetry_smoke.py — 'not slow' tier-1
                    # sweeps keep their 870s budget for the wide suite
def test_master_timeseries_and_cost_endpoint_live():
    from distributed_llm_inferencing_tpu.runtime.master import Master
    from distributed_llm_inferencing_tpu.runtime.worker import WorkerAgent

    agent = WorkerAgent()
    wsrv = agent.serve("127.0.0.1", 0, background=True)
    wport = wsrv.server_address[1]
    r = requests.post(f"http://127.0.0.1:{wport}/load_model", json={
        "model_name": "tiny-llama", "allow_random_init": True,
        "dtype": "float32", "serving": "batched", "slots": 2,
        "kv_blocks": 64, "kv_block_size": 8, "max_seq": 64}, timeout=600)
    assert r.status_code == 200, r.text
    m = Master(":memory:", health_interval=1.0, tsdb_step_s=0.3)
    msrv = m.service.serve("127.0.0.1", 0, background=True)
    base = f"http://127.0.0.1:{msrv.server_address[1]}"
    try:
        r = requests.post(f"{base}/api/nodes/add", json={
            "name": "w0", "host": "127.0.0.1", "port": wport}).json()
        assert r["status"] == "success", r
        m.start_background()
        rid = requests.post(f"{base}/api/inference/submit", json={
            "model_name": "tiny-llama", "prompt": "hello telemetry",
            "max_new_tokens": 6,
            "sampling": {"do_sample": False,
                         "allow_random_init": True}}).json()["request_id"]
        deadline = time.time() + 300
        while time.time() < deadline:
            st = requests.get(
                f"{base}/api/inference/status/{rid}").json()["request"]
            if st["status"] in ("completed", "failed"):
                break
            time.sleep(0.1)
        assert st["status"] == "completed", st
        # the completed row itself carries the parsed cost record
        assert isinstance(st["cost"], dict) and "decode_ms" in st["cost"]

        # two scrape intervals -> multi-sample series for the node
        time.sleep(1.0)
        ts = requests.get(f"{base}/api/timeseries",
                          params={"metric": "batcher_queue_depth"}).json()
        [s] = [x for x in ts["series"] if x["node"] == "w0"]
        assert len(s["points"]) >= 2, ts
        ts = requests.get(f"{base}/api/timeseries",
                          params={"metric": "tokens_generated",
                                  "node": "w0"}).json()
        assert ts["series"] and ts["series"][0]["kind"] == "counter"
        # catalog mode + breaker series exist
        cat = requests.get(f"{base}/api/timeseries").json()
        assert "w0" in cat["metrics"] and "master" in cat["metrics"]
        assert "breaker_state" in cat["metrics"]["w0"]

        # cost endpoint: phases sum close to the master-observed e2e
        c = requests.get(f"{base}/api/requests/{rid}/cost").json()
        assert c["status"] == "success", c
        phase_sum = (c["cost"]["queue_ms"] + c["cost"]["prefill_ms"]
                     + c["cost"]["decode_ms"])
        assert c["e2e_ms"] and phase_sum <= c["e2e_ms"] * 1.02
        assert c["within_slo"] in (True, False)
        # SLO evaluator recorded the completion; /api/slo reports it
        slo = requests.get(f"{base}/api/slo").json()
        assert slo["requests_total"] >= 1
        # unknown id -> 404
        assert requests.get(
            f"{base}/api/requests/999999/cost").status_code == 404

        # runtime profiler toggle through the worker + master scrape
        pr = requests.post(f"http://127.0.0.1:{wport}/api/profile",
                           json={"enabled": True}).json()
        assert pr["profilers"]["tiny-llama"]["enabled"] is True
        rid2 = requests.post(f"{base}/api/inference/submit", json={
            "model_name": "tiny-llama", "prompt": "profile me",
            "max_new_tokens": 6,
            "sampling": {"do_sample": False,
                         "allow_random_init": True}}).json()["request_id"]
        deadline = time.time() + 300
        while time.time() < deadline:
            st = requests.get(
                f"{base}/api/inference/status/{rid2}").json()["request"]
            if st["status"] in ("completed", "failed"):
                break
            time.sleep(0.1)
        assert st["status"] == "completed", st
        prof = requests.get(f"{base}/api/profile").json()
        summ = prof["nodes"]["w0"]["tiny-llama"]["summary"]
        assert summ["steps_sampled"] >= 1, prof
        assert "dispatch" in summ["phases"], prof
        # profiler spans merge into the worker's chrome-trace export
        tr = requests.get(f"http://127.0.0.1:{wport}/api/trace").json()
        assert any(e.get("name", "").startswith("profile.")
                   for e in tr["traceEvents"]), "no profiler trace spans"
    finally:
        m.stop()
        agent.service.shutdown()
