"""Sharded-execution tests on a virtual 8-device CPU mesh.

The golden property the reference never had (SURVEY.md §4): sharded output
must equal unsharded output.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from distributed_llm_inferencing_tpu.models import transformer
from distributed_llm_inferencing_tpu.models.params import init_params
from distributed_llm_inferencing_tpu.models.registry import get_config
from distributed_llm_inferencing_tpu.ops.kvcache import init_cache
from distributed_llm_inferencing_tpu.parallel import plan, sharding as shd
from distributed_llm_inferencing_tpu.parallel.mesh import (
    MeshSpec, create_mesh, validate_spec)


def _logits(cfg, params, tokens, mesh=None, mesh_spec=None):
    B, S = tokens.shape
    cache = init_cache(cfg, B, S, dtype=jnp.float32)
    lengths = jnp.full((B,), S, jnp.int32)
    if mesh is None:
        logits, _ = transformer.prefill(params, cfg, tokens, lengths, cache)
        return np.asarray(logits)
    with mesh:
        sp = shd.shard_params(params, mesh, cfg, mesh_spec)
        cache = jax.device_put(cache, shd.named(mesh, shd.cache_specs(cfg, mesh_spec)))
        logits, _ = jax.jit(
            lambda p, t, l, c: transformer.prefill(p, cfg, t, l, c)
        )(sp, tokens, lengths, cache)
    return np.asarray(logits)


def test_devices_available():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("spec", [
    MeshSpec(tp=4), MeshSpec(dp=2), MeshSpec(dp=2, tp=2),
    MeshSpec(tp=2, pp=2), MeshSpec(dp=2, tp=2, pp=2),
])
def test_sharded_equals_unsharded(spec):
    cfg = get_config("tiny-llama").replace(dtype="float32")
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    ref = _logits(cfg, params, tokens)
    got = _logits(cfg, params, tokens, create_mesh(spec), spec)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_moe_expert_parallel_equals_unsharded():
    cfg = get_config("tiny-mixtral").replace(dtype="float32")
    spec = MeshSpec(ep=4, tp=2)
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 8)), jnp.int32)
    ref = _logits(cfg, params, tokens)
    got = _logits(cfg, params, tokens, create_mesh(spec), spec)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_validate_spec_rejects_bad_shapes():
    cfg = get_config("tiny-llama")  # 8 heads, inter 128, 4 layers
    with pytest.raises(ValueError):
        validate_spec(MeshSpec(tp=3), cfg)
    with pytest.raises(ValueError):
        validate_spec(MeshSpec(pp=3), cfg)
    with pytest.raises(ValueError):
        validate_spec(MeshSpec(ep=2), cfg)  # dense model


@pytest.mark.parametrize("qk", ["rms_head", "rms_full", "ln_head"])
def test_qk_norm_sharded_equals_unsharded(qk):
    """The q_norm/k_norm leaves through tp x pp GSPMD: per-head scales
    replicate; the full-width RMS reduction spans every tp shard of q
    (XLA inserts the collective)."""
    cfg = get_config("tiny-llama").replace(dtype="float32", qk_norm=qk)
    spec = MeshSpec(tp=2, pp=2)
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(4), dtype=jnp.float32)
    # break the all-ones symmetry so the test can see a mis-sharded scale
    params["layers"]["q_norm"]["scale"] = jnp.asarray(
        np.random.default_rng(7).uniform(
            0.5, 1.5, params["layers"]["q_norm"]["scale"].shape),
        jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 8)),
        jnp.int32)
    ref = _logits(cfg, params, tokens)
    got = _logits(cfg, params, tokens, mesh=create_mesh(spec),
                  mesh_spec=spec)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_olmo2_topology_sharded_equals_unsharded():
    """sublayer_postnorm_only + residual_scale through tp x pp GSPMD
    (the olmo2/granite block mechanisms added in round 5)."""
    cfg = get_config("tiny-llama").replace(
        dtype="float32", qk_norm="rms_full", sublayer_postnorm_only=True,
        residual_scale=0.7)
    spec = MeshSpec(tp=2, pp=2)
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(5), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, cfg.vocab_size, (2, 8)),
        jnp.int32)
    ref = _logits(cfg, params, tokens)
    got = _logits(cfg, params, tokens, mesh=create_mesh(spec),
                  mesh_spec=spec)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_plan_memory_math():
    p = plan.make_plan("llama-3-8b", {"tp": 4}, max_seq=2048, batch=1)
    # 8B params in bf16 ~ 16GB total, ~4GB/device at tp=4
    assert 14e9 < p["param_bytes_total"] < 18e9
    assert abs(p["param_bytes_per_device"] - p["param_bytes_total"] / 4) / p["param_bytes_total"] < 0.15
    assert p["num_devices"] == 4
    # every leaf has a spec entry
    assert "layers.q.w" in p["partition_specs"]


def test_gemma2_topology_sharded_equals_unsharded():
    """Sandwich norms + softcaps + per-layer windows through tp x pp
    GSPMD: the attn_post_norm/mlp_post_norm leaves and the [L]
    attn_window leaf shard per param_specs."""
    cfg = get_config("tiny-llama").replace(
        dtype="float32", sliding_window=None,
        attn_windows=(None, 3, None, 3), attn_softcap=50.0,
        logit_softcap=30.0, post_block_norms=True)
    spec = MeshSpec(tp=2, pp=2)
    validate_spec(spec, cfg)
    params = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, cfg.vocab_size, (2, 8)),
        jnp.int32)
    ref = _logits(cfg, params, tokens)
    got = _logits(cfg, params, tokens, mesh=create_mesh(spec),
                  mesh_spec=spec)
    np.testing.assert_allclose(got, ref, atol=2e-4, rtol=2e-4)


def test_gpt_oss_tp_sharded_matches_single_device():
    """gpt-oss under tp=2: the [L, H] sinks leaf shards over tp with the
    heads, the expert biases over ep/tp — sharded greedy must equal
    single-device greedy (sinks/norms randomized in the builder so a
    mis-sharded leaf is visible)."""
    from conftest import tiny_gpt_oss_model
    from distributed_llm_inferencing_tpu.models import convert
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)
    model = tiny_gpt_oss_model(seed=63)
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32", attn_backend="xla")
    prompt = np.random.default_rng(63).integers(0, 128, 8).tolist()

    single = InferenceEngine(cfg, params, max_seq=32).generate(
        [prompt], max_new_tokens=8, sampling=SamplingParams.greedy()
    ).tokens[0]
    sharded = InferenceEngine(cfg, params, max_seq=32,
                              mesh_spec=MeshSpec(tp=2)).generate(
        [prompt], max_new_tokens=8, sampling=SamplingParams.greedy()
    ).tokens[0]
    assert sharded == single


def test_glm45_moe_tp_ep_sharded_matches_single_device():
    """GLM-4.5 MoE (deepseek routing + mixed dense-prefix stack) under
    tp=2 x ep=2: sharded greedy equals single-device greedy."""
    from conftest import tiny_glm45_moe_model
    from distributed_llm_inferencing_tpu.models import convert
    from distributed_llm_inferencing_tpu.ops.sampling import SamplingParams
    from distributed_llm_inferencing_tpu.runtime.engine import (
        InferenceEngine)
    model = tiny_glm45_moe_model(seed=64)
    cfg, params = convert.load_hf_model(model, dtype=jnp.float32)
    cfg = cfg.replace(dtype="float32", attn_backend="xla")
    prompt = np.random.default_rng(64).integers(0, 128, 8).tolist()

    single = InferenceEngine(cfg, params, max_seq=32).generate(
        [prompt], max_new_tokens=8, sampling=SamplingParams.greedy()
    ).tokens[0]
    sharded = InferenceEngine(cfg, params, max_seq=32,
                              mesh_spec=MeshSpec(tp=2, ep=2)).generate(
        [prompt], max_new_tokens=8, sampling=SamplingParams.greedy()
    ).tokens[0]
    assert sharded == single
